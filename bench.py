"""Benchmark: GPT-2 training through the REAL product path — ray_tpu.init +
JaxTrainer worker group + session report rounds — vs a donation-fair raw-jax
control on the same chip.

Prints ONE JSON line:
  {"metric", "value", "unit", "vs_baseline", "micro": {...}}
vs_baseline = framework-tokens/s / raw-jax-tokens/s. The BASELINE.json north
star asks for >= 0.90. "micro" carries control-plane microbenchmark numbers
(tasks/s, actor calls/s, put GiB/s — see microbench.py for the full table).

Each phase runs in its own subprocess so the driver process never initializes
the TPU backend before the train worker needs it (one process owns the chip).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

WARMUP = 3
STEPS = 10


def _model_kw(on_tpu: bool):
    if on_tpu:
        return dict(preset="124m"), 8, 1024
    return (
        dict(vocab_size=2048, block_size=256, n_layer=4, n_head=8, n_embd=256,
             dtype="float32", use_flash_attention=False),
        4, 256,
    )


def _build_cfg(model_kw):
    import jax.numpy as jnp

    from ray_tpu.models.gpt2 import GPT2Config

    kw = dict(model_kw)
    if kw.pop("preset", None) == "124m":
        return GPT2Config.gpt2_124m()
    kw["dtype"] = {"float32": jnp.float32, "bfloat16": jnp.bfloat16}[kw["dtype"]]
    return GPT2Config(**kw)


def _batch(vocab_size, B, T):
    import numpy as np

    rng = np.random.default_rng(0)
    idx = rng.integers(0, vocab_size, (B, T)).astype(np.int32)
    return {"idx": idx, "targets": np.roll(idx, -1, axis=1)}


# ------------------------------------------------------------ framework phase


def train_loop(config):
    """Runs inside the JaxTrainer worker: sharded TrainStep + real report
    rounds every step (the product path a user would write)."""
    import time

    import jax

    from ray_tpu import train
    from ray_tpu.parallel.mesh import make_mesh
    from ray_tpu.parallel.train_step import TrainStep

    cfg = _build_cfg(config["model_kw"])
    B, T = config["B"], config["T"]
    mesh = make_mesh({"dp": 1}, devices=jax.devices()[:1])
    ts = TrainStep(cfg, mesh)
    state = ts.init(jax.random.PRNGKey(0))
    batch = ts.shard_batch(_batch(cfg.vocab_size, B, T))
    for _ in range(config["warmup"]):
        state, m = ts.step(state, batch)
    jax.block_until_ready(m["loss"])
    t0 = time.perf_counter()
    for i in range(config["steps"]):
        state, m = ts.step(state, batch)
        # Per-step report round through the session (driver consumes + acks).
        # The live loss is NOT materialized mid-run — a raw jax loop wouldn't
        # sync either; the report itself is the framework overhead we measure.
        train.report({"step": i})
    jax.block_until_ready(m["loss"])
    dt = time.perf_counter() - t0
    train.report({
        "tokens_per_s": B * T * config["steps"] / dt,
        "loss": float(m["loss"]),
    })


def phase_framework(on_tpu: bool) -> float:
    import tempfile

    import ray_tpu
    from ray_tpu.train import JaxTrainer, RunConfig, ScalingConfig

    model_kw, B, T = _model_kw(on_tpu)
    ray_tpu.init(num_cpus=4)
    try:
        trainer = JaxTrainer(
            train_loop,
            train_loop_config={
                "model_kw": model_kw, "B": B, "T": T,
                "warmup": WARMUP, "steps": STEPS,
            },
            scaling_config=ScalingConfig(num_workers=1),
            run_config=RunConfig(
                name="bench", storage_path=tempfile.mkdtemp(prefix="rtpu_bench_")
            ),
        )
        result = trainer.fit()
        return result.metrics["tokens_per_s"]
    finally:
        ray_tpu.shutdown()


# -------------------------------------------------------------- control phase


def phase_control(on_tpu: bool) -> float:
    """Donation-fair raw-jax control: same model/optimizer/step math, buffers
    donated exactly like TrainStep's step (donate_argnums)."""
    import time

    import jax
    import jax.numpy as jnp
    import optax

    from ray_tpu.models.gpt2 import GPT2, loss_fn

    model_kw, B, T = _model_kw(on_tpu)
    cfg = _build_cfg(model_kw)
    model = GPT2(cfg)
    opt = optax.chain(
        optax.clip_by_global_norm(1.0),
        optax.adamw(3e-4, b2=0.95, weight_decay=0.1,
                    mask=lambda p: jax.tree.map(lambda x: x.ndim > 1, p)),
    )
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((2, 8), jnp.int32))["params"]
    opt_state = opt.init(params)

    @__import__("functools").partial(jax.jit, donate_argnums=(0, 1))
    def step(params, opt_state, idx, targets):
        def loss_of(p):
            return loss_fn(model.apply({"params": p}, idx), targets)

        loss, grads = jax.value_and_grad(loss_of)(params)
        updates, opt_state2 = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state2, loss

    b = _batch(cfg.vocab_size, B, T)
    idx, tgt = jnp.asarray(b["idx"]), jnp.asarray(b["targets"])
    for _ in range(WARMUP):
        params, opt_state, loss = step(params, opt_state, idx, tgt)
    jax.block_until_ready(loss)
    t0 = time.perf_counter()
    for _ in range(STEPS):
        params, opt_state, loss = step(params, opt_state, idx, tgt)
    jax.block_until_ready(loss)
    dt = time.perf_counter() - t0
    return B * T * STEPS / dt


# ---------------------------------------------------------------- micro phase


def phase_micro() -> dict:
    """Control-plane summary (full table: microbench.py)."""
    from microbench import run_quick

    return run_quick()


# ----------------------------------------------------------------------- main


def _detect_tpu() -> bool:
    # Peek without initializing a jax backend in THIS process.
    code = ("import jax,json;"
            "print(json.dumps(jax.devices()[0].platform))")
    try:
        out = subprocess.run([sys.executable, "-c", code], capture_output=True,
                             text=True, timeout=120, cwd=_repo_dir())
        return json.loads(out.stdout.strip().splitlines()[-1]) == "tpu"
    except Exception:
        return False


def _repo_dir():
    return os.path.dirname(os.path.abspath(__file__))


def _run_phase(phase: str) -> float | dict:
    env = dict(os.environ)
    out = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--phase", phase],
        capture_output=True, text=True, timeout=3600, env=env, cwd=_repo_dir(),
    )
    for line in reversed(out.stdout.strip().splitlines()):
        try:
            return json.loads(line)["result"]
        except Exception:
            continue
    raise RuntimeError(
        f"phase {phase} produced no result:\n{out.stdout[-2000:]}\n"
        f"{out.stderr[-2000:]}"
    )


def main():
    if "--phase" in sys.argv:
        phase = sys.argv[sys.argv.index("--phase") + 1]
        on_tpu = _detect_tpu() if phase != "micro" else False
        fn = {"framework": phase_framework, "control": phase_control,
              "micro": phase_micro}[phase]
        result = fn(on_tpu) if phase != "micro" else fn()
        print(json.dumps({"result": result}))
        return
    ours = _run_phase("framework")
    raw = _run_phase("control")
    try:
        micro = _run_phase("micro")
    except Exception:
        micro = {}
    print(json.dumps({
        "metric": "gpt2_train_tokens_per_s_via_JaxTrainer",
        "value": round(ours, 1),
        "unit": "tokens/s",
        "vs_baseline": round(ours / raw, 4),
        "raw_jax_control_tokens_per_s": round(raw, 1),
        "micro": micro,
    }))


if __name__ == "__main__":
    main()
