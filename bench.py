"""Benchmark: GPT-2-124M training throughput through the framework's sharded
train step vs a hand-written raw-jax loop on the same hardware.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
vs_baseline is framework-tokens/s divided by raw-jax tokens/s on this chip —
the BASELINE.json north star asks for >= 0.90.
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.models.gpt2 import GPT2, GPT2Config, loss_fn
from ray_tpu.parallel.mesh import make_mesh
from ray_tpu.parallel.train_step import TrainStep

WARMUP = 3
STEPS = 10


def _batch(cfg, B, T, rng):
    idx = rng.integers(0, cfg.vocab_size, (B, T)).astype(np.int32)
    return {"idx": idx, "targets": np.roll(idx, -1, axis=1)}


def bench_framework(cfg, B, T) -> float:
    mesh = make_mesh({"dp": 1}, devices=jax.devices()[:1])
    ts = TrainStep(cfg, mesh)
    state = ts.init(jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    batch = ts.shard_batch(_batch(cfg, B, T, rng))
    for _ in range(WARMUP):
        state, m = ts.step(state, batch)
    jax.block_until_ready(m["loss"])
    t0 = time.perf_counter()
    for _ in range(STEPS):
        state, m = ts.step(state, batch)
    jax.block_until_ready(m["loss"])
    dt = time.perf_counter() - t0
    return B * T * STEPS / dt


def bench_raw_jax(cfg, B, T) -> float:
    """The 'no framework' control: plain jit train step, same model/opt."""
    model = GPT2(cfg)
    opt = optax.chain(
        optax.clip_by_global_norm(1.0),
        optax.adamw(3e-4, b2=0.95, weight_decay=0.1,
                    mask=lambda p: jax.tree.map(lambda x: x.ndim > 1, p)),
    )
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((2, 8), jnp.int32))["params"]
    opt_state = opt.init(params)

    @jax.jit
    def step(params, opt_state, idx, targets):
        def loss_of(p):
            return loss_fn(model.apply({"params": p}, idx), targets)

        loss, grads = jax.value_and_grad(loss_of)(params)
        updates, opt_state2 = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state2, loss

    rng = np.random.default_rng(0)
    b = _batch(cfg, B, T, rng)
    idx, tgt = jnp.asarray(b["idx"]), jnp.asarray(b["targets"])
    for _ in range(WARMUP):
        params, opt_state, loss = step(params, opt_state, idx, tgt)
    jax.block_until_ready(loss)
    t0 = time.perf_counter()
    for _ in range(STEPS):
        params, opt_state, loss = step(params, opt_state, idx, tgt)
    jax.block_until_ready(loss)
    dt = time.perf_counter() - t0
    return B * T * STEPS / dt


def main():
    on_tpu = jax.devices()[0].platform == "tpu"
    cfg = GPT2Config.gpt2_124m() if on_tpu else GPT2Config(
        vocab_size=2048, block_size=256, n_layer=4, n_head=8, n_embd=256,
        dtype=jnp.float32, use_flash_attention=False,
    )
    B, T = (8, 1024) if on_tpu else (4, 256)
    ours = bench_framework(cfg, B, T)
    raw = bench_raw_jax(cfg, B, T)
    print(json.dumps({
        "metric": "gpt2_train_tokens_per_s_single_chip",
        "value": round(ours, 1),
        "unit": "tokens/s",
        "vs_baseline": round(ours / raw, 4),
    }))


if __name__ == "__main__":
    main()
