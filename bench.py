"""Benchmark: GPT-2 training through the REAL product path — ray_tpu.init +
JaxTrainer worker group + session report rounds — vs a donation-fair raw-jax
control on the same chip.

Prints ONE JSON line:
  {"metric", "value", "unit", "vs_baseline", "plane": "tpu"|"cpu",
   "micro": {...}}
When the shared-TPU tunnel is unreachable for the whole window the bench
falls back to the CPU plane (same interleaved protocol, host backend,
tagged "plane": "cpu") instead of emitting nothing — see
_cpu_plane_fallback.
vs_baseline = framework-tokens/s / raw-jax-tokens/s. The BASELINE.json north
star asks for >= 0.90. "micro" carries control-plane microbenchmark numbers
(tasks/s, actor calls/s, put GiB/s — see microbench.py for the full table).

Each phase runs in its own subprocess so the driver process never initializes
the TPU backend before the train worker needs it (one process owns the chip).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

WARMUP = int(os.environ.get("RTPU_BENCH_WARMUP", "40"))
STEPS = int(os.environ.get("RTPU_BENCH_STEPS", "1600"))
# Both sides run lax.scan chunks of SCAN steps per dispatch (XLA-idiomatic:
# "no data-dependent Python control flow inside jit"); the framework reports
# once per chunk — the standard log-every-N product pattern. Chunk sizing is
# a noise decision: a GPT-2-124M B=16 step is ~0.3 ms on-device but each
# dispatch through the shared-TPU tunnel costs ~1.7 ms with heavy jitter,
# so 40-step chunks keep the jitter under ~15% of a chunk and 30 timed
# chunks per side average it out (10-step chunks left ratio sigma ~11%/run;
# min-of-5 is judged, so per-run variance matters as much as the mean).
SCAN = int(os.environ.get("RTPU_BENCH_SCAN", "40"))


def _model_kw(on_tpu: bool):
    if on_tpu:
        # B=16 x T=1024 on GPT-2-124M: the largest batch that fits beside
        # the optimizer state in one chip's HBM (B=64 OOMs on the fp32
        # logits). Same workload on both sides of the ratio.
        return dict(preset="124m"), 16, 1024
    return (
        dict(vocab_size=2048, block_size=256, n_layer=4, n_head=8, n_embd=256,
             dtype="float32", use_flash_attention=False),
        4, 256,
    )


def _build_cfg(model_kw):
    import jax.numpy as jnp

    from ray_tpu.models.gpt2 import GPT2Config

    kw = dict(model_kw)
    if kw.pop("preset", None) == "124m":
        return GPT2Config.gpt2_124m()
    kw["dtype"] = {"float32": jnp.float32, "bfloat16": jnp.bfloat16}[kw["dtype"]]
    return GPT2Config(**kw)


def _batch(vocab_size, B, T):
    import numpy as np

    rng = np.random.default_rng(0)
    idx = rng.integers(0, vocab_size, (B, T)).astype(np.int32)
    return {"idx": idx, "targets": np.roll(idx, -1, axis=1)}


# ------------------------------------------------------------ framework phase


def _make_control(cfg, B, T):
    """Raw-jax control: same model/optimizer/step math as TrainStep, donated
    buffers, scanned in SCAN-step chunks, no framework. Returns a
    run_chunk() closure; timed chunks INTERLEAVE with the framework's so
    the shared-TPU tunnel's minute-scale throughput drift (measured 2-3x on
    identical workloads) cancels out of the ratio."""
    import functools

    import jax
    import jax.numpy as jnp
    import optax

    from ray_tpu.models.gpt2 import GPT2, loss_fn

    model = GPT2(cfg)
    opt = optax.chain(
        optax.clip_by_global_norm(1.0),
        optax.adamw(3e-4, b2=0.95, weight_decay=0.1,
                    mask=lambda p: jax.tree.map(lambda x: x.ndim > 1, p)),
    )
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((2, 8), jnp.int32))["params"]
    opt_state = opt.init(params)

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def step(params, opt_state, idx, targets):
        def loss_of(p):
            return loss_fn(model.apply({"params": p}, idx), targets)

        loss, grads = jax.value_and_grad(loss_of)(params)
        updates, opt_state2 = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state2, loss

    def multi(params, opt_state, idx, targets):
        def body(carry, _):
            p, o = carry
            p, o, loss = step(p, o, idx, targets)
            return (p, o), loss

        (p, o), losses = jax.lax.scan(
            body, (params, opt_state), None, length=SCAN)
        return p, o, losses

    multi = jax.jit(multi, donate_argnums=(0, 1))

    b = _batch(cfg.vocab_size, B, T)
    idx, tgt = jnp.asarray(b["idx"]), jnp.asarray(b["targets"])
    holder = {"p": params, "o": opt_state}

    def run_chunk():
        import jax as _jax
        import time as _time

        t0 = _time.perf_counter()
        holder["p"], holder["o"], losses = multi(
            holder["p"], holder["o"], idx, tgt)
        _jax.block_until_ready(losses)
        return _time.perf_counter() - t0

    return run_chunk


def train_loop(config):
    """Runs inside the JaxTrainer worker: the raw-jax control and the
    framework path (sharded TrainStep + report round per chunk — the
    product loop a user writes) alternate timed SCAN-step chunks in one
    process, so tunnel-throughput drift hits both sides equally."""
    import time

    import jax

    from ray_tpu import train
    from ray_tpu.parallel.mesh import make_mesh
    from ray_tpu.parallel.train_step import TrainStep

    cfg = _build_cfg(config["model_kw"])
    B, T = config["B"], config["T"]
    run_control_chunk = _make_control(cfg, B, T)

    mesh = make_mesh({"dp": 1}, devices=jax.devices()[:1])
    ts = TrainStep(cfg, mesh)
    state = ts.init(jax.random.PRNGKey(0))
    batch = ts.shard_batch(_batch(cfg.vocab_size, B, T))

    def run_ours_chunk(i):
        t0 = time.perf_counter()
        nonlocal state
        state, m = ts.multi_step(state, batch, SCAN)
        # Report round through the session (driver drains + acks) — the
        # framework overhead being measured rides inside the timed chunk.
        train.report({"chunk": i})
        jax.block_until_ready(m["loss"])
        return time.perf_counter() - t0

    warm_chunks = max(1, config["warmup"] // SCAN) + 1
    for i in range(warm_chunks):
        run_control_chunk()
        run_ours_chunk(-1 - i)
    chunks = config["steps"] // SCAN
    raw_times, ours_times = [], []
    for i in range(chunks):
        # counterbalanced pair order (R,O then O,R): any "second runner"
        # penalty from the tunnel (post-burst throttling, scheduler state)
        # lands on both sides equally instead of always on ours — the
        # per-update-interleaved rllib phase measures 0.97-1.00 with the
        # same trick while fixed-order pairs drift to ~0.93
        if i % 2 == 0:
            raw_times.append(run_control_chunk())
            ours_times.append(run_ours_chunk(i))
        else:
            ours_times.append(run_ours_chunk(i))
            raw_times.append(run_control_chunk())

    # Trimmed per-chunk statistics: the tunnel occasionally stalls a
    # single dispatch for tens of ms; with ~2 ms chunks one stall landing
    # on one side skews a whole run's SUM by >10%. A 20%-trimmed mean of
    # per-chunk times is robust to those tails while using both sides'
    # full chunk population.
    def trimmed_mean(xs):
        xs = sorted(xs)
        k = max(1, len(xs) // 5)
        core = xs[k:-k] if len(xs) > 2 * k else xs
        return sum(core) / len(core)

    tokens_per_chunk = B * T * SCAN
    train.report({
        "tokens_per_s": tokens_per_chunk / trimmed_mean(ours_times),
        "raw_tokens_per_s": tokens_per_chunk / trimmed_mean(raw_times),
        "sum_tokens_per_s": tokens_per_chunk * chunks / sum(ours_times),
        "sum_raw_tokens_per_s": tokens_per_chunk * chunks / sum(raw_times),
    })


def phase_framework(on_tpu: bool) -> float:
    import tempfile

    import ray_tpu
    from ray_tpu.train import JaxTrainer, RunConfig, ScalingConfig

    model_kw, B, T = _model_kw(on_tpu)
    ray_tpu.init(num_cpus=4)
    try:
        trainer = JaxTrainer(
            train_loop,
            train_loop_config={
                "model_kw": model_kw, "B": B, "T": T,
                "warmup": WARMUP, "steps": STEPS,
            },
            scaling_config=ScalingConfig(num_workers=1),
            run_config=RunConfig(
                name="bench", storage_path=tempfile.mkdtemp(prefix="rtpu_bench_")
            ),
        )
        result = trainer.fit()
        return {"ours": result.metrics["tokens_per_s"],
                "raw": result.metrics["raw_tokens_per_s"]}
    finally:
        ray_tpu.shutdown()


# -------------------------------------------------------------- control phase


def phase_control(on_tpu: bool) -> float:
    """Donation-fair raw-jax control: same model/optimizer/step math, buffers
    donated exactly like TrainStep's step (donate_argnums)."""
    import time

    import jax
    import jax.numpy as jnp
    import optax

    from ray_tpu.models.gpt2 import GPT2, loss_fn

    model_kw, B, T = _model_kw(on_tpu)
    cfg = _build_cfg(model_kw)
    model = GPT2(cfg)
    opt = optax.chain(
        optax.clip_by_global_norm(1.0),
        optax.adamw(3e-4, b2=0.95, weight_decay=0.1,
                    mask=lambda p: jax.tree.map(lambda x: x.ndim > 1, p)),
    )
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((2, 8), jnp.int32))["params"]
    opt_state = opt.init(params)

    @__import__("functools").partial(jax.jit, donate_argnums=(0, 1))
    def step(params, opt_state, idx, targets):
        def loss_of(p):
            return loss_fn(model.apply({"params": p}, idx), targets)

        loss, grads = jax.value_and_grad(loss_of)(params)
        updates, opt_state2 = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state2, loss

    b = _batch(cfg.vocab_size, B, T)
    idx, tgt = jnp.asarray(b["idx"]), jnp.asarray(b["targets"])
    for _ in range(WARMUP):
        params, opt_state, loss = step(params, opt_state, idx, tgt)
    jax.block_until_ready(loss)
    t0 = time.perf_counter()
    for _ in range(STEPS):
        params, opt_state, loss = step(params, opt_state, idx, tgt)
    jax.block_until_ready(loss)
    dt = time.perf_counter() - t0
    return B * T * STEPS / dt


# ---------------------------------------------------------------- micro phase


def phase_micro() -> dict:
    """Control-plane summary (full table: microbench.py)."""
    from microbench import run_quick

    return run_quick()


# ---------------------------------------------------------------- rllib phase


class _BenchLearner:
    """Learner actor hosting BOTH sides of the RL-learner ratio on the one
    chip: product-path updates arrive as driver RPCs (batch ship + update +
    weight readback — the IMPALA hot loop), the raw control runs the same
    updates in-process. Chunks interleave driver-side."""

    def __init__(self, obs_dim, num_actions, cfg, batch):
        from ray_tpu.rllib.core.impala_learner import ImpalaLearner

        self.learner = ImpalaLearner(obs_dim, num_actions, **cfg)
        self._batch = batch

    def update(self, batch):
        return self.learner.update_from_trajectories(batch)

    def get_weights(self):
        return self.learner.get_weights()

    def raw_chunk(self, k: int) -> float:
        """k no-framework updates (host batch -> device each time, like a
        raw jax loop); returns elapsed seconds measured in-process."""
        import time as _t

        t0 = _t.perf_counter()
        for _ in range(k):
            self.learner.update_from_trajectories(self._batch)
        return _t.perf_counter() - t0


def phase_rllib(on_tpu: bool) -> dict:
    """IMPALA learner throughput through the product path (driver->actor
    RPC per rollout + weight sync) vs the raw in-process jax loop,
    interleaved chunk-wise on the same chip."""
    import time

    import numpy as np

    import ray_tpu

    # IMPALA-scale batch: 8192 env steps/update amortizes the per-update
    # batch ship + RPC round trip the product path pays over the raw loop
    T, N = (64, 128) if on_tpu else (16, 8)
    obs_dim, num_actions = 4, 2
    rng = np.random.default_rng(0)
    batch = {
        "obs": rng.normal(size=(T, N, obs_dim)).astype(np.float32),
        "actions": rng.integers(0, num_actions, (T, N)),
        "behavior_logp": np.full((T, N), -0.69, np.float32),
        "rewards": rng.normal(size=(T, N)).astype(np.float32),
        "dones": np.zeros((T, N), np.float32),
        "valid": np.ones((T, N), np.float32),
        "bootstrap_obs": rng.normal(size=(N, obs_dim)).astype(np.float32),
    }
    cfg = dict(lr=5e-4, gamma=0.99, vf_coeff=0.5, entropy_coeff=0.01,
               rho_bar=1.0, c_bar=1.0, hidden=(64, 64), seed=0)
    ray_tpu.init(num_cpus=2)
    try:
        actor = ray_tpu.remote(_BenchLearner).remote(
            obs_dim, num_actions, cfg, batch
        )
        updates = 6 if not on_tpu else 24
        # warmup both paths (compile)
        ray_tpu.get(actor.update.remote(batch), timeout=600)
        ray_tpu.get(actor.raw_chunk.remote(1), timeout=600)
        # Interleave at SINGLE-update granularity: one ~0.5 s update pair
        # sits inside the tunnel's drift timescale, so the drift cancels
        # pairwise; trimmed means kill the residual stall tails (same
        # protocol as the train bench's chunks).
        raw_times, ours_times = [], []
        for i in range(updates):
            raw_times.append(
                ray_tpu.get(actor.raw_chunk.remote(1), timeout=600)
            )
            t0 = time.perf_counter()
            ray_tpu.get(actor.update.remote(batch), timeout=600)
            if i % 5 == 4:  # periodic weight sync, like the real algorithm
                ray_tpu.get(actor.get_weights.remote(), timeout=600)
            ours_times.append(time.perf_counter() - t0)

        def trimmed_mean(xs):
            xs = sorted(xs)
            k = max(1, len(xs) // 5)
            core = xs[k:-k] if len(xs) > 2 * k else xs
            return sum(core) / len(core)

        steps_per_update = T * N
        return {
            "ours_steps_per_s": steps_per_update / trimmed_mean(ours_times),
            "raw_steps_per_s": steps_per_update / trimmed_mean(raw_times),
        }
    finally:
        ray_tpu.shutdown()


# ----------------------------------------------------------------------- main


def _detect_tpu() -> bool:
    # Peek without initializing a jax backend in THIS process.
    code = ("import jax,json;"
            "print(json.dumps(jax.devices()[0].platform))")
    try:
        out = subprocess.run([sys.executable, "-c", code], capture_output=True,
                             text=True, timeout=120, cwd=_repo_dir())
        return json.loads(out.stdout.strip().splitlines()[-1]) == "tpu"
    except Exception:
        return False


def _repo_dir():
    return os.path.dirname(os.path.abspath(__file__))


def _log(msg: str):
    # Progress narration goes to stderr; stdout carries ONLY the one JSON line.
    print(f"[bench] {msg}", file=sys.stderr, flush=True)


def _probe_backend(attempts: int | None = None, backoff_s: float | None = None):
    """Touch the jax backend in a throwaway subprocess, retrying with
    exponential backoff. The shared-TPU axon tunnel goes UNAVAILABLE for
    minutes at a time (BENCH_r04 died on first contact with no retry);
    a bounded probe loop distinguishes 'tunnel down right now' from
    'tunnel down for the whole window'.

    Returns (platform | None, detail). platform None => backend unreachable.
    """
    if attempts is None:
        attempts = int(os.environ.get("RTPU_BENCH_PROBE_ATTEMPTS", "4"))
    if backoff_s is None:
        backoff_s = float(os.environ.get("RTPU_BENCH_PROBE_BACKOFF_S", "30"))
    code = ("import jax,json;"
            "print(json.dumps(jax.devices()[0].platform))")
    detail = ""
    for i in range(attempts):
        if i:
            delay = backoff_s * (2 ** (i - 1))  # 30, 60, 120
            _log(f"backend probe retry in {delay:.0f}s ({detail})")
            time.sleep(delay)
        try:
            out = subprocess.run(
                [sys.executable, "-c", code], capture_output=True, text=True,
                timeout=float(
                    os.environ.get("RTPU_BENCH_PROBE_TIMEOUT_S", "300")),
                cwd=_repo_dir(),
            )
            if out.returncode == 0 and out.stdout.strip():
                plat = json.loads(out.stdout.strip().splitlines()[-1])
                _log(f"backend up: platform={plat} (attempt {i + 1})")
                return plat, ""
            detail = (out.stderr or out.stdout).strip().splitlines()[-1:]
            detail = detail[0][:300] if detail else f"rc={out.returncode}"
        except subprocess.TimeoutExpired as e:
            detail = f"backend init timed out after {e.timeout:.0f}s"
        except Exception as e:  # noqa: BLE001
            detail = f"{type(e).__name__}: {e}"
    return None, detail


def _run_phase(phase: str, timeout: float = 3600,
               extra_env: dict | None = None) -> float | dict:
    env = dict(os.environ)
    if extra_env:
        env.update(extra_env)
    out = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--phase", phase],
        capture_output=True, text=True, timeout=timeout, env=env,
        cwd=_repo_dir(),
    )
    for line in reversed(out.stdout.strip().splitlines()):
        try:
            return json.loads(line)["result"]
        except Exception:
            continue
    raise RuntimeError(
        f"phase {phase} produced no result:\n{out.stdout[-2000:]}\n"
        f"{out.stderr[-2000:]}"
    )


def _run_phase_retry(phase: str, attempts: int = 2, timeout: float = 1800,
                     backoff_s: float = 45.0, extra_env: dict | None = None):
    """One phase run, retried on failure. Each phase is its own subprocess,
    so a tunnel stall kills at most one attempt, bounded by `timeout`."""
    last = None
    for i in range(attempts):
        if i:
            _log(f"phase {phase} attempt {i} failed ({last}); "
                 f"retrying in {backoff_s:.0f}s")
            time.sleep(backoff_s)
        try:
            return _run_phase(phase, timeout=timeout, extra_env=extra_env)
        except Exception as e:  # noqa: BLE001
            last = f"{type(e).__name__}: {str(e)[:300]}"
    raise RuntimeError(f"phase {phase} failed after {attempts} attempts: {last}")


def _emit(payload: dict):
    """The one stdout JSON line — ALWAYS printed, whatever happened.
    BENCH_r04 taught the lesson: a bench that crashes on first backend
    contact leaves no artifact at all. Every exit path routes through here
    with an explicit status."""
    print(json.dumps(payload))


def main():
    if "--phase" in sys.argv:
        phase = sys.argv[sys.argv.index("--phase") + 1]
        on_tpu = _detect_tpu() if phase != "micro" else False
        fn = {"framework": phase_framework, "control": phase_control,
              "micro": phase_micro, "rllib": phase_rllib}[phase]
        result = fn(on_tpu) if phase != "micro" else fn()
        print(json.dumps({"result": result}))
        return
    skeleton = {
        "metric": "gpt2_train_tokens_per_s_via_JaxTrainer",
        "value": None,
        "unit": "tokens/s",
        "vs_baseline": None,
    }
    try:
        _main_measure(skeleton)
    except Exception as e:  # noqa: BLE001
        _emit({**skeleton, "status": "error",
               "error": f"{type(e).__name__}: {str(e)[:500]}"})


def _main_measure(skeleton: dict):
    # The shared-TPU tunnel's throughput drifts minute to minute (2.4x
    # spread measured on identical workloads), so control and framework
    # chunks alternate INSIDE the same worker process per run; the per-run
    # ratio is drift-free. Protocol: 5 runs; report the median run's
    # throughput, plus min/median/CI over the per-run ratios so a single
    # lucky run can't carry the headline (the north star is judged on the
    # spread, not one sample). Every run is retried once on failure; the
    # headline reports over however many runs survived (>= 2 required).
    platform, detail = _probe_backend()
    if platform is None:
        # The outage blindspot fix: BENCH_r04/r05 produced NO trajectory at
        # all because the tunnel was down for the whole window. The CPU
        # plane runs the identical interleaved framework-vs-raw protocol on
        # the host backend, so the round still lands a comparable
        # vs_baseline ratio (framework overhead), clearly tagged.
        _cpu_plane_fallback(skeleton, detail)
        return
    n_runs = int(os.environ.get("RTPU_BENCH_RUNS", "5"))
    runs, failures = [], []
    for i in range(n_runs):
        try:
            runs.append(_run_phase_retry("framework", attempts=2))
            _log(f"framework run {i + 1}/{n_runs}: "
                 f"ratio={runs[-1]['ours'] / runs[-1]['raw']:.4f}")
        except Exception as e:  # noqa: BLE001
            failures.append(f"run {i + 1}: {str(e)[:200]}")
    if len(runs) < min(2, n_runs):
        # Tunnel died mid-window: same fallback, with the partial failures
        # recorded so the round is diagnosable.
        _cpu_plane_fallback(
            skeleton, "; ".join(failures)[:800] or "all runs failed")
        return
    ratios = sorted(r["ours"] / r["raw"] for r in runs)
    median_ratio = ratios[len(ratios) // 2]
    mean = sum(ratios) / len(ratios)
    var = sum((x - mean) ** 2 for x in ratios) / max(1, len(ratios) - 1)
    # 95% CI half-width on the mean ratio (t_{0.975,n-1}; 2.776 for n=5)
    t975 = {2: 12.706, 3: 4.303, 4: 3.182, 5: 2.776}.get(len(ratios), 2.776)
    ci95 = t975 * (var ** 0.5) / (len(ratios) ** 0.5)
    best = sorted(runs, key=lambda r: r["ours"] / r["raw"])[len(runs) // 2]
    try:
        micro = _run_phase_retry("micro", attempts=2, timeout=1200)
    except Exception:
        micro = {}
    try:
        rl = _run_phase_retry("rllib", attempts=2, timeout=1800)
        rl_extra = {
            "rllib_learner_env_steps_per_s": round(rl["ours_steps_per_s"], 1),
            "rllib_vs_raw": round(
                rl["ours_steps_per_s"] / rl["raw_steps_per_s"], 4
            ),
        }
    except Exception:
        rl_extra = {}
    _emit({
        **rl_extra,
        "status": "ok" if len(runs) == n_runs else "degraded",
        "platform": platform,
        "plane": "tpu" if platform == "tpu" else "cpu",
        "metric": "gpt2_train_tokens_per_s_via_JaxTrainer",
        "value": round(best["ours"], 1),
        "unit": "tokens/s",
        "vs_baseline": round(median_ratio, 4),
        "vs_baseline_min": round(ratios[0], 4),
        "vs_baseline_mean": round(mean, 4),
        "vs_baseline_ci95": round(ci95, 4),
        "raw_jax_control_tokens_per_s": round(best["raw"], 1),
        "runs_completed": len(runs),
        "run_failures": failures,
        "all_runs": [
            {"ours": round(r["ours"], 1), "raw": round(r["raw"], 1),
             "ratio": round(r["ours"] / r["raw"], 4)} for r in runs
        ],
        "micro": micro,
    })


def _cpu_plane_fallback(skeleton: dict, tunnel_error: str):
    """The TPU tunnel is unreachable: run the same interleaved
    framework-vs-raw protocol on the host CPU backend (JAX_PLATFORMS=cpu
    forced into the phase subprocesses, small model, shortened run) plus the
    control-plane micro table, and emit ONE valid JSON line tagged
    ``"plane": "cpu"``. The absolute tokens/s is not comparable to a TPU
    round, but ``vs_baseline`` (framework/raw on the SAME backend) and the
    micro block are — so a tunnel outage no longer leaves an empty
    BENCH_rNN.json with no trajectory at all."""
    _log(f"TPU tunnel unreachable ({tunnel_error}); "
         "falling back to the CPU plane")
    env = {
        "JAX_PLATFORMS": "cpu",
        "RTPU_BENCH_STEPS": str(min(STEPS, int(
            os.environ.get("RTPU_BENCH_CPU_STEPS", "400")))),
        "RTPU_BENCH_WARMUP": str(min(WARMUP, 20)),
    }
    n_runs = int(os.environ.get("RTPU_BENCH_CPU_RUNS", "3"))
    runs, failures = [], []
    for i in range(n_runs):
        try:
            runs.append(_run_phase_retry(
                "framework", attempts=2, timeout=1800, extra_env=env))
            _log(f"cpu-plane run {i + 1}/{n_runs}: "
                 f"ratio={runs[-1]['ours'] / runs[-1]['raw']:.4f}")
        except Exception as e:  # noqa: BLE001
            failures.append(f"cpu run {i + 1}: {str(e)[:200]}")
    try:
        micro = _run_phase_retry("micro", attempts=2, timeout=1200)
    except Exception:
        micro = {}
    if not runs:
        _emit({**skeleton, "status": "tunnel_down", "plane": "none",
               "error": tunnel_error[:500],
               "cpu_fallback_failures": failures})
        return
    ratios = sorted(r["ours"] / r["raw"] for r in runs)
    median_ratio = ratios[len(ratios) // 2]
    best = sorted(runs, key=lambda r: r["ours"] / r["raw"])[len(runs) // 2]
    _emit({
        **skeleton,
        "status": "cpu_fallback",
        "plane": "cpu",
        "platform": "cpu",
        "tunnel_error": tunnel_error[:500],
        "value": round(best["ours"], 1),
        "vs_baseline": round(median_ratio, 4),
        "vs_baseline_min": round(ratios[0], 4),
        "raw_jax_control_tokens_per_s": round(best["raw"], 1),
        "runs_completed": len(runs),
        "run_failures": failures,
        "all_runs": [
            {"ours": round(r["ours"], 1), "raw": round(r["raw"], 1),
             "ratio": round(r["ours"] / r["raw"], 4)} for r in runs
        ],
        "micro": micro,
    })


if __name__ == "__main__":
    main()
