"""Scalability-envelope benchmark (scaled-down port of the reference's
release/benchmarks/README.md:9-31 suite: many tasks, many actors, many
placement groups, object broadcast, many args).

Run:  python envelope.py            # full sizes, writes ENVELOPE.json
      python envelope.py --quick    # reduced sizes (CI smoke)

All scenarios run against a real in-process multi-node cluster (one
machine, multiple raylets — the reference's cluster_utils pattern). The
reference numbers come from 64-node clusters; this box has ONE core, so
the interesting property is that every scenario COMPLETES and scales
linearly in n, not the absolute rates.
"""

from __future__ import annotations

import argparse
import json
import time


def run(quick: bool = False) -> dict:
    import numpy as np

    import ray_tpu
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.util.broadcast import broadcast_object
    from ray_tpu.util.placement_group import (
        placement_group,
        remove_placement_group,
    )

    n_tasks = 5_000 if quick else 50_000
    n_actors = 200 if quick else 1_000
    n_pgs = 50 if quick else 200
    bcast_mb = 64 if quick else 512
    n_args = 1_000 if quick else 5_000

    results = {}

    cluster = Cluster(
        initialize_head=True, head_node_args={"resources": {"CPU": 4}}
    )
    for i in range(3):
        cluster.add_node(resources={"CPU": 2})
    cluster.wait_for_nodes()
    ray_tpu.init(address=cluster.address)
    try:
        # ---- queued-task drain (reference: 1M+ queued tasks) ----
        @ray_tpu.remote
        def tiny():
            return 1

        ray_tpu.get(tiny.remote())
        t0 = time.perf_counter()
        refs = [tiny.remote() for _ in range(n_tasks)]
        t_submit = time.perf_counter() - t0
        ray_tpu.get(refs)
        t_total = time.perf_counter() - t0
        results["queued_tasks"] = {
            "n": n_tasks,
            "submit_per_s": round(n_tasks / t_submit, 1),
            "drain_per_s": round(n_tasks / t_total, 1),
        }
        print(f"queued_tasks: {results['queued_tasks']}")
        del refs

        # ---- many actors (reference: 40k+ across a cluster) ----
        @ray_tpu.remote(num_cpus=0.001)
        class A:
            def ping(self):
                return 1

        t0 = time.perf_counter()
        actors = [A.remote() for _ in range(n_actors)]
        ray_tpu.get([a.ping.remote() for a in actors])
        dt = time.perf_counter() - t0
        results["many_actors"] = {
            "n": n_actors, "create_and_ping_per_s": round(n_actors / dt, 1),
        }
        print(f"many_actors: {results['many_actors']}")
        for a in actors:
            ray_tpu.kill(a)
        del actors

        # ---- many placement groups (reference: 1k+ simultaneous) ----
        t0 = time.perf_counter()
        pgs = [
            placement_group([{"CPU": 0.001}]) for _ in range(n_pgs)
        ]
        for pg in pgs:
            pg.ready()
        dt = time.perf_counter() - t0
        results["many_pgs"] = {
            "n": n_pgs, "create_per_s": round(n_pgs / dt, 1),
        }
        t0 = time.perf_counter()
        for pg in pgs:
            remove_placement_group(pg)
        results["many_pgs"]["remove_per_s"] = round(
            n_pgs / (time.perf_counter() - t0), 1
        )
        print(f"many_pgs: {results['many_pgs']}")

        # ---- object broadcast (reference: 1 GiB to 50+ nodes) ----
        data = np.zeros(bcast_mb * 1024 * 1024 // 8, dtype=np.float64)
        ref = ray_tpu.put(data)
        t0 = time.perf_counter()
        stats = broadcast_object(ref)
        dt = time.perf_counter() - t0
        srcs = {s for s, _ in stats["transfers"]}
        results["broadcast"] = {
            "mb": bcast_mb,
            "nodes": len(stats["nodes"]),
            "seconds": round(dt, 2),
            "mb_per_s": round(bcast_mb * len(stats["transfers"]) / dt, 1),
            "rounds": stats["rounds"],
            "distinct_sources": len(srcs),
        }
        print(f"broadcast: {results['broadcast']}")
        assert len(srcs) >= 2, "broadcast must fan out from >=2 sources"
        del ref, data

        # ---- many args to one task (reference: 10k+ args) ----
        @ray_tpu.remote
        def consume(*args):
            return len(args)

        t0 = time.perf_counter()
        assert ray_tpu.get(consume.remote(*range(n_args))) == n_args
        results["many_args"] = {
            "n": n_args,
            "seconds": round(time.perf_counter() - t0, 3),
        }
        print(f"many_args: {results['many_args']}")
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()
    return results


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    args = ap.parse_args()
    results = run(quick=args.quick)
    if not args.quick:
        with open("ENVELOPE.json", "w") as f:
            json.dump(results, f, indent=2)
    print(json.dumps(results))


if __name__ == "__main__":
    main()
