"""Control-plane microbenchmarks, ported from the reference's
`python/ray/_private/ray_perf.py` (task/actor-call/put throughput) and
`release/microbenchmark` metric names, re-targeted at ray_tpu.

Run:  python microbench.py                      # full table, writes MICROBENCH.md
      python microbench.py --only put           # just metrics matching 'put'
                                                # (comma-separated substrings;
                                                # prints, no file write)
      python microbench.py --json [--only ...]  # machine-readable line for the
                                                # perf gate/CI: per-metric value
                                                # + rep min/median/max (schema
                                                # microbench.v1; no file write)
      python -c 'import microbench; print(microbench.run_quick())'

Numbers compare against BASELINE.md (reference release rig, m5.16xlarge):
  single_client_tasks_sync 1,046/s · async 8,051/s · 1:1 actor sync 2,050/s ·
  async 8,719/s · n:n async 28,466/s · put 20.8 GiB/s · pg 814/s.
"""

from __future__ import annotations

import json
import time

import numpy as np


_REPS = 1  # set by run_benches: 3 for the committed table, 1 for quick

# Per-metric rep spread of the last run_benches call, keyed by the metric
# name (timeit's `key`): {"min", "median", "max", "reps"}. The --json output
# and perf_gate consume this instead of scraping the printed table.
_REP_DETAIL = {}


def timeit(name, fn, multiplier=1, warmup=1, min_time=2.0, reps=None,
           key=None):
    """Run fn repeatedly for >= min_time, `reps` times back-to-back in the
    same process state; report the MEDIAN rep's ops/s. Mirrors
    ray_perf.timeit plus a pinned repetition protocol — single runs on this
    box swing ±25-30%, so regressions would otherwise hide in noise."""
    if reps is None:
        reps = _REPS
    for _ in range(warmup):
        fn()
    rates = []
    for _ in range(reps):
        count = 0
        t0 = time.perf_counter()
        while True:
            fn()
            count += 1
            dt = time.perf_counter() - t0
            if dt >= min_time:
                break
        rates.append(count * multiplier / dt)
    rates.sort()
    rate = rates[len(rates) // 2]
    _REP_DETAIL[key or name] = {
        "min": min(rates), "median": rate, "max": max(rates), "reps": reps}
    spread = (
        f"  (min {min(rates):,.0f} max {max(rates):,.0f})" if reps > 1 else ""
    )
    print(f"  {name}: {rate:,.1f} /s{spread}")
    return rate


def _scale_detail(key, factor):
    """Apply a post-hoc unit conversion (e.g. puts/s -> GiB/s) to a rep
    detail record so --json reports the same unit as the table."""
    d = _REP_DETAIL.get(key)
    if d:
        for f in ("min", "median", "max"):
            d[f] *= factor


def last_run_detail() -> dict:
    """{metric: {"value", "min", "median", "max", "reps"}} for the metrics
    the last run_benches() call measured."""
    return {
        k: {"value": round(d["median"], 3),
            "min": round(d["min"], 3),
            "median": round(d["median"], 3),
            "max": round(d["max"], 3),
            "reps": d["reps"]}
        for k, d in _REP_DETAIL.items()
    }


def _bench_serve_llm(quick: bool, reps: int) -> dict:
    """serve/llm CPU-plane load test: the continuous-batching engine vs the
    same model (gpt2-tiny adapter, identical prompts/sampling) behind
    static request batching — groups of max_batch admitted together and run
    to completion before the next group, i.e. ``@serve.batch`` semantics at
    the request level. Both sides share the engine, cache and adapter; only
    the admission policy differs, so the ratio isolates iteration-level
    scheduling. Full mode runs >= 1k concurrent streams (the ROADMAP item 1
    acceptance scale); per-stream completion latency feeds the p99 metric
    (lower is better — the perf gate knows, see
    _private/perf_gate._LOWER_IS_BETTER).
    """
    import time as _time

    from ray_tpu.serve.llm.adapters import build_adapter
    from ray_tpu.serve.llm.engine import LLMEngine, SamplingParams

    n_streams = 256 if quick else 1024
    max_batch = 32
    adapter = build_adapter(
        "gpt2-tiny",
        {"n_layer": 2, "n_embd": 64, "n_head": 4, "vocab_size": 512,
         "block_size": 256, "use_flash_attention": False},
        seed=0)
    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, 512, int(rng.integers(4, 17))).tolist()
               for _ in range(n_streams)]
    # varied lengths: the continuous win comes from refilling the slots
    # short requests free — uniform lengths would understate it
    max_toks = rng.integers(4, 33, n_streams)
    total_tokens = int(max_toks.sum())

    def make_engine():
        return LLMEngine(adapter, num_blocks=4096, block_size=16,
                         max_batch=max_batch, max_waiting=n_streams + 1)

    def run_continuous():
        eng = make_engine()
        t0 = _time.perf_counter()
        rids = [eng.submit(p, SamplingParams(max_tokens=int(m)))
                for p, m in zip(prompts, max_toks)]
        idx = {r: i for i, r in enumerate(rids)}
        done_at = np.zeros(n_streams)
        while eng.has_work():
            st = eng.step()
            now = _time.perf_counter() - t0
            for r in st.get("finished_ids", ()):
                done_at[idx[r]] = now
        dt = _time.perf_counter() - t0
        return total_tokens / dt, float(np.percentile(done_at, 99) * 1000)

    def run_static():
        # Faithful @serve.batch inference: fixed-shape groups of max_batch,
        # every decode step computes the FULL padded batch (finished rows
        # included — compiled static shapes can't shrink), and the group
        # holds its slots until the longest member finishes. Dense
        # contiguous KV, no paging overhead — generous to this side.
        t0 = _time.perf_counter()
        for i in range(0, n_streams, max_batch):
            gp = prompts[i:i + max_batch]
            gm = max_toks[i:i + max_batch]
            B = len(gp)
            lens = np.asarray([len(p) for p in gp], dtype=np.int32)
            steps = int(gm.max())
            tmax = int(lens.max()) + steps
            L, H, D = (adapter.n_layers, adapter.n_kv_heads,
                       adapter.head_dim)
            k_ctx = np.zeros((B, L, tmax, H, D), dtype=np.float32)
            v_ctx = np.zeros_like(k_ctx)
            toks = np.zeros(B, dtype=np.int64)
            for j, p in enumerate(gp):
                logits, k, v = adapter.prefill(np.asarray(p))
                k_ctx[j, :, :lens[j]] = k
                v_ctx[j, :, :lens[j]] = v
                toks[j] = int(np.argmax(logits))
            for _ in range(steps - 1):
                logits, k_new, v_new = adapter.decode(
                    toks, lens.astype(np.int64), k_ctx, v_ctx, lens)
                for j in range(B):
                    k_ctx[j, :, lens[j]] = k_new[j]
                    v_ctx[j, :, lens[j]] = v_new[j]
                lens = lens + 1
                toks = np.argmax(logits, axis=-1)
        return total_tokens / (_time.perf_counter() - t0)

    cont, p99, stat = [], [], []
    for _ in range(reps):
        c, p = run_continuous()
        cont.append(c)
        p99.append(p)
        stat.append(run_static())
    out = {}
    for key, vals in (("serve_llm_tokens_per_s", cont),
                      ("serve_llm_static_batch_tokens_per_s", stat),
                      ("serve_llm_stream_p99_ms", p99)):
        vals = sorted(vals)
        med = vals[len(vals) // 2]
        _REP_DETAIL[key] = {"min": vals[0], "median": med, "max": vals[-1],
                            "reps": reps}
        out[key] = med
        print(f"  {key}: {med:,.1f}")
    print(f"  serve_llm continuous/static ratio: "
          f"{out['serve_llm_tokens_per_s'] / out['serve_llm_static_batch_tokens_per_s']:.2f} "
          f"({n_streams} streams)")
    return out


def _record_rows(rows: dict, reps: int) -> dict:
    """Fold per-rep lists into the _REP_DETAIL median protocol."""
    out = {}
    for key, vals in rows.items():
        vals = sorted(vals)
        med = vals[len(vals) // 2]
        _REP_DETAIL[key] = {"min": vals[0], "median": med, "max": vals[-1],
                            "reps": reps}
        out[key] = med
        print(f"  {key}: {med:,.3f}" if med < 10 else f"  {key}: {med:,.1f}")
    return out


def _bench_serve_llm_prefix(quick: bool, reps: int) -> dict:
    """Prefix-caching A/B at a high prompt-overlap mix: every stream's
    prompt is one shared ~96-token system prefix plus a short unique tail
    (>= 0.9 overlap — the million-users-one-template serving shape), run
    once with the prefix cache on and once cold on the SAME gpt2-tiny
    adapter/engine config. The warm run prefills only each tail, so the
    ratio isolates exactly what copy-on-write block sharing buys;
    `serve_llm_prefix_kv_hit_rate` (0-1, higher is better) gates the
    matcher itself — a hashing/registration regression shows up here even
    if throughput noise hides it.
    """
    import time as _time

    from ray_tpu.serve.llm.adapters import build_adapter
    from ray_tpu.serve.llm.engine import LLMEngine, SamplingParams

    # quick keeps the FULL workload geometry and only drops reps: the
    # admitted-cold fraction — and with it the tightly-banded hit-rate row
    # and the per-step throughput — must stay comparable to the full-mode
    # ledger baseline, and this section costs seconds, not minutes
    n_streams, max_batch = 96, 16
    adapter = build_adapter(
        "gpt2-tiny",
        {"n_layer": 2, "n_embd": 64, "n_head": 4, "vocab_size": 512,
         "block_size": 256, "use_flash_attention": False},
        seed=0)
    rng = np.random.default_rng(1)
    shared = rng.integers(0, 512, 96).tolist()          # the system prompt
    prompts = [shared + rng.integers(0, 512, int(rng.integers(4, 9))).tolist()
               for _ in range(n_streams)]
    max_toks = rng.integers(8, 17, n_streams)
    total_tokens = int(max_toks.sum())

    def run(prefix_cache: bool):
        eng = LLMEngine(adapter, num_blocks=4096, block_size=16,
                        max_batch=max_batch, max_waiting=n_streams + 1,
                        prefix_cache=prefix_cache)
        t0 = _time.perf_counter()
        for p, m in zip(prompts, max_toks):
            eng.submit(p, SamplingParams(max_tokens=int(m)))
        eng.run_until_drained()
        return total_tokens / (_time.perf_counter() - t0), \
            eng.cache.hit_rate()

    run(prefix_cache=True)   # untimed warmup: page-fault/alloc state
    warm, cold, hits = [], [], []
    for _ in range(reps):
        w, h = run(prefix_cache=True)
        warm.append(w)
        hits.append(h)
        cold.append(run(prefix_cache=False)[0])
    out = _record_rows({"serve_llm_prefix_tokens_per_s": warm,
                        "serve_llm_prefix_cold_tokens_per_s": cold,
                        "serve_llm_prefix_kv_hit_rate": hits}, reps)
    print(f"  serve_llm prefix warm/cold ratio: "
          f"{out['serve_llm_prefix_tokens_per_s'] / out['serve_llm_prefix_cold_tokens_per_s']:.2f} "
          f"({n_streams} streams, ~0.93 overlap)")
    return out


def _bench_serve_llm_spec(quick: bool, reps: int) -> dict:
    """Speculative-decoding A/B on the deterministic fake adapter with a
    modeled 10:1 target:draft step cost (the Gemma-31B-vs-2B serving
    shape, `step_cost_s` sleeps once per fused call like one accelerator
    dispatch) and a draft that deterministically disagrees on ~1/7 of
    positions. The row gates the ENGINE's propose/verify/rollback
    machinery and its overhead — model quality is fixed by construction,
    so `serve_llm_spec_acceptance` (0-1, higher is better) is a tight
    regression tripwire for the acceptance logic itself. The real-model
    correctness bar (byte-equality vs non-speculative greedy on gpt2 and
    llama) lives in tests/test_llm_prefix_spec.py.
    """
    import time as _time

    from ray_tpu.serve.llm.adapters import FakeAdapter
    from ray_tpu.serve.llm.engine import LLMEngine, SamplingParams

    n_streams = 16 if quick else 32
    max_tokens = 64
    rng = np.random.default_rng(2)
    prompts = [rng.integers(0, 97, int(rng.integers(4, 9))).tolist()
               for _ in range(n_streams)]
    total_tokens = n_streams * max_tokens

    def run(spec: bool):
        eng = LLMEngine(
            FakeAdapter(vocab_size=97, step_cost_s=5e-3),
            num_blocks=2048, block_size=16, max_batch=8,
            max_waiting=n_streams + 1, prefix_cache=False,
            draft_adapter=(FakeAdapter(vocab_size=97, step_cost_s=5e-4,
                                       disagree_every=7) if spec else None),
            spec_k=4)
        t0 = _time.perf_counter()
        for p in prompts:
            eng.submit(p, SamplingParams(max_tokens=max_tokens))
        eng.run_until_drained()
        return total_tokens / (_time.perf_counter() - t0), \
            eng.spec_acceptance()

    run(spec=True)           # untimed warmup (same reason as prefix)
    fast, base, acc = [], [], []
    for _ in range(reps):
        f, a = run(spec=True)
        fast.append(f)
        acc.append(a)
        base.append(run(spec=False)[0])
    out = _record_rows({"serve_llm_spec_tokens_per_s": fast,
                        "serve_llm_spec_baseline_tokens_per_s": base,
                        "serve_llm_spec_acceptance": acc}, reps)
    print(f"  serve_llm spec/baseline ratio: "
          f"{out['serve_llm_spec_tokens_per_s'] / out['serve_llm_spec_baseline_tokens_per_s']:.2f} "
          f"(k=4, 10:1 cost model)")
    return out


def _bench_submit_storm(quick: bool, reps: int, min_time: float) -> dict:
    """many_drivers_submit_storm: K driver-like client actors (separate
    processes, each with its own CoreWorker) concurrently flood the node
    with tiny no-arg tasks — the many-drivers control-plane shape ROADMAP
    item 1 names. Measured twice on identical fresh clusters: once with
    the plasma-backed submit ring (the default path: specs memcpy into
    shared memory, the raylet drains batches, one doorbell RPC per
    empty→non-empty transition) and once with the ring disabled
    (``RTPU_submit_ring_slots=0``: one PushTask RPC write per batch from
    each submitter). The pair is the ring-vs-RPC A/B the perf gate tracks;
    on a 1-core box both sides timeshare the core, so the ratio
    understates the design by the core count (same caveat as the other
    multi-process rows). Quick mode keeps the FULL storm geometry and only
    drops reps/min_time (the serve_llm_prefix precedent) — a smaller storm
    measures a different contention shape and would make quick runs
    incomparable with the committed ledger rows."""
    import os

    import ray_tpu

    n_cli = 4
    per = 200
    out = {}
    for key, ring in (("many_drivers_submit_storm", True),
                      ("many_drivers_submit_storm_rpc", False)):
        saved = os.environ.get("RTPU_submit_ring_slots")
        if not ring:
            os.environ["RTPU_submit_ring_slots"] = "0"
        try:
            ray_tpu.init(num_cpus=8)
            try:
                _small, _a, _aa, Client = _define_remotes()
                clients = [Client.remote([]) for _ in range(n_cli)]
                ray_tpu.get([c.task_batch.remote(1) for c in clients])
                out[key] = timeit(
                    key,
                    lambda: ray_tpu.get(
                        [c.task_batch.remote(per) for c in clients]),
                    multiplier=n_cli * per, min_time=min_time, reps=reps,
                    key=key)
            finally:
                ray_tpu.shutdown()
        finally:
            if not ring:
                if saved is None:
                    os.environ.pop("RTPU_submit_ring_slots", None)
                else:
                    os.environ["RTPU_submit_ring_slots"] = saved
    if out.get("many_drivers_submit_storm_rpc"):
        print(f"  submit storm ring/rpc ratio: "
              f"{out['many_drivers_submit_storm'] / out['many_drivers_submit_storm_rpc']:.2f} "
              f"({n_cli} drivers x {per}/batch)")
    return out


def _define_remotes():
    import ray_tpu

    @ray_tpu.remote
    def small_task():
        return b"ok"

    @ray_tpu.remote
    class Actor:
        def small_value(self):
            return b"ok"

        def small_value_arg(self, x):
            return b"ok"

    @ray_tpu.remote
    class AsyncActor:
        async def small_value(self):
            return b"ok"

    @ray_tpu.remote
    class Client:
        """A driver-side load generator living in its own process
        (ray_perf's multi-client benches)."""

        def __init__(self, servers):
            self.servers = servers

        def actor_batch(self, n):
            import ray_tpu as rt

            rt.get([s.small_value.remote() for s in self.servers
                    for _ in range(n)])

        def task_batch(self, n):
            import ray_tpu as rt

            rt.get([small_task.remote() for _ in range(n)])

    return small_task, Actor, AsyncActor, Client


def run_benches(quick: bool = False, only: str = None) -> dict:
    """Run the bench table. `only` (comma-separated substring match on the
    metric name) restricts the run to matching metrics — each section boots
    only the actors it needs, so `--only put` answers "did the put path
    regress?" in seconds instead of a full bench round."""
    import ray_tpu
    from ray_tpu.util.placement_group import placement_group, remove_placement_group

    global _REPS
    small_task, Actor, AsyncActor, Client = _define_remotes()
    results = {}
    _REP_DETAIL.clear()
    min_time = 0.5 if quick else 2.0
    batch = 100 if quick else 1000
    _REPS = 1 if quick else 3
    parts = [p for p in (only or "").split(",") if p]

    def sel(metric: str) -> bool:
        return not parts or any(p in metric for p in parts)

    # serve/llm engine A/B runs in-process (no cluster): the CI row
    # `--only serve_llm` answers "did continuous batching regress?" without
    # paying a cluster boot
    if (sel("serve_llm_tokens_per_s")
            or sel("serve_llm_static_batch_tokens_per_s")
            or sel("serve_llm_stream_p99_ms")):
        results.update(_bench_serve_llm(quick, reps=_REPS))
    if (sel("serve_llm_prefix_tokens_per_s")
            or sel("serve_llm_prefix_cold_tokens_per_s")
            or sel("serve_llm_prefix_kv_hit_rate")):
        results.update(_bench_serve_llm_prefix(quick, reps=_REPS))
    if (sel("serve_llm_spec_tokens_per_s")
            or sel("serve_llm_spec_baseline_tokens_per_s")
            or sel("serve_llm_spec_acceptance")):
        results.update(_bench_serve_llm_spec(quick, reps=_REPS))
    # submit-storm rows boot their own clusters (the ring-vs-RPC A/B needs
    # a different env per side), so they run outside the shared init below
    if sel("many_drivers_submit_storm") or sel("many_drivers_submit_storm_rpc"):
        results.update(_bench_submit_storm(quick, reps=_REPS,
                                           min_time=min_time))
    cluster_metrics = (
        "single_client_tasks_sync", "single_client_tasks_async",
        "wait_1k_refs", "multi_client_tasks_async", "1_1_actor_calls_sync",
        "1_1_actor_calls_async", "1_1_async_actor_calls_async",
        "n_n_actor_calls_async", "single_client_put_calls",
        "single_client_put_gigabytes", "single_client_get_calls_plasma",
        "placement_group_create_removal",
    )
    if not any(sel(m) for m in cluster_metrics):
        return {k: round(v, 3 if abs(v) < 10 else 1)
            for k, v in results.items()}

    ray_tpu.init(num_cpus=8)
    try:
        # tasks
        if sel("single_client_tasks_sync") or sel("single_client_tasks_async"):
            ray_tpu.get(small_task.remote())  # prime worker + fn export
        if sel("single_client_tasks_sync"):
            results["single_client_tasks_sync"] = timeit(
                "single client tasks sync",
                lambda: ray_tpu.get(small_task.remote()),
                min_time=min_time, key="single_client_tasks_sync")
        if sel("single_client_tasks_async"):
            results["single_client_tasks_async"] = timeit(
                "single client tasks async",
                lambda: ray_tpu.get([small_task.remote() for _ in range(batch)]),
                multiplier=batch, min_time=min_time,
                key="single_client_tasks_async")

        # wait() at 1k-ref scale (reference: release/benchmarks single-node
        # ray.get/wait batch limits)
        if sel("wait_1k_refs"):
            wait_n = 200 if quick else 1000

            def wait_cycle():
                refs = [small_task.remote() for _ in range(wait_n)]
                ready, _ = ray_tpu.wait(refs, num_returns=wait_n, timeout=60)
                assert len(ready) == wait_n

            results["wait_1k_refs"] = timeit(
                "wait on 1k refs", wait_cycle, multiplier=wait_n,
                min_time=min_time, key="wait_1k_refs")

        # multi-client task submission: n driver-like client actors each
        # submitting async task batches (ray_perf multi_client_tasks_async)
        if sel("multi_client_tasks_async"):
            n_cli = 2 if quick else 4
            per_cli = 50 if quick else 200
            task_clients = [Client.remote([]) for _ in range(n_cli)]
            ray_tpu.get([c.task_batch.remote(1) for c in task_clients])
            results["multi_client_tasks_async"] = timeit(
                "multi client tasks async",
                lambda: ray_tpu.get(
                    [c.task_batch.remote(per_cli) for c in task_clients]
                ),
                multiplier=n_cli * per_cli, min_time=min_time,
                key="multi_client_tasks_async")
            for c in task_clients:
                ray_tpu.kill(c)

        # actor calls
        if sel("1_1_actor_calls_sync") or sel("1_1_actor_calls_async"):
            a = Actor.remote()
            ray_tpu.get(a.small_value.remote())
            if sel("1_1_actor_calls_sync"):
                results["1_1_actor_calls_sync"] = timeit(
                    "1:1 actor calls sync",
                    lambda: ray_tpu.get(a.small_value.remote()),
                    min_time=min_time, key="1_1_actor_calls_sync")
            if sel("1_1_actor_calls_async"):
                results["1_1_actor_calls_async"] = timeit(
                    "1:1 actor calls async",
                    lambda: ray_tpu.get(
                        [a.small_value.remote() for _ in range(batch)]),
                    multiplier=batch, min_time=min_time,
                    key="1_1_actor_calls_async")
            ray_tpu.kill(a)

        if sel("1_1_async_actor_calls_async"):
            aa = AsyncActor.remote()
            ray_tpu.get(aa.small_value.remote())
            results["1_1_async_actor_calls_async"] = timeit(
                "1:1 async-actor calls async",
                lambda: ray_tpu.get(
                    [aa.small_value.remote() for _ in range(batch)]),
                multiplier=batch, min_time=min_time,
                key="1_1_async_actor_calls_async")
            ray_tpu.kill(aa)

        # n:n actor calls — n clients (separate processes) × n servers
        if sel("n_n_actor_calls_async"):
            n = 2 if quick else 4
            per = 50 if quick else 200
            servers = [Actor.remote() for _ in range(n)]
            ray_tpu.get([s.small_value.remote() for s in servers])
            clients = [Client.remote(servers) for _ in range(n)]
            ray_tpu.get([c.actor_batch.remote(1) for c in clients])
            results["n_n_actor_calls_async"] = timeit(
                "n:n actor calls async",
                lambda: ray_tpu.get(
                    [c.actor_batch.remote(per) for c in clients]),
                multiplier=n * n * per, min_time=min_time,
                key="n_n_actor_calls_async")
            for actor in servers + clients:
                ray_tpu.kill(actor)

        # puts
        if sel("single_client_put_calls"):
            small = b"x" * 100
            results["single_client_put_calls"] = timeit(
                "single client put calls (100B)",
                lambda: ray_tpu.put(small),
                min_time=min_time, key="single_client_put_calls")
        if sel("single_client_put_gigabytes"):
            big = np.zeros(256 * 1024 * 1024 // 8, dtype=np.float64)  # 256 MiB
            gib = big.nbytes / (1 << 30)
            results["single_client_put_gigabytes"] = timeit(
                "single client put GiB/s",
                lambda: ray_tpu.put(big),
                multiplier=1, min_time=min_time,
                key="single_client_put_gigabytes") * gib
            _scale_detail("single_client_put_gigabytes", gib)

        # plasma get calls
        if sel("single_client_get_calls_plasma"):
            ref = ray_tpu.put(np.zeros(2 * 1024 * 1024 // 8))  # 2 MiB -> plasma
            results["single_client_get_calls_plasma"] = timeit(
                "single client plasma get calls",
                lambda: ray_tpu.get(ref),
                min_time=min_time, key="single_client_get_calls_plasma")

        if sel("placement_group_create_removal"):
            def pg_cycle():
                pg = placement_group([{"CPU": 1}] * 2)
                pg.ready()  # blocks until reserved (returns self, not a ref)
                remove_placement_group(pg)

            results["placement_group_create_removal"] = timeit(
                "pg create+remove", pg_cycle, min_time=min_time,
                key="placement_group_create_removal")
    finally:
        ray_tpu.shutdown()
    return {k: round(v, 3 if abs(v) < 10 else 1)
        for k, v in results.items()}


def run_quick() -> dict:
    """Reduced-duration pass used by bench.py's JSON line."""
    return run_benches(quick=True)


BASELINE = {
    "single_client_tasks_sync": 1046,
    "single_client_tasks_async": 8051,
    "multi_client_tasks_async": 24773,
    "1_1_actor_calls_sync": 2050,
    "1_1_actor_calls_async": 8719,
    "n_n_actor_calls_async": 28466,
    "single_client_put_gigabytes": 20.8,
    "placement_group_create_removal": 814,
}


def main():
    import argparse

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument(
        "--only", default=None, metavar="METRIC",
        help="run only metrics whose name contains one of these "
             "comma-separated substrings (e.g. 'put', "
             "'single_client,1_1_actor'); prints results as JSON without "
             "rewriting MICROBENCH.md")
    ap.add_argument(
        "--quick", action="store_true",
        help="reduced-duration single-rep pass (bench.py protocol)")
    ap.add_argument(
        "--json", dest="as_json", action="store_true",
        help="print one machine-readable JSON line (schema microbench.v1: "
             "per-metric value + rep min/median/max) instead of rewriting "
             "MICROBENCH.md — the perf gate and CI consume this")
    args = ap.parse_args()
    if args.as_json:
        import os

        results = run_benches(quick=args.quick, only=args.only)
        if not results:
            raise SystemExit(f"no metric matches --only {args.only!r}")
        print(json.dumps({
            "schema": "microbench.v1",
            "time": time.time(),
            "quick": args.quick,
            "reps": 1 if args.quick else 3,
            "host": {"cpus": os.cpu_count()},
            "metrics": last_run_detail(),
        }))
        return
    if args.only is not None:
        results = run_benches(quick=args.quick, only=args.only)
        if not results:
            raise SystemExit(f"no metric matches --only {args.only!r}")
        print(json.dumps(results))
        return
    results = run_benches(quick=args.quick)
    lines = [
        "# Microbenchmarks (ray_perf port)",
        "",
        "Run on this machine's CPU control plane via `python microbench.py`.",
        "Protocol: each metric runs 3 back-to-back timing reps (>=2 s each)",
        "in the same process state; the table records the MEDIAN rep",
        "(single runs swing ±25-30% on this box).",
        "",
        "Context for the ratios: this box has ONE CPU core (`nproc` = 1);",
        "the reference numbers come from a 64-vCPU m5.16xlarge. The",
        "multi-process benches (multi_client, n:n) cannot exceed the",
        "single-stream aggregate here — every client/server process shares",
        "the core — so their ratios understate the design by the core",
        "count. Single-stream metrics are the honest comparison. The put",
        "path is single-copy (value -> mapped shm, serialization.write_blob);",
        "cold stores pay page faults (~2.1 GiB/s first-touch on this box),",
        "steady-state puts recycle already-faulted store pages and run at",
        "memcpy speed.",
        "",
        "See PROFILE.md for where the submit/push hot-path time goes and",
        "what rounds 3-6 changed.",
        "",
        "## Noise bands (what counts as a regression)",
        "",
        "The perf gate (`ray-tpu perf check`, `_private/perf_gate.py`,",
        "`.github/workflows/perf.yml`) turns the spread above into explicit",
        "per-metric thresholds. A comparison's band is chosen by the LESS",
        "reliable side (min reps of baseline and current), then scaled by",
        "`RTPU_perf_band_scale`; a drop beyond the band fails the gate, a rise",
        "beyond it is flagged `improved`.",
        "",
        "| metric | 1-rep band | 3-rep-median band | extra variance source |",
        "|---|---|---|---|",
        "| (default) | ±40% | ±25% | single runs swing ±25-30% on this box |",
        "| multi_client_tasks_async | ±50% | ±35% | processes timeshare one core |",
        "| n_n_actor_calls_async | ±50% | ±35% | processes timeshare one core |",
        "| many_drivers_submit_storm(_rpc) | ±50% | ±35% | multi-process + a fresh cluster boot per side (cold worker pools) |",
        "| single_client_put_gigabytes | ±45% | ±30% | store page-fault state (cold ~2.1 vs steady 6.7 GiB/s) |",
        "| wait_1k_refs | ±45% | ±30% | timer batching across the submit window |",
        "| serve_llm_* | ±45% | ±30% | multi-second numpy run: allocator/GC state; p99 row is LOWER-is-better (gate inverts) |",
        "| serve_llm_prefix_kv_hit_rate | ±15% | ±10% | 0-1 ratio over a deterministic prompt mix (higher is better) |",
        "| serve_llm_spec_acceptance | ±15% | ±10% | 0-1 ratio, deterministic draft disagreement (higher is better) |",
        "",
        "The committed trajectory lives in `PERF_HISTORY.jsonl` (append with",
        "`ray-tpu perf check --update` when refreshing this table);",
        "`microbench.py --json` emits the machine-readable per-metric",
        "value + rep min/median/max the gate consumes.",
        "",
        "| metric | ray_tpu | reference | ratio |",
        "|---|---|---|---|",
    ]
    for k, v in results.items():
        base = BASELINE.get(k)
        ratio = f"{v / base:.2f}" if base else "—"
        lines.append(f"| {k} | {v:,} | {base or '—'} | {ratio} |")
    with open("MICROBENCH.md", "w") as f:
        f.write("\n".join(lines) + "\n")
    print(json.dumps(results))


if __name__ == "__main__":
    main()
