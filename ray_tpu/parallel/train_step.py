"""Sharded training step builder: one jit, every parallelism axis.

This is the TPU-native replacement for the reference's torch DDP/FSDP wrapper
stack (reference: python/ray/train/torch/train_loop_utils.py:453 prepare_model
→ DDP, :184 FSDP): instead of wrapping modules and calling NCCL imperatively,
we build a `jax.sharding.Mesh`, assign PartitionSpecs to params/optimizer
state/batch, and compile ONE train step under jit — XLA inserts the ICI
collectives (grad psums over dp, param all-gathers over fsdp, activation
collectives over tp, ring ppermutes over sp) from the shardings.

Axes (any subset may be trivial/size-1, one rule set serves all):
  dp    batch;                 grads psum over it (DDP-equivalent)
  fsdp  param/optimizer shard; ZeRO-3-equivalent, also carries batch
  tp    Megatron tensor parallel over hidden/head dims
  sp    sequence/context parallel; attention runs a ppermute ring
"""

from __future__ import annotations

import functools
import time
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, PartitionSpec as P

from ray_tpu.models.gpt2 import (
    GPT2,
    GPT2Config,
    GPT2_SHARDING_RULES,
    loss_fn,
)
from ray_tpu.parallel.mesh import (
    ShardingRules,
    batch_sharding,
    filtered_tree_shardings,
)


def _jit_cache_size(fn) -> int:
    """Compiled-executable count of a jitted callable; -1 when the private
    probe is unavailable (telemetry then falls back to first-call-only
    compile detection)."""
    try:
        return fn._cache_size()
    except Exception:
        return -1


def _batch_counts(batch) -> Tuple[Optional[int], Optional[int]]:
    """(tokens, examples) in a batch dict for telemetry: the idx array's
    element count is token count, its second-to-last dim is batch size
    (works for (B, T) steps and (num_steps, B, T) scan stacks)."""
    try:
        idx = batch.get("idx")
        if idx is None or not hasattr(idx, "shape"):
            return None, None
        tokens = 1
        for d in idx.shape:
            tokens *= int(d)
        examples = tokens // int(idx.shape[-1]) if idx.shape[-1] else None
        return tokens, examples
    except Exception:
        return None, None


def _ring_attn_for_mesh(mesh: Mesh, seq_axis: str = "sp"):
    """Attention callable for GPT2Config.attn_fn: ring attention over the
    sequence axis via shard_map, local flash attention per chunk-pair."""
    from jax import shard_map

    from ray_tpu.ops.ring_attention import ring_causal_attention

    data = tuple(
        a for a in ("dp", "fsdp") if a in mesh.axis_names and mesh.shape[a] > 1
    )
    tp = "tp" if "tp" in mesh.axis_names and mesh.shape["tp"] > 1 else None
    spec = P(data if data else None, seq_axis, tp, None)  # (B, T, H, D)

    fn = shard_map(
        functools.partial(ring_causal_attention, axis_name=seq_axis),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return fn


def model_for_mesh(cfg, mesh: Optional[Mesh]):
    """Instantiate the model wired for this mesh: ring attention iff sp > 1;
    config type picks the family (GPT2 / GPT2MoE with an ep axis / Llama)."""
    import dataclasses

    if (
        mesh is not None
        and "sp" in mesh.axis_names
        and mesh.shape["sp"] > 1
    ):
        cfg = dataclasses.replace(cfg, attn_fn=_ring_attn_for_mesh(mesh))
    from ray_tpu.models.gpt2_moe import GPT2MoE, GPT2MoEConfig
    from ray_tpu.models.llama import Llama, LlamaConfig

    if isinstance(cfg, GPT2MoEConfig):
        return GPT2MoE(cfg)
    if isinstance(cfg, LlamaConfig):
        return Llama(cfg)
    return GPT2(cfg)


# Backwards-compatible alias (pre-Llama name).
gpt2_model_for_mesh = model_for_mesh


def default_rules_for(cfg) -> ShardingRules:
    from ray_tpu.models.gpt2_moe import GPT2_MOE_SHARDING_RULES, GPT2MoEConfig
    from ray_tpu.models.llama import LLAMA_SHARDING_RULES, LlamaConfig

    if isinstance(cfg, GPT2MoEConfig):
        return GPT2_MOE_SHARDING_RULES
    if isinstance(cfg, LlamaConfig):
        return LLAMA_SHARDING_RULES
    return GPT2_SHARDING_RULES


class TrainStep:
    """Compiled (init, step) pair with sharded state.

    Usage:
        ts = TrainStep(GPT2Config.tiny(), mesh)
        state = ts.init(jax.random.PRNGKey(0))
        state, metrics = ts.step(state, batch)   # batch: dict idx/targets (B, T)
    """

    def __init__(
        self,
        model_cfg: GPT2Config,
        mesh: Mesh,
        *,
        learning_rate: float = 3e-4,
        weight_decay: float = 0.1,
        beta2: float = 0.95,
        grad_clip: float = 1.0,
        rules: Optional[ShardingRules] = None,
        flops_per_step: Optional[float] = None,
        telemetry: bool = True,
    ):
        from ray_tpu.models.gpt2_moe import GPT2MoEConfig

        self._is_moe = isinstance(model_cfg, GPT2MoEConfig)
        if rules is None:
            rules = default_rules_for(model_cfg)
        self.model_cfg = model_cfg
        self.mesh = mesh
        self.model = model_for_mesh(model_cfg, mesh)
        self.optimizer = optax.chain(
            optax.clip_by_global_norm(grad_clip),
            optax.adamw(
                learning_rate, b2=beta2, weight_decay=weight_decay,
                mask=lambda params: jax.tree.map(lambda p: p.ndim > 1, params),
            ),
        )
        self.batch_sharding = batch_sharding(mesh)

        def init_fn(rng):
            # Dummy batch for shape inference must still satisfy the mesh:
            # B divisible by dp*fsdp, T by sp (ring attention shard_maps
            # over them even during init).
            data = 1
            for a in ("dp", "fsdp"):
                if a in mesh.shape:
                    data *= mesh.shape[a]
            sp = mesh.shape.get("sp", 1)
            T = min(8 * sp, model_cfg.block_size)
            idx = jnp.zeros((max(2, data), T), dtype=jnp.int32)
            params = self.model.init(rng, idx)["params"]
            return {
                "params": params,
                "opt_state": self.optimizer.init(params),
                "step": jnp.zeros((), jnp.int32),
            }

        state_shape = jax.eval_shape(init_fn, jax.random.PRNGKey(0))
        self.state_specs, self.state_shardings = filtered_tree_shardings(
            rules, state_shape, mesh
        )
        self._init = jax.jit(init_fn, out_shardings=self.state_shardings)

        def step_fn(state, batch):
            def loss_of(params):
                if self._is_moe:
                    logits, lstate = self.model.apply(
                        {"params": params}, batch["idx"], mutable=["losses"]
                    )
                    aux = sum(jax.tree.leaves(lstate.get("losses", {})))
                    return loss_fn(logits, batch["targets"]) + aux
                logits = self.model.apply({"params": params}, batch["idx"])
                return loss_fn(logits, batch["targets"])

            loss, grads = jax.value_and_grad(loss_of)(state["params"])
            updates, opt_state = self.optimizer.update(
                grads, state["opt_state"], state["params"]
            )
            params = optax.apply_updates(state["params"], updates)
            new_state = {
                "params": params,
                "opt_state": opt_state,
                "step": state["step"] + 1,
            }
            gnorm = optax.global_norm(grads)
            return new_state, {"loss": loss, "grad_norm": gnorm}

        self._step = jax.jit(
            step_fn,
            out_shardings=(self.state_shardings, None),
            donate_argnums=(0,),
        )
        self._step_fn = step_fn
        self._traced = False
        self._multi: Dict[int, Any] = {}
        self._tiled_cache = None
        # Step-level telemetry (train/_telemetry.py): wall time per step,
        # compile time (jit cache misses are known exactly here), MFU from
        # a per-model FLOPs estimate (flops_per_step overrides), goodput,
        # HBM. Registered process-globally so session.report auto-attaches
        # the summary. RTPU_TRAIN_TELEMETRY=0 disables.
        self.telemetry = None
        if telemetry:
            from ray_tpu.train import _telemetry

            self.telemetry = _telemetry.StepRecorder(
                flops_per_step=flops_per_step,
                flops_per_token=(
                    None if flops_per_step is not None
                    else _telemetry.estimate_flops_per_token(model_cfg)
                ),
                n_devices=mesh.devices.size,
            )
            _telemetry.set_current_recorder(self.telemetry)

    def init(self, rng) -> Dict[str, Any]:
        with self.mesh:
            return self._init(rng)

    def shard_batch(self, batch: Dict[str, Any]) -> Dict[str, Any]:
        return jax.device_put(batch, self.batch_sharding)

    def step(self, state, batch) -> Tuple[Dict[str, Any], Dict[str, Any]]:
        # No mesh context on the hot path: in/out shardings are explicit
        # NamedShardings, so dispatch doesn't need the ambient mesh — the
        # context manager costs real per-step Python time at small step
        # sizes. First call traces under the mesh (shard_map ring attention
        # resolves its axis names there), then cached dispatch skips it.
        rec = self.telemetry
        if rec is None:
            # Telemetry off: the flight recorder still gets a breadcrumb
            # per dispatch (one deque append) — "did step N ever start" is
            # exactly the question a hung mesh gets asked, and the recorder
            # is the layer that answers it post-mortem.
            from ray_tpu._private import flight_recorder as _fr

            if self._traced:
                _fr.record("train.step", b"", "dispatch")
                return self._step(state, batch)
            _fr.record("train.step", b"", "trace+compile")
            with self.mesh:
                out = self._step(state, batch)
            self._traced = True
            return out
        # Device-trace hook (train/_telemetry.DeviceTraceController): inert
        # two-attribute check unless a jax.profiler window was armed.
        rec.device_trace.on_step_begin()
        t0 = time.perf_counter()
        was_traced = self._traced
        cache_before = _jit_cache_size(self._step)
        if self._traced:
            out = self._step(state, batch)
        else:
            with self.mesh:
                out = self._step(state, batch)
            self._traced = True
        # Compile detection by actual jit cache miss (not just first-call):
        # the cache key includes the ambient mesh context, so the first
        # call after the traced flag flips recompiles too — both must be
        # booked as compile time, not step time.
        cache_after = _jit_cache_size(self._step)
        compiled = (
            cache_after != cache_before
            if cache_before >= 0 and cache_after >= 0
            else not was_traced
        )
        if compiled:
            # Contain the whole compile + first execution in THIS record:
            # without the sync, the async backlog drains inside the next
            # call's dispatch and poisons its step-time measurement.
            jax.block_until_ready(out)
        tokens, examples = _batch_counts(batch)
        rec.record_step(
            time.perf_counter() - t0,
            tokens=None if compiled else tokens,
            examples=None if compiled else examples,
            compile_step=compiled,
        )
        rec.device_trace.on_step_end(out)
        return out

    def multi_step(self, state, batches, num_steps: int):
        """Run `num_steps` optimizer steps in ONE dispatch via lax.scan
        (XLA-idiomatic: python per-call dispatch costs ~1-3ms, a compiled
        scan body costs nothing — at short step times the scan is the
        difference between dispatch-bound and MXU-bound).

        `batches`: dict of arrays with a leading (num_steps, ...) axis
        (stacked micro-batches), or a single batch dict to reuse each step.
        Returns (state, metrics) with metrics stacked over steps."""
        key = num_steps
        fn = self._multi.get(key)
        first = fn is None
        if first:
            def body(state, batch):
                new_state, m = self._step_fn(state, batch)
                return new_state, m

            def run(state, batches):
                return jax.lax.scan(body, state, batches, length=num_steps)

            fn = jax.jit(
                run,
                out_shardings=(self.state_shardings, None),
                donate_argnums=(0,),
            )
            self._multi[key] = fn
        # tile-or-not is decided per call from the actual layout (a cached
        # flag goes stale when batch layout or num_steps changes): a batch
        # is already stacked iff it carries the extra leading num_steps axis
        sample = next(iter(batches.values()))
        if sample.ndim < 3 or sample.shape[0] != num_steps:
            # reuse-one-batch convenience: tile once and cache — a per-call
            # broadcast adds a dispatch to every chunk. The cache holds
            # STRONG refs to the source arrays, so an id()-reuse after GC
            # can never produce a false hit.
            src = (num_steps,) + tuple(batches.values())
            cached = self._tiled_cache
            hit = (
                cached is not None
                and len(cached[0]) == len(src)
                and all(a is b for a, b in zip(cached[0], src))
            )
            if not hit:
                tiled = jax.tree.map(
                    lambda x: jnp.broadcast_to(
                        x[None], (num_steps,) + x.shape),
                    batches,
                )
                self._tiled_cache = (src, tiled)
            batches = self._tiled_cache[1]
        rec = self.telemetry
        if rec is None:
            from ray_tpu._private import flight_recorder as _fr

            _fr.record("train.step", b"", f"multi_step x{num_steps}")
        if rec is not None:
            rec.device_trace.on_step_begin()
        t0 = time.perf_counter() if rec is not None else 0.0
        cache_before = _jit_cache_size(fn) if rec is not None else -1
        if not first:
            # cached dispatch needs no ambient mesh (explicit shardings);
            # the context manager costs ~1ms/call
            out = fn(state, batches)
        else:
            with self.mesh:
                out = fn(state, batches)
        if rec is not None:
            # one recording per dispatch: the scan body runs num_steps
            # optimizer steps inside XLA, so per-call overhead is amortized
            cache_after = _jit_cache_size(fn)
            compiled = (
                cache_after != cache_before
                if cache_before >= 0 and cache_after >= 0
                else first
            )
            if compiled:
                # drain the compile + first-chunk backlog into this record
                # (see step()); throughput/tokens only count cached calls
                jax.block_until_ready(out)
                tokens = examples = None
            else:
                tokens, examples = _batch_counts(batches)
            rec.record_step(
                time.perf_counter() - t0, steps=num_steps,
                tokens=tokens, examples=examples, compile_step=compiled,
            )
            rec.device_trace.on_step_end(out)
        return out
