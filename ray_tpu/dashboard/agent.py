"""Per-node dashboard agent: host stats, metrics, profiling and log serving
off the raylet's event loop.

Counterpart of the reference's per-node agent process
(reference: python/ray/dashboard/agent.py:25 DashboardAgent,
dashboard/modules/reporter/reporter_agent.py:314 ReporterAgent — the
reference's raylet launches agent.py beside itself and the dashboard head
fans node-scoped queries out to the agents instead of doing the work
centrally). Here:

- The raylet spawns `python -m ray_tpu.dashboard.agent` at startup, watches
  the child from its reaper loop, reports a death to the GCS worker-failure
  log and restarts it (capped).
- The agent registers `{host, port, pid}` under the GCS KV namespace
  ``agents`` keyed by node-id hex; the dashboard head resolves agents from
  there to serve /api/node_stats and route /api/profile.
- Handlers: NodeStats (psutil host + per-worker RSS), Metrics (Prometheus
  text), ProfileWorker (proxied to the target worker's in-process stack
  sampler, like the reference's reporter-agent -> worker routing), ListLogs
  and ReadLog (this node's session logs).
"""

from __future__ import annotations

import argparse
import asyncio
import json
import logging
import os
import sys
import time

logger = logging.getLogger("ray_tpu.agent")


class DashboardAgent:
    def __init__(self, gcs_address: str, node_id_hex: str, raylet_port: int,
                 session_dir: str, host: str = "127.0.0.1"):
        from ray_tpu._private.gcs.client import GcsAioClient
        from ray_tpu._private.rpc import ClientPool, RpcServer

        self.node_id_hex = node_id_hex
        self.host = host
        self.raylet_port = raylet_port
        self.session_dir = session_dir
        gcs_host, gcs_port = gcs_address.rsplit(":", 1)
        self.gcs = GcsAioClient(gcs_host, int(gcs_port))
        self.pool = ClientPool()
        self.server = RpcServer(host)
        self.port = 0
        self.started = time.time()

    async def start(self, port: int = 0) -> int:
        self.server.register_all(self)
        self.port = await self.server.start(port)
        await self.gcs.kv_put(
            b"agents", self.node_id_hex.encode(),
            json.dumps({
                "host": self.host, "port": self.port, "pid": os.getpid(),
            }).encode(),
        )
        logger.info("agent for node %s on %s:%s",
                    self.node_id_hex[:12], self.host, self.port)
        return self.port

    # ------------------------------------------------------------- handlers

    async def _raylet(self):
        return await self.pool.get(self.host, self.raylet_port)

    async def handle_Ping(self, req):
        return {"ok": True, "node_id": self.node_id_hex,
                "uptime_s": time.time() - self.started}

    async def handle_NodeStats(self, req):
        """Host stats + per-worker RSS (reference: reporter_agent.py:314
        _get_all_stats — cpu/mem/disk/net + worker processes)."""
        import psutil

        stats = {
            "node_id": self.node_id_hex,
            "cpu_percent": psutil.cpu_percent(interval=None),
            "cpu_count": psutil.cpu_count(),
            "load_avg": list(os.getloadavg()),
        }
        vm = psutil.virtual_memory()
        stats["mem"] = {"total": vm.total, "used": vm.used,
                        "available": vm.available, "percent": vm.percent}
        try:
            du = psutil.disk_usage(self.session_dir or "/")
            stats["disk"] = {"total": du.total, "used": du.used,
                             "percent": du.percent}
        except Exception:
            stats["disk"] = {}
        try:
            nio = psutil.net_io_counters()
            stats["net"] = {"sent": nio.bytes_sent, "recv": nio.bytes_recv}
        except Exception:
            stats["net"] = {}
        workers = []
        try:
            raylet = await self._raylet()
            info = await raylet.call("GetLocalWorkerInfo", {}, timeout=5)
            procs = getattr(self, "_procs", None)
            if procs is None:
                procs = self._procs = {}
            for w in info.get("workers", []):
                rec = {"pid": w["pid"], "worker_id": w["worker_id"],
                       "leased": w.get("leased"), "alive": w.get("alive")}
                try:
                    # Cache Process objects across samples: cpu_percent on a
                    # fresh instance always reads 0.0 (reference:
                    # reporter_agent.py keeps its psutil handles).
                    p = procs.get(w["pid"])
                    if p is None:
                        p = procs[w["pid"]] = psutil.Process(w["pid"])
                        p.cpu_percent(interval=None)  # prime
                    rec["rss"] = p.memory_info().rss
                    rec["cpu_percent"] = p.cpu_percent(interval=None)
                except Exception:
                    procs.pop(w["pid"], None)
                workers.append(rec)
            live = {w["pid"] for w in info.get("workers", [])}
            for pid in list(procs):
                if pid not in live:
                    del procs[pid]
        except Exception as e:
            stats["workers_error"] = str(e)
        stats["workers"] = workers
        return stats

    async def handle_Metrics(self, req):
        """Prometheus text of this node's host metrics (the raylet's
        /metrics keeps the scheduler/object-plane series; the agent owns
        the host-level series, like the reference's reporter agent)."""
        from ray_tpu._private.metrics import render_prometheus

        stats = await self.handle_NodeStats({})
        node = self.node_id_hex[:12]
        samples = [
            ("ray_tpu_agent_cpu_percent", {"node": node},
             stats["cpu_percent"]),
            ("ray_tpu_agent_mem_used_bytes", {"node": node},
             stats["mem"]["used"]),
            ("ray_tpu_agent_mem_total_bytes", {"node": node},
             stats["mem"]["total"]),
            ("ray_tpu_agent_uptime_seconds", {"node": node},
             time.time() - self.started),
        ]
        if stats.get("disk"):
            samples.append(("ray_tpu_agent_disk_used_bytes", {"node": node},
                            stats["disk"]["used"]))
        for w in stats["workers"]:
            if "rss" in w:
                samples.append(
                    ("ray_tpu_agent_worker_rss_bytes",
                     {"node": node, "pid": str(w["pid"])}, w["rss"]))
        return {"text": render_prometheus(samples)}

    async def handle_ProfileWorker(self, req):
        """Stack-sample one of this node's workers (addressed by pid or
        worker_id): resolve via the raylet's worker table, then call the
        worker's in-process Profile handler."""
        raylet = await self._raylet()
        info = await raylet.call("GetLocalWorkerInfo", {}, timeout=5)
        target = None
        for w in info.get("workers", []):
            if ((req.get("pid") and w["pid"] == req["pid"])
                    or (req.get("worker_id")
                        and w["worker_id"] == req["worker_id"])):
                target = w
                break
        if target is None:
            return {"error": "no such worker on this node"}
        # The raylet proxies because it knows worker RPC addresses; reuse it.
        return await raylet.call("ProfileWorker", dict(req), timeout=60)

    async def handle_ListLogs(self, req):
        base = self.session_dir
        if not base or not os.path.isdir(base):
            return {"files": []}
        files = []
        for root, _dirs, names in os.walk(base):
            for name in names:
                if name.endswith((".log", ".out", ".err")):
                    p = os.path.join(root, name)
                    try:
                        files.append({
                            "path": os.path.relpath(p, base),
                            "size": os.path.getsize(p),
                        })
                    except OSError:
                        pass
        return {"files": files}

    async def handle_ReadLog(self, req):
        base = self.session_dir
        rel = req.get("path", "")
        path = os.path.normpath(os.path.join(base, rel))
        if not path.startswith(os.path.normpath(base) + os.sep):
            return {"error": "path escapes session dir"}
        try:
            size = os.path.getsize(path)
            tail = int(req.get("tail_bytes", 64 * 1024))
            with open(path, "rb") as f:
                if size > tail:
                    f.seek(size - tail)
                data = f.read(tail)
            return {"data": data, "size": size}
        except OSError as e:
            return {"error": str(e)}


def main(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--gcs-address", required=True)
    parser.add_argument("--node-id", required=True)
    parser.add_argument("--raylet-port", type=int, required=True)
    parser.add_argument("--session-dir", default="")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--raylet-pid", type=int, default=0)
    args = parser.parse_args(argv)
    logging.basicConfig(level=logging.INFO)

    async def run():
        agent = DashboardAgent(args.gcs_address, args.node_id,
                               args.raylet_port, args.session_dir, args.host)
        await agent.start(0)
        # Fate-share with the spawning raylet: when it dies (even SIGKILL,
        # where its async shutdown never runs) this agent must exit instead
        # of lingering as an orphan whose GCS client burns CPU reconnect-
        # looping (reference: the agent<->raylet fate-sharing contract in
        # dashboard/agent.py). The raylet's pid comes via argv — a ppid
        # snapshot would race (raylet killed before we sample -> we'd
        # capture init's pid and never notice).
        raylet_pid = args.raylet_pid or os.getppid()
        while True:
            await asyncio.sleep(2.0)
            try:
                os.kill(raylet_pid, 0)
            except ProcessLookupError:
                logger.info("raylet (pid %s) gone; agent exiting", raylet_pid)
                return
            except PermissionError:
                pass  # alive, different uid

    asyncio.run(run())


if __name__ == "__main__":
    main()
