"""Dashboard head: JSON/REST API over cluster state + job submission.

Counterpart of the reference's dashboard head server
(reference: python/ray/dashboard/head.py:79 — aiohttp app aggregating
state + the job module's REST endpoints
dashboard/modules/job/job_head.py). Dependency-free asyncio HTTP/1.1 here;
the React client is out of scope, but a plain HTML summary is served at /
so the endpoint is human-checkable.

Routes:
  GET  /api/cluster                cluster resource summary
  GET  /api/nodes|actors|tasks|objects|workers|placement_groups|jobs
  GET  /api/jobs/                  submitted jobs (job_submission API)
  POST /api/jobs/                  submit {entrypoint, runtime_env?, ...}
  GET  /api/jobs/<id>              job info
  GET  /api/jobs/<id>/logs         {"logs": "..."}
  POST /api/jobs/<id>/stop         {"stopped": bool}
  GET  /api/version
"""

from __future__ import annotations

import asyncio
import json
import logging
from typing import Optional, Tuple

logger = logging.getLogger("ray_tpu.dashboard")


class DashboardHead:
    def __init__(self, gcs_address: str, host: str = "127.0.0.1"):
        # Loopback by default: /api/jobs executes arbitrary entrypoints, so
        # exposing it beyond the host must be an explicit operator choice
        # (reference: the dashboard binds localhost unless configured).
        self.gcs_address = gcs_address
        self.host = host
        self._server: Optional[asyncio.AbstractServer] = None
        self.port = 0
        self._gcs = None
        self._mgr = None

    # --------------------------------------------------------- data access

    def _state(self):
        from ray_tpu.util import state

        return state

    def _gcs_client(self):
        if self._gcs is None:
            from ray_tpu._private.gcs.client import GcsClient

            self._gcs = GcsClient.from_address(self.gcs_address)
        return self._gcs

    def _job_manager(self):
        if self._mgr is None:
            from ray_tpu.job_submission import JobManager

            self._mgr = JobManager(self._gcs_client())
        return self._mgr

    def _collect(self, path: str, method: str, body: Optional[dict], query=None):
        """Blocking handler (run in executor): returns (status, payload)."""
        state = self._state()
        addr = self.gcs_address
        if path == "/api/cluster":
            return 200, {
                "cluster": self._gcs_client().get_cluster_resources(),
                "nodes": len(state.list_nodes(addr)),
            }
        if path == "/api/nodes":
            return 200, {"nodes": state.list_nodes(addr)}
        if path == "/api/actors":
            return 200, {"actors": state.list_actors(addr)}
        if path == "/api/tasks":
            return 200, {"tasks": state.list_tasks(addr)}
        if path == "/api/objects":
            return 200, {"objects": state.list_objects(addr)}
        if path == "/api/workers":
            return 200, {"workers": state.list_workers(addr)}
        if path == "/api/placement_groups":
            return 200, {"placement_groups": state.list_placement_groups(addr)}
        if path == "/api/version":
            from ray_tpu._version import version

            return 200, {"version": version}
        if path.startswith("/api/jobs"):
            return self._jobs_api(path, method, body, query or {})
        if path == "/" or path == "/index.html":
            return 200, None  # HTML handled by caller
        return 404, {"error": f"no route {path}"}

    def _jobs_api(self, path: str, method: str, body: Optional[dict], query):
        mgr = self._job_manager()
        parts = [p for p in path.split("/") if p]  # ["api","jobs",...]
        if len(parts) == 2:
            if method == "POST":
                body = body or {}
                if not body.get("entrypoint"):
                    return 400, {"error": "entrypoint is required"}
                sid = mgr.submit_job(
                    entrypoint=body["entrypoint"],
                    submission_id=body.get("submission_id"),
                    runtime_env=body.get("runtime_env"),
                    metadata=body.get("metadata"),
                )
                return 200, {"submission_id": sid}
            return 200, {"jobs": mgr.list_jobs()}
        sid = parts[2]
        try:
            if len(parts) == 3 and method == "GET":
                return 200, mgr.get_job_info(sid)
            if len(parts) == 4 and parts[3] == "logs":
                offset = int(query.get("offset", 0) or 0)
                return 200, {"logs": mgr.get_job_logs(sid, offset)}
            if len(parts) == 4 and parts[3] == "stop" and method == "POST":
                return 200, {"stopped": mgr.stop_job(sid)}
        except ValueError as e:
            return 404, {"error": str(e)}
        return 404, {"error": f"no route {path}"}

    def _index_html(self) -> bytes:
        state = self._state()
        nodes = state.list_nodes(self.gcs_address)
        actors = state.list_actors(self.gcs_address)
        rows = "".join(
            f"<tr><td>{n['node_id'][:12]}</td><td>{n['state']}</td>"
            f"<td>{n['node_ip']}</td><td>{n['resources_total']}</td></tr>"
            for n in nodes
        )
        return (
            "<html><head><title>ray_tpu dashboard</title></head><body>"
            f"<h2>ray_tpu cluster @ {self.gcs_address}</h2>"
            f"<p>{len(nodes)} nodes, {len(actors)} actors. "
            "JSON API under <code>/api/*</code>.</p>"
            "<table border=1 cellpadding=4><tr><th>node</th><th>state</th>"
            f"<th>ip</th><th>resources</th></tr>{rows}</table>"
            "</body></html>"
        ).encode()

    # ---------------------------------------------------------------- http

    async def _handle(self, reader, writer):
        try:
            request_line = await asyncio.wait_for(reader.readline(), 10)
            parts = request_line.decode("latin-1").split()
            if len(parts) < 2:
                return
            raw_path = parts[1]
            method, path = parts[0], raw_path.split("?")[0]
            query = {}
            if "?" in raw_path:
                for kv in raw_path.split("?", 1)[1].split("&"):
                    k, _, v = kv.partition("=")
                    query[k] = v
            headers = {}
            while True:
                line = await asyncio.wait_for(reader.readline(), 10)
                if line in (b"\r\n", b"\n", b""):
                    break
                k, _, v = line.decode("latin-1").partition(":")
                headers[k.strip().lower()] = v.strip()
            body = None
            length = int(headers.get("content-length", 0) or 0)
            if length:
                raw = await reader.readexactly(length)
                try:
                    body = json.loads(raw)
                except Exception:
                    body = None
            loop = asyncio.get_running_loop()
            try:
                status, payload = await loop.run_in_executor(
                    None, self._collect, path, method, body, query
                )
            except Exception as e:
                logger.exception("dashboard handler failed")
                status, payload = 500, {"error": str(e)}
            if payload is None and status == 200:
                out = await loop.run_in_executor(None, self._index_html)
                ctype = "text/html; charset=utf-8"
            else:
                out = json.dumps(payload, default=str).encode()
                ctype = "application/json"
            reason = {200: "OK", 400: "Bad Request", 404: "Not Found",
                      500: "Internal Server Error"}.get(status, "OK")
            writer.write(
                f"HTTP/1.1 {status} {reason}\r\nContent-Type: {ctype}\r\n"
                f"Content-Length: {len(out)}\r\nConnection: close\r\n\r\n".encode()
                + out
            )
            await writer.drain()
        except Exception:
            pass
        finally:
            try:
                writer.close()
            except Exception:
                pass

    async def start(self, port: int = 0) -> int:
        self._server = await asyncio.start_server(self._handle, self.host, port)
        self.port = self._server.sockets[0].getsockname()[1]
        logger.info("dashboard on http://%s:%d", self.host, self.port)
        return self.port


def start_dashboard(gcs_address: str, port: int = 0) -> Tuple[DashboardHead, int]:
    """Start a dashboard in this process (on the shared IO thread)."""
    from ray_tpu._private.rpc import IoThread

    head = DashboardHead(gcs_address)
    actual = IoThread.current().run(head.start(port))
    return head, actual


def main(argv=None):
    import argparse
    import sys

    parser = argparse.ArgumentParser()
    parser.add_argument("--gcs-address", required=True)
    parser.add_argument("--port", type=int, default=8265)
    parser.add_argument("--host", default="127.0.0.1",
                        help="bind address; 0.0.0.0 exposes job execution "
                             "to the network — opt in deliberately")
    parser.add_argument("--port-file", default="")
    args = parser.parse_args(argv)
    logging.basicConfig(level=logging.INFO, stream=sys.stderr)

    async def run():
        head = DashboardHead(args.gcs_address, host=args.host)
        port = await head.start(args.port)
        if args.port_file:
            import os

            tmp = args.port_file + ".tmp"
            with open(tmp, "w") as f:
                f.write(str(port))
            os.replace(tmp, args.port_file)
        await asyncio.Event().wait()

    asyncio.run(run())


if __name__ == "__main__":
    main()
