"""Dashboard head: JSON/REST API over cluster state + job submission.

Counterpart of the reference's dashboard head server
(reference: python/ray/dashboard/head.py:79 — aiohttp app aggregating
state + the job module's REST endpoints
dashboard/modules/job/job_head.py). Dependency-free asyncio HTTP/1.1 here;
the React client is out of scope, but a plain HTML summary is served at /
so the endpoint is human-checkable.

Routes:
  GET  /api/cluster                cluster resource summary
  GET  /api/nodes|actors|tasks|objects|workers|placement_groups|jobs
  GET  /api/profile                cluster-wide CPU capture (merged trace;
                                   ?format=flame folded, ?latest=1 registry,
                                   ?pid=/?worker_id= one-worker folded)
  GET  /api/memory                 cluster memory report (plasma + RSS +
                                   HBM rollups, ownership ledgers;
                                   ?group_by=job|actor|node, ?leaks=1
                                   runs the leak detector)
  GET  /api/perf                   perf-gate ledger + latest delta report
                                   (?metric= one metric's trajectory,
                                   ?limit=N history depth)
  GET  /api/jobs/                  submitted jobs (job_submission API)
  POST /api/jobs/                  submit {entrypoint, runtime_env?, ...}
  GET  /api/jobs/<id>              job info
  GET  /api/jobs/<id>/logs         {"logs": "..."}
  POST /api/jobs/<id>/stop         {"stopped": bool}
  GET  /api/version
"""

from __future__ import annotations

import asyncio
import json
import logging
from typing import Optional, Tuple

logger = logging.getLogger("ray_tpu.dashboard")


class DashboardHead:
    def __init__(self, gcs_address: str, host: str = "127.0.0.1"):
        # Loopback by default: /api/jobs executes arbitrary entrypoints, so
        # exposing it beyond the host must be an explicit operator choice
        # (reference: the dashboard binds localhost unless configured).
        self.gcs_address = gcs_address
        self.host = host
        self._server: Optional[asyncio.AbstractServer] = None
        self.port = 0
        self._gcs = None
        self._mgr = None

    # --------------------------------------------------------- data access

    def _state(self):
        from ray_tpu.util import state

        return state

    def _gcs_client(self):
        if self._gcs is None:
            from ray_tpu._private.gcs.client import GcsClient

            self._gcs = GcsClient.from_address(self.gcs_address)
        return self._gcs

    def _job_manager(self):
        if self._mgr is None:
            from ray_tpu.job_submission import JobManager

            self._mgr = JobManager(self._gcs_client())
        return self._mgr

    def _collect(self, path: str, method: str, body: Optional[dict], query=None):
        """Blocking handler (run in executor): returns (status, payload)."""
        state = self._state()
        addr = self.gcs_address
        if path == "/api/cluster":
            return 200, {
                "cluster": self._gcs_client().get_cluster_resources(),
                "nodes": len(state.list_nodes(addr)),
            }
        if path == "/api/nodes":
            return 200, {"nodes": state.list_nodes(addr)}
        if path == "/api/actors":
            return 200, {"actors": state.list_actors(addr)}
        if path == "/api/tasks":
            return 200, {"tasks": state.list_tasks(addr)}
        if path == "/api/objects":
            return 200, {"objects": state.list_objects(addr)}
        if path == "/api/workers":
            return 200, {"workers": state.list_workers(addr)}
        if path == "/api/placement_groups":
            return 200, {"placement_groups": state.list_placement_groups(addr)}
        if path == "/api/version":
            from ray_tpu._version import version

            return 200, {"version": version}
        if path.startswith("/api/logs"):
            return self._logs_api(path, query or {})
        if path.startswith("/api/profile"):
            return self._profile_api(query or {})
        if path == "/api/perf":
            return self._perf_api(query or {})
        if path == "/api/memory":
            return self._memory_api(query or {})
        if path == "/api/node_stats":
            return self._node_stats_api(query or {})
        if path == "/api/agent_metrics":
            return self._agent_metrics_api()
        if path == "/api/train":
            return self._train_api()
        if path == "/api/serve":
            return self._serve_api()
        if path == "/api/grafana_dashboard":
            from ray_tpu.dashboard.grafana import generate_dashboard

            return 200, generate_dashboard()
        if path.startswith("/api/jobs"):
            return self._jobs_api(path, method, body, query or {})
        if path == "/" or path == "/index.html":
            return 200, None  # HTML handled by caller
        return 404, {"error": f"no route {path}"}

    def _jobs_api(self, path: str, method: str, body: Optional[dict], query):
        mgr = self._job_manager()
        parts = [p for p in path.split("/") if p]  # ["api","jobs",...]
        if len(parts) == 2:
            if method == "POST":
                body = body or {}
                if not body.get("entrypoint"):
                    return 400, {"error": "entrypoint is required"}
                sid = mgr.submit_job(
                    entrypoint=body["entrypoint"],
                    submission_id=body.get("submission_id"),
                    runtime_env=body.get("runtime_env"),
                    metadata=body.get("metadata"),
                )
                return 200, {"submission_id": sid}
            return 200, {"jobs": mgr.list_jobs()}
        sid = parts[2]
        try:
            if len(parts) == 3 and method == "GET":
                return 200, mgr.get_job_info(sid)
            if len(parts) == 4 and parts[3] == "logs":
                offset = int(query.get("offset", 0) or 0)
                return 200, {"logs": mgr.get_job_logs(sid, offset)}
            if len(parts) == 4 and parts[3] == "stop" and method == "POST":
                return 200, {"stopped": mgr.stop_job(sid)}
        except ValueError as e:
            return 404, {"error": str(e)}
        return 404, {"error": f"no route {path}"}

    def _profile_api(self, query):
        """GET /api/profile: the profiling plane over HTTP.

        With ``?pid=N`` / ``?worker_id=hex``: on-demand stack sampling of
        one worker process, flamegraph-folded output (reference: dashboard
        reporter profile_manager.py:78 — py-spy-shaped capability without
        the binary dependency). Optional ``node_id``/``duration``/``hz``.

        Without either: a cluster-wide synchronized capture
        (StartProfile/CollectProfile fan-out) returned as one
        Perfetto-loadable merged trace — ``?format=flame`` returns the
        aggregated folded stacks instead; ``?latest=1`` lists registered
        captures without sampling anything."""
        pid = query.get("pid")
        worker_id = query.get("worker_id")
        try:
            duration = float(query.get("duration", 2.0) or 2.0)
            hz = float(query.get("hz", 99.0) or 99.0)
            pid = int(pid) if pid else None
            wid = bytes.fromhex(worker_id) if worker_id else None
        except ValueError as e:
            return 400, {"error": f"bad query value: {e}"}
        if not pid and not wid:
            return self._cluster_profile_api(query, duration, hz)
        # Prefer the node's agent (keeps sampling fan-out off the raylet
        # loop); fall back to the raylet proxy when no agent is registered.
        node_id = query.get("node_id")
        if node_id:
            rec = self._agents().get(node_id)
            if rec is not None:
                try:
                    r = self._ask_agent(
                        rec, "ProfileWorker",
                        {"pid": pid, "worker_id": wid, "duration": duration,
                         "hz": hz},
                        timeout=duration + 30,
                    )
                    return (200, r) if "error" not in r else (404, r)
                except Exception:
                    pass  # agent gone mid-query: raylet path still works
        from ray_tpu._private.profiling import profile_via_raylets

        return profile_via_raylets(
            self._gcs_client().get_all_node_info(),
            pid=pid, worker_id=wid, node_filter=query.get("node_id"),
            duration=duration, hz=hz,
        )

    def _cluster_profile_api(self, query, duration, hz):
        from ray_tpu._private import profiling

        gcs = self._gcs_client()
        if query.get("latest"):
            return 200, {
                "captures": profiling.list_registered(gcs, "capture"),
                "device_traces": profiling.list_registered(
                    gcs, "device_trace"),
            }
        # Bound what one HTTP call can cost the cluster.
        duration = min(duration, 30.0)
        bundle = profiling.capture_cluster_profile(
            gcs.get_all_node_info(), gcs,
            duration=duration, hz=hz, node_filter=query.get("node_id"),
        )
        if query.get("format") == "flame":
            folded = profiling.fold_bundle(bundle)
            text = "\n".join(
                f"{s} {c}"
                for s, c in sorted(folded.items(), key=lambda kv: -kv[1]))
            return 200, {"folded": text,
                         "samples": sum(folded.values()),
                         "errors": bundle["errors"]}
        from ray_tpu._private.timeline import merged_profile_trace

        try:
            task_events = gcs.call(
                "GetTaskEvents", {"limit": 100_000})["events"]
        except Exception:
            task_events = []
        device = profiling.list_registered(gcs, "device_trace")
        return 200, merged_profile_trace(bundle, task_events, device)

    def _memory_api(self, query):
        """GET /api/memory: the memory observability plane over HTTP —
        the cluster memory report (per-node plasma/pin/spill state joined
        with worker ownership ledgers) plus a rollup.
        ``?group_by=job|actor|node`` picks the rollup key (default job);
        ``?leaks=1`` forces a leak sweep and returns the findings;
        ``?objects=0`` drops the per-object listings (cheap summary)."""
        state = self._state()
        addr = self.gcs_address
        group_by = query.get("group_by") or "job"
        if group_by not in ("job", "actor", "node"):
            return 400, {"error": f"bad group_by {group_by!r}"}
        try:
            if query.get("leaks"):
                return 200, {
                    "leaks": state.find_memory_leaks(addr, sweep=True)}
            include_objects = query.get("objects", "1") not in ("0", "false")
            report = state.memory_report(
                addr, include_objects=include_objects)
            report["rollup"] = {
                "group_by": group_by,
                "rows": state.memory_rollup(report, group_by=group_by),
            }
            return 200, report
        except Exception as e:
            return 500, {"error": str(e)}

    def _perf_api(self, query):
        """GET /api/perf: the perf regression plane over HTTP — the ledger
        trajectory (PERF_HISTORY.jsonl via _private/perf_gate.py), the delta
        report between the two newest entries, and the newest incident that
        carries an auto-analysis ("why was that step slow"). Read-only: this
        endpoint never runs a bench."""
        from ray_tpu._private import perf_analysis, perf_gate as pg

        try:
            limit = int(query.get("limit", 20) or 20)
        except ValueError:
            return 400, {"error": "limit must be an integer"}
        entries = pg.load_history(limit=limit)
        out = {"path": pg.history_path(),
               "history": [
                   {k: e.get(k) for k in
                    ("time", "iso", "git", "reps", "quick", "note",
                     "metrics")}
                   for e in entries
               ]}
        if len(entries) >= 2:
            base, cur = entries[-2], entries[-1]
            out["delta"] = pg.compare(
                base["metrics"], cur["metrics"],
                base_reps=base.get("reps", 1), cur_reps=cur.get("reps", 1))
        metric = query.get("metric")
        if metric:
            out["series"] = [
                {"time": e.get("time"), "git": e.get("git", ""),
                 "value": e["metrics"].get(metric)}
                for e in entries
            ]
        try:
            analysis = perf_analysis.latest_incident_analysis(
                self._gcs_client())
        except Exception:
            analysis = None  # ledger output stays useful without a GCS
        if analysis:
            out["latest_incident_analysis"] = analysis
        return 200, out

    # ------------------------------------------------- workload telemetry

    def _user_metrics(self, prefix: str) -> list:
        try:
            return self._gcs_client().call(
                "GetUserMetrics", {"prefix": prefix}
            ).get("records", [])
        except Exception:
            return []

    @staticmethod
    def _merge_hist(acc: dict, rec: dict):
        """Merge one histogram record into an accumulator (buckets sum)."""
        acc["count"] += rec.get("count", 0)
        acc["sum"] += rec.get("sum", 0.0)
        if not acc["boundaries"]:
            acc["boundaries"] = list(rec.get("boundaries") or [])
        for b, c in (rec.get("buckets") or {}).items():
            acc["buckets"][b] = acc["buckets"].get(b, 0) + c

    @staticmethod
    def _hist_summary(acc: dict) -> dict:
        """count/mean/p50/p90/p99 from merged Prometheus-style buckets.
        Quantiles resolve to the bucket upper bound — coarse but monotone,
        the same estimate Grafana's histogram_quantile gives."""
        count = acc["count"]
        out = {"count": count}
        if not count:
            return out
        out["mean"] = acc["sum"] / count
        for q, key in ((0.5, "p50"), (0.9, "p90"), (0.99, "p99")):
            target = q * count
            cum = 0
            val = None
            for b in acc["boundaries"]:
                cum += acc["buckets"].get(str(b), 0)
                if cum >= target:
                    val = b
                    break
            out[key] = val  # None == above the largest finite bucket
        return out

    def _train_api(self):
        """GET /api/train: per-job training telemetry summary aggregated
        from the ray_tpu_train_* series (train/_telemetry.py). Throughput
        sums across workers; MFU/goodput average; step-time quantiles come
        from the merged step histogram."""
        jobs: dict = {}

        def job(rec):
            jid = rec["labels"].get("JobId", "")
            return jobs.setdefault(jid, {
                "steps": 0, "tokens_per_second": 0.0,
                "examples_per_second": 0.0, "workers": set(),
                "_mfu": [], "_goodput": [], "compile_seconds": 0.0,
                "hbm_bytes_in_use": 0.0,
                "_hist": {"count": 0, "sum": 0.0, "buckets": {},
                          "boundaries": []},
            })

        for rec in self._user_metrics("ray_tpu_train_"):
            j = job(rec)
            j["workers"].add(rec["labels"].get("WorkerId", ""))
            name = rec["name"]
            if name == "ray_tpu_train_steps_total":
                j["steps"] += int(rec["value"])
            elif name == "ray_tpu_train_tokens_per_second":
                j["tokens_per_second"] += rec["value"]
            elif name == "ray_tpu_train_examples_per_second":
                j["examples_per_second"] += rec["value"]
            elif name == "ray_tpu_train_mfu_ratio":
                j["_mfu"].append(rec["value"])
            elif name == "ray_tpu_train_goodput_ratio":
                j["_goodput"].append(rec["value"])
            elif name == "ray_tpu_train_compile_seconds":
                j["compile_seconds"] = max(j["compile_seconds"], rec["value"])
            elif name == "ray_tpu_train_hbm_bytes_in_use":
                j["hbm_bytes_in_use"] += rec["value"]
            elif name == "ray_tpu_train_step_seconds":
                self._merge_hist(j["_hist"], rec)
        out = {}
        for jid, j in jobs.items():
            mfu = j.pop("_mfu")
            goodput = j.pop("_goodput")
            hist = j.pop("_hist")
            j["workers"] = len(j["workers"] - {""}) or len(j["workers"])
            if mfu:
                j["mfu"] = sum(mfu) / len(mfu)
            if goodput:
                j["goodput"] = sum(goodput) / len(goodput)
            j["step_seconds"] = self._hist_summary(hist)
            out[jid or "unknown"] = j
        return 200, {"jobs": out}

    def _serve_api(self):
        """GET /api/serve: per-deployment request/latency summary from the
        ray_tpu_serve_* series (replica- and handle-side)."""
        deps: dict = {}

        def dep(rec):
            name = rec["labels"].get("deployment", "")
            return deps.setdefault(name, {
                "requests_total": 0, "errors_total": 0,
                "inflight": 0.0, "queue_depth": 0.0, "replicas": set(),
                "_lat": {"count": 0, "sum": 0.0, "buckets": {},
                         "boundaries": []},
                "_handle_lat": {"count": 0, "sum": 0.0, "buckets": {},
                                "boundaries": []},
            })

        for rec in self._user_metrics("ray_tpu_serve_"):
            d = dep(rec)
            name = rec["name"]
            replica = rec["labels"].get("replica", "")
            if replica:
                d["replicas"].add(replica)
            if name == "ray_tpu_serve_requests_total":
                d["requests_total"] += int(rec["value"])
            elif name == "ray_tpu_serve_handle_requests_total":
                d["handle_requests_total"] = (
                    d.get("handle_requests_total", 0) + int(rec["value"]))
            elif name == "ray_tpu_serve_request_errors_total":
                d["errors_total"] += int(rec["value"])
            elif name == "ray_tpu_serve_inflight_requests":
                d["inflight"] += rec["value"]
            elif name == "ray_tpu_serve_queue_depth":
                d["queue_depth"] += rec["value"]
            elif name == "ray_tpu_serve_request_latency_seconds":
                self._merge_hist(d["_lat"], rec)
            elif name == "ray_tpu_serve_handle_latency_seconds":
                self._merge_hist(d["_handle_lat"], rec)
        out = {}
        for name, d in deps.items():
            d["replicas"] = len(d["replicas"])
            d["latency_seconds"] = self._hist_summary(d.pop("_lat"))
            d["handle_latency_seconds"] = self._hist_summary(
                d.pop("_handle_lat"))
            out[name or "unknown"] = d
        return 200, {"deployments": out}

    def _agents(self) -> dict:
        """node_id_hex -> {host, port, pid} from the GCS agent registry
        (reference: the head discovers per-node agents and fans node-scoped
        queries out to them, dashboard/head.py + reporter_head.py)."""
        out = {}
        try:
            gcs = self._gcs_client()
            for key in gcs.kv_keys(b"agents"):
                raw = gcs.kv_get(b"agents", key)
                if raw:
                    out[key.decode()] = json.loads(raw)
        except Exception:
            pass
        return out

    @staticmethod
    async def _call_agent(rec: dict, method: str, payload: dict, timeout):
        from ray_tpu._private.rpc import RpcClient

        client = RpcClient(rec["host"], rec["port"])
        await client.connect()
        try:
            return await client.call(method, payload, timeout=timeout)
        finally:
            await client.close()

    def _ask_agent(self, rec: dict, method: str, payload: dict, timeout=5.0):
        from ray_tpu._private.rpc import IoThread

        return IoThread.current().run(
            self._call_agent(rec, method, payload, timeout),
            timeout=timeout + 5)

    def _ask_agents(self, agents: dict, method: str, timeout=5.0):
        """Concurrent fan-out: one io-thread round, latency = slowest agent
        (a dead agent must not serialize with the healthy ones)."""
        from ray_tpu._private.rpc import IoThread

        items = list(agents.items())

        async def _gather():
            results = await asyncio.gather(
                *(self._call_agent(rec, method, {}, timeout)
                  for _hexid, rec in items),
                return_exceptions=True)
            return results

        results = IoThread.current().run(_gather(), timeout=timeout + 10)
        ok, errors = [], {}
        for (hexid, _rec), r in zip(items, results):
            if isinstance(r, Exception):
                errors[hexid] = str(r)
            else:
                ok.append((hexid, r))
        return ok, errors

    def _node_stats_api(self, query):
        """GET /api/node_stats[?node_id=hex]: per-node host stats served by
        the node's agent (fan-out when no node_id given)."""
        agents = self._agents()
        node_id = query.get("node_id")
        if node_id:
            rec = agents.get(node_id)
            if rec is None:
                return 404, {"error": f"no agent for node {node_id}"}
            try:
                return 200, self._ask_agent(rec, "NodeStats", {})
            except Exception as e:
                return 502, {"error": f"agent unreachable: {e}"}
        ok, errors = self._ask_agents(agents, "NodeStats")
        return 200, {"nodes": [r for _h, r in ok], "errors": errors,
                     "agent_count": len(agents)}

    def _agent_metrics_api(self):
        """GET /api/agent_metrics: concatenated Prometheus text from every
        node agent (host-level series; raylet /metrics keeps the
        scheduler/object series)."""
        ok, errors = self._ask_agents(self._agents(), "Metrics")
        chunks = [r["text"] for _h, r in ok]
        chunks += [f"# agent {h} unreachable\n" for h in errors]
        return 200, {"text": "".join(chunks)}

    def _session_dir(self) -> str:
        """Cluster session dir from the GCS, cached (it never changes);
        same fallback as JobManager._session_dir on a transient GCS error."""
        if getattr(self, "_session_dir_cache", None):
            return self._session_dir_cache
        try:
            info = self._gcs_client().call("GetInternalConfig", {})
            self._session_dir_cache = info.get("session_dir") or ""
        except Exception:
            return ""
        return self._session_dir_cache

    def _logs_api(self, path: str, query):
        """Session log files (reference: dashboard log module —
        dashboard/modules/log/ serves per-process logs over HTTP).

        GET /api/logs            list {name, size_bytes}
        GET /api/logs/<name>     {"lines": [...]} — ?tail=N (default 200)
        """
        import os
        from collections import deque

        log_dir = os.path.join(self._session_dir(), "logs")
        if not os.path.isdir(log_dir):
            return 404, {"error": "no session log directory"}
        parts = [p for p in path.split("/") if p]  # ["api","logs",...]
        if len(parts) == 2:
            files = sorted(os.listdir(log_dir))
            return 200, {"logs": [
                {"name": n,
                 "size_bytes": os.path.getsize(os.path.join(log_dir, n))}
                for n in files
            ]}
        name = parts[2]
        # the filename comes off the URL: never let it traverse out
        target = os.path.realpath(os.path.join(log_dir, name))
        if (os.path.dirname(target) != os.path.realpath(log_dir)
                or not os.path.isfile(target)):
            return 404, {"error": f"no log file {name!r}"}
        try:
            tail = int(query.get("tail", "") or 200)
        except ValueError:
            return 400, {"error": "tail must be an integer"}
        tail = max(0, min(tail, 100_000))
        # bounded tail: never materialize a multi-GB log in memory
        with open(target, "r", errors="replace") as f:
            lines = deque(f, maxlen=tail)
        return 200, {"name": name,
                     "lines": [ln.rstrip("\n") for ln in lines]}

    def _index_html(self) -> bytes:
        """Single-page live dashboard: vanilla JS polling the /api routes
        (reference: dashboard/client/ — a React app; same information
        surface, no build step)."""
        return _INDEX_HTML.replace(
            b"__GCS__", self.gcs_address.encode()
        )

    # ---------------------------------------------------------------- http

    async def _handle(self, reader, writer):
        try:
            request_line = await asyncio.wait_for(reader.readline(), 10)
            parts = request_line.decode("latin-1").split()
            if len(parts) < 2:
                return
            raw_path = parts[1]
            method, path = parts[0], raw_path.split("?")[0]
            query = {}
            if "?" in raw_path:
                for kv in raw_path.split("?", 1)[1].split("&"):
                    k, _, v = kv.partition("=")
                    query[k] = v
            headers = {}
            while True:
                line = await asyncio.wait_for(reader.readline(), 10)
                if line in (b"\r\n", b"\n", b""):
                    break
                k, _, v = line.decode("latin-1").partition(":")
                headers[k.strip().lower()] = v.strip()
            body = None
            length = int(headers.get("content-length", 0) or 0)
            if length:
                raw = await reader.readexactly(length)
                try:
                    body = json.loads(raw)
                except Exception:
                    body = None
            loop = asyncio.get_running_loop()
            try:
                status, payload = await loop.run_in_executor(
                    None, self._collect, path, method, body, query
                )
            except Exception as e:
                logger.exception("dashboard handler failed")
                status, payload = 500, {"error": str(e)}
            if payload is None and status == 200:
                out = await loop.run_in_executor(None, self._index_html)
                ctype = "text/html; charset=utf-8"
            else:
                out = json.dumps(payload, default=str).encode()
                ctype = "application/json"
            reason = {200: "OK", 400: "Bad Request", 404: "Not Found",
                      500: "Internal Server Error"}.get(status, "OK")
            writer.write(
                f"HTTP/1.1 {status} {reason}\r\nContent-Type: {ctype}\r\n"
                f"Content-Length: {len(out)}\r\nConnection: close\r\n\r\n".encode()
                + out
            )
            await writer.drain()
        except Exception:
            pass
        finally:
            try:
                writer.close()
            except Exception:
                pass

    async def start(self, port: int = 0) -> int:
        self._server = await asyncio.start_server(self._handle, self.host, port)
        self.port = self._server.sockets[0].getsockname()[1]
        logger.info("dashboard on http://%s:%d", self.host, self.port)
        return self.port


def start_dashboard(gcs_address: str, port: int = 0) -> Tuple[DashboardHead, int]:
    """Start a dashboard in this process (on the shared IO thread)."""
    from ray_tpu._private.rpc import IoThread

    head = DashboardHead(gcs_address)
    actual = IoThread.current().run(head.start(port))
    return head, actual


def main(argv=None):
    import argparse
    import sys

    parser = argparse.ArgumentParser()
    parser.add_argument("--gcs-address", required=True)
    parser.add_argument("--port", type=int, default=8265)
    parser.add_argument("--host", default="127.0.0.1",
                        help="bind address; 0.0.0.0 exposes job execution "
                             "to the network — opt in deliberately")
    parser.add_argument("--port-file", default="")
    args = parser.parse_args(argv)
    logging.basicConfig(level=logging.INFO, stream=sys.stderr)

    async def run():
        head = DashboardHead(args.gcs_address, host=args.host)
        port = await head.start(args.port)
        if args.port_file:
            import os

            tmp = args.port_file + ".tmp"
            with open(tmp, "w") as f:
                f.write(str(port))
            os.replace(tmp, args.port_file)
        await asyncio.Event().wait()

    asyncio.run(run())


if __name__ == "__main__":
    main()


_INDEX_HTML = b"""<!doctype html>
<html><head><title>ray_tpu dashboard</title>
<style>
 body{font-family:system-ui,sans-serif;margin:0;background:#f6f7f9;color:#1a1d21}
 header{background:#1a1d21;color:#fff;padding:10px 20px;display:flex;align-items:baseline;gap:14px}
 header h1{font-size:16px;margin:0} header span{color:#9aa3ad;font-size:12px}
 .tiles{display:flex;gap:12px;padding:16px 20px;flex-wrap:wrap}
 .tile{background:#fff;border:1px solid #e2e5e9;border-radius:8px;padding:12px 18px;min-width:110px}
 .tile .v{font-size:22px;font-weight:600} .tile .k{font-size:11px;color:#6b7380;text-transform:uppercase}
 section{margin:6px 20px 18px} h2{font-size:13px;color:#6b7380;text-transform:uppercase;margin:14px 0 6px}
 table{border-collapse:collapse;width:100%;background:#fff;border:1px solid #e2e5e9;border-radius:8px;overflow:hidden}
 th,td{font-size:12.5px;text-align:left;padding:6px 10px;border-bottom:1px solid #eef0f3;font-variant-numeric:tabular-nums}
 th{background:#fafbfc;color:#6b7380;font-weight:600}
 .ALIVE,.RUNNING,.SUCCEEDED,.CREATED{color:#0a7d33;font-weight:600}
 .DEAD,.FAILED,.ERRORED{color:#b3261e;font-weight:600}
 .PENDING_CREATION,.PENDING,.RESTARTING,.RESCHEDULING{color:#9a6b00;font-weight:600}
 code{background:#eef0f3;border-radius:4px;padding:1px 5px}
</style></head><body>
<header><h1>ray_tpu</h1><span>cluster @ __GCS__</span>
<span id=err style="color:#ff8a80"></span></header>
<div class=tiles id=tiles></div>
<section><h2>Nodes</h2><table id=nodes></table></section>
<section><h2>Actors</h2><table id=actors></table></section>
<section><h2>Jobs</h2><table id=jobs></table></section>
<section><h2>Placement groups</h2><table id=pgs></table></section>
<section style="color:#6b7380;font-size:12px">JSON API under <code>/api/*</code>
&middot; refreshes every 2s</section>
<script>
async function j(p){const r=await fetch(p);return r.json()}
function esc(s){return String(s).replace(/[&<>"']/g,c=>({'&':'&amp;','<':'&lt;','>':'&gt;','"':'&quot;',"'":'&#39;'}[c]))}
function row(cells,h){return '<tr>'+cells.map(c=>(h?'<th>':'<td>')+c+(h?'</th>':'</td>')).join('')+'</tr>'}
function st(s){return '<span class="'+esc(s)+'">'+esc(s)+'</span>'}
function fmtRes(r){return Object.entries(r||{}).map(([k,v])=>k+':'+(typeof v=='number'?Math.round(v*10)/10:v)).join(' ')}
async function tick(){
 try{
  const [clusterR,nodesR,actorsR,jobsR,pgsR]=await Promise.all([
    j('/api/cluster'),j('/api/nodes'),j('/api/actors'),j('/api/jobs'),j('/api/placement_groups')]);
  const nodes=nodesR.nodes||[],actors=actorsR.actors||[],
        jobs=jobsR.jobs||[],pgs=pgsR.placement_groups||[];
  const alive=nodes.filter(n=>n.state=='ALIVE');
  const total=(clusterR.cluster||{}).total||{},avail=(clusterR.cluster||{}).available||{};
  document.getElementById('tiles').innerHTML=
   [['nodes',alive.length],['actors',actors.filter(a=>a.state=='ALIVE').length],
    ['jobs',jobs.length],['CPU',Math.round(((total.CPU||0)-(avail.CPU||0))*10)/10+' / '+(total.CPU||0)],
    ['TPU',Math.round(((total.TPU||0)-(avail.TPU||0))*10)/10+' / '+(total.TPU||0)]]
   .map(([k,v])=>'<div class=tile><div class=v>'+v+'</div><div class=k>'+k+'</div></div>').join('');
  document.getElementById('nodes').innerHTML=row(['node','state','ip','total','available'],1)+
   nodes.map(n=>row([esc(n.node_id.slice(0,12)),st(n.state),esc(n.node_ip),esc(fmtRes(n.resources_total)),esc(fmtRes(n.resources_available))])).join('');
  document.getElementById('actors').innerHTML=row(['actor','class','name','state','node','restarts'],1)+
   actors.slice(0,200).map(a=>row([esc(a.actor_id.slice(0,12)),esc(a.class_name||''),esc(a.name||''),st(a.state),esc((a.node_id||'').slice(0,12)),a.num_restarts||0])).join('');
  document.getElementById('jobs').innerHTML=row(['job','entrypoint','status','start'],1)+
   jobs.map(x=>row([esc(x.job_id||x.submission_id||''),esc((x.entrypoint||'').slice(0,80)),st(x.status||x.state||''),x.start_time?new Date(x.start_time*1000).toLocaleTimeString():''])).join('');
  document.getElementById('pgs').innerHTML=row(['pg','name','strategy','state','bundles'],1)+
   pgs.map(p=>row([esc(p.placement_group_id.slice(0,12)),esc(p.name||''),esc(p.strategy),st(p.state),p.bundles.length])).join('');
  document.getElementById('err').textContent='';
 }catch(e){document.getElementById('err').textContent='api error: '+e}
}
tick();setInterval(tick,2000);
</script></body></html>
"""
