"""Grafana dashboard generation from the framework's metric catalog.

Reference: dashboard/modules/metrics/grafana_dashboard_factory.py — the
dashboard ships ready-made Grafana JSON for its Prometheus metrics. Same
here: `generate_dashboard()` returns an importable Grafana dashboard
covering the node/scheduler/object-store/worker gauges the GCS and
raylets expose on their /metrics endpoints, and the dashboard head serves
it at GET /api/grafana_dashboard.
"""

from __future__ import annotations

import json
from typing import List

_PANELS = [
    ("Cluster CPU utilization", [
        ("sum(ray_tpu_node_resource_total{resource=\"CPU\"}) - "
         "sum(ray_tpu_node_resource_available{resource=\"CPU\"})", "used"),
        ("sum(ray_tpu_node_resource_total{resource=\"CPU\"})", "total"),
    ], "short"),
    ("TPU chips in use", [
        ("sum(ray_tpu_node_resource_total{resource=\"TPU\"}) - "
         "sum(ray_tpu_node_resource_available{resource=\"TPU\"})", "used"),
        ("sum(ray_tpu_node_resource_total{resource=\"TPU\"})", "total"),
    ], "short"),
    ("Workers by state", [
        ("sum(ray_tpu_node_workers) by (state)", "{{state}}"),
    ], "short"),
    ("Active leases", [
        ("sum(ray_tpu_node_leases)", "leases"),
    ], "short"),
    ("Object store used", [
        ("sum(ray_tpu_object_store_used_bytes)", "used"),
        ("sum(ray_tpu_object_store_capacity_bytes)", "capacity"),
    ], "bytes"),
    ("Objects in store", [
        ("sum(ray_tpu_object_store_num_objects)", "objects"),
    ], "short"),
    ("Spilled bytes", [
        ("sum(ray_tpu_spilled_bytes)", "spilled"),
    ], "bytes"),
    ("Object pulls in flight", [
        ("sum(ray_tpu_pulls_in_flight)", "pulls"),
    ], "short"),
    ("Node CPU percent", [
        ("ray_tpu_node_cpu_percent", "{{node}}"),
    ], "percent"),
    ("Node memory used", [
        ("ray_tpu_node_mem_used_bytes", "{{node}}"),
    ], "bytes"),
    ("Worker RSS", [
        ("ray_tpu_worker_rss_bytes", "{{node}}/{{pid}}"),
    ], "bytes"),
    ("Placement-group bundles", [
        ("sum(ray_tpu_node_pg_bundles)", "bundles"),
    ], "short"),
    # ---- workload telemetry (train/_telemetry.py + serve metrics): the
    # step-level training and request-level serving series the GCS exports
    # from the ray_tpu.util.metrics pipeline.
    ("Training throughput (tokens/s)", [
        ("sum(ray_tpu_train_tokens_per_second) by (JobId)", "{{JobId}}"),
    ], "short"),
    ("Training step time", [
        ("histogram_quantile(0.5, sum(rate("
         "ray_tpu_train_step_seconds_bucket[5m])) by (le))", "p50"),
        ("histogram_quantile(0.95, sum(rate("
         "ray_tpu_train_step_seconds_bucket[5m])) by (le))", "p95"),
    ], "s"),
    ("Model FLOPs utilization", [
        ("avg(ray_tpu_train_mfu_ratio) by (JobId)", "{{JobId}}"),
    ], "percentunit"),
    ("Training goodput", [
        ("avg(ray_tpu_train_goodput_ratio) by (JobId)", "{{JobId}}"),
    ], "percentunit"),
    ("HBM in use", [
        ("sum(ray_tpu_train_hbm_bytes_in_use) by (WorkerId)",
         "{{WorkerId}}"),
    ], "bytes"),
    ("Serve request rate", [
        ("sum(rate(ray_tpu_serve_requests_total[1m])) by (deployment)",
         "{{deployment}}"),
        ("sum(rate(ray_tpu_serve_request_errors_total[1m])) "
         "by (deployment)", "{{deployment}} errors"),
    ], "reqps"),
    ("Serve request latency", [
        ("histogram_quantile(0.5, sum(rate("
         "ray_tpu_serve_request_latency_seconds_bucket[5m])) "
         "by (le, deployment))", "{{deployment}} p50"),
        ("histogram_quantile(0.99, sum(rate("
         "ray_tpu_serve_request_latency_seconds_bucket[5m])) "
         "by (le, deployment))", "{{deployment}} p99"),
    ], "s"),
    ("Serve in-flight / queue depth", [
        ("sum(ray_tpu_serve_inflight_requests) by (deployment)",
         "{{deployment}} in-flight"),
        ("sum(ray_tpu_serve_queue_depth) by (deployment)",
         "{{deployment}} queued"),
    ], "short"),
]


def _panel(panel_id: int, title: str, targets: List[tuple], unit: str,
           x: int, y: int) -> dict:
    return {
        "id": panel_id,
        "title": title,
        "type": "timeseries",
        "datasource": {"type": "prometheus", "uid": "${datasource}"},
        "fieldConfig": {"defaults": {"unit": unit}, "overrides": []},
        "gridPos": {"h": 8, "w": 12, "x": x, "y": y},
        "targets": [
            {"expr": expr, "legendFormat": legend, "refId": chr(65 + i)}
            for i, (expr, legend) in enumerate(targets)
        ],
    }


def generate_dashboard() -> dict:
    """Importable Grafana dashboard JSON for the cluster's metrics."""
    panels = []
    for i, (title, targets, unit) in enumerate(_PANELS):
        panels.append(
            _panel(i + 1, title, targets, unit,
                   x=(i % 2) * 12, y=(i // 2) * 8)
        )
    return {
        "title": "ray_tpu cluster",
        "uid": "ray-tpu-cluster",
        "timezone": "browser",
        "schemaVersion": 39,
        "refresh": "10s",
        "time": {"from": "now-30m", "to": "now"},
        "templating": {"list": [{
            "name": "datasource",
            "type": "datasource",
            "query": "prometheus",
        }]},
        "panels": panels,
    }


def dashboard_json() -> str:
    return json.dumps(generate_dashboard(), indent=2)
