"""Node providers: the pluggable "launch me a node" backend.

Counterpart of the reference's NodeProvider plugin API
(reference: python/ray/autoscaler/node_provider.py:13) and the fake
multi-node provider used for cloud-free autoscaler e2e tests
(reference: autoscaler/_private/fake_multi_node/node_provider.py).
"""

from __future__ import annotations

import threading
import uuid
from typing import Dict, List, Optional


class NodeProvider:
    """Minimal provider contract: launch/terminate/list, by node type."""

    def create_node(self, node_type: str, count: int = 1) -> List[str]:
        raise NotImplementedError

    def terminate_node(self, provider_node_id: str) -> None:
        raise NotImplementedError

    def non_terminated_nodes(self) -> Dict[str, str]:
        """provider_node_id -> node_type"""
        raise NotImplementedError


class FakeMultiNodeProvider(NodeProvider):
    """Launches REAL raylet processes on this machine, one per 'node'
    (reference: fake_multi_node/node_provider.py — autoscaler e2e without a
    cloud). Each created node joins the target cluster's GCS with the node
    type's resources/labels.
    """

    def __init__(self, gcs_address: str, node_types: Dict[str, dict], session_dir: str = ""):
        self.gcs_address = gcs_address
        self.node_types = node_types
        self.session_dir = session_dir
        self._nodes: Dict[str, dict] = {}  # provider id -> {"node": Node, "type": str}
        self._lock = threading.Lock()

    def create_node(self, node_type: str, count: int = 1) -> List[str]:
        from ray_tpu._private.node import Node

        cfg = self.node_types[node_type]
        created = []
        for _ in range(count):
            pid = f"fake-{node_type}-{uuid.uuid4().hex[:6]}"
            node = Node(
                head=False,
                gcs_address=self.gcs_address,
                resources=dict(cfg.get("resources", {})),
                labels={**cfg.get("labels", {}), "node_type": node_type},
                session_dir=self.session_dir or None,
                node_name=pid,
            )
            with self._lock:
                self._nodes[pid] = {"node": node, "type": node_type}
            created.append(pid)
        return created

    def terminate_node(self, provider_node_id: str) -> None:
        with self._lock:
            rec = self._nodes.pop(provider_node_id, None)
        if rec is not None:
            rec["node"].shutdown()

    def non_terminated_nodes(self) -> Dict[str, str]:
        with self._lock:
            return {pid: rec["type"] for pid, rec in self._nodes.items()}

    def raylet_node_id(self, provider_node_id: str) -> Optional[bytes]:
        with self._lock:
            rec = self._nodes.get(provider_node_id)
        return rec["node"].node_id.binary() if rec else None

    def shutdown(self):
        with self._lock:
            nodes, self._nodes = list(self._nodes.values()), {}
        for rec in nodes:
            rec["node"].shutdown()


class CommandNodeProvider(NodeProvider):
    """Generic on-prem/provisioner provider: nodes launch and terminate
    via user-configured shell commands (reference: the local/on-prem
    provider and ssh updater stack, autoscaler/_private/local/ +
    command_runner.py — the cloud-SDK providers are that machinery with
    vendor APIs swapped in).

    Per node type:
        {"up": "ssh host1 ray-tpu start --address $gcs_address",
         "down": "ssh host1 pkill -f raylet"}   # optional

    Placeholders use $-substitution ($gcs_address, $node_type,
    $provider_node_id) so shell/JSON braces in commands never need
    escaping. The "up" command must start a node that joins the cluster
    (e.g. the `ray-tpu start --address` CLI); "down" tears it down.
    Commands run synchronously; the autoscaler's view of cluster
    membership comes from GCS node records as usual.
    """

    def __init__(self, gcs_address: str, node_types: Dict[str, dict],
                 command_timeout_s: float = 120.0):
        self.gcs_address = gcs_address
        self.node_types = node_types
        self.command_timeout_s = command_timeout_s
        self._nodes: Dict[str, str] = {}  # provider id -> node type
        self._lock = threading.Lock()

    def _run(self, template: str, node_type: str, pid: str):
        import string
        import subprocess

        cmd = string.Template(template).safe_substitute(
            gcs_address=self.gcs_address, node_type=node_type,
            provider_node_id=pid,
        )
        try:
            r = subprocess.run(
                cmd, shell=True, capture_output=True, text=True,
                timeout=self.command_timeout_s,
            )
        except subprocess.TimeoutExpired:
            raise RuntimeError(
                f"provider command failed ({cmd!r}): timed out after "
                f"{self.command_timeout_s}s — NOTE: only the shell was "
                "killed; a grandchild provisioner may still be running "
                "and its node could join the cluster unrecorded"
            )
        if r.returncode != 0:
            raise RuntimeError(
                f"provider command failed ({cmd!r}):\n{r.stdout[-1000:]}"
                f"\n{r.stderr[-1000:]}"
            )

    def create_node(self, node_type: str, count: int = 1) -> List[str]:
        cfg = self.node_types[node_type]
        created = []
        for _ in range(count):
            pid = f"cmd-{node_type}-{uuid.uuid4().hex[:6]}"
            self._run(cfg["up"], node_type, pid)
            with self._lock:
                self._nodes[pid] = node_type
            created.append(pid)
        return created

    def terminate_node(self, provider_node_id: str) -> None:
        with self._lock:
            node_type = self._nodes.pop(provider_node_id, None)
        if node_type is None:
            return
        down = self.node_types.get(node_type, {}).get("down")
        if down:
            try:
                self._run(down, node_type, provider_node_id)
            except Exception:
                pass  # best effort — GCS health marks the node dead anyway

    def non_terminated_nodes(self) -> Dict[str, str]:
        with self._lock:
            return dict(self._nodes)


class RecordingNodeProvider(NodeProvider):
    """Test double that only records launch/terminate calls."""

    def __init__(self, node_types: Optional[Dict[str, dict]] = None):
        self.node_types = node_types or {}
        self.launches: List[str] = []
        self.terminations: List[str] = []
        self._nodes: Dict[str, str] = {}

    def create_node(self, node_type: str, count: int = 1) -> List[str]:
        out = []
        for _ in range(count):
            pid = f"rec-{node_type}-{len(self.launches)}"
            self.launches.append(node_type)
            self._nodes[pid] = node_type
            out.append(pid)
        return out

    def terminate_node(self, provider_node_id: str) -> None:
        self.terminations.append(provider_node_id)
        self._nodes.pop(provider_node_id, None)

    def non_terminated_nodes(self) -> Dict[str, str]:
        return dict(self._nodes)
