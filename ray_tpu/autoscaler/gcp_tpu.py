"""GCP TPU-VM node provider: create/terminate/list real TPU slices.

Counterpart of the reference's GCPNodeProvider + GCPTPUNode machinery
(reference: python/ray/autoscaler/_private/gcp/node_provider.py:63,
gcp/node.py GCPTPUNode — the reference drives the TPU REST API via
googleapiclient). This image has no cloud SDK and zero egress, so the
provider shells out to the ``gcloud compute tpus tpu-vm`` CLI instead —
the command builder is pure and the executor is injectable, which is also
how the tests record command shapes without a cloud (the reference tests
mock the discovery client the same way, gcp/test_gcp_node_provider.py).

Slice awareness: one TPU pod slice = one gcloud resource but MANY hosts.
``slice_hosts`` expands a created/listed node into its per-host network
endpoints so the launcher can bootstrap every host of a v5e-64 the way the
reference's TPUPodType handling does (gcp/config.py _get_num_tpu_visible_
chips_per_host).
"""

from __future__ import annotations

import json
import subprocess
import uuid
from typing import Callable, Dict, List, Optional

from ray_tpu.autoscaler.node_provider import NodeProvider

CLUSTER_LABEL = "rtpu-cluster"
TYPE_LABEL = "rtpu-node-type"


def _default_runner(argv: List[str], timeout: float) -> str:
    out = subprocess.run(argv, capture_output=True, text=True, timeout=timeout)
    if out.returncode != 0:
        raise RuntimeError(
            f"gcloud failed ({' '.join(argv[:6])}...): {out.stderr.strip()}"
        )
    return out.stdout


class GcpTpuNodeProvider(NodeProvider):
    """Provider config (cluster YAML ``provider:`` section):

        type: gcp-tpu
        project: my-project
        zone: us-central2-b

    Node types (``tpu_node_types:``) map a logical type to TPU-VM create
    arguments:

        head:   {accelerator_type: v5litepod-8, version: tpu-ubuntu2204-base}
        worker: {accelerator_type: v5litepod-16, version: tpu-ubuntu2204-base,
                 spot: true, network: default}
    """

    def __init__(self, project: str, zone: str, cluster_name: str,
                 node_types: Dict[str, dict],
                 runner: Optional[Callable[[List[str], float], str]] = None,
                 timeout_s: float = 900.0):
        self.project = project
        self.zone = zone
        self.cluster_name = cluster_name
        self.node_types = node_types
        self._run = runner or _default_runner
        self.timeout_s = timeout_s

    # ------------------------------------------------------- command builders

    def _base(self, verb: str) -> List[str]:
        return ["gcloud", "compute", "tpus", "tpu-vm", verb,
                "--project", self.project, "--zone", self.zone]

    def _create_argv(self, name: str, node_type: str) -> List[str]:
        cfg = self.node_types[node_type]
        argv = self._base("create") + [
            name,
            "--accelerator-type", cfg["accelerator_type"],
            "--version", cfg.get("version", "tpu-ubuntu2204-base"),
            "--labels",
            f"{CLUSTER_LABEL}={self.cluster_name},{TYPE_LABEL}={node_type}",
        ]
        if cfg.get("network"):
            argv += ["--network", cfg["network"]]
        if cfg.get("spot"):
            argv += ["--spot"]
        for extra in cfg.get("extra_args", []):
            argv.append(str(extra))
        return argv

    # ---------------------------------------------------------- provider API

    def create_node(self, node_type: str, count: int = 1) -> List[str]:
        created = []
        for _ in range(count):
            name = (f"{self.cluster_name}-{node_type}-"
                    f"{uuid.uuid4().hex[:6]}")
            self._run(self._create_argv(name, node_type), self.timeout_s)
            created.append(name)
        return created

    def terminate_node(self, provider_node_id: str) -> None:
        self._run(self._base("delete") + [provider_node_id, "--quiet"],
                  self.timeout_s)

    def non_terminated_nodes(self) -> Dict[str, str]:
        out = self._run(
            self._base("list")
            + ["--filter", f"labels.{CLUSTER_LABEL}={self.cluster_name}",
               "--format", "json"],
            self.timeout_s,
        )
        nodes = {}
        for rec in json.loads(out or "[]"):
            state = rec.get("state", "")
            if state in ("DELETING", "TERMINATED", "PREEMPTED"):
                continue
            name = rec["name"].rsplit("/", 1)[-1]
            nodes[name] = rec.get("labels", {}).get(TYPE_LABEL, "")
        return nodes

    # -------------------------------------------------------- slice expansion

    def describe(self, provider_node_id: str) -> dict:
        out = self._run(
            self._base("describe")
            + [provider_node_id, "--format", "json"],
            self.timeout_s,
        )
        return json.loads(out)

    def slice_hosts(self, provider_node_id: str,
                    internal: bool = True) -> List[str]:
        """Per-host IPs of one TPU slice, in worker order. A v5litepod-16 is
        one gcloud resource with 4 networkEndpoints; every host runs a
        raylet (the reference reaches them via GCPTPUNode.get_internal_ip
        per worker index)."""
        rec = self.describe(provider_node_id)
        ips = []
        for ep in rec.get("networkEndpoints", []):
            if internal:
                ips.append(ep.get("ipAddress"))
            else:
                access = ep.get("accessConfig") or {}
                ips.append(access.get("externalIp") or ep.get("ipAddress"))
        return [ip for ip in ips if ip]

    def wait_ready(self, provider_node_id: str, poll_s: float = 10.0,
                   timeout_s: float = 900.0) -> dict:
        """Poll describe until the slice is READY (reference:
        gcp/node.py is_running / _get_node polling)."""
        import time

        deadline = time.monotonic() + timeout_s
        while True:
            rec = self.describe(provider_node_id)
            if rec.get("state") == "READY":
                return rec
            if time.monotonic() >= deadline:
                raise TimeoutError(
                    f"TPU {provider_node_id} not READY after {timeout_s}s "
                    f"(state={rec.get('state')})")
            time.sleep(poll_s)


def cluster_ips(provider: GcpTpuNodeProvider, config: dict) -> tuple:
    """Launcher glue: ensure the configured fleet exists and return
    (head_ip, [worker_ips...]) covering EVERY host of every slice. The
    head is host 0 of the head slice."""
    want_head = config["provider"].get("head_type", "head")
    want_workers: Dict[str, int] = dict(
        config["provider"].get("worker_types", {}))
    have = provider.non_terminated_nodes()
    head_ids = [pid for pid, t in have.items() if t == want_head]
    if not head_ids:
        head_ids = provider.create_node(want_head, 1)
    by_type: Dict[str, List[str]] = {}
    for pid, t in provider.non_terminated_nodes().items():
        by_type.setdefault(t, []).append(pid)
    for wtype, count in want_workers.items():
        missing = count - len(by_type.get(wtype, []))
        if missing > 0:
            by_type.setdefault(wtype, []).extend(
                provider.create_node(wtype, missing))
    provider.wait_ready(head_ids[0])
    head_hosts = provider.slice_hosts(head_ids[0])
    workers: List[str] = head_hosts[1:]  # extra hosts of the head slice
    for wtype in want_workers:
        for pid in by_type.get(wtype, []):
            provider.wait_ready(pid)
            workers.extend(provider.slice_hosts(pid))
    return head_hosts[0], workers


def teardown(provider: GcpTpuNodeProvider) -> List[str]:
    """Delete every slice carrying this cluster's label."""
    gone = []
    for pid in provider.non_terminated_nodes():
        provider.terminate_node(pid)
        gone.append(pid)
    return gone
