"""Cluster launcher: ``ray-tpu up / down`` from a YAML cluster config.

Counterpart of the reference's launcher stack
(reference: python/ray/autoscaler/_private/commands.py:221
create_or_update_cluster, updater.py:40 NodeUpdater, command_runner.py:159
SSHCommandRunner, local/node_provider.py). Redesigned for the TPU-pod
shape: a pod's hosts are a FIXED fleet (provisioned by the cloud when the
slice is created), so the primary provider is a static host list reached
over SSH; elastic providers plug in through the same create/terminate
seam the autoscaler's CommandNodeProvider uses.

Config (YAML):

    cluster_name: my-tpu-pod
    provider:
      type: static            # static | command | process (tests)
      head_ip: 10.0.0.2
      worker_ips: [10.0.0.3, 10.0.0.4]
    auth:
      ssh_user: ubuntu
      ssh_private_key: ~/.ssh/id_rsa     # optional
    file_mounts:
      /remote/path: /local/path          # rsync'd before setup
    initialization_commands: []          # run once per node, pre-setup
    setup_commands:                      # run per node before start
      - pip install -e /remote/path
    head_setup_commands: []              # extra, head only
    worker_setup_commands: []            # extra, workers only
    head_start_command: >-
      ray-tpu start --head --host $RTPU_NODE_IP --port 6379
    worker_start_command: >-
      ray-tpu start --address=$RTPU_HEAD_IP:6379 --host $RTPU_NODE_IP
    stop_command: ray-tpu stop

Every command runs with RTPU_NODE_IP / RTPU_HEAD_IP / RTPU_CLUSTER_NAME
exported. ``type: command`` adds create/terminate shell templates for
elastic fleets; ``type: process`` runs each "node" as local processes in
isolated state dirs (the fake-multinode e2e,
reference: autoscaler/_private/fake_multi_node/node_provider.py).
"""

from __future__ import annotations

import os
import shlex
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional

import yaml

DEFAULT_HEAD_START = (
    "ray-tpu start --head --host $RTPU_NODE_IP --port 6379"
)
DEFAULT_WORKER_START = (
    "ray-tpu start --address=$RTPU_HEAD_IP:6379 --host $RTPU_NODE_IP"
)
DEFAULT_STOP = "ray-tpu stop"


class LauncherError(RuntimeError):
    pass


def load_cluster_config(path: str) -> dict:
    with open(path) as f:
        config = yaml.safe_load(f)
    if not isinstance(config, dict):
        raise LauncherError(f"{path}: config must be a mapping")
    for key in ("cluster_name", "provider"):
        if key not in config:
            raise LauncherError(f"{path}: missing required key '{key}'")
    provider = config["provider"]
    ptype = provider.get("type")
    if ptype not in ("static", "command", "process", "gcp-tpu"):
        raise LauncherError(
            f"provider.type must be static|command|process|gcp-tpu, got {ptype!r}"
        )
    if ptype in ("static", "process") and "head_ip" not in provider:
        raise LauncherError("provider.head_ip is required")
    if ptype == "command" and "create_command" not in provider:
        raise LauncherError(
            "provider.create_command is required for type: command"
        )
    if ptype == "gcp-tpu":
        for key in ("project", "zone"):
            if key not in provider:
                raise LauncherError(f"provider.{key} is required for gcp-tpu")
        if not config.get("tpu_node_types"):
            raise LauncherError("gcp-tpu needs a tpu_node_types section")
    config.setdefault("auth", {})
    config.setdefault("file_mounts", {})
    config.setdefault("initialization_commands", [])
    config.setdefault("setup_commands", [])
    config.setdefault("head_setup_commands", [])
    config.setdefault("worker_setup_commands", [])
    config.setdefault("head_start_command", DEFAULT_HEAD_START)
    config.setdefault("worker_start_command", DEFAULT_WORKER_START)
    config.setdefault("stop_command", DEFAULT_STOP)
    # reject an unknowable GCS address BEFORE provisioning anything
    _extract_port(config["head_start_command"])
    return config


# --------------------------------------------------------------- runners


class CommandRunner:
    """Runs shell commands / syncs files on one node."""

    def run(self, cmd: str, env: Optional[Dict[str, str]] = None,
            timeout: float = 600.0) -> str:
        raise NotImplementedError

    def sync(self, local: str, remote: str) -> None:
        raise NotImplementedError


class SSHCommandRunner(CommandRunner):
    """ssh/rsync with connection multiplexing (reference:
    command_runner.py:159 — same ControlMaster trick so N setup commands
    pay one handshake)."""

    def __init__(self, ip: str, auth: dict, cluster_name: str):
        self.ip = ip
        self.user = auth.get("ssh_user", "")
        self.key = os.path.expanduser(auth.get("ssh_private_key", "")) or None
        control_dir = os.path.join(
            os.environ.get("TMPDIR", "/tmp"), "rtpu_ssh", cluster_name
        )
        os.makedirs(control_dir, exist_ok=True)
        self._opts = [
            "-o", "StrictHostKeyChecking=no",
            "-o", "UserKnownHostsFile=/dev/null",
            "-o", "LogLevel=ERROR",
            "-o", "ConnectTimeout=10",
            "-o", "ControlMaster=auto",
            "-o", f"ControlPath={control_dir}/%r@%h:%p",
            "-o", "ControlPersist=60s",
        ]
        if self.key:
            self._opts += ["-i", self.key]

    def _target(self) -> str:
        return f"{self.user}@{self.ip}" if self.user else self.ip

    def run(self, cmd: str, env: Optional[Dict[str, str]] = None,
            timeout: float = 600.0) -> str:
        exports = "".join(
            f"export {k}={shlex.quote(str(v))}; " for k, v in (env or {}).items()
        )
        full = ["ssh"] + self._opts + [self._target(),
                                       f"bash -lc {shlex.quote(exports + cmd)}"]
        proc = subprocess.run(
            full, capture_output=True, text=True, timeout=timeout
        )
        if proc.returncode != 0:
            raise LauncherError(
                f"[{self.ip}] `{cmd}` failed (rc={proc.returncode}):\n"
                f"{proc.stdout}\n{proc.stderr}"
            )
        return proc.stdout

    def sync(self, local: str, remote: str) -> None:
        ssh_cmd = " ".join(["ssh"] + [shlex.quote(o) for o in self._opts])
        if os.path.isdir(local):
            # trailing slash: copy CONTENTS into `remote` (same semantics
            # as the process runner's copytree), not a nested dir
            local = local.rstrip("/") + "/"
            self.run(f"mkdir -p {shlex.quote(remote)}")
        else:
            self.run(f"mkdir -p {shlex.quote(os.path.dirname(remote) or '/')}")
        proc = subprocess.run(
            ["rsync", "-az", "-e", ssh_cmd, local,
             f"{self._target()}:{remote}"],
            capture_output=True, text=True, timeout=600,
        )
        if proc.returncode != 0:
            raise LauncherError(
                f"[{self.ip}] rsync {local} -> {remote} failed:\n{proc.stderr}"
            )


class ProcessCommandRunner(CommandRunner):
    """Runs "remote" commands as local subprocesses in a per-node state
    dir — the fake-multinode provider's runner. Each logical node gets its
    own RTPU_STATE_FILE and TMPDIR so head/workers on one machine don't
    clobber each other."""

    def __init__(self, ip: str, node_dir: str):
        self.ip = ip
        self.node_dir = node_dir
        os.makedirs(node_dir, exist_ok=True)

    def run(self, cmd: str, env: Optional[Dict[str, str]] = None,
            timeout: float = 600.0) -> str:
        full_env = dict(os.environ)
        full_env.update(env or {})
        full_env["RTPU_STATE_FILE"] = os.path.join(self.node_dir, "state.json")
        # `ray-tpu` resolves through the current interpreter even when the
        # console script isn't on PATH (test environments).
        from ray_tpu._private import repo_root

        full_env["PYTHONPATH"] = (
            repo_root() + os.pathsep + full_env.get("PYTHONPATH", "")
        )
        cmd = cmd.replace("ray-tpu ", f"{sys.executable} -m ray_tpu.scripts ")
        proc = subprocess.run(
            ["bash", "-c", cmd], capture_output=True, text=True,
            timeout=timeout, env=full_env, cwd=self.node_dir,
        )
        if proc.returncode != 0:
            raise LauncherError(
                f"[{self.ip}] `{cmd}` failed (rc={proc.returncode}):\n"
                f"{proc.stdout}\n{proc.stderr}"
            )
        return proc.stdout

    def sync(self, local: str, remote: str) -> None:
        import shutil

        dest = os.path.join(self.node_dir, remote.lstrip("/"))
        os.makedirs(os.path.dirname(dest), exist_ok=True)
        if os.path.isdir(local):
            shutil.copytree(local, dest, dirs_exist_ok=True)
        else:
            shutil.copy2(local, dest)


# --------------------------------------------------------------- updater


class NodeUpdater:
    """Brings one node from bare to running: wait-for-reachable, sync file
    mounts, initialization + setup commands, start command (reference:
    updater.py:40 NodeUpdater.run)."""

    def __init__(self, ip: str, runner: CommandRunner, config: dict,
                 head_ip: str, is_head: bool):
        self.ip = ip
        self.runner = runner
        self.config = config
        self.head_ip = head_ip
        self.is_head = is_head
        self.error: Optional[Exception] = None

    def _env(self) -> Dict[str, str]:
        return {
            "RTPU_NODE_IP": self.ip,
            "RTPU_HEAD_IP": self.head_ip,
            "RTPU_CLUSTER_NAME": self.config["cluster_name"],
        }

    def wait_ready(self, timeout: float = 120.0):
        deadline = time.time() + timeout
        last = None
        while time.time() < deadline:
            try:
                self.runner.run("uptime", timeout=15)
                return
            except Exception as e:
                last = e
                time.sleep(3)
        raise LauncherError(f"node {self.ip} never became reachable: {last}")

    def update(self):
        try:
            self.wait_ready()
            for remote, local in self.config["file_mounts"].items():
                self.runner.sync(os.path.expanduser(local), remote)
            env = self._env()
            commands = list(self.config["initialization_commands"])
            commands += self.config["setup_commands"]
            commands += (
                self.config["head_setup_commands"] if self.is_head
                else self.config["worker_setup_commands"]
            )
            commands.append(
                self.config["head_start_command"] if self.is_head
                else self.config["worker_start_command"]
            )
            for cmd in commands:
                print(f"[{self.ip}] $ {cmd}")
                out = self.runner.run(cmd, env=env)
                if out.strip():
                    print("\n".join(
                        f"[{self.ip}] {line}"
                        for line in out.strip().splitlines()[-5:]
                    ))
        except Exception as e:  # captured for the parallel-update driver
            self.error = e


# --------------------------------------------------------------- up/down


def _runner_for(config: dict, ip: str, node_index: int) -> CommandRunner:
    ptype = config["provider"]["type"]
    if ptype == "process":
        base = config["provider"].get(
            "state_dir",
            os.path.join(os.environ.get("TMPDIR", "/tmp"), "rtpu_fake_nodes"),
        )
        return ProcessCommandRunner(
            ip, os.path.join(base, config["cluster_name"], f"node-{node_index}")
        )
    return SSHCommandRunner(ip, config["auth"], config["cluster_name"])


def _node_ips(config: dict) -> tuple:
    provider = config["provider"]
    ptype = provider["type"]
    if ptype in ("static", "process"):
        return provider["head_ip"], list(provider.get("worker_ips", []))
    if ptype == "gcp-tpu":
        from ray_tpu.autoscaler.gcp_tpu import cluster_ips

        return cluster_ips(_gcp_provider(config), config)
    if ptype == "command":
        # Elastic: shell templates create the fleet, then report its IPs.
        create = provider["create_command"]  # $RTPU_NODE_COUNT substituted
        n = int(provider.get("num_workers", 0)) + 1
        out = subprocess.run(
            ["bash", "-c", create.replace("$RTPU_NODE_COUNT", str(n))],
            capture_output=True, text=True, timeout=1800,
        )
        if out.returncode != 0:
            raise LauncherError(f"create_command failed:\n{out.stderr}")
        ips = out.stdout.split()
        if len(ips) < n:
            raise LauncherError(
                f"create_command printed {len(ips)} IPs, need {n}"
            )
        return ips[0], ips[1:n]
    raise LauncherError(f"unknown provider type {ptype}")


def up(config_path: str) -> dict:
    """Provision + bootstrap the cluster; returns {head_ip, gcs_address}."""
    config = load_cluster_config(config_path)
    head_ip, worker_ips = _node_ips(config)
    print(f"cluster '{config['cluster_name']}': head {head_ip}, "
          f"{len(worker_ips)} workers")

    head = NodeUpdater(
        head_ip, _runner_for(config, head_ip, 0), config, head_ip, True
    )
    head.update()
    if head.error:
        raise LauncherError(f"head bootstrap failed: {head.error}")

    updaters = [
        NodeUpdater(ip, _runner_for(config, ip, i + 1), config, head_ip, False)
        for i, ip in enumerate(worker_ips)
    ]
    threads = [
        threading.Thread(target=u.update, daemon=True) for u in updaters
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    failed = [u for u in updaters if u.error]
    if failed:
        raise LauncherError(
            "; ".join(f"{u.ip}: {u.error}" for u in failed)
        )
    gcs_port = _extract_port(config["head_start_command"])
    print(f"cluster up: connect with ray_tpu.init("
          f"address='{head_ip}:{gcs_port}')")
    return {"head_ip": head_ip, "gcs_address": f"{head_ip}:{gcs_port}"}


def _extract_port(head_start_command: str) -> int:
    toks = head_start_command.split()
    port = None
    for i, t in enumerate(toks):
        if t == "--port" and i + 1 < len(toks):
            port = int(toks[i + 1])
        elif t.startswith("--port="):
            port = int(t.split("=", 1)[1])
    if not port:  # absent or explicit 0 (auto): the address is unknowable
        raise LauncherError(
            "head_start_command must pin a fixed --port so workers and "
            "drivers can address the GCS (auto ports only work "
            "single-node)"
        )
    return port


def down(config_path: str) -> None:
    """Stop every node (workers first so the head sees clean departures),
    then terminate elastic fleets."""
    config = load_cluster_config(config_path)
    head_ip, worker_ips = _node_ips_cached_or_static(config)
    stop = config["stop_command"]
    for i, ip in enumerate(worker_ips):
        try:
            _runner_for(config, ip, i + 1).run(stop, timeout=60)
            print(f"[{ip}] stopped")
        except Exception as e:
            print(f"[{ip}] stop failed: {e}", file=sys.stderr)
    if head_ip:
        try:
            _runner_for(config, head_ip, 0).run(stop, timeout=60)
            print(f"[{head_ip}] stopped")
        except Exception as e:
            print(f"[{head_ip}] stop failed: {e}", file=sys.stderr)
    terminate = config["provider"].get("terminate_command")
    if terminate:
        subprocess.run(["bash", "-c", terminate], timeout=1800)
    if config["provider"]["type"] == "gcp-tpu":
        from ray_tpu.autoscaler.gcp_tpu import teardown

        for pid in teardown(_gcp_provider(config)):
            print(f"terminated TPU slice {pid}")


def _gcp_provider(config: dict):
    from ray_tpu.autoscaler.gcp_tpu import GcpTpuNodeProvider

    provider = config["provider"]
    return GcpTpuNodeProvider(
        project=provider["project"], zone=provider["zone"],
        cluster_name=config["cluster_name"],
        node_types=config.get("tpu_node_types", {}),
        timeout_s=float(provider.get("gcloud_timeout_s", 900.0)),
    )


def _node_ips_cached_or_static(config: dict) -> tuple:
    provider = config["provider"]
    if provider["type"] in ("static", "process"):
        return provider["head_ip"], list(provider.get("worker_ips", []))
    if provider["type"] == "gcp-tpu":
        gcp = _gcp_provider(config)
        head_type = provider.get("head_type", "head")
        # Head slice first: down() stops workers before the head, so ips[0]
        # must really be the head host, whatever order gcloud lists in.
        nodes = sorted(gcp.non_terminated_nodes().items(),
                       key=lambda kv: kv[1] != head_type)
        ips: list = []
        for pid, _ntype in nodes:
            ips.extend(gcp.slice_hosts(pid))
        if not ips:
            return "", []
        return ips[0], ips[1:]
    # command provider: the operator's list_command reports the live fleet
    lister = provider.get("list_command")
    if not lister:
        raise LauncherError(
            "command provider needs list_command for `down`"
        )
    out = subprocess.run(
        ["bash", "-c", lister], capture_output=True, text=True, timeout=300
    )
    if out.returncode != 0:
        raise LauncherError(
            f"list_command failed (rc={out.returncode}):\n{out.stderr}"
        )
    ips = out.stdout.split()
    if not ips:
        return "", []
    return ips[0], ips[1:]
