"""Actor API: ActorClass / ActorHandle / ActorMethod
(reference: python/ray/actor.py — ActorClass :566, ActorHandle :1223,
ActorMethod :116)."""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional

from ray_tpu._private import task_spec as ts
from ray_tpu._private.ids import ActorID
from ray_tpu._private.worker import get_global_worker
from ray_tpu.util.scheduling_strategies import strategy_to_dict

_ACTOR_OPTION_DEFAULTS = dict(
    num_cpus=None,
    num_tpus=None,
    num_gpus=None,
    memory=None,
    resources=None,
    # None = RTPU_actor_max_restarts_default (0 unless overridden), so
    # operators can give every actor a restart budget cluster-wide without
    # touching call sites — mirrors max_retries in remote_function.py
    max_restarts=None,
    max_task_retries=0,
    max_concurrency=None,
    name=None,
    namespace=None,
    lifetime=None,
    get_if_exists=False,
    scheduling_strategy=None,
    runtime_env=None,
    max_pending_calls=-1,
)


class ActorMethod:
    def __init__(self, handle: "ActorHandle", method_name: str, num_returns: int = 1):
        self._handle = handle
        self._method_name = method_name
        self._num_returns = num_returns

    def options(self, num_returns: Optional[int] = None, name: str = ""):
        m = ActorMethod(self._handle, self._method_name, num_returns or self._num_returns)
        return m

    def bind(self, *args, **kwargs):
        """Build a DAG node calling this method on the live actor
        (reference: actor.py ActorMethod.bind for dag/compiled use)."""
        from ray_tpu.dag.node import ClassMethodNode, _LiveActorNode

        return ClassMethodNode(
            _LiveActorNode(self._handle), self._method_name, args, kwargs
        )

    def remote(self, *args, **kwargs):
        worker = get_global_worker()
        refs = worker.submit_actor_task(
            self._handle._actor_id,
            self._method_name,
            args,
            kwargs,
            num_returns=self._num_returns,
            name=f"{self._handle._class_name}.{self._method_name}",
        )
        if self._num_returns == 1:
            return refs[0]
        if self._num_returns == 0:
            return None
        return refs

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Actor method '{self._method_name}' cannot be called directly; "
            "use .remote()"
        )


class ActorHandle:
    def __init__(self, actor_id: bytes, class_name: str = "Actor", method_meta: Optional[dict] = None):
        self._actor_id = actor_id
        self._class_name = class_name
        self._method_meta = method_meta or {}

    @property
    def _actor_id_hex(self) -> str:
        return self._actor_id.hex()

    def __getattr__(self, item):
        if item == "__ray_call__":
            # run an arbitrary fn against the actor instance:
            # handle.__ray_call__.remote(lambda self, ...: ...)
            return ActorMethod(self, item, 1)
        if item.startswith("_"):
            raise AttributeError(item)
        return ActorMethod(self, item, self._method_meta.get(item, 1))

    def __repr__(self):
        return f"ActorHandle({self._class_name}, {self._actor_id.hex()[:12]})"

    def __reduce__(self):
        return (ActorHandle, (self._actor_id, self._class_name, self._method_meta))

    def __hash__(self):
        return hash(self._actor_id)

    def __eq__(self, other):
        return isinstance(other, ActorHandle) and other._actor_id == self._actor_id


class ActorClass:
    def __init__(self, cls, options: Optional[Dict[str, Any]] = None):
        self._cls = cls
        self._options = dict(_ACTOR_OPTION_DEFAULTS)
        if options:
            self._apply(options)
        functools.update_wrapper(self, cls, updated=[])

    def _apply(self, overrides):
        for k, v in overrides.items():
            if k not in _ACTOR_OPTION_DEFAULTS:
                raise ValueError(f"unknown option '{k}' for actor")
            self._options[k] = v

    def options(self, **overrides) -> "ActorClass":
        ac = ActorClass(self._cls, None)
        ac._options = dict(self._options)
        ac._apply(overrides)
        return ac

    def remote(self, *args, **kwargs) -> ActorHandle:
        worker = get_global_worker()
        o = self._options
        if o["num_gpus"]:
            raise ValueError("num_gpus is not supported on a TPU cluster; use num_tpus")
        if o["get_if_exists"] and o["name"]:
            try:
                return get_actor(o["name"], o["namespace"])
            except ValueError:
                pass
        resources = ts.normalize_resources(
            o["num_cpus"], o["num_tpus"], o["memory"], o["resources"], default_cpus=1.0
        )
        max_restarts = o["max_restarts"]
        if max_restarts is None:
            from ray_tpu._private.config import RTPU_CONFIG

            max_restarts = RTPU_CONFIG.actor_max_restarts_default
        actor_id = worker.create_actor(
            self._cls,
            args,
            kwargs,
            name=o["name"] or "",
            namespace=o["namespace"] or "",
            resources=resources,
            max_restarts=max_restarts,
            max_concurrency=o["max_concurrency"] or 1,
            lifetime=o["lifetime"] or "",
            scheduling_strategy=strategy_to_dict(o["scheduling_strategy"]),
            runtime_env=o["runtime_env"],
        )
        method_meta = {
            m: getattr(getattr(self._cls, m), "_rtpu_num_returns")
            for m in dir(self._cls)
            if hasattr(getattr(self._cls, m, None), "_rtpu_num_returns")
        }
        return ActorHandle(actor_id, self._cls.__name__, method_meta)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Actor class '{self._cls.__name__}' cannot be instantiated directly; "
            "use .remote()"
        )

    def bind(self, *args, **kwargs):
        from ray_tpu.dag.node import ClassNode

        return ClassNode(self, args, kwargs)


def method(num_returns: int = 1):
    """Per-method option decorator (reference: python/ray/actor.py ray.method)."""

    def deco(fn):
        fn._rtpu_num_returns = num_returns
        return fn

    return deco


def get_actor(name: str, namespace: Optional[str] = None) -> ActorHandle:
    worker = get_global_worker()
    reply = worker.gcs.call(
        "GetActorByName", {"name": name, "namespace": namespace or ""}
    )
    if not reply.get("found"):
        raise ValueError(f"no actor named '{name}'")
    rec = reply["actor"]
    if rec["state"] == "DEAD":
        raise ValueError(f"actor '{name}' is dead")
    return ActorHandle(rec["actor_id"], name)
