"""Operator CLI: ``python -m ray_tpu.scripts <command>``.

Counterpart of the reference's ``ray`` CLI
(reference: python/ray/scripts/scripts.py — start :571, stop, status,
memory, timeline, logs, plus the job CLI dashboard/modules/job/cli.py).

Commands:
  start --head [--port P] [--resources JSON] [--dashboard-port P]
  start --address HOST:PORT [--resources JSON]     (worker node)
  stop
  status   [--address]
  nodes    [--address]
  actors   [--address]
  memory   [--address] [--group-by job|actor|node] [--leaks]
           [--sort-by size|plasma|rss|objects]
                                 cluster memory report: per-node object
                                 store, rollups unifying plasma/RSS/HBM,
                                 top owned objects w/ callsites; --leaks
                                 runs the leak detector w/ attribution
  timeline [--address] [--job HEX] [--trace-id ID] -o FILE
                                 Chrome-trace dump (filters server-side;
                                 spill/restore/leak instants fanned in
                                 from raylet flight rings)
  profile  [--address] [--duration S] [--hz N] [--node HEX] [-o FILE]
                                 cluster-wide CPU capture merged with the
                                 task timeline (Perfetto JSON); --flame for
                                 folded stacks, --pid N for one worker
  grafana  [-o FILE]             generated Grafana dashboard JSON
  perf check   [--only SUBSTR] [--quick] [--history FILE] [--update]
               [--strict]        run microbench metrics and gate them
                                 against the PERF_HISTORY.jsonl baseline
                                 (exit 1 on regression beyond noise band;
                                 advisory on 1-core boxes unless --strict)
  perf compare BASE HEAD [-o FILE] [--skip-noisy]
                                 gate two microbench --json result files
                                 (the CI A/B path, perf.yml)
  perf history [--metric M] [--limit N]
                                 print the perf ledger trajectory
  lint [PATHS...] [--baseline F] [--update-baseline] [--json] [--verbose]
                                 invariant lint plane: stability-contract
                                 cross-check (flags/metrics/events/chaos
                                 sites), shard-safety/thread-ownership
                                 analysis, blocking-call-in-coroutine
                                 detection; exit 1 on findings not in the
                                 committed baseline (CI gate)
  job submit  --address ADDR -- ENTRYPOINT...
  job status  --address ADDR SUBMISSION_ID
  job logs    --address ADDR SUBMISSION_ID
  job stop    --address ADDR SUBMISSION_ID
  job list    --address ADDR
"""

from __future__ import annotations

import argparse
import json
import os
import sys

# Overridable so a launcher driving several logical nodes on one machine
# (fake multi-node e2e) can keep per-node state files.
_STATE_FILE = os.environ.get("RTPU_STATE_FILE") or os.path.join(
    os.environ.get("TMPDIR", "/tmp"), "ray_tpu", "cli_cluster.json"
)


def _resolve_address(args) -> str:
    addr = getattr(args, "address", None) or os.environ.get("RTPU_ADDRESS")
    if not addr and os.path.exists(_STATE_FILE):
        with open(_STATE_FILE) as f:
            addr = json.load(f).get("gcs_address")
    if not addr:
        sys.exit("no cluster address: pass --address or set RTPU_ADDRESS")
    return addr


def cmd_start(args):
    from ray_tpu._private.node import Node

    resources = json.loads(args.resources) if args.resources else None
    if args.head:
        node = Node(head=True, resources=resources, host=args.host,
                    gcs_port=args.port)
        info = {
            "gcs_address": node.gcs_address,
            "session_dir": node.session_dir,
            "pids": [p.pid for p in node.processes.values()],
        }
        if args.dashboard_port >= 0:
            import subprocess

            port_file = os.path.join(node.session_dir, "dashboard_port")
            env = dict(os.environ)
            from ray_tpu._private import repo_root

            env["PYTHONPATH"] = (
                repo_root() + os.pathsep + env.get("PYTHONPATH", "")
            )
            dash_out = open(
                os.path.join(node.session_dir, "logs", "dashboard.out"), "ab"
            )
            dash_err = open(
                os.path.join(node.session_dir, "logs", "dashboard.err"), "ab"
            )
            dash = subprocess.Popen(
                [
                    sys.executable, "-m", "ray_tpu.dashboard.head",
                    f"--gcs-address={node.gcs_address}",
                    f"--port={args.dashboard_port}",
                    f"--port-file={port_file}",
                ],
                env=env,
                stdout=dash_out,
                stderr=dash_err,
                start_new_session=True,
            )
            info["pids"].append(dash.pid)
            from ray_tpu._private.node import _wait_port_file

            info["dashboard_port"] = _wait_port_file(port_file, dash)
            print(f"dashboard: http://127.0.0.1:{info['dashboard_port']}")
        info["role"] = "head"
        _record_node(info, replace=True)
        print(f"head started; GCS at {node.gcs_address}")
        print(f"connect with: ray_tpu.init(address='{node.gcs_address}')")
        # The supervising Node object must stay alive for the GCS monitor;
        # detach by keeping this process around unless --block=false-like
        # behavior is wanted. The processes themselves are daemons of no
        # one (start_new_session), so exiting here is safe: monitoring
        # simply stops.
        node._gcs_monitor = None
    else:
        addr = _resolve_address(args)
        node = Node(head=False, gcs_address=addr, resources=resources,
                    host=args.host)
        # Appended (never replacing) so head+worker on one machine — or
        # several workers — all stay stoppable by `ray-tpu stop`.
        _record_node({
            "role": "worker",
            "gcs_address": addr,
            "session_dir": node.session_dir,
            "pids": [p.pid for p in node.processes.values()],
        }, replace=False)
        print(f"worker node started; raylet on port {node.raylet_port}")


def _record_node(entry: dict, *, replace: bool):
    """State file holds EVERY node started on this machine:
    {"gcs_address": ..., "nodes": [{role, session_dir, pids}, ...]} —
    `stop` tears all of them down. A head start replaces the record (new
    cluster); workers append."""
    os.makedirs(os.path.dirname(_STATE_FILE), exist_ok=True)
    state = {"nodes": []}
    if not replace and os.path.exists(_STATE_FILE):
        try:
            with open(_STATE_FILE) as f:
                state = json.load(f)
        except (json.JSONDecodeError, OSError):
            state = {"nodes": []}
        if "nodes" not in state:  # legacy single-entry format
            state = {"gcs_address": state.get("gcs_address", ""),
                     "nodes": [state]}
    state.setdefault("nodes", [])
    state["nodes"].append(entry)
    if entry.get("gcs_address"):
        state["gcs_address"] = entry["gcs_address"]
    if "dashboard_port" in entry:
        state["dashboard_port"] = entry["dashboard_port"]
    with open(_STATE_FILE, "w") as f:
        json.dump(state, f)


def cmd_stop(args):
    import signal

    if not os.path.exists(_STATE_FILE):
        sys.exit("no recorded cluster (started with this CLI?)")
    with open(_STATE_FILE) as f:
        state = json.load(f)
    nodes = state.get("nodes")
    if nodes is None:  # legacy single-entry format
        nodes = [state]
    for entry in nodes:
        for pid in entry.get("pids", []):
            try:
                os.kill(pid, signal.SIGTERM)
                print(f"stopped pid {pid}")
            except ProcessLookupError:
                pass
    os.remove(_STATE_FILE)


def cmd_up(args):
    from ray_tpu.autoscaler.launcher import up

    up(args.config)


def cmd_down(args):
    from ray_tpu.autoscaler.launcher import down

    down(args.config)


def cmd_status(args):
    from ray_tpu._private.gcs.client import GcsClient

    addr = _resolve_address(args)
    gcs = GcsClient.from_address(addr)
    res = gcs.get_cluster_resources()
    nodes = gcs.get_all_node_info()
    alive = [n for n in nodes if n["state"] == "ALIVE"]
    print(f"cluster at {addr}: {len(alive)} alive / {len(nodes)} total nodes")
    print("resources:")
    for k in sorted(res["total"]):
        print(f"  {res['available'].get(k, 0):.1f}/{res['total'][k]:.1f} {k}")
    # Memory visibility without running `memory`: per-node object-store
    # utilization + the top-consuming job, from the same aggregation path.
    try:
        from ray_tpu.util import state as _state

        report = _state.memory_report(addr, include_objects=True,
                                      include_drivers=False)
        print("object store:")
        for node in report["nodes"]:
            s = node.get("plasma", {})
            cap = s.get("capacity_bytes") or 0
            used = s.get("used_bytes") or 0
            pct = f" ({100.0 * used / cap:.0f}%)" if cap else ""
            print(f"  {node['node_id'][:12]}: {_fmt_bytes(used)}/"
                  f"{_fmt_bytes(cap)}{pct} used, "
                  f"{_fmt_bytes(node['pinned_bytes'])} pinned"
                  + (f", {len(node['leaks'])} leaked objects"
                     if node.get("leaks") else ""))
        rollup = _state.memory_rollup(report, group_by="job")
        rollup.pop("?", None)
        if rollup:
            top_job, r = max(
                rollup.items(),
                key=lambda kv: kv[1]["plasma_bytes"] + kv[1]["rss_bytes"])
            print(f"  top job: {top_job[:12]} — "
                  f"{_fmt_bytes(r['plasma_bytes'])} plasma, "
                  f"{_fmt_bytes(r['rss_bytes'])} rss, "
                  f"{r['objects']} objects")
    except Exception:
        print("object store: unavailable")
    # Stall visibility without running `debug`: the watchdogs publish
    # incidents to the GCS; a non-zero count here is the first hint.
    try:
        open_count = gcs.call("ListIncidents", {"limit": 1}).get("open", 0)
    except Exception:
        open_count = None
    if open_count is None:
        print("incidents: unavailable")
    else:
        print(f"incidents: {open_count} open"
              + (" (run `ray-tpu debug incidents`)" if open_count else ""))


def cmd_nodes(args):
    from ray_tpu.util import state

    for n in state.list_nodes(_resolve_address(args)):
        print(
            f"{n['node_id'][:12]} {n['state']:<6} {n['node_ip']}:"
            f"{n['raylet_port']} head={n['is_head_node']} {n['resources_total']}"
        )


def cmd_actors(args):
    from ray_tpu.util import state

    for a in state.list_actors(_resolve_address(args)):
        name = a["name"] or "-"
        print(f"{a['actor_id'][:12]} {a['state']:<8} name={name}")


def _fmt_bytes(n) -> str:
    from ray_tpu._private.memory_report import _fmt_bytes as f

    return f(n)


def cmd_memory(args):
    """Memory observability plane: per-node object-store state, per-group
    rollups (job/actor/node) unifying plasma + RSS + HBM, the largest
    owned objects with creation callsites, and (--leaks) the leak
    detector's findings with attribution."""
    from ray_tpu.util import state

    addr = _resolve_address(args)
    group_by = getattr(args, "group_by", "job") or "job"
    sort_by = getattr(args, "sort_by", "size") or "size"

    if getattr(args, "leaks", False):
        leaks = state.find_memory_leaks(addr, sweep=True)
        if not leaks:
            print("no leaked objects detected "
                  "(pinned primaries all have live owner references)")
            return
        print(f"{len(leaks)} leaked object(s), "
              f"{_fmt_bytes(sum(l.get('size') or 0 for l in leaks))} total:")
        for l in leaks:
            where = f" @ {l['callsite']}" if l.get("callsite") else ""
            owner = (f" actor={l['actor_id'][:12]}" if l.get("actor_id")
                     else "")
            print(f"  {l['object_id'][:12]} {_fmt_bytes(l.get('size'))} "
                  f"node={l['node_id'][:12]} job={l['job_id'][:12] or '?'}"
                  f"{owner}{where}"
                  + (" [spilled]" if l.get("spilled") else ""))
        print("details: `ray-tpu debug incidents` (kind=object_leak)")
        return

    report = state.memory_report(addr)
    for node in report["nodes"]:
        s = node.get("plasma", {})
        cap = s.get("capacity_bytes") or 0
        used = s.get("used_bytes") or 0
        pct = f" ({100.0 * used / cap:.0f}%)" if cap else ""
        leak_note = (f", {len(node['leaks'])} LEAKED"
                     if node.get("leaks") else "")
        print(f"node {node['node_id'][:12]}: object store "
              f"{_fmt_bytes(used)}/{_fmt_bytes(cap)}{pct}, "
              f"{node['pinned_count']} pinned "
              f"({_fmt_bytes(node['pinned_bytes'])}), "
              f"{node['spilled_count']} spilled "
              f"({_fmt_bytes(node['spilled_bytes'])}), "
              f"raylet rss {_fmt_bytes(node['raylet_rss'])}{leak_note}")
    rollup = state.memory_rollup(report, group_by=group_by)
    sort_key = {
        "size": lambda kv: -(kv[1]["plasma_bytes"] + kv[1]["rss_bytes"]),
        "plasma": lambda kv: -kv[1]["plasma_bytes"],
        "rss": lambda kv: -kv[1]["rss_bytes"],
        "objects": lambda kv: -kv[1]["objects"],
    }.get(sort_by, lambda kv: -(kv[1]["plasma_bytes"] + kv[1]["rss_bytes"]))
    if rollup:
        print(f"\nby {group_by}:")
        hdr = (f"  {'key':<14} {'plasma':>10} {'objects':>8} "
               f"{'spilled':>10} {'rss':>10} {'hbm':>10} {'leaked':>10}")
        print(hdr)
        for key, r in sorted(rollup.items(), key=sort_key):
            print(f"  {key[:14]:<14} {_fmt_bytes(r['plasma_bytes']):>10} "
                  f"{r['objects']:>8} {_fmt_bytes(r['spilled_bytes']):>10} "
                  f"{_fmt_bytes(r['rss_bytes']):>10} "
                  f"{_fmt_bytes(r['hbm_bytes']):>10} "
                  f"{_fmt_bytes(r['leaked_bytes']):>10}")
    # top holders across every ledger, largest first
    holders = []
    for node in report["nodes"]:
        for w in node["workers"]:
            for row in w.get("ledger", []):
                holders.append((row, w))
    for w in report.get("drivers", []):
        for row in w.get("ledger", []):
            holders.append((row, w))
    holders.sort(key=lambda t: -(t[0].get("size") or 0))
    shown = [h for h in holders[:10] if (h[0].get("size") or 0) > 0]
    if shown:
        print("\ntop owned objects:")
        for row, w in shown:
            owner = (f"actor {w['actor_id'][:12]}" if w.get("actor_id")
                     else w.get("mode", "worker"))
            where = row.get("callsite") or "?"
            print(f"  {row['object_id'][:12]} {_fmt_bytes(row['size']):>10} "
                  f"age={row.get('age_s', 0):.0f}s "
                  f"{'plasma ' if row.get('plasma') else ''}"
                  f"owner={owner} @ {where}")
    if not report["nodes"]:
        print("no alive nodes")


def cmd_profile(args):
    """Profiling plane, two modes:

    With ``--pid``: on-demand stack sampling of ONE worker (reference:
    `ray`'s dashboard py-spy integration), flamegraph-folded output —
    shares the dashboard endpoint's fan-out, ambiguity guard and errors.

    Without ``--pid``: a CLUSTER-WIDE capture — every raylet, its live
    workers and the GCS sample one synchronized window
    (StartProfile/CollectProfile fan-out) and the samples merge with
    task/span events and registered device traces into one
    Perfetto-loadable JSON (``-o``, default profile.json). ``--flame``
    emits the aggregated folded stacks instead (flamegraph.pl/speedscope
    input)."""
    from ray_tpu._private.gcs.client import GcsClient
    from ray_tpu._private.profiling import profile_via_raylets

    gcs = GcsClient.from_address(_resolve_address(args))
    if args.pid is None:
        return _cluster_profile(args, gcs)
    status, payload = profile_via_raylets(
        gcs.get_all_node_info(), pid=args.pid,
        node_filter=args.node_id, duration=args.duration, hz=args.hz,
    )
    if status != 200:
        print(f"error ({status}): {payload.get('error')}", file=sys.stderr)
        sys.exit(1)
    out = payload["folded"]
    if args.output:
        with open(args.output, "w") as f:
            f.write(out + "\n")
        print(f"wrote {payload['samples']} samples to {args.output}")
    else:
        print(out)


def _cluster_profile(args, gcs):
    from ray_tpu._private import profiling
    from ray_tpu._private.timeline import merged_profile_trace

    bundle = profiling.capture_cluster_profile(
        gcs.get_all_node_info(), gcs,
        duration=args.duration, hz=args.hz, node_filter=args.node_id,
    )
    all_profiles = (
        [p for n in bundle["nodes"] for p in n["profiles"]]
        + bundle.get("drivers", [])
        + ([bundle["gcs"]] if bundle.get("gcs") else [])
    )
    n_profiles = len(all_profiles)
    n_samples = sum(len(p["samples"]) for p in all_profiles)
    for err in bundle["errors"]:
        print(f"warning: {err}", file=sys.stderr)
    if args.flame:
        folded = profiling.fold_bundle(bundle)
        text = "\n".join(
            f"{stack} {c}"
            for stack, c in sorted(folded.items(), key=lambda kv: -kv[1])
        )
        if args.output:
            with open(args.output, "w") as f:
                f.write(text + "\n")
            print(f"wrote {n_samples} samples from {n_profiles} processes "
                  f"to {args.output}")
        else:
            print(text)
        return
    try:
        task_events = gcs.call("GetTaskEvents", {"limit": 100_000})["events"]
    except Exception:
        task_events = []
    device = profiling.list_registered(gcs, "device_trace")
    trace = merged_profile_trace(bundle, task_events, device)
    out = args.output or "profile.json"
    with open(out, "w") as f:
        json.dump(trace, f)
    profiling.register_capture(gcs, os.path.abspath(out), reason="cli")
    print(f"wrote {len(trace['traceEvents'])} events "
          f"({n_samples} CPU samples from {n_profiles} processes) to {out}")
    print("open in https://ui.perfetto.dev or chrome://tracing")


def cmd_grafana(args):
    """Dump the generated Grafana dashboard JSON (reference:
    grafana_dashboard_factory.py)."""
    from ray_tpu.dashboard.grafana import dashboard_json

    if args.output:
        with open(args.output, "w") as f:
            f.write(dashboard_json())
        print(f"wrote dashboard to {args.output}")
    else:
        print(dashboard_json())


def cmd_timeline(args):
    from ray_tpu._private.gcs.client import GcsClient
    from ray_tpu._private.timeline import (
        chrome_trace_events, flight_instant_events)

    addr = _resolve_address(args)
    gcs = GcsClient.from_address(addr)
    req = {"limit": 100_000}
    if getattr(args, "job", None):
        req["job_id"] = args.job
    if getattr(args, "trace_id", None):
        req["trace_id"] = args.trace_id
    events = chrome_trace_events(gcs.call("GetTaskEvents", req)["events"])
    # Object-plane instants (spill/restore/leak) live in the raylets'
    # flight-recorder rings, not the GCS task-event log — fan them in so
    # "the step stalled while the store was spilling" is one view.
    if not getattr(args, "no_object_events", False):
        from ray_tpu.util import state

        try:
            for n, reply in state._fanout_raylets(
                addr, "DumpFlightRecorder", timeout=15,
                payload={"include_workers": False},
            ):
                events.extend(flight_instant_events(
                    n["node_id"].hex(), reply.get("events", [])))
        except Exception as e:
            print(f"warning: object-event fan-in failed: {e}",
                  file=sys.stderr)
        events.sort(key=lambda e: e["ts"])
    with open(args.output, "w") as f:
        json.dump(events, f)
    print(f"wrote {len(events)} events to {args.output}")


def collect_debug_dump(address: str, *, ring_limit: int = 1000,
                       stack_duration: float = 0.3) -> dict:
    """Gather the whole cluster's forensics into {archive_name: text}.

    One pass over the live cluster: state-API listings, the GCS incident
    table (full detail), every raylet's flight-recorder ring fanned in with
    its live workers' rings, per-node object-store stats, and a stack
    sample of every live worker. This is the "why did step 4017 never
    finish" bundle — callable from tests; `ray-tpu debug dump` zips it.
    """
    from ray_tpu._private.gcs.client import GcsClient
    from ray_tpu.util import state

    gcs = GcsClient.from_address(address)
    files: dict = {}

    def put_json(name, obj):
        files[name] = json.dumps(obj, indent=2, default=repr)

    # 1. state-API listings
    listings = {
        "nodes": state.list_nodes,
        "actors": state.list_actors,
        "jobs": state.list_jobs,
        "placement_groups": state.list_placement_groups,
        "tasks": state.list_tasks,
        "workers": state.list_workers,
        "objects": state.list_objects,
    }
    for name, fn in listings.items():
        try:
            put_json(f"state/{name}.json", fn(address))
        except Exception as e:
            files[f"state/{name}.json"] = json.dumps({"error": str(e)})
    # 2. incidents (full detail: stacks + rings)
    try:
        put_json("incidents.json",
                 state.list_incidents(address, limit=500, detail=True))
    except Exception as e:
        files["incidents.json"] = json.dumps({"error": str(e)})
    # 3. profiling plane: the capture registry (triggered + on-demand
    #    cluster profiles, device-trace dirs) and the latest capture files
    #    themselves when they're readable from this host
    try:
        from ray_tpu._private import profiling as _prof

        caps = _prof.list_registered(gcs, "capture")
        put_json("profiles/index.json", {
            "captures": caps,
            "device_traces": _prof.list_registered(gcs, "device_trace"),
        })
        for rec in caps[-3:]:
            path = rec.get("path", "")
            try:
                if (path and os.path.isfile(path)
                        and os.path.getsize(path) <= 64 * 1024 * 1024):
                    with open(path) as f:
                        files[f"profiles/{os.path.basename(path)}"] = f.read()
            except OSError:
                continue
    except Exception as e:
        files["profiles/index.json"] = json.dumps({"error": str(e)})
    # 4. cluster config snapshot + the GCS's own ring (a control-plane
    #    stall is as diagnosable as a data-plane one)
    try:
        put_json("config.json", gcs.call("GetInternalConfig", {}))
    except Exception:
        pass
    try:
        put_json("flight/gcs.json",
                 gcs.call("DumpFlightRecorder", {"limit": ring_limit}))
    except Exception:
        pass
    # 5. per-node: flight rings (raylet + its live workers), object-store
    #    stats, and all-worker stacks
    for n, reply in state._fanout_raylets(
        address, "DumpFlightRecorder", timeout=30,
        payload={"limit": ring_limit, "include_workers": True},
    ):
        node = n["node_id"].hex()[:12]
        put_json(f"flight/node_{node}.json", {
            "node_id": n["node_id"].hex(),
            "raylet_events": reply.get("events", []),
            "workers": [
                {"worker_id": w.get("worker_id", b"").hex()
                 if isinstance(w.get("worker_id"), bytes)
                 else str(w.get("worker_id")),
                 "pid": w.get("pid"),
                 "events": w.get("events", [])}
                for w in reply.get("workers", [])
            ],
        })
    for n, reply in state._fanout_raylets(address, "GetNodeInfo", timeout=15):
        node = n["node_id"].hex()[:12]
        put_json(f"nodes/node_{node}.json", reply)
    # 5b. memory plane: per-node memory reports (plasma/pin/spill tables
    #     joined with worker ownership ledgers) + the cluster rollup —
    #     the "who was holding what" half of a hang/OOM post-mortem
    try:
        report = state.memory_report(address)
        for node in report["nodes"]:
            put_json(f"memory/node_{node['node_id'][:12]}.json", node)
        put_json("memory/rollup.json", {
            gb: state.memory_rollup(report, group_by=gb)
            for gb in ("job", "actor", "node")
        })
        put_json("memory/drivers.json", report.get("drivers", []))
    except Exception as e:
        files["memory/rollup.json"] = json.dumps({"error": str(e)})
    for n, reply in state._fanout_raylets(
        address, "GetLocalWorkerInfo", timeout=15
    ):
        node = n["node_id"].hex()[:12]
        sections = []
        for w in reply.get("workers", []):
            if not w.get("alive"):
                continue
            try:
                from ray_tpu._private.profiling import profile_via_raylets

                status, payload = profile_via_raylets(
                    [n], worker_id=w["worker_id"],
                    duration=stack_duration, hz=100.0,
                )
            except Exception as e:
                status, payload = 500, {"error": str(e)}
            head = (f"== worker {w['worker_id'].hex()[:12]} pid={w.get('pid')}"
                    f" leased={w.get('leased')} ==")
            body = (payload.get("folded", "") if status == 200
                    else f"<error: {payload.get('error')}>")
            sections.append(f"{head}\n{body}\n")
        files[f"stacks/node_{node}.txt"] = "\n".join(sections) or "<no live workers>\n"
    return files


def cmd_debug(args):
    """Hang/crash forensics: `debug dump` writes one archive with the
    cluster's full debugging state; `debug incidents` lists watchdog
    incidents."""
    addr = _resolve_address(args)
    if args.debug_cmd == "incidents":
        from ray_tpu.util import state

        incidents = state.list_incidents(addr, limit=args.limit)
        if not incidents:
            print("no incidents")
            return
        for i in incidents:
            import datetime

            t = datetime.datetime.fromtimestamp(i.get("time", 0))
            print(f"{i.get('id', '?')}  {t:%H:%M:%S}  "
                  f"{i.get('kind', '?'):<12} [{i.get('source', '?')}] "
                  f"{i.get('detail', '')}")
        return
    # dump
    import time as _time
    import zipfile

    out = args.output or f"ray_tpu_debug_{int(_time.time())}.zip"
    files = collect_debug_dump(addr, ring_limit=args.ring_limit)
    with zipfile.ZipFile(out, "w", zipfile.ZIP_DEFLATED) as z:
        for name, text in sorted(files.items()):
            z.writestr(name, text)
    print(f"wrote {len(files)} files to {out}")


def cmd_perf(args):
    """Perf regression plane (no cluster address needed — the bench boots
    its own): `check` measures now and gates against the ledger head,
    `compare` gates two saved measurements (CI), `history` prints the
    ledger. Exit code 1 = regression beyond the noise band."""
    from ray_tpu._private import perf_gate as pg

    if args.perf_cmd == "check":
        report, _result = pg.check(
            only=args.only, quick=args.quick, history=args.history,
            update=args.update, note=args.note)
        exit_fail = report["status"] == "fail"
        if exit_fail and pg.is_noisy_runner() and not args.strict:
            # Cross-TIME comparison on a single-core box: co-tenant load is
            # indistinguishable from a code regression (the CI A/B measures
            # base and head back-to-back instead, so it stays strict).
            report["advisory"] = True
            exit_fail = False
        if exit_fail and report.get("host_mismatch") and not args.strict:
            # Baseline and current ran on different core counts: the
            # multi-process rows measure the box, not the code.
            report["advisory"] = True
            exit_fail = False
        if args.as_json:
            print(json.dumps(report))
        else:
            print(pg.render_report(report))
            if report.get("host_mismatch"):
                hm = report["host_mismatch"]
                print(f"warning: baseline measured on "
                      f"{hm['baseline_cpus']} cpus, this run on "
                      f"{hm['current_cpus']} — cross-core-count deltas on "
                      "the multi-process rows track the runner, not the "
                      "code")
            if report.get("advisory"):
                print("warning: regression(s) measured on a single-core box "
                      "or across different core counts are ADVISORY — pass "
                      "--strict to fail anyway, A/B the suspect metric "
                      "back-to-back on one box, or re-baseline with "
                      "--update")
        if args.output:
            with open(args.output, "w") as f:
                json.dump(report, f, indent=2)
        if exit_fail:
            sys.exit(1)
        return

    if args.perf_cmd == "compare":
        base = pg.load_result_entry(args.base)
        head = pg.load_result_entry(args.head)
        cpus_differ = (base["cpus"] and head["cpus"]
                       and base["cpus"] != head["cpus"])
        if args.skip_noisy and pg.is_noisy_runner():
            report = {"status": "skipped",
                      "reason": "single-core runner: multi-process metrics "
                                "measure the OS scheduler, not the framework",
                      "metrics": {}}
            print("perf gate skipped: " + report["reason"])
        elif args.skip_noisy and cpus_differ:
            report = {"status": "skipped",
                      "reason": f"core-count mismatch (base "
                                f"{base['cpus']} vs head {head['cpus']} "
                                "cpus): the multi-process rows scale with "
                                "the core count — this comparison gates "
                                "the runner, not the code",
                      "metrics": {}}
            print("perf gate skipped: " + report["reason"])
        else:
            report = pg.compare(base["metrics"], head["metrics"],
                                base_reps=base["reps"],
                                cur_reps=head["reps"])
            if cpus_differ:
                report["host_mismatch"] = {"baseline_cpus": base["cpus"],
                                           "current_cpus": head["cpus"]}
            print(pg.render_report(report))
            if cpus_differ:
                print(f"warning: base measured on {base['cpus']} cpus, "
                      f"head on {head['cpus']} — deltas on the "
                      "multi-process rows track the runner, not the code "
                      "(pass --skip-noisy to skip such comparisons)")
        if args.output:
            with open(args.output, "w") as f:
                json.dump(report, f, indent=2)
        if report["status"] == "fail":
            sys.exit(1)
        return

    # history
    entries = pg.load_history(args.history, limit=args.limit)
    if not entries:
        print(f"no perf history at {pg.history_path(args.history)}")
        return
    if args.metric:
        for e in entries:
            v = e["metrics"].get(args.metric)
            if v is not None:
                print(f"{e.get('iso', e.get('time')):<25} "
                      f"{e.get('git', ''):<12} reps={e.get('reps', 1)} "
                      f"{args.metric}={v}")
        return
    for e in entries:
        print(f"{e.get('iso', e.get('time')):<25} {e.get('git', ''):<12} "
              f"reps={e.get('reps', 1)} {len(e['metrics'])} metrics"
              + (f"  [{e['note']}]" if e.get("note") else ""))


def cmd_job(args):
    from ray_tpu.job_submission import JobSubmissionClient

    client = JobSubmissionClient(_resolve_address(args))
    if args.job_cmd == "submit":
        import shlex

        entrypoint = [a for a in args.entrypoint if a != "--"]
        sid = client.submit_job(
            entrypoint=" ".join(shlex.quote(a) for a in entrypoint)
        )
        print(sid)
        if args.wait:
            for chunk in client.tail_job_logs(sid):
                sys.stdout.write(chunk)
            print(f"status: {client.get_job_status(sid)}")
    elif args.job_cmd == "status":
        print(client.get_job_status(args.submission_id))
    elif args.job_cmd == "logs":
        sys.stdout.write(client.get_job_logs(args.submission_id))
    elif args.job_cmd == "stop":
        print("stopped" if client.stop_job(args.submission_id) else "not running")
    elif args.job_cmd == "list":
        for j in client.list_jobs():
            print(f"{j['submission_id']}  {j['status']:<10} {j['entrypoint']}")


def cmd_lint(args):
    from ray_tpu._private import lint as lint_mod

    root = args.root or lint_mod.find_repo_root()
    baseline_path = args.baseline
    if baseline_path is None and not args.no_baseline:
        cand = os.path.join(root, lint_mod.DEFAULT_BASELINE)
        if os.path.exists(cand):
            baseline_path = cand
    baseline = (
        lint_mod.load_baseline(baseline_path) if baseline_path else None
    )
    result = lint_mod.run_lint(
        paths=args.paths or None, root=root,
        baseline=None if args.update_baseline else baseline,
    )
    if args.update_baseline:
        path = baseline_path or os.path.join(root, lint_mod.DEFAULT_BASELINE)
        n = lint_mod.save_baseline(path, result.findings)
        print(f"wrote {n} accepted finding(s) to {path}")
        return
    if args.json:
        json.dump(result.to_json(), sys.stdout, indent=2)
        sys.stdout.write("\n")
    else:
        print(lint_mod.render_report(result, verbose=args.verbose))
    if not result.ok:
        sys.exit(1)


def main(argv=None):
    parser = argparse.ArgumentParser(prog="ray_tpu")
    sub = parser.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("start")
    p.add_argument("--head", action="store_true")
    p.add_argument("--address", default=None)
    p.add_argument("--resources", default=None)
    p.add_argument("--host", default="127.0.0.1",
                   help="bind address (use the node's reachable IP for "
                        "multi-host clusters)")
    p.add_argument("--port", type=int, default=0,
                   help="fixed GCS port for the head (0 = auto)")
    p.add_argument("--dashboard-port", type=int, default=-1,
                   help=">=0 to start the dashboard (0 = auto port)")
    p.set_defaults(fn=cmd_start)

    p = sub.add_parser("stop")
    p.set_defaults(fn=cmd_stop)

    p = sub.add_parser("up", help="provision + bootstrap a cluster from YAML")
    p.add_argument("config")
    p.set_defaults(fn=cmd_up)

    p = sub.add_parser("down", help="stop + terminate a YAML-defined cluster")
    p.add_argument("config")
    p.set_defaults(fn=cmd_down)

    for name, fn in (("status", cmd_status), ("nodes", cmd_nodes),
                     ("actors", cmd_actors)):
        p = sub.add_parser(name)
        p.add_argument("--address", default=None)
        p.set_defaults(fn=fn)

    p = sub.add_parser(
        "memory",
        help="cluster memory report: object-store state per node, "
             "job/actor/node rollups (plasma+RSS+HBM), top owned objects "
             "with callsites; --leaks runs the leak detector")
    p.add_argument("--address", default=None)
    p.add_argument("--group-by", dest="group_by", default="job",
                   choices=("job", "actor", "node"))
    p.add_argument("--sort-by", dest="sort_by", default="size",
                   choices=("size", "plasma", "rss", "objects"))
    p.add_argument("--leaks", action="store_true",
                   help="force a leak sweep on every node and list "
                        "pinned/spilled primaries with no live owner "
                        "reference (with job/actor/callsite attribution)")
    p.set_defaults(fn=cmd_memory)

    p = sub.add_parser("timeline")
    p.add_argument("--address", default=None)
    p.add_argument("--job", default=None,
                   help="only this job's events (hex id, server-side)")
    p.add_argument("--trace-id", dest="trace_id", default=None,
                   help="only this trace's spans (server-side)")
    p.add_argument("--no-object-events", dest="no_object_events",
                   action="store_true",
                   help="skip the spill/restore/leak instants fanned in "
                        "from the raylets' flight recorders")
    p.add_argument("-o", "--output", default="timeline.json")
    p.set_defaults(fn=cmd_timeline)

    p = sub.add_parser(
        "profile",
        help="cluster-wide CPU profile merged with the task timeline; "
             "--pid samples one worker")
    p.add_argument("--address", default=None)
    p.add_argument("--pid", type=int, default=None,
                   help="sample ONE worker (folded output); omit for a "
                        "cluster-wide capture")
    p.add_argument("--node", "--node-id", dest="node_id", default=None,
                   help="restrict to nodes whose id starts with this hex "
                        "prefix")
    p.add_argument("--duration", type=float, default=2.0)
    p.add_argument("--hz", type=float, default=99.0)
    p.add_argument("--flame", action="store_true",
                   help="folded-stack (flamegraph/speedscope) output "
                        "instead of the merged Perfetto trace")
    p.add_argument("-o", "--output", default=None)
    p.set_defaults(fn=cmd_profile)

    p = sub.add_parser("grafana")
    p.add_argument("-o", "--output", default=None)
    p.set_defaults(fn=cmd_grafana)

    p = sub.add_parser(
        "perf", help="perf regression gate: microbench A/B vs the "
                     "PERF_HISTORY.jsonl ledger with per-metric noise bands")
    psub = p.add_subparsers(dest="perf_cmd", required=True)
    c = psub.add_parser("check", help="measure now, gate vs the ledger head")
    c.add_argument("--only", default=None,
                   help="comma-separated metric-name substrings "
                        "(microbench --only)")
    c.add_argument("--quick", action="store_true",
                   help="single-rep reduced-duration pass (wider noise band)")
    c.add_argument("--history", default=None,
                   help="ledger path (default: RTPU_perf_history_path, "
                        "PERF_HISTORY.jsonl at the repo root)")
    c.add_argument("--update", action="store_true",
                   help="append this measurement to the ledger")
    c.add_argument("--note", default="", help="ledger entry note")
    c.add_argument("--strict", action="store_true",
                   help="fail on regression even on a single-core box "
                        "(default: advisory there — co-tenant load is "
                        "indistinguishable from a code regression)")
    c.add_argument("--json", dest="as_json", action="store_true",
                   help="print the structured delta report instead of the "
                        "table")
    c.add_argument("-o", "--output", default=None,
                   help="also write the delta report JSON to FILE")
    c.set_defaults(fn=cmd_perf)
    c = psub.add_parser(
        "compare", help="gate two microbench --json result files (CI A/B)")
    c.add_argument("base", help="baseline microbench --json output file")
    c.add_argument("head", help="candidate microbench --json output file")
    c.add_argument("--skip-noisy", action="store_true",
                   help="exit 0 with a skipped report on a single-core "
                        "runner (the A/B would measure the scheduler)")
    c.add_argument("-o", "--output", default=None,
                   help="write the delta report JSON to FILE (CI artifact)")
    c.set_defaults(fn=cmd_perf)
    c = psub.add_parser("history", help="print the perf ledger")
    c.add_argument("--history", default=None, help="ledger path override")
    c.add_argument("--metric", default=None,
                   help="print one metric's trajectory")
    c.add_argument("--limit", type=int, default=0)
    c.set_defaults(fn=cmd_perf)

    p = sub.add_parser(
        "lint",
        help="invariant lint plane: contract cross-check, shard-safety, "
             "event-loop blocking-call detection (rule reference: "
             "ray_tpu/_private/lint/__init__.py)")
    p.add_argument("paths", nargs="*",
                   help="files/dirs to lint (default: the ray_tpu package)")
    p.add_argument("--baseline", default=None,
                   help="accepted-findings file (default: "
                        ".lint-baseline.json at the repo root if present)")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore any baseline: report every finding")
    p.add_argument("--update-baseline", action="store_true",
                   help="triage mode: write ALL current findings to the "
                        "baseline and exit 0")
    p.add_argument("--json", action="store_true",
                   help="machine-readable ray_tpu.lint.v1 report (CI "
                        "artifact mode)")
    p.add_argument("--verbose", action="store_true",
                   help="also print baseline-accepted findings")
    p.add_argument("--root", default=None,
                   help="repo root override (contracts + baseline resolve "
                        "against it)")
    p.set_defaults(fn=cmd_lint)

    p = sub.add_parser(
        "debug", help="hang/crash forensics: dump archive, list incidents")
    p.add_argument("--address", default=None)
    dsub = p.add_subparsers(dest="debug_cmd", required=True)
    d = dsub.add_parser("dump", help="one archive: state listings, "
                        "all-worker stacks, per-node flight-recorder "
                        "rings, object-store stats, incidents")
    d.add_argument("-o", "--output", default=None)
    d.add_argument("--ring-limit", type=int, default=1000,
                   help="max flight-recorder events per process")
    i = dsub.add_parser("incidents", help="list stall-watchdog incidents")
    i.add_argument("--limit", type=int, default=100)
    p.set_defaults(fn=cmd_debug)

    p = sub.add_parser("job")
    p.add_argument("--address", default=None)
    jsub = p.add_subparsers(dest="job_cmd", required=True)
    j = jsub.add_parser("submit")
    j.add_argument("--wait", action="store_true")
    j.add_argument("entrypoint", nargs=argparse.REMAINDER)
    for name in ("status", "logs", "stop"):
        j = jsub.add_parser(name)
        j.add_argument("submission_id")
    jsub.add_parser("list")
    p.set_defaults(fn=cmd_job)

    args = parser.parse_args(argv)
    args.fn(args)


if __name__ == "__main__":
    main()
