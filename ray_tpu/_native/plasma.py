"""ctypes binding for the native shared-memory object store
(ray_tpu/_native/plasma_store.cc).

The store client in the reference talks to a store server over a unix socket
with fd passing (reference: src/ray/object_manager/plasma/client.cc); here every
client attaches the named shm segment directly and the C library synchronizes
through a robust in-segment mutex, so get() of a sealed object is a hash probe
plus an mmap'd memoryview — no syscalls on the hot path after attach.
"""

from __future__ import annotations

import ctypes
import mmap
import os
import subprocess
import threading
from typing import Optional, Tuple

from ray_tpu._private.ids import ObjectID

PS_OK = 0
PS_NOT_FOUND = 1
PS_EXISTS = 2
PS_OOM = 3
PS_NOT_SEALED = 4
PS_PINNED = 5
PS_ERROR = 6

_ID_SIZE = 20

_build_lock = threading.Lock()
_lib = None


def _src_path():
    # the C++ source ships inside the package so installed copies
    # (pip install, wheels) can build without the repo checkout
    return os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "plasma_store.cc"
    )


def _lib_path():
    """Per-(version, source-hash) cached .so in a user-writable dir —
    site-packages may be read-only, and a content-keyed name makes the
    existence check the freshness check (reference: python/setup.py ships
    prebuilt binaries; here the toolchain is baked into the image so a
    first-import build + cache is simpler)."""
    import hashlib

    from ray_tpu._version import version

    with open(_src_path(), "rb") as f:
        digest = hashlib.sha1(f.read()).hexdigest()[:12]
    cache = os.environ.get("RTPU_CACHE_DIR") or os.path.join(
        os.path.expanduser("~"), ".cache", "ray_tpu"
    )
    return os.path.join(cache, f"libplasma-{version}-{digest}.so")


def build_native(force: bool = False) -> str:
    """Compile libplasma.so if not cached for this (version, source);
    returns its path."""
    with _build_lock:
        lib = _lib_path()
        if not force and os.path.exists(lib):
            return lib
        os.makedirs(os.path.dirname(lib), exist_ok=True)
        tmp = lib + f".tmp{os.getpid()}"
        cmd = [
            "g++", "-O2", "-fPIC", "-shared", "-std=c++17",
            "-o", tmp, _src_path(), "-lpthread", "-lrt",
        ]
        subprocess.run(cmd, check=True, capture_output=True)
        os.replace(tmp, lib)
        return lib


def _load():
    global _lib
    if _lib is not None:
        return _lib
    path = build_native()
    lib = ctypes.CDLL(path)
    lib.ps_open.restype = ctypes.c_void_p
    lib.ps_open.argtypes = [ctypes.c_char_p, ctypes.c_uint64, ctypes.c_int]
    lib.ps_close.argtypes = [ctypes.c_void_p]
    lib.ps_unlink.argtypes = [ctypes.c_char_p]
    lib.ps_base.restype = ctypes.POINTER(ctypes.c_uint8)
    lib.ps_base.argtypes = [ctypes.c_void_p]
    lib.ps_capacity.restype = ctypes.c_uint64
    lib.ps_capacity.argtypes = [ctypes.c_void_p]
    lib.ps_arena_offset.restype = ctypes.c_uint64
    lib.ps_arena_offset.argtypes = [ctypes.c_void_p]
    u64p = ctypes.POINTER(ctypes.c_uint64)
    lib.ps_create.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64, u64p]
    lib.ps_seal.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.ps_get.argtypes = [ctypes.c_void_p, ctypes.c_char_p, u64p, u64p]
    lib.ps_contains.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.ps_release.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.ps_delete.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.ps_abort.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.ps_evict.argtypes = [ctypes.c_void_p, ctypes.c_uint64, u64p]
    lib.ps_stats.argtypes = [ctypes.c_void_p, u64p, u64p, u64p, u64p, u64p]
    lib.ps_list.restype = ctypes.c_uint64
    lib.ps_list.argtypes = [ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint8), ctypes.c_uint64]
    lib.ps_test_lock.argtypes = [ctypes.c_void_p]
    lib.ps_recovered_count.restype = ctypes.c_uint64
    lib.ps_recovered_count.argtypes = [ctypes.c_void_p]
    lib.ps_poisoned.argtypes = [ctypes.c_void_p]
    _lib = lib
    return lib


class PlasmaOOM(Exception):
    pass


class PlasmaClient:
    """Handle to one node-local store segment.

    The raylet creates the segment (create=True); workers attach.
    """

    def __init__(self, name: str, capacity: int = 0, create: bool = False):
        self._libref = _load()
        self.name = name
        self._handle = self._libref.ps_open(name.encode(), capacity, 1 if create else 0)
        if not self._handle:
            raise RuntimeError(f"failed to open plasma store {name}")
        # Build a zero-copy view over the whole arena via /dev/shm mmap.
        shm_path = f"/dev/shm{name}" if name.startswith("/") else f"/dev/shm/{name}"
        self._f = open(shm_path, "r+b")
        self._mm = mmap.mmap(self._f.fileno(), 0)
        self._arena_off = self._libref.ps_arena_offset(self._handle)
        self._view = memoryview(self._mm)

    @staticmethod
    def _id_bytes(object_id) -> bytes:
        if isinstance(object_id, ObjectID):
            return object_id.binary()
        return bytes(object_id)

    def create(self, object_id, size: int) -> memoryview:
        off = ctypes.c_uint64()
        rc = self._libref.ps_create(
            self._handle, self._id_bytes(object_id), size, ctypes.byref(off)
        )
        if rc == PS_EXISTS:
            raise FileExistsError(f"object {object_id} already exists")
        if rc == PS_OOM:
            raise PlasmaOOM(f"object store out of memory creating {size} bytes")
        if rc != PS_OK:
            raise RuntimeError(f"plasma create failed rc={rc}")
        start = self._arena_off + off.value
        return self._view[start : start + size]

    def seal(self, object_id):
        rc = self._libref.ps_seal(self._handle, self._id_bytes(object_id))
        if rc != PS_OK:
            raise RuntimeError(f"plasma seal failed rc={rc}")
        # Drop the creator pin taken at create().
        self._libref.ps_release(self._handle, self._id_bytes(object_id))

    def put_blob(self, object_id, data) -> bool:
        """Create+copy+seal in one step — the single copy of the store's
        zero-copy discipline (callers hand raw views, never pre-materialized
        bytes; the hot path streams via serialization.write_blob instead).
        Returns False if it already existed."""
        data = memoryview(data)
        nbytes = data.nbytes
        try:
            dest = self.create(object_id, nbytes)
        except FileExistsError:
            return False
        if nbytes:  # cast("B") rejects empty multi-dim views
            dest[:] = data.cast("B")
        dest.release()
        self.seal(object_id)
        return True

    def get(self, object_id) -> Optional[memoryview]:
        """Zero-copy view of a sealed object; pins it until release()."""
        off = ctypes.c_uint64()
        size = ctypes.c_uint64()
        rc = self._libref.ps_get(
            self._handle, self._id_bytes(object_id), ctypes.byref(off), ctypes.byref(size)
        )
        if rc in (PS_NOT_FOUND, PS_NOT_SEALED):
            return None
        if rc != PS_OK:
            raise RuntimeError(f"plasma get failed rc={rc}")
        start = self._arena_off + off.value
        return self._view[start : start + size.value]

    def contains(self, object_id) -> bool:
        return bool(self._libref.ps_contains(self._handle, self._id_bytes(object_id)))

    def release(self, object_id):
        self._libref.ps_release(self._handle, self._id_bytes(object_id))

    def delete(self, object_id) -> bool:
        rc = self._libref.ps_delete(self._handle, self._id_bytes(object_id))
        return rc == PS_OK

    def abort(self, object_id):
        self._libref.ps_abort(self._handle, self._id_bytes(object_id))

    def evict(self, num_bytes: int) -> int:
        freed = ctypes.c_uint64()
        self._libref.ps_evict(self._handle, num_bytes, ctypes.byref(freed))
        return freed.value

    def list_object_ids(self, max_objects: int = 65536):
        buf = (ctypes.c_uint8 * (max_objects * _ID_SIZE))()
        n = self._libref.ps_list(self._handle, buf, max_objects)
        raw = bytes(buf)
        return [ObjectID(raw[i * _ID_SIZE : (i + 1) * _ID_SIZE]) for i in range(n)]

    def stats(self):
        used = ctypes.c_uint64()
        cap = ctypes.c_uint64()
        num = ctypes.c_uint64()
        ev_b = ctypes.c_uint64()
        ev_c = ctypes.c_uint64()
        self._libref.ps_stats(
            self._handle, ctypes.byref(used), ctypes.byref(cap), ctypes.byref(num),
            ctypes.byref(ev_b), ctypes.byref(ev_c),
        )
        return {
            "used_bytes": used.value,
            "capacity_bytes": cap.value,
            "num_objects": num.value,
            "evicted_bytes": ev_b.value,
            "evicted_count": ev_c.value,
        }

    def recovered_count(self) -> int:
        """Owner-death free-list rebuilds performed on this store."""
        return self._libref.ps_recovered_count(self._handle)

    def poisoned(self) -> bool:
        """True if unrecoverable corruption was detected; all ops fail."""
        return bool(self._libref.ps_poisoned(self._handle))

    def _test_lock_and_abandon(self):
        """Test-only: take the store mutex and never release it. The calling
        process is expected to exit, triggering EOWNERDEAD recovery in the
        next locker."""
        self._libref.ps_test_lock(self._handle)

    def close(self, unmap: bool = False):
        """Detach from the store.

        By default the mapping is left in place until process exit: zero-copy
        values deserialized from the store may still alias it, and unmapping
        under them would turn later reads into segfaults. Pass unmap=True only
        when no views can be outstanding (e.g. the raylet destroying the store).
        """
        if self._handle:
            try:
                self._f.close()
            except Exception:
                pass
            if unmap:
                try:
                    self._view.release()
                    self._mm.close()
                except Exception:
                    pass
                self._libref.ps_close(self._handle)
            self._handle = None

    @staticmethod
    def unlink(name: str):
        _load().ps_unlink(name.encode())
