// rtpu plasma: node-local shared-memory immutable object store.
//
// TPU-native counterpart of the reference's plasma store
// (reference: src/ray/object_manager/plasma/store.h, dlmalloc.cc): one POSIX
// shared-memory arena per node, a first-fit free-list allocator with
// coalescing, and an open-addressing object table — all resident *inside* the
// shared segment so every process (raylet, workers, drivers) maps the same
// memory and reads sealed objects with zero copies. Unlike the reference
// there is no store server socket protocol or fd-passing: clients attach to
// the named segment directly and synchronize through a robust process-shared
// mutex; the raylet remains the control-plane authority (eviction policy,
// spill decisions) but the data path is pure shared memory.
//
// Object lifecycle: CREATE (allocate, writer fills bytes) -> SEAL (immutable,
// readable by all) -> [GET pins / RELEASE unpins] -> DELETE or LRU-EVICT.
//
// Exposed as a flat C ABI consumed from Python via ctypes
// (ray_tpu/_native/plasma.py).

#include <errno.h>
#include <fcntl.h>
#include <pthread.h>
#include <stdint.h>
#include <stdio.h>
#include <string.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

#include <atomic>
#include <unordered_map>
#include <unordered_set>

namespace {

constexpr uint64_t kMagic = 0x52545055504c4153ULL;  // "RTPUPLAS"
constexpr uint32_t kIdSize = 20;
constexpr uint32_t kTableSize = 1 << 16;  // max objects per node store
constexpr uint64_t kAlign = 64;
constexpr uint32_t kStateFree = 0;
constexpr uint32_t kStateCreated = 1;
constexpr uint32_t kStateSealed = 2;
constexpr uint32_t kStateTombstone = 3;

struct Entry {
  uint8_t id[kIdSize];
  uint32_t state;
  uint64_t offset;     // data offset from arena base
  uint64_t data_size;  // usable bytes
  int32_t pin_count;   // readers currently mapping the object
  uint32_t pending_delete;  // freed by owner; reclaim when pin_count drops to 0
  uint64_t lru_tick;   // last touch, for eviction ordering
};

// Free/used block header living immediately before each data region.
// Padded to kAlign (64) so that data offsets — which sit sizeof(Block) past
// an aligned boundary — are themselves 64-byte aligned end-to-end (zero-copy
// numpy views and future DMA mappings rely on this).
struct Block {
  uint64_t size;       // total block size including header
  uint64_t prev_size;  // size of physically-previous block (0 if first)
  uint32_t free;
  uint32_t _pad;
  uint64_t next_free;  // offset of next free block (0 = none); valid if free
  uint64_t prev_free;  // offset of prev free block
  uint64_t _pad2[3];   // pad header to 64 bytes
};
static_assert(sizeof(Block) == kAlign, "Block header must equal kAlign");

struct Header {
  uint64_t magic;
  uint64_t capacity;    // arena bytes (data region)
  uint64_t arena_off;   // offset of arena base from segment start
  uint64_t used;        // bytes allocated (incl. headers)
  uint64_t num_objects;
  uint64_t lru_clock;
  uint64_t free_head;   // offset of first free block (0 = none)
  uint64_t evicted_bytes;
  uint64_t evicted_count;
  uint64_t poisoned;        // structural corruption detected; all ops fail
  uint64_t recovered_count; // successful free-list rebuilds after owner death
  pthread_mutex_t mutex;
  Entry table[kTableSize];
};

struct Store {
  Header* hdr;
  uint8_t* base;  // segment base
  uint64_t map_size;
};

inline uint64_t align_up(uint64_t v) { return (v + kAlign - 1) & ~(kAlign - 1); }

inline Block* block_at(Store* s, uint64_t off) {
  return reinterpret_cast<Block*>(s->base + s->hdr->arena_off + off - sizeof(Block));
}

// Block bookkeeping uses "data offsets": offset of the data region within the
// arena; the header sits sizeof(Block) before it. Offset 0 is reserved (null).

inline uint64_t hash_id(const uint8_t* id) {
  // FNV-1a over the 20-byte id.
  uint64_t h = 1469598103934665603ULL;
  for (uint32_t i = 0; i < kIdSize; i++) {
    h ^= id[i];
    h *= 1099511628211ULL;
  }
  return h;
}

// A process died while holding the mutex, possibly mid-way through a
// multi-step mutation (arena_alloc split, arena_free splice, create/delete
// entry update). The block headers (size/free flags) are single-word writes
// updated before any list pointers, so the physical chain of blocks is still
// walkable — rebuild the free list, reconcile the entry table against it,
// and recompute the counters. Returns 0 on success, -1 if the chain itself
// is corrupt (then the store must be poisoned, not silently reused).
int rebuild_after_owner_death(Store* s) {
  Header* h = s->hdr;
  const uint64_t kMaxBlocks = kTableSize * 4ULL;

  // Pass 1: validate that blocks tile the arena exactly. ps_open aligns
  // capacity to kAlign and every allocation is align_up'd, so all sizes must
  // be kAlign multiples — a stale-payload "header" mid-split rarely is.
  uint64_t off = sizeof(Block);
  uint64_t prev_size = 0;
  uint64_t walked = 0;
  while (off - sizeof(Block) < h->capacity) {
    Block* b = block_at(s, off);
    if (b->size < sizeof(Block) || b->size % kAlign != 0 || b->free > 1 ||
        off - sizeof(Block) + b->size > h->capacity)
      return -1;
    b->prev_size = prev_size;  // repairable from the walk; fix unconditionally
    prev_size = b->size;
    off += b->size;
    if (++walked > kMaxBlocks) return -1;
  }
  if (off - sizeof(Block) != h->capacity) return -1;

  // Pass 2: reconcile the entry table. An entry is live only if it points at
  // the start of a used block big enough to hold it (a crash between
  // arena_free and the tombstone write in ps_delete/ps_abort, or mid-create,
  // leaves entries referencing free space — ps_get must never see those).
  // Process-local index of used blocks keeps this O(entries + blocks).
  std::unordered_map<uint64_t, uint64_t> used_blocks;  // data off -> block size
  for (uint64_t boff = sizeof(Block); boff - sizeof(Block) < h->capacity;) {
    Block* b = block_at(s, boff);
    if (!b->free) used_blocks.emplace(boff, b->size);
    boff += b->size;
  }
  uint64_t num_objects = 0;
  std::unordered_set<uint64_t> referenced;
  for (uint32_t i = 0; i < kTableSize; i++) {
    Entry* e = &h->table[i];
    if (e->state != kStateCreated && e->state != kStateSealed) continue;
    auto it = used_blocks.find(e->offset);
    if (it != used_blocks.end() &&
        it->second - sizeof(Block) >= e->data_size) {
      num_objects++;
      referenced.insert(e->offset);
    } else {
      e->state = kStateTombstone;
    }
  }

  // Pass 3: reclaim orphaned used blocks (allocated, but no entry references
  // them — a crash between arena_alloc and the entry write in ps_create, or
  // a half-finished split's tail).
  for (const auto& kv : used_blocks) {
    if (referenced.find(kv.first) == referenced.end())
      block_at(s, kv.first)->free = 1;
  }

  // Pass 4: rebuild the free list (coalescing adjacent frees) + counters.
  h->free_head = 0;
  uint64_t used = 0;
  uint64_t tail_free = 0;  // trailing free run start, for coalescing
  for (uint64_t boff = sizeof(Block); boff - sizeof(Block) < h->capacity;) {
    Block* b = block_at(s, boff);
    uint64_t bsize = b->size;
    if (b->free) {
      if (tail_free) {
        Block* tf = block_at(s, tail_free);
        tf->size += bsize;
        Block* after = block_at(s, boff + bsize);
        if (boff + bsize - sizeof(Block) < h->capacity)
          after->prev_size = tf->size;
      } else {
        tail_free = boff;
      }
    } else {
      if (tail_free) {
        Block* tf = block_at(s, tail_free);
        tf->next_free = h->free_head;
        tf->prev_free = 0;
        if (h->free_head) block_at(s, h->free_head)->prev_free = tail_free;
        h->free_head = tail_free;
        tail_free = 0;
      }
      b->next_free = b->prev_free = 0;
      used += bsize;
    }
    boff += bsize;
  }
  if (tail_free) {
    Block* tf = block_at(s, tail_free);
    tf->next_free = h->free_head;
    tf->prev_free = 0;
    if (h->free_head) block_at(s, h->free_head)->prev_free = tail_free;
    h->free_head = tail_free;
  }
  h->used = used;
  h->num_objects = num_objects;
  h->recovered_count++;
  return 0;
}

// Returns 0 when the lock is held and the store is usable; nonzero otherwise.
int lock(Store* s) {
  int rc = pthread_mutex_lock(&s->hdr->mutex);
  if (rc == EOWNERDEAD) {
    // A crashed process held the lock: the shared structures may be
    // half-mutated. Recover what is provably recoverable; otherwise poison
    // the store so every client fails loudly instead of corrupting data.
    pthread_mutex_consistent(&s->hdr->mutex);
    if (rebuild_after_owner_death(s) != 0) s->hdr->poisoned = 1;
  } else if (rc != 0) {
    return rc;
  }
  if (s->hdr->poisoned) {
    pthread_mutex_unlock(&s->hdr->mutex);
    return -1;
  }
  return 0;
}

void unlock(Store* s) { pthread_mutex_unlock(&s->hdr->mutex); }

Entry* find_entry(Store* s, const uint8_t* id) {
  uint64_t h = hash_id(id) % kTableSize;
  for (uint32_t probe = 0; probe < kTableSize; probe++) {
    Entry* e = &s->hdr->table[(h + probe) % kTableSize];
    if (e->state == kStateFree) return nullptr;
    if (e->state != kStateTombstone && memcmp(e->id, id, kIdSize) == 0) return e;
  }
  return nullptr;
}

Entry* find_slot(Store* s, const uint8_t* id) {
  uint64_t h = hash_id(id) % kTableSize;
  Entry* first_tomb = nullptr;
  for (uint32_t probe = 0; probe < kTableSize; probe++) {
    Entry* e = &s->hdr->table[(h + probe) % kTableSize];
    if (e->state == kStateFree) return first_tomb ? first_tomb : e;
    if (e->state == kStateTombstone) {
      if (!first_tomb) first_tomb = e;
    } else if (memcmp(e->id, id, kIdSize) == 0) {
      return e;  // caller checks state
    }
  }
  return first_tomb;
}

// ---- free-list allocator --------------------------------------------------

void freelist_remove(Store* s, Block* b, uint64_t off) {
  Header* h = s->hdr;
  if (b->prev_free)
    block_at(s, b->prev_free)->next_free = b->next_free;
  else
    h->free_head = b->next_free;
  if (b->next_free) block_at(s, b->next_free)->prev_free = b->prev_free;
  b->free = 0;
  b->next_free = b->prev_free = 0;
}

void freelist_push(Store* s, Block* b, uint64_t off) {
  Header* h = s->hdr;
  b->free = 1;
  b->next_free = h->free_head;
  b->prev_free = 0;
  if (h->free_head) block_at(s, h->free_head)->prev_free = off;
  h->free_head = off;
}

inline uint64_t block_off(Store* s, Block* b) {
  return reinterpret_cast<uint8_t*>(b) + sizeof(Block) - (s->base + s->hdr->arena_off);
}

// Allocate a data region of `size` bytes; returns data offset or 0 on OOM.
uint64_t arena_alloc(Store* s, uint64_t size) {
  Header* h = s->hdr;
  uint64_t need = align_up(size + sizeof(Block));
  uint64_t off = h->free_head;
  while (off) {
    Block* b = block_at(s, off);
    if (b->size >= need) {
      freelist_remove(s, b, off);
      uint64_t leftover = b->size - need;
      if (leftover >= sizeof(Block) + kAlign) {
        // split: carve the tail into a new free block. Write the tail header
        // fully BEFORE shrinking b->size: owner-death recovery walks blocks
        // by size, so at every intermediate crash point the chain must tile
        // the arena (old b->size hides the half-written tail; new b->size
        // exposes an already-valid tail header).
        uint64_t tail_off = off + need;  // data offsets advance with block size
        Block* tail = block_at(s, tail_off);
        tail->size = leftover;
        tail->prev_size = need;
        tail->free = 0;  // orphan-used until pushed; recovery reclaims it
        tail->next_free = tail->prev_free = 0;
        std::atomic_thread_fence(std::memory_order_release);
        b->size = need;
        uint64_t after_off = tail_off + leftover;
        Block* ab = block_at(s, after_off);
        if (reinterpret_cast<uint8_t*>(ab) < s->base + h->arena_off + h->capacity)
          ab->prev_size = leftover;
        freelist_push(s, tail, tail_off);
      }
      h->used += b->size;
      return off;
    }
    off = b->next_free;
  }
  return 0;
}

void arena_free(Store* s, uint64_t off) {
  Header* h = s->hdr;
  Block* b = block_at(s, off);
  h->used -= b->size;
  // coalesce with physically-next block if free
  uint64_t next_off = off + b->size;
  Block* nb = block_at(s, next_off);
  if (reinterpret_cast<uint8_t*>(nb) < s->base + h->arena_off + h->capacity &&
      nb->free) {
    freelist_remove(s, nb, next_off);
    b->size += nb->size;
  }
  // coalesce with physically-previous block if free
  if (b->prev_size) {
    uint64_t prev_off = off - b->prev_size;
    Block* pb = block_at(s, prev_off);
    if (pb->free) {
      freelist_remove(s, pb, prev_off);
      pb->size += b->size;
      b = pb;
      off = prev_off;
    }
  }
  // fix next block's prev_size after coalescing
  uint64_t after_off = off + b->size;
  Block* ab = block_at(s, after_off);
  if (reinterpret_cast<uint8_t*>(ab) < s->base + h->arena_off + h->capacity) {
    ab->prev_size = b->size;
  }
  freelist_push(s, b, off);
}

// Evict least-recently-used unpinned sealed objects until `bytes` are free-able.
// Returns bytes actually freed. Caller holds the lock.
uint64_t evict_lru(Store* s, uint64_t bytes) {
  Header* h = s->hdr;
  uint64_t freed = 0;
  while (freed < bytes) {
    Entry* victim = nullptr;
    for (uint32_t i = 0; i < kTableSize; i++) {
      Entry* e = &h->table[i];
      if (e->state == kStateSealed && e->pin_count == 0) {
        if (!victim || e->lru_tick < victim->lru_tick) victim = e;
      }
    }
    if (!victim) break;
    freed += victim->data_size;
    h->evicted_bytes += victim->data_size;
    h->evicted_count += 1;
    arena_free(s, victim->offset);
    victim->state = kStateTombstone;
    h->num_objects--;
  }
  return freed;
}

}  // namespace

extern "C" {

// Status codes shared with the Python binding.
enum {
  PS_OK = 0,
  PS_NOT_FOUND = 1,
  PS_EXISTS = 2,
  PS_OOM = 3,
  PS_NOT_SEALED = 4,
  PS_PINNED = 5,
  PS_ERROR = 6,
};

// Contract: at most one process per node creates a given store name (the
// raylet); other processes attach with create=0. The stillborn-unlink below
// is only safe under that contract — it reclaims a name whose creator died
// mid-init, and would misfire only if a *live* creator stalled >10 s between
// ftruncate and publishing the magic word.
void* ps_open(const char* name, uint64_t capacity, int create) {
  // Two attempts: if attempt 1 finds a stillborn segment (a creator died
  // between shm_open and publishing the magic word), unlink it and retry the
  // exclusive create so the name is not wedged forever.
  for (int attempt = 0; attempt < 2; attempt++) {
    uint64_t map_size = sizeof(Header) + capacity + kAlign;
    bool init = false;
    int fd = -1;
    if (create) {
      // O_EXCL picks exactly one initializer: concurrent creators that lose
      // the race fall through to the attach path and wait for the magic word,
      // so the header/mutex/free-list are written by a single process.
      fd = shm_open(name, O_RDWR | O_CREAT | O_EXCL, 0600);
      if (fd >= 0) {
        if (ftruncate(fd, map_size) != 0) {
          close(fd);
          shm_unlink(name);
          return nullptr;
        }
        init = true;
      } else if (errno != EEXIST) {
        return nullptr;
      }
    }
    if (fd < 0) {
      fd = shm_open(name, O_RDWR, 0600);
      if (fd < 0) {
        if (create && errno == ENOENT) continue;  // creator unlinked; retry
        return nullptr;
      }
      // The winning creator may not have ftruncate'd yet; wait for the size.
      struct stat st;
      st.st_size = 0;
      for (int i = 0; i < 10000; i++) {
        if (fstat(fd, &st) != 0) {
          close(fd);
          return nullptr;
        }
        if (st.st_size > 0) break;
        usleep(1000);
      }
      if (st.st_size == 0) {
        close(fd);
        if (create) {
          shm_unlink(name);  // stillborn: creator died pre-ftruncate
          continue;
        }
        return nullptr;
      }
      map_size = st.st_size;
    }
    void* mem = mmap(nullptr, map_size, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
    close(fd);
    if (mem == MAP_FAILED) return nullptr;
    Store* s = new Store();
    s->base = static_cast<uint8_t*>(mem);
    s->hdr = static_cast<Header*>(mem);
    s->map_size = map_size;
    if (init) {
      Header* h = s->hdr;
      memset(h, 0, sizeof(Header));
      // Align capacity down to kAlign so every block size is a kAlign
      // multiple — rebuild_after_owner_death relies on this invariant.
      h->capacity = (map_size - sizeof(Header) - kAlign) & ~(kAlign - 1);
      h->arena_off = align_up(sizeof(Header));
      pthread_mutexattr_t attr;
      pthread_mutexattr_init(&attr);
      pthread_mutexattr_setpshared(&attr, PTHREAD_PROCESS_SHARED);
      pthread_mutexattr_setrobust(&attr, PTHREAD_MUTEX_ROBUST);
      pthread_mutex_init(&h->mutex, &attr);
      // one giant free block spanning the arena; data offset starts after one
      // header
      uint64_t first_off = sizeof(Block);
      Block* b = block_at(s, first_off);
      b->size = h->capacity;
      b->prev_size = 0;
      b->free = 0;
      b->next_free = b->prev_free = 0;
      freelist_push(s, b, first_off);
      std::atomic_thread_fence(std::memory_order_seq_cst);
      h->magic = kMagic;
    } else {
      // wait for creator to finish init
      for (int i = 0; i < 10000 && s->hdr->magic != kMagic; i++) usleep(1000);
      if (s->hdr->magic != kMagic) {
        munmap(mem, map_size);
        delete s;
        if (create) {
          shm_unlink(name);  // stillborn: creator died pre-magic
          continue;
        }
        return nullptr;
      }
    }
    return s;
  }
  return nullptr;
}

void ps_close(void* handle) {
  Store* s = static_cast<Store*>(handle);
  munmap(s->base, s->map_size);
  delete s;
}

void ps_unlink(const char* name) { shm_unlink(name); }

uint8_t* ps_base(void* handle) {
  Store* s = static_cast<Store*>(handle);
  return s->base + s->hdr->arena_off;
}

uint64_t ps_capacity(void* handle) {
  return static_cast<Store*>(handle)->hdr->capacity;
}

// Byte offset of the arena from the start of the shm segment/file.
uint64_t ps_arena_offset(void* handle) {
  return static_cast<Store*>(handle)->hdr->arena_off;
}

// Create an object of `size` bytes. On success *out_offset is the data offset
// from ps_base(). Evicts LRU unpinned objects on pressure.
int ps_create(void* handle, const uint8_t* id, uint64_t size, uint64_t* out_offset) {
  Store* s = static_cast<Store*>(handle);
  if (lock(s) != 0) return PS_ERROR;
  Entry* existing = find_entry(s, id);
  if (existing) {
    unlock(s);
    return PS_EXISTS;
  }
  uint64_t off = arena_alloc(s, size);
  if (!off) {
    evict_lru(s, align_up(size + sizeof(Block)));
    off = arena_alloc(s, size);
  }
  if (!off) {
    unlock(s);
    return PS_OOM;
  }
  Entry* e = find_slot(s, id);
  if (!e) {
    arena_free(s, off);
    unlock(s);
    return PS_OOM;  // table full
  }
  memcpy(e->id, id, kIdSize);
  e->state = kStateCreated;
  e->offset = off;
  e->data_size = size;
  e->pending_delete = 0;
  e->pin_count = 1;  // creator holds a pin until seal+release
  e->lru_tick = ++s->hdr->lru_clock;
  s->hdr->num_objects++;
  *out_offset = off;
  unlock(s);
  return PS_OK;
}

int ps_seal(void* handle, const uint8_t* id) {
  Store* s = static_cast<Store*>(handle);
  if (lock(s) != 0) return PS_ERROR;
  Entry* e = find_entry(s, id);
  if (!e) {
    unlock(s);
    return PS_NOT_FOUND;
  }
  e->state = kStateSealed;
  e->lru_tick = ++s->hdr->lru_clock;
  unlock(s);
  return PS_OK;
}

// Get pins the object. *out_offset/*out_size valid when PS_OK.
int ps_get(void* handle, const uint8_t* id, uint64_t* out_offset, uint64_t* out_size) {
  Store* s = static_cast<Store*>(handle);
  if (lock(s) != 0) return PS_ERROR;
  Entry* e = find_entry(s, id);
  if (!e) {
    unlock(s);
    return PS_NOT_FOUND;
  }
  if (e->state != kStateSealed || e->pending_delete) {
    unlock(s);
    return e->pending_delete ? PS_NOT_FOUND : PS_NOT_SEALED;
  }
  e->pin_count++;
  e->lru_tick = ++s->hdr->lru_clock;
  *out_offset = e->offset;
  *out_size = e->data_size;
  unlock(s);
  return PS_OK;
}

int ps_contains(void* handle, const uint8_t* id) {
  Store* s = static_cast<Store*>(handle);
  if (lock(s) != 0) return 0;
  Entry* e = find_entry(s, id);
  int ok = (e && e->state == kStateSealed && !e->pending_delete) ? 1 : 0;
  unlock(s);
  return ok;
}

int ps_release(void* handle, const uint8_t* id) {
  Store* s = static_cast<Store*>(handle);
  if (lock(s) != 0) return PS_ERROR;
  Entry* e = find_entry(s, id);
  if (!e) {
    unlock(s);
    return PS_NOT_FOUND;
  }
  if (e->pin_count > 0) e->pin_count--;
  if (e->pin_count == 0 && e->pending_delete) {
    arena_free(s, e->offset);
    e->state = kStateTombstone;
    s->hdr->num_objects--;
  }
  unlock(s);
  return PS_OK;
}

int ps_delete(void* handle, const uint8_t* id) {
  // If readers still pin the object, defer reclamation to the last release —
  // zero-copy views held by live Python values stay valid (same contract as
  // the reference plasma client's buffer refcounting).
  Store* s = static_cast<Store*>(handle);
  if (lock(s) != 0) return PS_ERROR;
  Entry* e = find_entry(s, id);
  if (!e) {
    unlock(s);
    return PS_NOT_FOUND;
  }
  if (e->pin_count > 0) {
    e->pending_delete = 1;
    unlock(s);
    return PS_PINNED;
  }
  arena_free(s, e->offset);
  e->state = kStateTombstone;
  s->hdr->num_objects--;
  unlock(s);
  return PS_OK;
}

int ps_abort(void* handle, const uint8_t* id) {
  // Abort an unsealed create (e.g. writer failed mid-copy). Sealed
  // objects are NOT abortable: readers may hold zero-copy views, so
  // freeing here would be a cross-process use-after-free — sealed
  // removal goes through ps_delete's pin-aware path instead.
  Store* s = static_cast<Store*>(handle);
  if (lock(s) != 0) return PS_ERROR;
  Entry* e = find_entry(s, id);
  if (!e) {
    unlock(s);
    return PS_NOT_FOUND;
  }
  if (e->state == kStateSealed) {
    unlock(s);
    return PS_NOT_SEALED;  // "wrong state for this op"
  }
  arena_free(s, e->offset);
  e->state = kStateTombstone;
  s->hdr->num_objects--;
  unlock(s);
  return PS_OK;
}

int ps_evict(void* handle, uint64_t bytes, uint64_t* out_freed) {
  Store* s = static_cast<Store*>(handle);
  if (lock(s) != 0) return PS_ERROR;
  *out_freed = evict_lru(s, bytes);
  unlock(s);
  return PS_OK;
}

void ps_stats(void* handle, uint64_t* used, uint64_t* capacity, uint64_t* num_objects,
              uint64_t* evicted_bytes, uint64_t* evicted_count) {
  Store* s = static_cast<Store*>(handle);
  *used = *capacity = *num_objects = *evicted_bytes = *evicted_count = 0;
  if (lock(s) != 0) return;
  *used = s->hdr->used;
  *capacity = s->hdr->capacity;
  *num_objects = s->hdr->num_objects;
  *evicted_bytes = s->hdr->evicted_bytes;
  *evicted_count = s->hdr->evicted_count;
  unlock(s);
}

// Test-only: acquire the store mutex and return WITHOUT unlocking, so a test
// process can exit while "holding" it and exercise the EOWNERDEAD recovery.
int ps_test_lock(void* handle) { return lock(static_cast<Store*>(handle)); }

// Observability: how many owner-death free-list rebuilds have happened, and
// whether the store has been poisoned by unrecoverable corruption.
uint64_t ps_recovered_count(void* handle) {
  return static_cast<Store*>(handle)->hdr->recovered_count;
}

int ps_poisoned(void* handle) {
  return static_cast<Store*>(handle)->hdr->poisoned ? 1 : 0;
}

// List up to max sealed object ids into out (max * kIdSize bytes); returns count.
uint64_t ps_list(void* handle, uint8_t* out, uint64_t max) {
  Store* s = static_cast<Store*>(handle);
  if (lock(s) != 0) return 0;
  uint64_t n = 0;
  for (uint32_t i = 0; i < kTableSize && n < max; i++) {
    Entry* e = &s->hdr->table[i];
    if (e->state == kStateSealed) {
      memcpy(out + n * kIdSize, e->id, kIdSize);
      n++;
    }
  }
  unlock(s);
  return n;
}

}  // extern "C"
