"""Per-worker training session: rank info + report() channel back to the
trainer (reference: train/_internal/session.py:111 _TrainSession, report
:667). The user loop runs on a thread inside the worker actor; report() blocks
until the driver has consumed the report, which gives the same per-report
barrier semantics as the reference."""

from __future__ import annotations

import queue
import threading
from typing import Any, Dict, Optional

from ray_tpu.train._checkpoint import Checkpoint


class TrainContext:
    def __init__(self, world_rank: int, world_size: int, local_rank: int,
                 local_world_size: int, node_ip: str,
                 experiment_name: str = ""):
        self._world_rank = world_rank
        self._world_size = world_size
        self._local_rank = local_rank
        self._local_world_size = local_world_size
        self._node_ip = node_ip
        self._experiment_name = experiment_name

    def get_world_rank(self) -> int:
        return self._world_rank

    def get_world_size(self) -> int:
        return self._world_size

    def get_local_rank(self) -> int:
        return self._local_rank

    def get_local_world_size(self) -> int:
        return self._local_world_size

    def get_node_ip(self) -> str:
        return self._node_ip

    def get_experiment_name(self) -> str:
        return self._experiment_name


class _Session:
    def __init__(self, ctx: TrainContext, latest_checkpoint: Optional[Checkpoint],
                 dataset_shards: Optional[Dict[str, Any]] = None,
                 pipeline_depth: int = 1):
        self.ctx = ctx
        self.latest_checkpoint = latest_checkpoint
        self.dataset_shards = dataset_shards or {}
        self.reports: "queue.Queue" = queue.Queue()
        self.consumed = threading.Event()
        # Pipelined reports (reference: _internal/session.py uses a bounded
        # result queue): report(i) returns immediately while the driver
        # consumes asynchronously; report(i+depth) blocks until i is acked.
        # Strict per-report lockstep (depth 1, the Tune-trial default) puts
        # a full driver round-trip on the step critical path; the Train
        # worker group uses a deeper pipeline + batched drains so reporting
        # every step costs ~nothing relative to the compiled step.
        self.pipeline_depth = max(1, pipeline_depth)
        self._slot = threading.Semaphore(self.pipeline_depth)
        self._ack_cond = threading.Condition()
        self._submitted = 0
        self._acked = 0
        self.finished = False
        self.error: Optional[BaseException] = None

    def report(self, metrics: Dict[str, Any], checkpoint: Optional[Checkpoint]):
        self._slot.acquire()  # wait for a free pipeline slot
        with self._ack_cond:
            seq = self._submitted
            self._submitted += 1
        self.consumed.clear()
        self.reports.put({"metrics": metrics, "checkpoint": checkpoint})
        if self.pipeline_depth == 1:
            # strict barrier: return only after the consumer acked THIS
            # report — Tune trial loops rely on it (a checkpoint dir may be
            # reused right after report() returns)
            self.consumed.wait()
        elif checkpoint is not None:
            # Reference semantics (train/_internal/session.py report :667):
            # the checkpoint is persisted before report() returns, so the
            # user may delete or reuse the dir immediately after. Block
            # until the driver acked THIS report (acks are released only
            # after _consume_round copied/uploaded the dir). Metrics-only
            # reports keep the deep pipeline.
            with self._ack_cond:
                while self._acked <= seq:
                    self._ack_cond.wait()

    def ack(self, n: int = 1):
        self.consumed.set()
        with self._ack_cond:
            self._acked += n
            self._ack_cond.notify_all()
        for _ in range(n):
            self._slot.release()


_session: Optional[_Session] = None
_session_lock = threading.Lock()


def init_session(ctx: TrainContext, checkpoint: Optional[Checkpoint],
                 dataset_shards: Optional[Dict[str, Any]] = None,
                 pipeline_depth: int = 1) -> _Session:
    global _session
    # A reused worker process must not report the previous run's telemetry.
    from ray_tpu.train import _telemetry

    _telemetry.set_current_recorder(None)
    with _session_lock:
        _session = _Session(ctx, checkpoint, dataset_shards, pipeline_depth)
        return _session


def shutdown_session():
    global _session
    with _session_lock:
        _session = None


def get_session() -> Optional[_Session]:
    return _session


# ------------------------------------------------------------- public API


def get_context() -> TrainContext:
    s = get_session()
    if s is None:
        raise RuntimeError("ray_tpu.train.get_context() outside a train worker")
    return s.ctx


def report(metrics: Dict[str, Any], checkpoint: Optional[Checkpoint] = None):
    s = get_session()
    if s is None:
        raise RuntimeError("ray_tpu.train.report() outside a train worker")
    # Auto-attach step telemetry (train/_telemetry.py): if this worker runs
    # a TrainStep (or registered a StepRecorder), every report carries the
    # rolling step-time/MFU/goodput/throughput summary under telemetry/*
    # keys — user metrics always win on collision.
    from ray_tpu.train import _telemetry

    auto = _telemetry.auto_report_metrics()
    if auto:
        metrics = {**auto, **metrics}
    s.report(metrics, checkpoint)


def get_dataset_shard(name: str = "train"):
    """This worker's split of a dataset passed to the trainer
    (reference: train.get_dataset_shard / DataConfig sharding)."""
    s = get_session()
    if s is None:
        raise RuntimeError(
            "ray_tpu.train.get_dataset_shard() outside a train worker"
        )
    shard = s.dataset_shards.get(name)
    if shard is None:
        raise KeyError(
            f"no dataset {name!r} was passed to the trainer "
            f"(available: {list(s.dataset_shards)})"
        )
    return shard


def get_checkpoint() -> Optional[Checkpoint]:
    s = get_session()
    if s is None:
        raise RuntimeError("ray_tpu.train.get_checkpoint() outside a train worker")
    return s.latest_checkpoint
