"""DataParallelTrainer / JaxTrainer: drive a worker group through a training
run with report/checkpoint rounds and group-restart fault tolerance.

Reference call stack (SURVEY.md §3.4): TorchTrainer.fit →
BackendExecutor.start → WorkerGroup actors → _setup_torch_process_group →
start_training → poll reports (train/base_trainer.py:567,
_internal/backend_executor.py:67/:445, data_parallel_trainer.py:428). Here the
process-group setup is `jax.distributed.initialize` and the data plane is the
XLA-compiled sharded step, not NCCL."""

from __future__ import annotations

import logging
import os
import shutil
import time
from typing import Any, Callable, Dict, List, Optional

from ray_tpu.train._checkpoint import Checkpoint
from ray_tpu.train._config import (
    CheckpointConfig,
    FailureConfig,
    JaxConfig,
    RunConfig,
    ScalingConfig,
)
from ray_tpu.train._session import TrainContext
from ray_tpu.train._worker_group import WorkerGroup

logger = logging.getLogger("ray_tpu.train")


class TrainingFailedError(RuntimeError):
    pass


class Result:
    def __init__(self, metrics: Dict[str, Any], checkpoint: Optional[Checkpoint],
                 path: str, error: Optional[Exception] = None,
                 metrics_history: Optional[List[Dict[str, Any]]] = None):
        self.metrics = metrics
        self.checkpoint = checkpoint
        self.path = path
        self.error = error
        self.metrics_history = metrics_history or []

    def __repr__(self):
        return (f"Result(metrics={self.metrics!r}, "
                f"checkpoint={self.checkpoint!r}, error={self.error!r})")


class DataParallelTrainer:
    """SPMD function trainer: run `train_loop_per_worker` on every worker.

    Subclasses configure the worker runtime (JaxTrainer wires jax.distributed
    + env); the base class owns scheduling, report rounds, checkpoint
    persistence and group restarts."""

    # Worker report pipeline depth: the loop may run this many reports
    # ahead of the driver's consumption (drained at 20Hz in batches), so
    # per-step report() costs ~nothing relative to a compiled train step.
    # Depth must cover one 50ms poll interval of fast reports (~30 at 2ms
    # steps). Tune trial sessions use depth 1 (schedulers decide per
    # report).
    _report_pipeline_depth = 64

    def __init__(
        self,
        train_loop_per_worker: Callable,
        *,
        train_loop_config: Optional[dict] = None,
        scaling_config: Optional[ScalingConfig] = None,
        run_config: Optional[RunConfig] = None,
        resume_from_checkpoint: Optional[Checkpoint] = None,
        datasets: Optional[Dict[str, Any]] = None,
    ):
        self._train_fn = train_loop_per_worker
        self._train_config = train_loop_config
        self._datasets = datasets or {}
        self.scaling_config = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        self._resume_checkpoint = resume_from_checkpoint
        name = self.run_config.name or f"train_{int(time.time())}"
        from ray_tpu.train._storage import is_remote_uri

        self._remote_storage = is_remote_uri(self.run_config.storage_path)
        if self._remote_storage:
            # URI storage (mock://, s3://, ...): checkpoints upload from the
            # workers' nodes; the driver only tracks URIs (no shared FS).
            self.experiment_dir = (
                self.run_config.storage_path.rstrip("/") + "/" + name
            )
        else:
            storage = self.run_config.storage_path or os.path.join(
                os.path.expanduser("~"), "ray_tpu_results"
            )
            self.experiment_dir = os.path.join(storage, name)

    # ------------------------------------------------------------ backend hooks

    def _worker_env(self) -> Dict[str, str]:
        return {}

    def _on_group_start(self, group: WorkerGroup):
        """Backend setup after actors exist, before the user loop starts."""

    # ------------------------------------------------------------------- fit

    def fit(self) -> Result:
        """Run training. Like the reference (base_trainer.py:819 wraps the
        trainer into a Tune Trainable), fit() is a 1-trial Tune run; inside a
        trial actor it runs the training loop directly."""
        from ray_tpu.train._session import get_session

        if get_session() is not None:
            return self._fit_direct()
        from ray_tpu.tune import Tuner

        grid = Tuner(self).fit()
        r = grid[0]
        if r.error:
            raise TrainingFailedError(
                f"training failed (trial {r.trial_id}):\n{r.error}"
            )
        # Final telemetry gauges re-published from THIS process: the GCS
        # drops dead workers' gauges, and the trial/train actors are gone
        # by now — the driver keeps the run's summary scrapeable.
        from ray_tpu.train import _telemetry

        _telemetry.publish_report_summary(
            dict(r.metrics or {}), os.path.basename(self.experiment_dir))
        return Result(
            metrics=dict(r.metrics or {}),
            # The trial persisted its own copy of the latest checkpoint the
            # inner workers reported; fall back to any checkpoints a direct
            # run left in this trainer's experiment dir.
            checkpoint=r.checkpoint or self._latest_persisted_checkpoint(),
            path=self.experiment_dir,
            metrics_history=list(r.metrics_history),
        )

    def _fit_direct(self, report_callback=None) -> Result:
        if not self._remote_storage:
            os.makedirs(self.experiment_dir, exist_ok=True)
        failure_config = self.run_config.failure_config or FailureConfig()
        ckpt_config = self.run_config.checkpoint_config or CheckpointConfig()
        retries_left = failure_config.max_failures
        latest_checkpoint = self._resume_checkpoint
        while True:
            try:
                return self._fit_once(latest_checkpoint, ckpt_config,
                                      report_callback)
            except TrainingFailedError:
                raise
            except Exception as e:
                # group failure (worker/actor death) — restart from the last
                # persisted checkpoint (reference: FailureConfig(max_failures),
                # whole-group restart, air/config.py:395)
                latest_checkpoint = self._latest_persisted_checkpoint()
                if retries_left == 0:
                    raise TrainingFailedError(
                        f"training failed with no retries left: {e}"
                    ) from e
                retries_left -= 1
                logger.warning(
                    "worker group failed (%s); restarting from %s "
                    "(%d retries left)", e, latest_checkpoint, retries_left,
                )

    def _fit_once(self, checkpoint: Optional[Checkpoint],
                  ckpt_config: CheckpointConfig,
                  report_callback=None) -> Result:
        sc = self.scaling_config
        group = WorkerGroup(
            sc.num_workers,
            sc.worker_resources(),
            placement_strategy=sc.placement_strategy,
            env=self._worker_env(),
        )
        try:
            self._on_group_start(group)
            ips = group.execute("node_ip")
            local_ranks = self._local_ranks(ips)
            # Shard datasets across workers: lazy block-granular split so
            # every rank STREAMS a disjoint slice without materializing the
            # plan on the driver (reference: DataConfig/streaming_split).
            shards_by_rank = [dict() for _ in range(sc.num_workers)]
            for ds_name, ds in self._datasets.items():
                if sc.num_workers > 1:
                    splits = ds.split_blocks(sc.num_workers)
                else:
                    splits = [ds]
                for rank, shard in enumerate(splits):
                    shards_by_rank[rank][ds_name] = shard
            per_worker = []
            for rank in range(sc.num_workers):
                ctx = TrainContext(
                    world_rank=rank,
                    world_size=sc.num_workers,
                    local_rank=local_ranks[rank],
                    local_world_size=ips.count(ips[rank]) if ips else 1,
                    node_ip=ips[rank],
                    experiment_name=os.path.basename(self.experiment_dir),
                )
                per_worker.append(
                    (self._train_fn, self._train_config, ctx, checkpoint,
                     shards_by_rank[rank], self._report_pipeline_depth)
                )
            group.execute("start_run", per_worker_args=per_worker)
            return self._poll_reports(group, ckpt_config, report_callback)
        finally:
            group.shutdown()

    def _local_ranks(self, ips: List[str]) -> List[int]:
        counters: Dict[str, int] = {}
        out = []
        for ip in ips:
            out.append(counters.get(ip, 0))
            counters[ip] = out[-1] + 1
        return out

    def _poll_reports(self, group: WorkerGroup,
                      ckpt_config: CheckpointConfig,
                      report_callback=None) -> Result:
        import ray_tpu

        metrics_history: List[Dict[str, Any]] = []
        last_metrics: Dict[str, Any] = {}
        result_checkpoint: Optional[Checkpoint] = None
        # Continue numbering after any checkpoints a previous (crashed)
        # attempt persisted, so restarts never overwrite newer state.
        if self._remote_storage:
            from ray_tpu.train._storage import get_storage

            existing = [
                d for d in get_storage(self.experiment_dir).list_dirs()
                if d.startswith("checkpoint_")
            ]
        else:
            existing = [
                d for d in os.listdir(self.experiment_dir)
                if d.startswith("checkpoint_")
            ] if os.path.isdir(self.experiment_dir) else []
        ckpt_index = (
            max(int(d.split("_")[-1]) for d in existing) + 1 if existing else 0
        )
        active = list(range(group.num_workers))
        saved: List[tuple] = []  # (score, path)
        rs = {
            "ckpt_index": ckpt_index,
            "last_metrics": last_metrics,
            "result_checkpoint": result_checkpoint,
        }
        # Polling drains at 20Hz with piggybacked acks: the workers' report
        # queues have NO parked consumer thread, so report() never preempts
        # the training thread's jax dispatch (see drain_reports). Workers
        # may be drained at different report offsets — buffer per worker by
        # global round number and consume a round once every active worker
        # has reached it (reports are lockstep per round index).
        buf: Dict[int, Dict[int, dict]] = {i: {} for i in active}
        seen: Dict[int, int] = {i: 0 for i in active}  # reports received
        pending_ack: Dict[int, int] = {i: 0 for i in active}
        next_round = 0
        while active or any(buf[i] for i in buf):
            if active:
                refs = [
                    (i, group.async_call(i, "drain_reports", pending_ack[i]))
                    for i in active
                ]
                for i, _ in refs:
                    pending_ack[i] = 0
                batches = {i: ray_tpu.get(ref) for i, ref in refs}
            else:
                batches = {}
            got_any = False
            for i, items in batches.items():
                for rep in items:
                    got_any = True
                    if rep["type"] == "error":
                        raise TrainingFailedError(
                            f"worker {i} failed:\n"
                            f"{rep['traceback'] or rep['error']}"
                        )
                    if rep["type"] == "finished":
                        active.remove(i)
                    else:
                        buf[i][seen[i]] = rep
                        seen[i] += 1
            # consume every globally-complete round, in order
            while True:
                if any(seen[i] <= next_round for i in active):
                    break  # an active worker hasn't reached this round yet
                reports = {
                    i: buf[i].pop(next_round)
                    for i in buf if next_round in buf[i]
                }
                if not reports:
                    break
                self._consume_round(
                    reports, ckpt_config, report_callback, group,
                    metrics_history, saved, rs,
                )
                for i in reports:
                    pending_ack[i] += 1
                next_round += 1
            if active:
                # Pace the polls even while reports flow: draining in a
                # tight RPC loop steals the worker's GIL from the train
                # thread's jax dispatch (measured 2.5x dispatch slowdown).
                # A deep pipeline (Train workers, depth 64) absorbs a 100 ms
                # consumption latency for free and every poll RPC costs the
                # worker two thread wakeups mid-dispatch, so poll at 10 Hz
                # there; shallow pipelines (Tune trials) keep the snappier
                # 25/50 ms cadence for per-report scheduler decisions.
                if self._report_pipeline_depth >= 16:
                    time.sleep(0.1 if got_any else 0.15)
                else:
                    time.sleep(0.025 if got_any else 0.05)
        # release the final acks so the workers' sessions unblock cleanly
        for i, n in pending_ack.items():
            if n and i < group.num_workers:
                try:
                    group.async_call(i, "ack_report", n)
                except Exception:
                    pass
        return Result(
            metrics=rs["last_metrics"],
            checkpoint=rs["result_checkpoint"],
            path=self.experiment_dir,
            metrics_history=metrics_history,
        )

    def _consume_round(self, reports, ckpt_config, report_callback, group,
                       metrics_history, saved, rs):
        """Process one lockstep report round (metrics + optional checkpoint
        persistence/retention); state carries across rounds in `rs`."""
        if not reports:
            return
        # rank-0 metrics win; lowest reporting rank if 0 has finished
        lead = reports[min(reports)]["metrics"]
        rs["last_metrics"] = lead
        metrics_history.append(lead)
        # live per-round gauges from the polling process (it outlives the
        # workers, so the series survive worker-group shutdown)
        from ray_tpu.train import _telemetry

        _telemetry.publish_report_summary(
            lead, os.path.basename(self.experiment_dir))
        ckpt_worker, ckpt_path = next(
            ((i, r["checkpoint_path"]) for i, r in reports.items()
             if "checkpoint_path" in r), (None, None),
        )
        if ckpt_path:
            rel = f"checkpoint_{rs['ckpt_index']:06d}"
            rs["ckpt_index"] += 1
            if self._remote_storage:
                # the reporting worker uploads from ITS node — no shared
                # filesystem assumed
                dest = group.execute_single(
                    ckpt_worker, "upload_checkpoint",
                    ckpt_path, self.experiment_dir, rel,
                )
            else:
                dest = os.path.join(self.experiment_dir, rel)
                shutil.copytree(ckpt_path, dest, dirs_exist_ok=True)
            attr = ckpt_config.checkpoint_score_attribute
            score = lead.get(attr, 0.0) if attr else None
            saved.append((score, dest))
            rs["result_checkpoint"] = Checkpoint(dest)
            if (ckpt_config.num_to_keep
                    and len(saved) > ckpt_config.num_to_keep):
                if attr:
                    # drop the worst-scoring checkpoint
                    sign = (1 if ckpt_config.checkpoint_score_order
                            == "max" else -1)
                    worst = min(
                        range(len(saved)),
                        key=lambda j: sign * saved[j][0],
                    )
                else:
                    worst = 0  # FIFO
                _, drop = saved.pop(worst)
                if self._remote_storage:
                    from ray_tpu.train._storage import get_storage

                    get_storage(self.experiment_dir).delete_dir(
                        drop.rsplit("/", 1)[-1]
                    )
                else:
                    shutil.rmtree(drop, ignore_errors=True)
                if rs["result_checkpoint"].path == drop:
                    rs["result_checkpoint"] = Checkpoint(saved[-1][1])
        if report_callback is not None:
            # forward the round (and any just-persisted checkpoint) to the
            # enclosing Tune trial session
            report_callback(
                lead,
                rs["result_checkpoint"].path
                if (ckpt_path and rs["result_checkpoint"]) else None,
            )

    def _latest_persisted_checkpoint(self) -> Optional[Checkpoint]:
        if self._remote_storage:
            from ray_tpu.train._storage import get_storage

            storage = get_storage(self.experiment_dir)
            ckpts = sorted(
                d for d in storage.list_dirs() if d.startswith("checkpoint_")
            )
            if not ckpts:
                return self._resume_checkpoint
            return Checkpoint(storage.uri_of(ckpts[-1]))
        if not os.path.isdir(self.experiment_dir):
            return None
        ckpts = sorted(
            d for d in os.listdir(self.experiment_dir)
            if d.startswith("checkpoint_")
        )
        if not ckpts:
            return self._resume_checkpoint
        return Checkpoint(os.path.join(self.experiment_dir, ckpts[-1]))


class JaxTrainer(DataParallelTrainer):
    """Trainer whose workers form one jax SPMD world.

    - one worker per TPU host (or per slice via ScalingConfig.topology);
    - with >1 worker and jax_config.distributed, rank 0 hosts the jax
      coordinator and every worker runs jax.distributed.initialize — the
      global mesh then spans hosts, collectives ride ICI/DCN;
    - the reference's closest analogue is TorchXLAConfig
      (train/torch/xla/config.py:20) which only supported AWS Neuron; this is
      the real TPU path."""

    def __init__(self, *args, jax_config: Optional[JaxConfig] = None, **kw):
        super().__init__(*args, **kw)
        self.jax_config = jax_config or JaxConfig()

    def _worker_env(self) -> Dict[str, str]:
        return dict(self.jax_config.env)

    def _on_group_start(self, group: WorkerGroup):
        jc = self.jax_config
        distributed = jc.distributed
        if distributed is None:
            distributed = group.num_workers > 1
        if not distributed:
            return
        ip = group.execute_single(0, "node_ip")
        port = jc.coordinator_port or group.execute_single(0, "free_port")
        coordinator = f"{ip}:{port}"
        refs = [
            group.async_call(i, "init_jax_distributed", coordinator,
                             group.num_workers, i)
            for i in range(group.num_workers)
        ]
        import ray_tpu

        counts = ray_tpu.get(refs, timeout=120)
        logger.info("jax.distributed up: %s global devices", counts[0])
