"""Worker group: N long-lived actors, one per (host, slice), gang-scheduled
via a placement group (reference: train/_internal/worker_group.py:102 +
backend_executor.py:67). The driver never holds device arrays — each worker is
its own jax process (multi-controller SPMD), which is how jax wants to scale."""

from __future__ import annotations

import logging
import os
import socket
import threading
import traceback
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.train._checkpoint import Checkpoint
from ray_tpu.train._session import (
    TrainContext,
    get_session,
    init_session,
    shutdown_session,
)
from ray_tpu.util.placement_group import placement_group, remove_placement_group
from ray_tpu.util.scheduling_strategies import PlacementGroupSchedulingStrategy

logger = logging.getLogger("ray_tpu.train")


def _to_actor_options(res: Dict[str, float]) -> Dict[str, Any]:
    """Split a bundle-style resources dict into actor options (CPU/TPU/memory
    use dedicated options; the rest ride the custom-resources dict)."""
    res = dict(res)
    return {
        "num_cpus": res.pop("CPU", 0),
        "num_tpus": res.pop("TPU", 0),
        "memory": res.pop("memory", 0),
        "resources": res,
    }


class _TrainWorker:
    """Actor hosting one training process (one jax process per worker)."""

    def __init__(self, rank: int, env: Dict[str, str]):
        import sys

        for k, v in env.items():
            os.environ[k] = str(v)
        # The fork server preimports the runtime, which pulls in jax — its
        # import-time config snapshot predates our env vars. The backend is
        # still uninitialized here (nothing touched a device), so pushing the
        # platform through jax.config makes the env effective anyway;
        # XLA_FLAGS / TPU_VISIBLE_CHIPS are read at backend init and work
        # as plain env vars.
        if "jax" in sys.modules and "JAX_PLATFORMS" in env:
            import jax

            jax.config.update("jax_platforms", env["JAX_PLATFORMS"] or None)
        self._rank = rank
        self._thread: Optional[threading.Thread] = None

    def node_ip(self) -> str:
        return socket.gethostbyname(socket.gethostname())

    def free_port(self) -> int:
        s = socket.socket()
        s.bind(("", 0))
        port = s.getsockname()[1]
        s.close()
        return port

    def init_jax_distributed(self, coordinator: str, num_processes: int,
                             process_id: int):
        import jax

        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=num_processes,
            process_id=process_id,
        )
        return len(jax.devices())

    def init_torch_process_group(self, master_ip: str, master_port: int,
                                 world_size: int, rank: int,
                                 backend: str = "gloo",
                                 timeout_s: float = 120.0):
        """torch.distributed bootstrap (reference: train/torch/config.py:65
        _setup_torch_process_group — MASTER_ADDR/PORT + init_process_group)."""
        import datetime

        import torch.distributed as dist

        os.environ["MASTER_ADDR"] = master_ip
        os.environ["MASTER_PORT"] = str(master_port)
        dist.init_process_group(
            backend=backend,
            init_method=f"tcp://{master_ip}:{master_port}",
            world_size=world_size,
            rank=rank,
            timeout=datetime.timedelta(seconds=timeout_s),
        )
        return dist.get_rank()

    def start_run(
        self,
        train_fn: Callable,
        config: Optional[dict],
        ctx: TrainContext,
        checkpoint: Optional[Checkpoint],
        dataset_shards: Optional[Dict[str, Any]] = None,
        pipeline_depth: int = 1,
    ):
        session = init_session(ctx, checkpoint, dataset_shards, pipeline_depth)

        import inspect

        try:
            takes_config = len(inspect.signature(train_fn).parameters) > 0
        except (TypeError, ValueError):
            takes_config = True

        def runner():
            try:
                if takes_config:
                    train_fn(config if config is not None else {})
                else:
                    train_fn()
            except BaseException as e:  # noqa: BLE001 — reported to driver
                session.error = e
                session.error_tb = traceback.format_exc()
            finally:
                # Flush telemetry/user metrics BEFORE signaling finished:
                # the driver kills the group right after consuming the
                # finished report, and the 1s async flush cadence would
                # lose the run's final step deltas.
                try:
                    from ray_tpu._private import worker as worker_mod

                    if worker_mod.global_worker is not None:
                        worker_mod.global_worker.flush_user_metrics_sync()
                except Exception:
                    pass
                session.finished = True
                # wake any blocked report consumer hand-off
                session.reports.put(None)

        self._thread = threading.Thread(target=runner, daemon=True)
        self._thread.start()
        return True

    def _report_to_wire(self, item) -> dict:
        session = get_session()
        if item is None:
            if session.error is not None:
                return {
                    "type": "error",
                    "error": str(session.error),
                    "traceback": getattr(session, "error_tb", ""),
                }
            return {"type": "finished"}
        out = {"type": "report", "metrics": item["metrics"]}
        ckpt = item["checkpoint"]
        if ckpt is not None:
            out["checkpoint_path"] = ckpt.path
        return out

    def next_report(self) -> dict:
        """Block until the worker's loop reports, errors, or finishes."""
        return self._report_to_wire(get_session().reports.get())

    def drain_reports(self, ack: int = 0) -> List[dict]:
        """Non-blocking batched drain with piggybacked acks — the Train
        driver's consumption path. Crucially there is NO thread parked on
        the report queue: report() is then a bare deque append, so the
        training thread's jax dispatch is never preempted by report-handler
        wakeups (at ~2ms TPU steps, per-report GIL handoffs measured ~3.6
        ms/step). The driver polls at 20Hz; Tune keeps the blocking
        per-report next_report so schedulers decide on every round."""
        import queue as _q

        session = get_session()
        if ack:
            session.ack(ack)
        items = []
        while True:
            try:
                items.append(session.reports.get_nowait())
            except _q.Empty:
                break
            if items[-1] is None:
                break
        return [self._report_to_wire(i) for i in items]

    def ack_report(self, n: int = 1):
        session = get_session()
        if session is not None:
            session.ack(n)
        return True

    def upload_checkpoint(self, local_path: str, experiment_uri: str,
                          rel: str) -> str:
        """Upload this worker's checkpoint dir into experiment storage from
        the worker's own node (reference: StorageContext uploads happen
        worker-side, train/_internal/storage.py:352 — the driver never
        touches worker-local paths)."""
        from ray_tpu.train._storage import get_storage

        return get_storage(experiment_uri).upload_dir(local_path, rel)

    def finish(self):
        shutdown_session()
        return True


class WorkerGroup:
    def __init__(
        self,
        num_workers: int,
        resources_per_worker: Dict[str, float],
        placement_strategy: str = "PACK",
        env: Optional[Dict[str, str]] = None,
    ):
        self.num_workers = num_workers
        self._pg = placement_group(
            [dict(resources_per_worker)] * num_workers,
            strategy=placement_strategy,
        )
        if not self._pg.wait(120):
            remove_placement_group(self._pg)
            raise RuntimeError(
                f"could not reserve {num_workers} x {resources_per_worker} "
                "for the train worker group"
            )
        actor_cls = ray_tpu.remote(_TrainWorker)
        opts = _to_actor_options(resources_per_worker)
        self.workers = [
            actor_cls.options(
                **opts,
                scheduling_strategy=PlacementGroupSchedulingStrategy(self._pg, i),
            ).remote(i, env or {})
            for i in range(num_workers)
        ]

    def execute(self, method: str, *args, per_worker_args: Optional[List[tuple]] = None,
                timeout: Optional[float] = None) -> List[Any]:
        refs = []
        for i, w in enumerate(self.workers):
            call_args = per_worker_args[i] if per_worker_args is not None else args
            refs.append(getattr(w, method).remote(*call_args))
        return ray_tpu.get(refs, timeout=timeout)

    def execute_single(self, i: int, method: str, *args) -> Any:
        return ray_tpu.get(getattr(self.workers[i], method).remote(*args))

    def async_call(self, i: int, method: str, *args):
        return getattr(self.workers[i], method).remote(*args)

    def shutdown(self):
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
        try:
            remove_placement_group(self._pg)
        except Exception:
            pass
