"""Step-level workload telemetry for training loops: MFU, goodput, HBM.

The control plane is instrumented end to end (GCS/raylet /metrics, task
events, flamegraphs) but the training loop itself — the thing this
framework exists to run — was an observability black hole. This module is
the training counterpart of the serve request metrics: a ``StepRecorder``
captures per-step wall time, first-step compile time, tokens/examples per
second, estimated MFU, goodput and per-device HBM in use, and publishes
them through the three surfacing pipelines that already exist:

  1. ``ray_tpu.util.metrics`` Gauge/Counter/Histogram records, which ride
     the worker's task-event flush to the GCS aggregator and out the
     Prometheus ``/metrics`` endpoint (zero new transport);
  2. one ``SPAN`` task event per step, so ``ray-tpu timeline`` renders
     step boundaries in the Chrome trace next to task execution;
  3. ``session.report`` auto-attaches the rolling summary, so trainer
     results and the dashboard's ``/api/train`` see the same numbers.

Step time is measured as the wall time of the dispatched step call (for
``TrainStep`` this includes XLA dispatch and, under buffer donation on a
busy device, converges to the true device step time). Goodput is the
fraction of wall time since the recorder started that was spent inside
productive (post-compile) steps — restarts, stalls, data loading and
checkpoint pauses all show up as lost goodput, which is the number the
TPU-scaling literature treats as the primary scaling diagnostic.

Metric names are a stability contract — see ``ray_tpu/util/metrics.py``.
"""

from __future__ import annotations

import os
import statistics
import threading
import time
import uuid
from collections import deque
from typing import Any, Dict, Optional

# Peak dense matmul throughput per chip (bf16 FLOP/s), keyed by substrings
# of jax's ``device_kind``. Used for the MFU estimate; unknown device kinds
# (CPU, new TPU generations) simply don't get an MFU gauge rather than a
# wrong one.
_PEAK_FLOPS_BY_KIND = {
    "TPU v6": 918e12,
    "TPU v5p": 459e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v4": 275e12,
    "TPU v3": 123e12,
    "TPU v2": 45e12,
}

_HBM_SAMPLE_EVERY = 16  # memory_stats() per step would be pure overhead

# Histogram boundaries for step seconds: log-spaced 1ms .. 60s covers
# everything from dispatch-bound CPU smoke steps to pod-scale LLM steps.
_STEP_SECONDS_BOUNDARIES = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)


def peak_flops_per_device(device_kind: str) -> Optional[float]:
    """Best-effort peak bf16 FLOP/s for a jax ``device_kind`` string."""
    for kind, flops in _PEAK_FLOPS_BY_KIND.items():
        if kind.lower() in device_kind.lower():
            return flops
    return None


def estimate_flops_per_token(model_cfg: Any) -> Optional[float]:
    """~6N FLOPs/token (fwd+bwd) from a transformer config's shape fields.

    Works for any config exposing n_layer/n_embd/vocab_size (GPT2, MoE,
    Llama configs here). Attention FLOPs are sequence-length dependent and
    omitted — for the model sizes this underestimates MFU by a few percent,
    which is the conventional (and conservative) choice. Pass
    ``flops_per_step`` to ``TrainStep`` for an exact per-model number.
    """
    n_layer = getattr(model_cfg, "n_layer", None)
    n_embd = getattr(model_cfg, "n_embd", None)
    vocab = getattr(model_cfg, "vocab_size", None)
    if not (n_layer and n_embd and vocab):
        return None
    # params ≈ 12 * L * d^2 (attn qkv/proj + 4d MLP) + vocab embedding
    params = 12 * n_layer * n_embd * n_embd + vocab * n_embd
    return 6.0 * params


class StepRecorder:
    """Accumulates step-level training telemetry and publishes it.

    Thread-safe; one recorder per training run (``TrainStep`` creates and
    registers one automatically, ``current_recorder()`` hands it to
    ``session.report``).

    Clock injection (``clock``/``wall_clock``) exists for deterministic
    unit tests; production uses monotonic time for durations and wall time
    for span boundaries.
    """

    def __init__(
        self,
        *,
        flops_per_step: Optional[float] = None,
        flops_per_token: Optional[float] = None,
        peak_flops: Optional[float] = None,
        n_devices: Optional[int] = None,
        run_name: str = "",
        emit_metrics: bool = True,
        emit_spans: bool = True,
        publish_interval_s: float = 0.5,
        clock=time.monotonic,
        wall_clock=time.time,
        devices=None,
    ):
        self._lock = threading.Lock()
        self._clock = clock
        self._wall = wall_clock
        self._flops_per_step = flops_per_step
        self._flops_per_token = flops_per_token
        self._explicit_peak = peak_flops
        self._n_devices = n_devices
        self._devices = devices
        self.run_name = run_name
        self._emit_metrics = emit_metrics and os.environ.get(
            "RTPU_TRAIN_TELEMETRY", "1") != "0"
        self._emit_spans = emit_spans and os.environ.get(
            "RTPU_TRAIN_STEP_SPANS", "1") != "0"
        self._start = self._clock()
        self._trace_id = uuid.uuid4().hex
        self.steps = 0
        self.productive_steps = 0
        self.productive_s = 0.0
        self.compile_s = 0.0
        self.tokens = 0
        self.examples = 0
        self._last_step_s = 0.0
        self._metrics = None
        self._hbm_bytes: Dict[str, float] = {}
        # Derived gauges (goodput/MFU/throughput) recompute at most every
        # publish_interval_s — the per-step hot cost stays at one histogram
        # observe + one counter inc + one span buffer append (~µs), which
        # matters at millisecond TPU step times.
        self._publish_interval = publish_interval_s
        self._last_gauge_pub = float("-inf")
        self._last_step_at = self._start  # stall-watchdog freshness probe
        # Slow-step detection for the profiling plane: per-step durations
        # feed a trailing window; a step slower than
        # RTPU_profile_slow_step_factor x the window median is flagged and
        # picked up by the stall watchdog (pop_slow_step), which captures a
        # cluster profile while the cause is likely still warm. The factor
        # is snapshotted once — each RTPU_CONFIG read is an os.environ
        # probe, too slow for a per-step path.
        from ray_tpu._private.config import RTPU_CONFIG

        self._slow_factor = RTPU_CONFIG.profile_slow_step_factor
        self._recent_steps: deque = deque(maxlen=32)
        self._median_cache: Optional[float] = None  # refreshed every 8 steps
        self._steps_since_median = 0
        self._slow_step: Optional[Dict[str, float]] = None
        # Compile-storm detection (perf regression plane): the jit-cache-miss
        # bookkeeping above already *knows* every recompilation; this turns
        # "many compiles long after warmup" — the unstable-shapes/dtypes
        # failure mode that silently halves throughput — into a flag the
        # watchdog promotes to a jit_cache_miss_storm GCS incident. Config
        # snapshotted once (per-step path).
        self._storm_k = int(RTPU_CONFIG.perf_compile_storm_k)
        self._storm_window = float(RTPU_CONFIG.perf_compile_storm_window_s)
        self._storm_warmup = int(RTPU_CONFIG.perf_compile_warmup_steps)
        self._compile_times: deque = deque(maxlen=64)
        self._compile_storm: Optional[Dict[str, float]] = None
        # Device-trace window (jax.profiler) armed via request_device_trace
        # or RTPU_device_trace_steps; driven by TrainStep around dispatch.
        self.device_trace = DeviceTraceController()

    # ------------------------------------------------------------ recording

    def record_step(
        self,
        duration_s: float,
        *,
        steps: int = 1,
        tokens: Optional[int] = None,
        examples: Optional[int] = None,
        compile_step: bool = False,
        start_wall: Optional[float] = None,
    ) -> None:
        """Record ``steps`` optimizer steps that took ``duration_s`` of wall
        time in total. ``compile_step`` marks a jit-cache-miss call whose
        duration is compile + one step — it's booked as compile time, not
        productive step time, so MFU/throughput aren't poisoned by it."""
        duration_s = max(0.0, float(duration_s))
        from ray_tpu._private import flight_recorder as _fr

        _fr.record("train.step", b"",
                   f"{steps}x {duration_s:.4f}s"
                   + (" compile" if compile_step else ""))
        with self._lock:
            self.steps += steps
            self._last_step_at = self._clock()
            if compile_step:
                self.compile_s += duration_s
                if self._storm_k > 0 and self.steps > self._storm_warmup:
                    now_m = self._clock()
                    self._compile_times.append(now_m)
                    recent = [t for t in self._compile_times
                              if now_m - t <= self._storm_window]
                    if len(recent) >= self._storm_k:
                        self._compile_storm = {
                            "compiles": len(recent),
                            "window_s": self._storm_window,
                            "step": self.steps,
                            "compile_s": self.compile_s,
                            "time": self._wall(),
                        }
            else:
                self.productive_s += duration_s
                self.productive_steps += steps
                per_step = duration_s / max(steps, 1)
                self._last_step_s = per_step
                # flag BEFORE appending: the outlier must not dilute the
                # median it is judged against. The median itself refreshes
                # every 8 steps — a per-step O(1) compare, not a per-step
                # sort (this path runs at millisecond step times).
                med = self._median_cache
                if (self._slow_factor > 0 and med is not None and med > 0
                        and per_step > self._slow_factor * med):
                    self._slow_step = {
                        "step": self.steps,
                        "duration_s": per_step,
                        "median_s": med,
                        "ratio": per_step / med,
                        "time": self._wall(),
                    }
                self._recent_steps.append(per_step)
                self._steps_since_median += 1
                if (self._steps_since_median >= 8
                        and len(self._recent_steps) >= 8):
                    self._median_cache = statistics.median(self._recent_steps)
                    self._steps_since_median = 0
            if tokens:
                self.tokens += tokens
            if examples:
                self.examples += examples
            sample_hbm = (
                self.steps <= steps or self.steps % _HBM_SAMPLE_EVERY == 0
            )
        if sample_hbm:
            self._sample_hbm()
        if self._emit_metrics:
            self._publish(duration_s, steps, compile_step)
        if self._emit_spans:
            self._emit_step_span(duration_s, steps, tokens, compile_step,
                                 start_wall)

    def step_timer(self):
        """Context manager measuring one step call: ``with rec.step_timer():``"""
        return _StepTimer(self)

    def seconds_since_last_step(self) -> Optional[float]:
        """Age of the newest recorded step; None before the first step.
        The stall watchdog (_private/watchdog.py) reads this to detect a
        training loop that recorded steps and then went silent."""
        with self._lock:
            if self.steps == 0:
                return None
            return self._clock() - self._last_step_at

    def pop_slow_step(self) -> Optional[Dict[str, float]]:
        """Latest pending slow-step flag (step slower than
        profile_slow_step_factor x trailing median), cleared on read. The
        watchdog polls this and answers with an automatic cluster-profile
        capture + ``slow_step`` incident."""
        with self._lock:
            out, self._slow_step = self._slow_step, None
            return out

    def pop_compile_storm(self) -> Optional[Dict[str, float]]:
        """Pending compile-storm flag (> K post-warmup jit compiles within
        the configured window), cleared on read. The watchdog polls this and
        publishes a ``jit_cache_miss_storm`` incident with an attached
        cluster capture + auto-analysis."""
        with self._lock:
            out, self._compile_storm = self._compile_storm, None
            return out

    # ------------------------------------------------------------- derived

    def _elapsed(self) -> float:
        return max(self._clock() - self._start, 1e-9)

    def goodput(self) -> float:
        """Fraction of elapsed wall time spent in productive steps."""
        return min(1.0, self.productive_s / self._elapsed())

    def tokens_per_second(self) -> Optional[float]:
        if not self.tokens or self.productive_s <= 0:
            return None
        return self.tokens / self.productive_s

    def examples_per_second(self) -> Optional[float]:
        if not self.examples or self.productive_s <= 0:
            return None
        return self.examples / self.productive_s

    def _total_peak_flops(self) -> Optional[float]:
        if self._explicit_peak is not None:
            n = self._n_devices or len(self._jax_devices() or []) or 1
            return self._explicit_peak * n
        devices = self._jax_devices()
        if not devices:
            return None
        per = peak_flops_per_device(getattr(devices[0], "device_kind", ""))
        if per is None:
            return None
        return per * (self._n_devices or len(devices))

    def mfu(self) -> Optional[float]:
        """Model FLOPs utilization: achieved FLOP/s over peak FLOP/s.

        Needs a FLOPs estimate (flops_per_step, or flops_per_token x
        observed tokens) and a known device peak; returns None otherwise
        (e.g. on CPU) rather than a fabricated number."""
        peak = self._total_peak_flops()
        if peak is None or self.productive_s <= 0:
            return None
        if self._flops_per_step is not None:
            achieved = self._flops_per_step * self.productive_steps
        elif self._flops_per_token is not None and self.tokens:
            achieved = self._flops_per_token * self.tokens
        else:
            return None
        return achieved / self.productive_s / peak

    def hbm_bytes_in_use(self) -> Dict[str, float]:
        """Latest per-device HBM bytes in use ({} on CPU — memory_stats()
        is absent there)."""
        with self._lock:
            return dict(self._hbm_bytes)

    def summary(self) -> Dict[str, Any]:
        """Rolling summary dict, also what session.report auto-attaches."""
        with self._lock:
            steps = self.steps
            productive = self.productive_s
            compile_s = self.compile_s
            last = self._last_step_s
        out = {
            "steps": steps,
            "step_time_s": last,
            "productive_time_s": round(productive, 6),
            "compile_time_s": round(compile_s, 6),
            "goodput": round(self.goodput(), 6),
        }
        tps = self.tokens_per_second()
        if tps is not None:
            out["tokens_per_s"] = round(tps, 3)
        eps = self.examples_per_second()
        if eps is not None:
            out["examples_per_s"] = round(eps, 3)
        mfu = self.mfu()
        if mfu is not None:
            out["mfu"] = round(mfu, 6)
        hbm = self.hbm_bytes_in_use()
        if hbm:
            out["hbm_bytes_in_use"] = max(hbm.values())
        return out

    # ------------------------------------------------------------ emission

    def _jax_devices(self):
        if self._devices is not None:
            return self._devices
        try:
            import jax

            self._devices = jax.local_devices()
        except Exception:
            self._devices = []
        return self._devices

    def _sample_hbm(self):
        """Per-device HBM bytes in use via device.memory_stats() —
        gracefully absent on CPU (memory_stats() returns None there)."""
        for d in self._jax_devices() or []:
            try:
                stats = d.memory_stats()
            except Exception:
                stats = None
            if not stats or "bytes_in_use" not in stats:
                continue
            key = f"{getattr(d, 'platform', 'dev')}:{getattr(d, 'id', 0)}"
            with self._lock:
                self._hbm_bytes[key] = float(stats["bytes_in_use"])

    def _metric_objects(self):
        if self._metrics is None:
            from ray_tpu.util.metrics import Counter, Gauge, Histogram

            tags = ("run",)
            self._metrics = {
                "step_seconds": Histogram(
                    "ray_tpu_train_step_seconds",
                    "wall time per optimizer step",
                    boundaries=_STEP_SECONDS_BOUNDARIES, tag_keys=tags),
                "steps_total": Counter(
                    "ray_tpu_train_steps_total",
                    "optimizer steps completed", tag_keys=tags),
                "tokens_per_s": Gauge(
                    "ray_tpu_train_tokens_per_second",
                    "training throughput, tokens/s", tag_keys=tags),
                "examples_per_s": Gauge(
                    "ray_tpu_train_examples_per_second",
                    "training throughput, examples/s", tag_keys=tags),
                "mfu": Gauge(
                    "ray_tpu_train_mfu_ratio",
                    "estimated model FLOPs utilization (0-1)", tag_keys=tags),
                "goodput": Gauge(
                    "ray_tpu_train_goodput_ratio",
                    "productive step time / elapsed wall time (0-1)",
                    tag_keys=tags),
                "compile_s": Gauge(
                    "ray_tpu_train_compile_seconds",
                    "cumulative jit compile time", tag_keys=tags),
                "hbm": Gauge(
                    "ray_tpu_train_hbm_bytes_in_use",
                    "per-device HBM bytes in use",
                    tag_keys=tags + ("device",)),
            }
        return self._metrics

    def _publish(self, duration_s: float, steps: int, compile_step: bool):
        try:
            m = self._metric_objects()
            tags = {"run": self.run_name}
            if compile_step:
                m["compile_s"].set(self.compile_s, tags=tags)
            else:
                # one observation per step CALL (a multi_step scan is one
                # dispatch) at the per-step duration — quantiles stay
                # representative and a 10k-step scan costs one bucket bump
                m["step_seconds"].observe(
                    duration_s / max(steps, 1), tags=tags)
            m["steps_total"].inc(steps, tags=tags)
            now = self._clock()
            if (now - self._last_gauge_pub < self._publish_interval
                    and not compile_step):
                return
            self._last_gauge_pub = now
            m["goodput"].set(self.goodput(), tags=tags)
            tps = self.tokens_per_second()
            if tps is not None:
                m["tokens_per_s"].set(tps, tags=tags)
            eps = self.examples_per_second()
            if eps is not None:
                m["examples_per_s"].set(eps, tags=tags)
            mfu = self.mfu()
            if mfu is not None:
                m["mfu"].set(mfu, tags=tags)
            for dev, used in self.hbm_bytes_in_use().items():
                m["hbm"].set(used, tags={**tags, "device": dev})
        except Exception:
            pass  # telemetry must never fail a training step

    def _emit_step_span(self, duration_s, steps, tokens, compile_step,
                        start_wall):
        """One SPAN task event per step call: ``ray-tpu timeline`` renders
        step boundaries in the Chrome trace beside task execution."""
        try:
            from ray_tpu._private import worker as worker_mod

            w = worker_mod.global_worker
            if w is None:
                return
            end = self._wall()
            start = start_wall if start_wall is not None else end - duration_s
            ctx = {
                "trace_id": self._trace_id,
                "span_id": uuid.uuid4().hex[:16],
                "parent_span_id": "",
            }
            name = "train_step.compile" if compile_step else "train_step"
            attrs = {"step": self.steps, "num_steps": steps}
            if tokens:
                attrs["tokens"] = tokens
            w.task_events.record_span(name, start, end, ctx, attrs)
        except Exception:
            pass


class _StepTimer:
    def __init__(self, recorder: StepRecorder):
        self._rec = recorder
        self._t0 = None
        self._w0 = None
        self.tokens: Optional[int] = None
        self.examples: Optional[int] = None
        self.steps = 1
        self.compile_step = False

    def __enter__(self):
        self._t0 = self._rec._clock()
        self._w0 = self._rec._wall()
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc_type is None:
            self._rec.record_step(
                self._rec._clock() - self._t0,
                steps=self.steps, tokens=self.tokens, examples=self.examples,
                compile_step=self.compile_step, start_wall=self._w0,
            )
        return False


# ------------------------------------------------------ device-trace window
# The host-side sampler (profiling plane) sees Python; XLA device time is a
# black box to it. This controller arms ``jax.profiler.trace`` around a
# window of N train steps — TrainStep calls on_step_begin/on_step_end around
# each dispatch — and registers the produced trace directory with the GCS so
# the merged Perfetto timeline links to it (open with `tensorboard
# --logdir` / xprof for the device view).


class DeviceTraceController:
    """Arm-once device-trace windows; inert (two attribute reads per step)
    unless armed via ``request()`` or ``RTPU_device_trace_steps=N``."""

    def __init__(self):
        self._lock = threading.Lock()
        self._active = False
        self._dir: Optional[str] = None
        self._count = 0
        self._target = 0
        self._requested_dir: Optional[str] = None
        from ray_tpu._private.config import RTPU_CONFIG

        self._armed = max(0, int(RTPU_CONFIG.device_trace_steps))

    # ------------------------------------------------------------- control

    def request(self, num_steps: int = 3,
                trace_dir: Optional[str] = None) -> None:
        """Arm a trace window around the next ``num_steps`` step calls."""
        with self._lock:
            if not self._active:
                self._armed = max(1, int(num_steps))
                self._requested_dir = trace_dir

    @staticmethod
    def supported() -> bool:
        """Device tracing is a no-op on CPU or without a usable jax
        profiler — RTPU_device_trace_force=1 overrides (tests, host-trace
        debugging)."""
        from ray_tpu._private.config import RTPU_CONFIG

        if RTPU_CONFIG.device_trace_force:
            return True
        try:
            import jax

            if not hasattr(jax.profiler, "start_trace"):
                return False
            return any(d.platform != "cpu" for d in jax.local_devices())
        except Exception:
            return False

    def _trace_dir(self) -> str:
        if self._requested_dir:
            return self._requested_dir
        base = ""
        try:
            from ray_tpu._private import worker as worker_mod

            w = worker_mod.global_worker
            if w is not None and w.session_dir:
                base = os.path.join(w.session_dir, "logs", "device_traces")
        except Exception:
            pass
        if not base:
            import tempfile

            base = os.path.join(tempfile.gettempdir(), "ray_tpu_device_traces")
        return os.path.join(base, f"trace_{int(time.time() * 1000)}")

    # ----------------------------------------------------------- per step

    def on_step_begin(self) -> None:
        if not self._armed or self._active:
            return
        with self._lock:
            if not self._armed or self._active:
                return
            target, self._armed = self._armed, 0
            if not self.supported():
                return  # silently disarm: no-op on CPU/absent profiler
            try:
                import jax

                path = self._trace_dir()
                os.makedirs(path, exist_ok=True)
                jax.profiler.start_trace(path)
            except Exception:
                return
            self._active = True
            self._dir = path
            self._target = target
            self._count = 0

    def on_step_end(self, out=None) -> None:
        if not self._active:
            return
        with self._lock:
            if not self._active:
                return
            self._count += 1
            if self._count < self._target:
                return
            self._active = False
            path, self._dir = self._dir, None
            try:
                import jax

                if out is not None:
                    # drain the async dispatch backlog so the window holds
                    # the whole last step, not its launch
                    jax.block_until_ready(out)
                jax.profiler.stop_trace()
            except Exception:
                return
        self._register(path)

    def _register(self, path: str) -> None:
        try:
            from ray_tpu._private import profiling, worker as worker_mod

            w = worker_mod.global_worker
            if w is not None:
                profiling.register_device_trace(
                    w.gcs, path, steps=self._target)
        except Exception:
            pass


def request_device_trace(num_steps: int = 3,
                         trace_dir: Optional[str] = None) -> bool:
    """Arm a device-trace window on the current recorder; False when no
    recorder is registered in this process."""
    rec = current_recorder()
    if rec is None:
        return False
    rec.device_trace.request(num_steps, trace_dir)
    return True


# ----------------------------------------------------- process-global hookup
# TrainStep registers its recorder here; session.report auto-attaches the
# summary of whatever recorder is current in this process.

_current: Optional[StepRecorder] = None
_current_lock = threading.Lock()


def set_current_recorder(recorder: Optional[StepRecorder]) -> None:
    global _current
    with _current_lock:
        _current = recorder


def current_recorder() -> Optional[StepRecorder]:
    return _current


def get_or_create_recorder(**kwargs) -> StepRecorder:
    global _current
    with _current_lock:
        if _current is None:
            _current = StepRecorder(**kwargs)
        return _current


def auto_report_metrics() -> Dict[str, Any]:
    """Telemetry keys merged into every session.report() (namespaced so they
    never collide with user metrics)."""
    rec = current_recorder()
    if rec is None:
        return {}
    return {f"telemetry/{k}": v for k, v in rec.summary().items()}


_REPORT_GAUGES = {
    "telemetry/goodput": "ray_tpu_train_goodput_ratio",
    "telemetry/tokens_per_s": "ray_tpu_train_tokens_per_second",
    "telemetry/examples_per_s": "ray_tpu_train_examples_per_second",
    "telemetry/mfu": "ray_tpu_train_mfu_ratio",
    "telemetry/compile_time_s": "ray_tpu_train_compile_seconds",
    "telemetry/step_time_s": "ray_tpu_train_last_step_seconds",
    "telemetry/hbm_bytes_in_use": "ray_tpu_train_hbm_bytes_in_use",
}
_report_gauge_objs: Dict[str, Any] = {}


def publish_report_summary(metrics: Dict[str, Any], run_name: str = ""):
    """Re-publish a report's auto-attached telemetry/* keys as gauges from
    the CALLING process (trainer driver). The GCS drops a dead worker's
    gauges (stale last-writes poison aggregations), so without this the
    run's final throughput/goodput/MFU would vanish from /metrics the
    moment the worker group shuts down; the driver outlives the run."""
    try:
        from ray_tpu.util.metrics import Gauge

        for key, name in _REPORT_GAUGES.items():
            value = metrics.get(key)
            if not isinstance(value, (int, float)):
                continue
            g = _report_gauge_objs.get(name)
            if g is None:
                g = _report_gauge_objs[name] = Gauge(
                    name, "driver-side rolling train telemetry",
                    tag_keys=("run",))
            g.set(float(value), tags={"run": run_name})
    except Exception:
        pass  # telemetry must never fail a report round
