"""Checkpoint: a directory of files, moved by path (reference:
python/ray/train/_checkpoint.py:56 — a dir + pyarrow-fs URI; here local/NFS
paths, which is also what orbax writes)."""

from __future__ import annotations

import contextlib
import os
import shutil
import tempfile
from typing import Optional


class Checkpoint:
    def __init__(self, path: str):
        self.path = os.path.abspath(path)

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        return cls(path)

    def as_directory(self):
        """Context manager yielding a local directory with the contents."""
        return contextlib.nullcontext(self.path)

    def to_directory(self, path: Optional[str] = None) -> str:
        dest = path or tempfile.mkdtemp(prefix="rtpu_ckpt_")
        if os.path.abspath(dest) != self.path:
            shutil.copytree(self.path, dest, dirs_exist_ok=True)
        return dest

    def __repr__(self):
        return f"Checkpoint(path={self.path!r})"

    def __reduce__(self):
        return (Checkpoint, (self.path,))
