"""Mutable shared-memory channels for compiled actor DAGs.

TPU-native counterpart of the reference's shared-memory channels
(reference: python/ray/experimental/channel/shared_memory_channel.py:147,
src/ray/core_worker/experimental_mutable_object_manager.h:39): a channel is a
plasma object that is sealed once and then *mutated in place* — every
process on the node maps the same writable segment, so handoff is one memcpy
with no RPC, no allocation, and no per-step object creation.

Protocol (single writer, up to MAX_READERS readers, buffer depth 1):

    header: [u64 write_seq][u64 data_len][u32 flags][u32 n_readers]
            [u64 ack_seq x MAX_READERS]
    body:   serialized payload (serialization.write_blob format)

- writer: wait until every registered reader's ack_seq == write_seq
  (previous value consumed), write body + data_len + flags, memory fence,
  then publish write_seq+1.
- reader r: wait until write_seq > last seen, read body, set ack_seq[r].
Because the writer never mutates while a reader is between "observe seq"
and "ack", readers never see torn data. Blocking is adaptive spin
(0 -> 100 us -> 1 ms), fine for the ~ms-scale steps pipelines push through
channels; a teardown flag turns every blocked peer into ChannelClosed.
"""

from __future__ import annotations

import ctypes
import os
import platform
import struct
import time
from typing import Any, Dict, Optional

from ray_tpu._private import serialization
from ray_tpu._private.ids import ObjectID

MAX_READERS = 8
_HEADER = struct.Struct("<QQII" + "Q" * MAX_READERS)
_FLAG_ERROR = 1
_FLAG_CLOSED = 2

DEFAULT_BUFFER_SIZE = 4 * 1024 * 1024


# --------------------------------------------------------------------- futex
# Event-based blocking on the shared header words (reference analogue: the
# mutable-object manager blocks on a sema,
# core_worker/experimental_mutable_object_manager.h:39). A blocked peer
# sleeps in the kernel instead of burning a core in a spin loop; wakers are
# the writer's publish and each reader's ack. Falls back to adaptive spin
# where the futex syscall is unavailable.

_SYS_FUTEX = {"x86_64": 202, "aarch64": 98}.get(platform.machine())
_FUTEX_WAIT = 0
_FUTEX_WAKE = 1


class _timespec(ctypes.Structure):
    _fields_ = [("tv_sec", ctypes.c_long), ("tv_nsec", ctypes.c_long)]


try:
    _libc = ctypes.CDLL(None, use_errno=True)
    _libc.syscall  # probe
except Exception:  # pragma: no cover - non-POSIX
    _libc = None

# futex is Linux-only: on other POSIX systems the same syscall number is
# an unrelated call, so gate on the OS, not just the arch
_FUTEX_OK = (
    _SYS_FUTEX is not None
    and _libc is not None
    and platform.system() == "Linux"
)


def _futex_wait(addr: int, expected_u32: int, timeout: float):
    """Sleep while *(u32*)addr == expected, up to timeout seconds. Spurious
    returns (EINTR/EAGAIN/timeout) are fine — callers re-check their
    predicate."""
    ts = _timespec(int(timeout), int((timeout % 1.0) * 1e9))
    _libc.syscall(
        _SYS_FUTEX, ctypes.c_void_p(addr), _FUTEX_WAIT,
        ctypes.c_uint(expected_u32), ctypes.byref(ts), None, 0,
    )


def _futex_wake(addr: int):
    _libc.syscall(
        _SYS_FUTEX, ctypes.c_void_p(addr), _FUTEX_WAKE,
        ctypes.c_int(0x7FFFFFFF), None, None, 0,
    )


class ChannelClosed(Exception):
    pass


class ChannelFull(Exception):
    pass


def _plasma():
    from ray_tpu._private.worker import get_global_worker

    return get_global_worker().plasma


class Channel:
    """One-writer/N-reader mutable shared-memory slot.

    Create with ``Channel.create(n_readers)`` on the driver; ship the
    descriptor (``.descriptor()``) to actors which ``Channel.attach`` it with
    their reader index (or as writer with ``reader_index=None``).
    """

    def __init__(self, oid: bytes, view, reader_index: Optional[int],
                 n_readers: int, own_view=None):
        self._oid = oid
        self._view = view  # writable memoryview over the plasma payload
        self._reader_index = reader_index
        self._n_readers = n_readers
        # Resume from this reader's own ack slot — NOT the current write seq:
        # a value published before the reader attached must still be read.
        if reader_index is not None:
            self._last_seen = _HEADER.unpack_from(view, 0)[4 + reader_index]
        else:
            self._last_seen = 0
        self._own = own_view
        # base address of the mapped header for futex waits (0 = fall back
        # to spin: non-Linux, or a non-ctypes-mappable buffer)
        try:
            self._base_addr = (
                ctypes.addressof(ctypes.c_char.from_buffer(view))
                if _FUTEX_OK else 0
            )
        except Exception:
            self._base_addr = 0

    # ------------------------------------------------------------ lifecycle

    @staticmethod
    def create(n_readers: int, buffer_size: int = DEFAULT_BUFFER_SIZE):
        if not (1 <= n_readers <= MAX_READERS):
            raise ValueError(f"n_readers must be in [1, {MAX_READERS}]")
        plasma = _plasma()
        oid = os.urandom(20)
        total = _HEADER.size + buffer_size
        buf = plasma.create(oid, total)
        buf[: _HEADER.size] = _HEADER.pack(0, 0, 0, n_readers,
                                           *([0] * MAX_READERS))
        buf.release()
        plasma.seal(oid)
        view = plasma.get(oid)  # pins; writable (shared PROT_WRITE mapping)
        return Channel(oid, view, None, n_readers, own_view=view)

    @staticmethod
    def attach(descriptor: dict, reader_index: Optional[int]):
        plasma = _plasma()
        view = plasma.get(descriptor["oid"])
        if view is None:
            raise ChannelClosed(
                f"channel object {descriptor['oid'].hex()} not found"
            )
        return Channel(descriptor["oid"], view, reader_index,
                       descriptor["n_readers"], own_view=view)

    def descriptor(self) -> dict:
        return {"oid": self._oid, "n_readers": self._n_readers}

    def close(self):
        """Mark closed; blocked peers raise ChannelClosed."""
        flags = struct.unpack_from("<I", self._view, 16)[0]
        struct.pack_into("<I", self._view, 16, flags | _FLAG_CLOSED)
        if self._base_addr:
            _futex_wake(self._base_addr)  # seq waiters
            for r in range(self._n_readers):
                _futex_wake(self._base_addr + 24 + 8 * r)  # ack waiters

    def release(self):
        try:
            if self._own is not None:
                self._own.release()
                _plasma().release(ObjectID(self._oid))
                self._own = None
        except Exception:
            pass

    def destroy(self):
        self.close()
        self.release()
        try:
            _plasma().delete(ObjectID(self._oid))
        except Exception:
            pass

    # ------------------------------------------------------------- plumbing

    def _peek_seq(self) -> int:
        return struct.unpack_from("<Q", self._view, 0)[0]

    def _flags(self) -> int:
        return struct.unpack_from("<I", self._view, 16)[0]

    def _acks(self):
        return _HEADER.unpack_from(self._view, 0)[4:4 + self._n_readers]

    @staticmethod
    def _spin(predicate, timeout: Optional[float], what: str):
        deadline = None if timeout is None else time.monotonic() + timeout
        delay = 0.0
        while not predicate():
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError(f"channel {what} timed out")
            if delay:
                time.sleep(delay)
            delay = min((delay or 5e-5) * 2, 1e-3)

    # ------------------------------------------------------------------- io

    def write(self, value: Any, timeout: Optional[float] = None,
              is_error: bool = False):
        seq = self._peek_seq()

        def consumed():
            if self._flags() & _FLAG_CLOSED:
                raise ChannelClosed("channel closed")
            return all(a >= seq for a in self._acks())

        if self._base_addr:
            deadline = None if timeout is None else time.monotonic() + timeout
            while not consumed():
                if deadline is not None and time.monotonic() > deadline:
                    raise TimeoutError("channel write timed out")
                acks = self._acks()
                for r, a in enumerate(acks):
                    if a < seq:
                        # sleep until reader r's ack word changes (each ack
                        # slot has a single writing process, so the low
                        # 32 bits are a valid futex value)
                        _futex_wait(
                            self._base_addr + 24 + 8 * r,
                            a & 0xFFFFFFFF, 0.2,
                        )
                        break
        else:
            self._spin(consumed, timeout, "write")
        # raw protocol-5 buffers stream straight into the shared-memory ring
        # (one copy total) — same discipline as the plasma put path
        p, bufs, _refs = serialization.serialize(value)
        size = serialization.blob_size(p, bufs)
        cap = len(self._view) - _HEADER.size
        if size > cap:
            raise ChannelFull(
                f"serialized value is {size} bytes; channel buffer is {cap} "
                "(pass a larger buffer_size_bytes to experimental_compile)"
            )
        serialization.write_blob(self._view[_HEADER.size:], p, bufs)
        struct.pack_into("<QI", self._view, 8, size,
                         _FLAG_ERROR if is_error else 0)
        # publish: plain store is a fence-enough on x86/ARM under the GIL
        struct.pack_into("<Q", self._view, 0, seq + 1)
        if self._base_addr:
            _futex_wake(self._base_addr)

    def read(self, timeout: Optional[float] = None) -> Any:
        """Blocking read of the next value; deserializes a fresh copy."""
        r = self._reader_index
        if r is None:
            raise RuntimeError("writer end cannot read")

        def available():
            if self._flags() & _FLAG_CLOSED:
                raise ChannelClosed("channel closed")
            return self._peek_seq() > self._last_seen

        if self._base_addr:
            deadline = None if timeout is None else time.monotonic() + timeout
            while not available():
                if deadline is not None and time.monotonic() > deadline:
                    raise TimeoutError("channel read timed out")
                _futex_wait(
                    self._base_addr, self._last_seen & 0xFFFFFFFF, 0.2
                )
        else:
            self._spin(available, timeout, "read")
        seq = self._peek_seq()
        size, flags = struct.unpack_from("<QI", self._view, 8)
        body = self._view[_HEADER.size:_HEADER.size + size]
        value, _refs = serialization.read_blob(bytes(body))
        self._last_seen = seq
        struct.pack_into("<Q", self._view, 24 + 8 * r, seq)
        if self._base_addr:
            _futex_wake(self._base_addr + 24 + 8 * r)
        if flags & _FLAG_ERROR:
            raise _PropagatedError(value)
        return value


class _PropagatedError(Exception):
    """Wraps an upstream exception flowing through a channel."""

    def __init__(self, inner):
        super().__init__(repr(inner))
        self.inner = inner


# ------------------------------------------------------------ socket channel


class SocketChannel:
    """Cross-node channel edge: the same single-writer/N-reader depth-1
    write/ack protocol as the shm Channel, over persistent TCP streams.

    This is the DCN hop of a multi-host pipeline (reference GPU analogue:
    python/ray/experimental/channel/torch_tensor_nccl_channel.py:191 —
    where the reference moves tensors over NCCL p2p, a TPU pipeline's
    cross-host edge rides the host NICs; the intra-host edges stay on
    shared memory).

    Wire: writer listens; each reader connects and sends [u32 reader_idx].
    Value frames writer->reader: [u64 seq][u32 flags][u64 len][payload];
    ack frames reader->writer: [u64 seq]. The writer publishes seq N only
    after every reader acked N-1 (depth 1), matching the shm semantics so
    the compiled-DAG exec loop treats both identically.
    """

    def __init__(self, n_readers: int):
        self._n_readers = n_readers
        self._server = None
        self._conns: Dict[int, Any] = {}
        self._seq = 0
        self._closed = False
        self._addr = None
        self._token = os.urandom(8)
        self._acked: Dict[int, int] = {}  # per reader: last ack consumed
        self._rxbuf: Dict[int, bytearray] = {}  # per reader: partial acks

    def _recv_buffered(self, ridx, conn, n: int, deadline) -> bytes:
        buf = self._rxbuf.setdefault(ridx, bytearray())
        return _buffered_recv_exact(
            conn, buf, n, deadline,
            timeout_msg="channel write timed out awaiting ack",
            closed_msg=f"reader {ridx} gone",
        )

    # --------------------------------------------------------------- writer

    @staticmethod
    def create(n_readers: int, buffer_size: int = 0) -> "SocketChannel":
        import socket as _socket

        ch = SocketChannel(n_readers)
        srv = _socket.socket(_socket.AF_INET, _socket.SOCK_STREAM)
        srv.setsockopt(_socket.SOL_SOCKET, _socket.SO_REUSEADDR, 1)
        srv.bind((_node_ip(), 0))
        srv.listen(n_readers)
        ch._server = srv
        ch._addr = srv.getsockname()
        import threading

        t = threading.Thread(target=ch._accept_loop, daemon=True,
                             name="rtpu-chan-accept")
        t.start()
        return ch

    def _accept_loop(self):
        import socket as _socket

        try:
            while len(self._conns) < self._n_readers and not self._closed:
                conn, _ = self._server.accept()
                conn.setsockopt(_socket.IPPROTO_TCP, _socket.TCP_NODELAY, 1)
                tok = _recv_exact(conn, 8)
                ridx = struct.unpack("<I", _recv_exact(conn, 4))[0]
                if tok != self._token or not (0 <= ridx < self._n_readers):
                    conn.close()
                    continue
                self._conns[ridx] = conn
        except OSError:
            return  # closed during accept

    def descriptor(self) -> dict:
        return {
            "type": "socket",
            "addr": list(self._addr),
            "n_readers": self._n_readers,
            "token": self._token,
        }

    def write(self, value: Any, timeout: Optional[float] = None,
              is_error: bool = False):
        deadline = None if timeout is None else time.monotonic() + timeout
        while len(self._conns) < self._n_readers:
            if self._closed:
                raise ChannelClosed("channel closed")
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError("channel write timed out (readers absent)")
            time.sleep(0.005)
        if self._seq > 0:
            # depth-1 backpressure: collect every reader's ack of seq-1.
            # Resumable buffered recv: a timeout mid-ack must not desync
            # the stream (the bytes stay buffered for the retry), and must
            # surface as TimeoutError like the shm channel, not
            # ChannelClosed.
            for ridx, conn in self._conns.items():
                if self._acked.get(ridx, 0) >= self._seq:
                    continue  # already consumed on an earlier (timed-out) try
                ack = struct.unpack(
                    "<Q", self._recv_buffered(ridx, conn, 8, deadline)
                )[0]
                if ack != self._seq:
                    raise ChannelClosed(
                        f"protocol error: reader {ridx} acked {ack}, "
                        f"expected {self._seq}"
                    )
                self._acked[ridx] = ack
        blob = serialization.serialize_to_blob(value)
        self._seq += 1
        header = struct.pack("<QIQ", self._seq,
                             _FLAG_ERROR if is_error else 0, len(blob))
        for ridx, conn in list(self._conns.items()):
            # Honor the caller's deadline during the send too: a reader
            # stalled with a full kernel buffer must not block forever.
            # A deadline that is ALREADY spent raises retryable
            # TimeoutError before any bytes go out; a timeout mid-frame is
            # unrecoverable for this stream (sendall may have written part
            # of the frame) -> ChannelClosed.
            remaining = (
                None if deadline is None else deadline - time.monotonic()
            )
            if remaining is not None and remaining <= 0.05:
                raise TimeoutError("channel write timed out before send")
            conn.settimeout(remaining)
            try:
                conn.sendall(header + blob)
            except TimeoutError:
                raise ChannelClosed(
                    f"reader {ridx} stalled mid-frame (send timeout)"
                )
            except OSError as e:
                raise ChannelClosed(f"reader {ridx} gone: {e}")

    # --------------------------------------------------------------- reader

    @staticmethod
    def attach(descriptor: dict, reader_index: int) -> "_SocketReader":
        return _SocketReader(descriptor, reader_index)

    # ------------------------------------------------------------ lifecycle

    def close(self):
        self._closed = True
        for conn in self._conns.values():
            try:
                conn.close()
            except Exception:
                pass
        try:
            self._server.close()
        except Exception:
            pass

    def destroy(self):
        self.close()


class _SocketReader:
    def __init__(self, descriptor: dict, reader_index: int):
        import socket as _socket

        if reader_index is None:
            raise RuntimeError(
                "socket channel writer must be the creating process"
            )
        self._sock = _socket.create_connection(
            tuple(descriptor["addr"]), timeout=30
        )
        self._sock.setsockopt(_socket.IPPROTO_TCP, _socket.TCP_NODELAY, 1)
        self._sock.sendall(
            descriptor["token"] + struct.pack("<I", reader_index)
        )
        self._sock.settimeout(None)
        self._rxbuf = bytearray()
        self._hdr = None  # parsed header of a frame whose body is pending

    def _recv_exact(self, n: int, deadline) -> bytes:
        return _buffered_recv_exact(
            self._sock, self._rxbuf, n, deadline,
            timeout_msg="channel read timed out",
            closed_msg="writer closed the channel",
        )

    def read(self, timeout: Optional[float] = None) -> Any:
        deadline = None if timeout is None else time.monotonic() + timeout
        if self._hdr is None:
            self._hdr = struct.unpack("<QIQ", self._recv_exact(20, deadline))
        seq, flags, length = self._hdr
        body = self._recv_exact(length, deadline)
        self._hdr = None
        value, _refs = serialization.read_blob(memoryview(body))
        try:
            self._sock.sendall(struct.pack("<Q", seq))
        except OSError:
            raise ChannelClosed("writer gone at ack")
        if flags & _FLAG_ERROR:
            raise _PropagatedError(value)
        return value

    def close(self):
        try:
            self._sock.close()
        except Exception:
            pass

    def destroy(self):
        self.close()


def _buffered_recv_exact(sock, buf: bytearray, n: int, deadline,
                         timeout_msg: str, closed_msg: str) -> bytes:
    """Shared resumable recv over a caller-owned bytearray: consumes and
    returns n bytes once available. Partial bytes accumulate IN PLACE, so
    they survive a timeout and a retry continues mid-frame instead of
    desyncing the stream. TimeoutError means retryable; ChannelClosed
    means the peer is gone."""
    while len(buf) < n:
        sock.settimeout(
            None if deadline is None
            else max(0.01, deadline - time.monotonic())
        )
        try:
            chunk = sock.recv(n - len(buf))
        except TimeoutError:
            raise TimeoutError(timeout_msg) from None
        except OSError as e:
            raise ChannelClosed(f"{closed_msg}: {e}")
        if not chunk:
            raise ChannelClosed(closed_msg)
        buf += chunk
    out = bytes(buf[:n])
    del buf[:n]
    return out


def _recv_exact(sock, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise EOFError("socket closed")
        buf += chunk
    return buf


def _node_ip() -> str:
    from ray_tpu._private.worker import get_global_worker

    try:
        return get_global_worker().host
    except Exception:
        return "127.0.0.1"


# -------------------------------------------------- registry + attach helper
# Channels created inside an actor process on behalf of a compiled DAG are
# kept alive (and torn down) through this registry, keyed by a token the
# driver holds.

_registry: Dict[bytes, Any] = {}


def register_channel(token: bytes, ch) -> bytes:
    _registry[token] = ch
    return token


def close_registered(token: bytes):
    ch = _registry.pop(token, None)
    if ch is not None:
        try:
            ch.destroy()
        except Exception:
            pass


def attach_channel(descriptor: dict, reader_index: Optional[int]):
    """Attach either channel kind from its descriptor. The writer end of a
    socket channel only exists in its creating process — resolve it from
    the registry there."""
    if descriptor.get("type") == "socket":
        if reader_index is None:
            ch = _registry.get(descriptor["token"])
            if ch is None:
                raise ChannelClosed("socket channel writer not in this process")
            return ch
        return SocketChannel.attach(descriptor, reader_index)
    return Channel.attach(descriptor, reader_index)
