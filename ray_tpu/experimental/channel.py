"""Mutable shared-memory channels for compiled actor DAGs.

TPU-native counterpart of the reference's shared-memory channels
(reference: python/ray/experimental/channel/shared_memory_channel.py:147,
src/ray/core_worker/experimental_mutable_object_manager.h:39): a channel is a
plasma object that is sealed once and then *mutated in place* — every
process on the node maps the same writable segment, so handoff is one memcpy
with no RPC, no allocation, and no per-step object creation.

Protocol (single writer, up to MAX_READERS readers, buffer depth 1):

    header: [u64 write_seq][u64 data_len][u32 flags][u32 n_readers]
            [u64 ack_seq x MAX_READERS]
    body:   serialized payload (serialization.write_blob format)

- writer: wait until every registered reader's ack_seq == write_seq
  (previous value consumed), write body + data_len + flags, memory fence,
  then publish write_seq+1.
- reader r: wait until write_seq > last seen, read body, set ack_seq[r].
Because the writer never mutates while a reader is between "observe seq"
and "ack", readers never see torn data. Blocking is adaptive spin
(0 -> 100 us -> 1 ms), fine for the ~ms-scale steps pipelines push through
channels; a teardown flag turns every blocked peer into ChannelClosed.
"""

from __future__ import annotations

import os
import struct
import time
from typing import Any, Optional

from ray_tpu._private import serialization
from ray_tpu._private.ids import ObjectID

MAX_READERS = 8
_HEADER = struct.Struct("<QQII" + "Q" * MAX_READERS)
_FLAG_ERROR = 1
_FLAG_CLOSED = 2

DEFAULT_BUFFER_SIZE = 4 * 1024 * 1024


class ChannelClosed(Exception):
    pass


class ChannelFull(Exception):
    pass


def _plasma():
    from ray_tpu._private.worker import get_global_worker

    return get_global_worker().plasma


class Channel:
    """One-writer/N-reader mutable shared-memory slot.

    Create with ``Channel.create(n_readers)`` on the driver; ship the
    descriptor (``.descriptor()``) to actors which ``Channel.attach`` it with
    their reader index (or as writer with ``reader_index=None``).
    """

    def __init__(self, oid: bytes, view, reader_index: Optional[int],
                 n_readers: int, own_view=None):
        self._oid = oid
        self._view = view  # writable memoryview over the plasma payload
        self._reader_index = reader_index
        self._n_readers = n_readers
        # Resume from this reader's own ack slot — NOT the current write seq:
        # a value published before the reader attached must still be read.
        if reader_index is not None:
            self._last_seen = _HEADER.unpack_from(view, 0)[4 + reader_index]
        else:
            self._last_seen = 0
        self._own = own_view

    # ------------------------------------------------------------ lifecycle

    @staticmethod
    def create(n_readers: int, buffer_size: int = DEFAULT_BUFFER_SIZE):
        if not (1 <= n_readers <= MAX_READERS):
            raise ValueError(f"n_readers must be in [1, {MAX_READERS}]")
        plasma = _plasma()
        oid = os.urandom(20)
        total = _HEADER.size + buffer_size
        buf = plasma.create(oid, total)
        buf[: _HEADER.size] = _HEADER.pack(0, 0, 0, n_readers,
                                           *([0] * MAX_READERS))
        buf.release()
        plasma.seal(oid)
        view = plasma.get(oid)  # pins; writable (shared PROT_WRITE mapping)
        return Channel(oid, view, None, n_readers, own_view=view)

    @staticmethod
    def attach(descriptor: dict, reader_index: Optional[int]):
        plasma = _plasma()
        view = plasma.get(descriptor["oid"])
        if view is None:
            raise ChannelClosed(
                f"channel object {descriptor['oid'].hex()} not found"
            )
        return Channel(descriptor["oid"], view, reader_index,
                       descriptor["n_readers"], own_view=view)

    def descriptor(self) -> dict:
        return {"oid": self._oid, "n_readers": self._n_readers}

    def close(self):
        """Mark closed; blocked peers raise ChannelClosed."""
        flags = struct.unpack_from("<I", self._view, 16)[0]
        struct.pack_into("<I", self._view, 16, flags | _FLAG_CLOSED)

    def release(self):
        try:
            if self._own is not None:
                self._own.release()
                _plasma().release(ObjectID(self._oid))
                self._own = None
        except Exception:
            pass

    def destroy(self):
        self.close()
        self.release()
        try:
            _plasma().delete(ObjectID(self._oid))
        except Exception:
            pass

    # ------------------------------------------------------------- plumbing

    def _peek_seq(self) -> int:
        return struct.unpack_from("<Q", self._view, 0)[0]

    def _flags(self) -> int:
        return struct.unpack_from("<I", self._view, 16)[0]

    def _acks(self):
        return _HEADER.unpack_from(self._view, 0)[4:4 + self._n_readers]

    @staticmethod
    def _spin(predicate, timeout: Optional[float], what: str):
        deadline = None if timeout is None else time.monotonic() + timeout
        delay = 0.0
        while not predicate():
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError(f"channel {what} timed out")
            if delay:
                time.sleep(delay)
            delay = min((delay or 5e-5) * 2, 1e-3)

    # ------------------------------------------------------------------- io

    def write(self, value: Any, timeout: Optional[float] = None,
              is_error: bool = False):
        seq = self._peek_seq()

        def consumed():
            if self._flags() & _FLAG_CLOSED:
                raise ChannelClosed("channel closed")
            return all(a >= seq for a in self._acks())

        self._spin(consumed, timeout, "write")
        payload, _ = serialization.serialize_inline(value)
        size = serialization.blob_size(payload["p"], payload["b"])
        cap = len(self._view) - _HEADER.size
        if size > cap:
            raise ChannelFull(
                f"serialized value is {size} bytes; channel buffer is {cap} "
                "(pass a larger buffer_size_bytes to experimental_compile)"
            )
        serialization.write_blob(
            self._view[_HEADER.size:], payload["p"], payload["b"]
        )
        struct.pack_into("<QI", self._view, 8, size,
                         _FLAG_ERROR if is_error else 0)
        # publish: plain store is a fence-enough on x86/ARM under the GIL
        struct.pack_into("<Q", self._view, 0, seq + 1)

    def read(self, timeout: Optional[float] = None) -> Any:
        """Blocking read of the next value; deserializes a fresh copy."""
        r = self._reader_index
        if r is None:
            raise RuntimeError("writer end cannot read")

        def available():
            if self._flags() & _FLAG_CLOSED:
                raise ChannelClosed("channel closed")
            return self._peek_seq() > self._last_seen

        self._spin(available, timeout, "read")
        seq = self._peek_seq()
        size, flags = struct.unpack_from("<QI", self._view, 8)
        body = self._view[_HEADER.size:_HEADER.size + size]
        value, _refs = serialization.read_blob(bytes(body))
        self._last_seen = seq
        struct.pack_into("<Q", self._view, 24 + 8 * r, seq)
        if flags & _FLAG_ERROR:
            raise _PropagatedError(value)
        return value


class _PropagatedError(Exception):
    """Wraps an upstream exception flowing through a channel."""

    def __init__(self, inner):
        super().__init__(repr(inner))
        self.inner = inner
