"""ray_tpu — a TPU-native distributed AI runtime with the capabilities of Ray.

Core runtime: tasks, actors, a shared-memory object store, ownership-based
distributed refcounting, resource-aware two-level scheduling, placement
groups, fault tolerance — plus ML libraries (train/tune/data/serve/rllib)
whose device plane is jax/XLA/pallas over TPU ICI instead of torch/NCCL.
"""

from ray_tpu._version import version as __version__  # noqa: F401
from ray_tpu._private.object_ref import ObjectRef  # noqa: F401
from ray_tpu.actor import ActorClass, ActorHandle, get_actor  # noqa: F401
from ray_tpu.api import (  # noqa: F401
    available_resources,
    cancel,
    cluster_resources,
    get,
    init,
    is_initialized,
    kill,
    nodes,
    put,
    remote,
    shutdown,
    timeline,
    wait,
)
from ray_tpu.remote_function import RemoteFunction  # noqa: F401
from ray_tpu.runtime_context import get_runtime_context  # noqa: F401
from ray_tpu import exceptions  # noqa: F401

__all__ = [
    "__version__",
    "ObjectRef",
    "ActorClass",
    "ActorHandle",
    "get_actor",
    "available_resources",
    "cancel",
    "cluster_resources",
    "get",
    "init",
    "is_initialized",
    "kill",
    "nodes",
    "put",
    "remote",
    "shutdown",
    "timeline",
    "wait",
    "RemoteFunction",
    "get_runtime_context",
    "exceptions",
]
