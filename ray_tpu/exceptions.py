"""Public exception types (mirrors reference python/ray/exceptions.py surface)."""

from __future__ import annotations

import traceback


class RayTpuError(Exception):
    pass


class TaskError(RayTpuError):
    """A task raised; re-raised at every `get` of its returns.

    Carries the remote traceback like the reference's RayTaskError
    (reference: python/ray/exceptions.py RayTaskError).
    """

    def __init__(self, cause: Exception, remote_tb: str, task_name: str = ""):
        self.cause = cause
        self.remote_traceback = remote_tb
        self.task_name = task_name
        super().__init__(f"task {task_name} failed:\n{remote_tb}")

    def as_instanceof_cause(self):
        return self


class WorkerCrashedError(RayTpuError):
    """The worker executing the task died unexpectedly."""


class ActorDiedError(RayTpuError):
    def __init__(self, actor_id=None, msg="actor died"):
        self.actor_id = actor_id
        super().__init__(msg)

    def __reduce__(self):
        # Exception pickling replays __init__ with self.args, which holds
        # only (msg,) — without this the death cause would land in actor_id
        # and the message reset to the default after any serialization hop.
        return (type(self), (self.actor_id, str(self)))


class ActorUnavailableError(RayTpuError):
    pass


class OwnerDiedError(RayTpuError):
    """The owner process of an object is gone; its value is unrecoverable."""


class ObjectLostError(RayTpuError):
    """All copies of a plasma object were lost and reconstruction failed."""


class ObjectFreedError(RayTpuError):
    pass


class GetTimeoutError(RayTpuError, TimeoutError):
    pass


class TaskCancelledError(RayTpuError):
    def __init__(self, task_id=None):
        self.task_id = task_id
        super().__init__("task was cancelled")

    def __reduce__(self):
        return (type(self), (self.task_id,))


class PendingCallsLimitExceeded(RayTpuError):
    pass


class NodeDiedError(RayTpuError):
    pass


class RuntimeEnvSetupError(RayTpuError):
    pass


class OutOfMemoryError(RayTpuError):
    pass


class PlacementGroupUnschedulableError(RayTpuError):
    pass


def format_exception(e: Exception) -> str:
    return "".join(traceback.format_exception(type(e), e, e.__traceback__))
