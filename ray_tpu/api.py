"""Top-level API: init/shutdown/remote/get/put/wait/kill/cancel and cluster
introspection (reference: python/ray/_private/worker.py — init :1225,
remote :3149, get :2576, put :2691, wait :2756)."""

from __future__ import annotations

import atexit
import inspect
import os
import threading
from typing import Any, Dict, List, Optional, Sequence, Union

from ray_tpu._private import accelerators
from ray_tpu._private.config import RTPU_CONFIG
from ray_tpu._private.ids import JobID
from ray_tpu._private.node import Node
from ray_tpu._private.object_ref import ObjectRef
from ray_tpu._private.worker import (
    MODE_DRIVER,
    CoreWorker,
    get_global_worker,
    set_global_worker,
)
from ray_tpu.actor import ActorClass, ActorHandle, get_actor  # noqa: F401
from ray_tpu.remote_function import RemoteFunction

_init_lock = threading.Lock()
_local_node: Optional[Node] = None
_job_counter = 0


def is_initialized() -> bool:
    from ray_tpu._private import worker as worker_mod

    return worker_mod.global_worker is not None


def init(
    address: Optional[str] = None,
    *,
    num_cpus: Optional[int] = None,
    num_tpus: Optional[int] = None,
    resources: Optional[Dict[str, float]] = None,
    labels: Optional[Dict[str, str]] = None,
    object_store_memory: Optional[int] = None,
    namespace: Optional[str] = None,
    ignore_reinit_error: bool = False,
    _system_config: Optional[dict] = None,
    log_to_driver: bool = True,
):
    """Start (or connect to) a cluster and attach this process as the driver."""
    global _local_node, _job_counter
    with _init_lock:
        if is_initialized():
            if ignore_reinit_error:
                return
            raise RuntimeError("ray_tpu.init() called twice")
        RTPU_CONFIG.apply_system_config(_system_config)

        if address is None:
            res = dict(resources or {})
            if num_cpus is not None:
                res["CPU"] = float(num_cpus)
            res.setdefault("CPU", float(os.cpu_count() or 1))
            if num_tpus is not None:
                res["TPU"] = float(num_tpus)
            else:
                auto_res, auto_labels = accelerators.node_resources_and_labels()
                for k, v in auto_res.items():
                    res.setdefault(k, v)
                labels = {**auto_labels, **(labels or {})}
            _local_node = Node(
                head=True,
                resources=res,
                labels=labels or {},
                object_store_memory=object_store_memory,
            )
            gcs_address = _local_node.gcs_address
            raylet_addr = _local_node.raylet_address
        else:
            if address == "auto":
                address = os.environ.get("RTPU_ADDRESS", "")
                if not address:
                    raise ValueError("address='auto' requires RTPU_ADDRESS env var")
            from ray_tpu._private.gcs.client import GcsClient

            gcs = GcsClient.from_address(address)
            nodes = [n for n in gcs.get_all_node_info() if n["state"] == "ALIVE"]
            if not nodes:
                raise RuntimeError(f"no alive nodes in cluster at {address}")
            import socket

            my_ips = {"127.0.0.1", "0.0.0.0", socket.gethostname()}
            try:
                my_ips.add(socket.gethostbyname(socket.gethostname()))
            except Exception:
                pass
            local = [n for n in nodes if n["ip"] in my_ips]
            head = [n for n in nodes if n.get("is_head")]
            target = (local or head or nodes)[0]
            gcs_address = address
            raylet_addr = (target["ip"], target["raylet_port"])

        _job_counter += 1
        job_id = JobID.from_int((os.getpid() << 8 | (_job_counter & 0xFF)) & 0xFFFFFFFF)
        worker = CoreWorker(
            mode=MODE_DRIVER,
            gcs_address=gcs_address,
            raylet_addr=raylet_addr,
            job_id=job_id,
            startup_token=-1,
        )
        worker.namespace = namespace or ""
        set_global_worker(worker)
        import sys as _sys

        worker.gcs.call(
            "AddJob",
            {
                "job_id": job_id.binary(),
                "driver_addr": list(worker.address),
                "entrypoint": " ".join(os.sys.argv if hasattr(os, "sys") else []),
                # Workers extend their sys.path with the driver's so that
                # by-reference-pickled functions (modules importable on the
                # driver) resolve on workers too (reference: job_config
                # code-search-path propagation).
                "driver_sys_path": [p for p in _sys.path if p],
            },
        )
        if log_to_driver:
            worker.enable_log_to_driver()
        atexit.register(shutdown)
        return _ClientContext(gcs_address)


class _ClientContext:
    def __init__(self, address):
        self.address_info = {"gcs_address": address}

    def __enter__(self):
        return self

    def __exit__(self, *a):
        shutdown()


def shutdown():
    global _local_node
    from ray_tpu._private import worker as worker_mod

    with _init_lock:
        worker = worker_mod.global_worker
        if worker is not None:
            try:
                worker.gcs.call("MarkJobFinished", {"job_id": worker.job_id.binary()}, timeout=5)
            except Exception:
                pass
            worker.shutdown()
            set_global_worker(None)
        if _local_node is not None:
            _local_node.shutdown()
            _local_node = None
        try:
            atexit.unregister(shutdown)
        except Exception:
            pass


def remote(*args, **kwargs):
    """Decorator: turn a function into a RemoteFunction / class into an ActorClass."""

    def make(obj):
        if inspect.isclass(obj):
            return ActorClass(obj, kwargs or None)
        return RemoteFunction(obj, kwargs or None)

    if len(args) == 1 and not kwargs and (inspect.isfunction(args[0]) or inspect.isclass(args[0])):
        return make(args[0])
    if args:
        raise TypeError("@remote takes keyword options only, e.g. @remote(num_cpus=2)")
    return make


def get(
    object_refs: Union[ObjectRef, Sequence[ObjectRef]],
    *,
    timeout: Optional[float] = None,
) -> Any:
    worker = get_global_worker()
    single = isinstance(object_refs, ObjectRef)
    refs = [object_refs] if single else list(object_refs)
    for r in refs:
        if not isinstance(r, ObjectRef):
            raise TypeError(f"get() expects ObjectRef(s), got {type(r)}")
    values = worker.get(refs, timeout=timeout)
    return values[0] if single else values


def put(value: Any) -> ObjectRef:
    if isinstance(value, ObjectRef):
        raise TypeError("put() of an ObjectRef is not allowed")
    return get_global_worker().put(value)


def wait(
    object_refs: Sequence[ObjectRef],
    *,
    num_returns: int = 1,
    timeout: Optional[float] = None,
    fetch_local: bool = True,
):
    worker = get_global_worker()
    refs = list(object_refs)
    if num_returns > len(refs):
        raise ValueError("num_returns > len(object_refs)")
    seen = set()
    for r in refs:
        if r in seen:
            raise ValueError("wait() got duplicate ObjectRefs")
        seen.add(r)
    return worker.wait(refs, num_returns=num_returns, timeout=timeout, fetch_local=fetch_local)


def kill(actor: ActorHandle, *, no_restart: bool = True):
    get_global_worker().kill_actor(actor._actor_id, no_restart)


def cancel(ref: ObjectRef, *, force: bool = False, recursive: bool = True):
    get_global_worker().cancel_task(ref, force, recursive)


def nodes() -> List[dict]:
    out = []
    for n in get_global_worker().gcs.get_all_node_info():
        out.append(
            {
                "NodeID": n["node_id"].hex(),
                "Alive": n["state"] == "ALIVE",
                "NodeManagerAddress": n["ip"],
                "NodeManagerPort": n["raylet_port"],
                "Resources": n["resources_total"],
                "Available": n["resources_available"],
                "Labels": n.get("labels", {}),
                "IsHead": n.get("is_head", False),
            }
        )
    return out


def cluster_resources() -> Dict[str, float]:
    return get_global_worker().gcs.get_cluster_resources()["total"]


def available_resources() -> Dict[str, float]:
    return get_global_worker().gcs.get_cluster_resources()["available"]


def timeline(filename: Optional[str] = None, *,
             job_id: Optional[str] = None, trace_id: Optional[str] = None):
    """Chrome-tracing dump of task events (reference: _private/state.py:944
    chrome_tracing_dump; open in chrome://tracing or ui.perfetto.dev).
    ``job_id`` (hex) / ``trace_id`` filter server-side."""
    from ray_tpu._private.timeline import timeline as _timeline

    get_global_worker()  # raise early if not initialized
    result = _timeline(filename, job_id=job_id, trace_id=trace_id)
    return filename if filename else result
