"""Durable workflows: checkpointed DAG execution with resume
(reference: python/ray/workflow/ — workflow_executor.py,
workflow_state_from_dag.py, task_executor.py; the function-step subset).

A workflow takes a ``ray_tpu.dag`` graph, executes it step by step as tasks,
and writes every step's output to storage before moving on. If the driver (or
the whole cluster) dies, ``workflow.resume(workflow_id)`` reloads the graph
and skips every step whose checkpoint exists — exactly-once step semantics by
way of write-ahead results.

Dynamic continuation is supported the way the reference's
``workflow.continuation`` works: a step may return another DAG, which is
spliced in and executed (with namespaced step ids) before its caller's value
resolves.

    import ray_tpu
    from ray_tpu import workflow

    @ray_tpu.remote
    def add(a, b):
        return a + b

    workflow.init(storage="/tmp/wf")
    out = workflow.run(add.bind(1, add.bind(2, 3)), workflow_id="w1")
"""

from __future__ import annotations

import json
import os
import shutil
import time
from typing import Any, Dict, List, Optional

import cloudpickle

import ray_tpu
from ray_tpu.dag.node import (
    DAGNode,
    FunctionNode,
    InputNode,
    MultiOutputNode,
    _AttrProxy,
)

_storage_root: Optional[str] = None

RUNNING = "RUNNING"
SUCCESSFUL = "SUCCESSFUL"
FAILED = "FAILED"


def init(storage: Optional[str] = None):
    """Set the workflow storage root (a directory; any shared filesystem)."""
    global _storage_root
    _storage_root = storage or os.environ.get(
        "RTPU_WORKFLOW_STORAGE", os.path.expanduser("~/.ray_tpu/workflows")
    )
    os.makedirs(_storage_root, exist_ok=True)


def _root() -> str:
    if _storage_root is None:
        init()
    return _storage_root


def _wf_dir(workflow_id: str) -> str:
    return os.path.join(_root(), workflow_id)


def _meta_path(workflow_id: str) -> str:
    return os.path.join(_wf_dir(workflow_id), "meta.json")


def _write_meta(workflow_id: str, **updates):
    path = _meta_path(workflow_id)
    meta = {}
    if os.path.exists(path):
        with open(path) as f:
            meta = json.load(f)
    meta.update(updates)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(meta, f)
    os.replace(tmp, path)
    return meta


class _StepStore:
    """Write-ahead step results under <wf>/steps/<step_id>.pkl."""

    def __init__(self, workflow_id: str):
        self.dir = os.path.join(_wf_dir(workflow_id), "steps")
        os.makedirs(self.dir, exist_ok=True)

    def _path(self, step_id: str) -> str:
        # continuation step ids are namespaced with '/'; store flat
        return os.path.join(self.dir, step_id.replace("/", "--") + ".pkl")

    def has(self, step_id: str) -> bool:
        return os.path.exists(self._path(step_id))

    def load(self, step_id: str):
        with open(self._path(step_id), "rb") as f:
            return cloudpickle.load(f)

    def save(self, step_id: str, value):
        tmp = self._path(step_id) + ".tmp"
        with open(tmp, "wb") as f:
            cloudpickle.dump(value, f)
        os.replace(tmp, self._path(step_id))


def _step_name(node: FunctionNode) -> str:
    fn = getattr(node._remote_fn, "_function", None)
    return getattr(fn, "__name__", "step")


class _Executor:
    """Deterministic DFS walk: a node's step id is its structural PATH in the
    graph (child-index chain from the root), so ids are stable under resume
    regardless of which subtrees short-circuit on cached checkpoints — a
    counter would shift when a cached node skips walking its children."""

    def __init__(self, workflow_id: str, store: _StepStore):
        self.workflow_id = workflow_id
        self.store = store

    def exec_node(self, node, input_value, path: str = "r") -> Any:
        if isinstance(node, InputNode):
            return input_value
        if isinstance(node, _AttrProxy):
            base = self.exec_node(node._base, input_value, path + ".p")
            return base[node._key]
        if isinstance(node, MultiOutputNode):
            return [self.exec_node(n, input_value, f"{path}.{i}")
                    for i, n in enumerate(node._nodes)]
        if isinstance(node, FunctionNode):
            step_id = f"{path}_{_step_name(node)}"
            if self.store.has(step_id):
                return self.store.load(step_id)
            args = [self.exec_node(a, input_value, f"{path}.{i}")
                    if isinstance(a, DAGNode) else a
                    for i, a in enumerate(node._bound_args)]
            kwargs = {k: self.exec_node(v, input_value, f"{path}.k{k}")
                      if isinstance(v, DAGNode) else v
                      for k, v in node._bound_kwargs.items()}
            result = ray_tpu.get(node._remote_fn.remote(*args, **kwargs))
            if isinstance(result, DAGNode):
                # continuation: splice the returned DAG in, namespaced so its
                # step ids cannot collide with ours
                result = self.exec_node(
                    result, input_value, path=step_id + "/r"
                )
            self.store.save(step_id, result)
            return result
        raise TypeError(
            f"workflow steps must be function DAG nodes, got {type(node)}"
        )


def run(dag: DAGNode, *, workflow_id: Optional[str] = None,
        input_value: Any = None) -> Any:
    """Execute the DAG durably; returns the final output."""
    workflow_id = workflow_id or f"wf-{int(time.time() * 1000):x}"
    wf_dir = _wf_dir(workflow_id)
    os.makedirs(wf_dir, exist_ok=True)
    # persist the graph itself so resume() can rebuild it
    with open(os.path.join(wf_dir, "dag.pkl"), "wb") as f:
        cloudpickle.dump((dag, input_value), f)
    _write_meta(workflow_id, status=RUNNING, start_time=time.time())
    store = _StepStore(workflow_id)
    try:
        result = _Executor(workflow_id, store).exec_node(dag, input_value)
    except Exception:
        _write_meta(workflow_id, status=FAILED, end_time=time.time())
        raise
    store.save("__output__", result)
    _write_meta(workflow_id, status=SUCCESSFUL, end_time=time.time())
    return result


def resume(workflow_id: str) -> Any:
    """Re-run a workflow from storage; completed steps are skipped."""
    dag_path = os.path.join(_wf_dir(workflow_id), "dag.pkl")
    if not os.path.exists(dag_path):
        raise ValueError(f"no workflow '{workflow_id}' in {_root()}")
    with open(dag_path, "rb") as f:
        dag, input_value = cloudpickle.load(f)
    _write_meta(workflow_id, status=RUNNING)
    store = _StepStore(workflow_id)
    try:
        result = _Executor(workflow_id, store).exec_node(dag, input_value)
    except Exception:
        _write_meta(workflow_id, status=FAILED, end_time=time.time())
        raise
    store.save("__output__", result)
    _write_meta(workflow_id, status=SUCCESSFUL, end_time=time.time())
    return result


def get_output(workflow_id: str) -> Any:
    store = _StepStore(workflow_id)
    if not store.has("__output__"):
        raise ValueError(f"workflow '{workflow_id}' has no output yet")
    return store.load("__output__")


def get_status(workflow_id: str) -> str:
    path = _meta_path(workflow_id)
    if not os.path.exists(path):
        raise ValueError(f"no workflow '{workflow_id}'")
    with open(path) as f:
        return json.load(f)["status"]


def list_all(status_filter: Optional[str] = None) -> List[Dict[str, Any]]:
    out = []
    root = _root()
    for wid in sorted(os.listdir(root)):
        mp = _meta_path(wid)
        if not os.path.exists(mp):
            continue
        with open(mp) as f:
            meta = json.load(f)
        if status_filter and meta.get("status") != status_filter:
            continue
        out.append({"workflow_id": wid, **meta})
    return out


def delete(workflow_id: str):
    shutil.rmtree(_wf_dir(workflow_id), ignore_errors=True)


# ------------------------------------------------------------ virtual actors
from ray_tpu.workflow.virtual_actor import (  # noqa: E402,F401
    VirtualActorHandle,
    get_actor,
    list_actors,
    virtual_actor,
)
