"""Virtual actors: durable actor state, checkpointed per method call.

Reference: python/ray/workflow/ virtual actors — an actor whose identity
and state live in workflow storage, not in any process. Every
non-readonly method call runs as a task that loads the latest state
snapshot, applies the method, and COMMITS the new snapshot write-ahead
before the result resolves; a crashed call simply re-runs against the
last committed state (exactly-once on committed state, at-least-once on
the method body). ``get_actor(actor_id)`` resurrects the actor on any
cluster from storage alone.

    from ray_tpu import workflow

    @workflow.virtual_actor
    class Counter:
        def __init__(self, start=0):
            self.count = start

        def add(self, n):
            self.count += n
            return self.count

        @workflow.virtual_actor.readonly
        def get(self):
            return self.count

    workflow.init(storage="/tmp/wf")
    c = Counter.get_or_create("my-counter", 10)
    assert c.add.run(5) == 15
    # ... cluster restarts ...
    c2 = workflow.get_actor("my-counter")
    assert c2.get.run() == 15
"""

from __future__ import annotations

import json
import os
import threading
from typing import Any, Optional

import cloudpickle

import ray_tpu


def _actors_root():
    from ray_tpu import workflow as _wf

    path = os.path.join(_wf._root(), "virtual_actors")
    os.makedirs(path, exist_ok=True)
    return path


def _actor_dir(actor_id: str) -> str:
    return os.path.join(_actors_root(), actor_id)


def _latest_seq(adir: str) -> int:
    best = -1
    for f in os.listdir(adir):
        if f.startswith("state_") and f.endswith(".pkl"):
            try:
                best = max(best, int(f[len("state_"):-len(".pkl")]))
            except ValueError:
                pass
    return best


def _commit_state(adir: str, seq: int, state: dict, exclusive: bool = False):
    """Write snapshot `seq`. exclusive=True is optimistic concurrency for
    method commits: os.link fails if ANOTHER writer committed this seq
    first, turning a cross-handle race into a loud conflict instead of a
    silent lost update."""
    path = os.path.join(adir, f"state_{seq:08d}.pkl")
    tmp = path + f".tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        cloudpickle.dump(state, f)
    try:
        if exclusive:
            try:
                os.link(tmp, path)
            except FileExistsError:
                raise RuntimeError(
                    f"concurrent write conflict on virtual actor state "
                    f"{path} — another handle committed seq {seq} first; "
                    "retry the call against the new state"
                )
        else:
            os.replace(tmp, path)
    finally:
        try:
            os.remove(tmp)
        except OSError:
            pass
    # retain only the latest two snapshots (the previous one guards
    # against a torn read racing the replace on exotic filesystems)
    for f in os.listdir(adir):
        if f.startswith("state_") and f.endswith(".pkl"):
            try:
                s = int(f[len("state_"):-len(".pkl")])
            except ValueError:
                continue
            if s < seq - 1:
                try:
                    os.remove(os.path.join(adir, f))
                except OSError:
                    pass


@ray_tpu.remote(max_retries=0)
def _virtual_actor_call(adir: str, method_name: str, args, kwargs,
                        readonly: bool):
    """One durable method call: load latest state -> apply -> commit."""
    with open(os.path.join(adir, "class.pkl"), "rb") as f:
        cls = cloudpickle.load(f)
    seq = _latest_seq(adir)
    if seq < 0:
        raise RuntimeError(f"virtual actor storage at {adir} has no state")
    with open(os.path.join(adir, f"state_{seq:08d}.pkl"), "rb") as f:
        state = cloudpickle.load(f)
    inst = cls.__new__(cls)
    inst.__dict__.update(state)
    result = getattr(inst, method_name)(*args, **kwargs)
    if not readonly:
        _commit_state(adir, seq + 1, dict(inst.__dict__), exclusive=True)
    return result


class _VirtualMethod:
    def __init__(self, handle: "VirtualActorHandle", name: str,
                 readonly: bool):
        self._handle = handle
        self._name = name
        self._readonly = readonly

    def run(self, *args, **kwargs):
        return ray_tpu.get(self.run_async(*args, **kwargs), timeout=600)

    def run_async(self, *args, **kwargs):
        h = self._handle
        if self._readonly:
            # readers never take the writer lock: they read the latest
            # committed snapshot and commit nothing
            return _virtual_actor_call.remote(
                h._dir, self._name, args, kwargs, True
            )
        # Per-actor writer serialization: durable state has no reorder
        # buffer, so overlapping writers would both load snapshot N and
        # both commit N+1 (lost update). A writer that outlives the wait
        # budget FAILS the next submission loudly — proceeding anyway
        # would silently drop one of the commits.
        with h._lock:
            ref = _virtual_actor_call.remote(
                h._dir, self._name, args, kwargs, False
            )
            ready, _ = ray_tpu.wait([ref], num_returns=1, timeout=300)
            if not ready:
                raise TimeoutError(
                    f"virtual actor {h.actor_id!r} write "
                    f"{self._name!r} did not commit within 300s; "
                    "not submitting further writes (ordering would break)"
                )
            return ref


class VirtualActorHandle:
    def __init__(self, actor_id: str):
        self.actor_id = actor_id
        self._dir = _actor_dir(actor_id)
        self._lock = threading.Lock()
        with open(os.path.join(self._dir, "class.pkl"), "rb") as f:
            self._cls = cloudpickle.load(f)

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        attr = getattr(self._cls, name, None)
        if attr is None or not callable(attr):
            raise AttributeError(
                f"virtual actor {self._cls.__name__} has no method {name!r}"
            )
        return _VirtualMethod(
            self, name, getattr(attr, "_workflow_readonly", False)
        )


class VirtualActorClass:
    def __init__(self, cls):
        self._cls = cls

    def get_or_create(self, actor_id: str, *args, **kwargs) -> VirtualActorHandle:
        adir = _actor_dir(actor_id)
        if not os.path.exists(os.path.join(adir, "class.pkl")):
            os.makedirs(adir, exist_ok=True)
            inst = self._cls(*args, **kwargs)
            with open(os.path.join(adir, "class.pkl.tmp"), "wb") as f:
                cloudpickle.dump(self._cls, f)
            os.replace(os.path.join(adir, "class.pkl.tmp"),
                       os.path.join(adir, "class.pkl"))
            _commit_state(adir, 0, dict(inst.__dict__))
            with open(os.path.join(adir, "meta.json"), "w") as f:
                json.dump({"actor_id": actor_id,
                           "class": self._cls.__name__}, f)
        return VirtualActorHandle(actor_id)


def virtual_actor(cls) -> VirtualActorClass:
    """Class decorator making a durable, storage-backed actor class."""
    return VirtualActorClass(cls)


def _readonly(method):
    """Mark a virtual-actor method as not mutating state: it reads the
    latest snapshot without committing a new one."""
    method._workflow_readonly = True
    return method


virtual_actor.readonly = _readonly


def get_actor(actor_id: str) -> VirtualActorHandle:
    """Resurrect a virtual actor from storage (any process, any cluster)."""
    adir = _actor_dir(actor_id)
    if not os.path.exists(os.path.join(adir, "class.pkl")):
        raise ValueError(f"no virtual actor {actor_id!r} in storage")
    return VirtualActorHandle(actor_id)


def list_actors() -> list:
    root = _actors_root()
    out = []
    for aid in sorted(os.listdir(root)):
        meta = os.path.join(root, aid, "meta.json")
        if os.path.exists(meta):
            with open(meta) as f:
                out.append(json.load(f))
    return out
