"""RLModule: the policy/value network (reference: rllib/core/rl_module/ —
re-designed as a flax module; the torch DDP wrapper is replaced by pjit
sharding in the learner).
"""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np


class ActorCriticModule(nn.Module):
    """Shared-nothing MLP actor-critic for discrete action spaces."""

    num_actions: int
    hidden: Sequence[int] = (64, 64)

    @nn.compact
    def __call__(self, obs):
        x = obs
        for h in self.hidden:
            x = nn.tanh(nn.Dense(h)(x))
        logits = nn.Dense(self.num_actions, name="pi")(x)
        v = nn.Dense(1, name="vf")(x)
        return logits, jnp.squeeze(v, -1)

    def init_params(self, obs_dim: int, seed: int = 0):
        return self.init(
            jax.random.PRNGKey(seed), jnp.zeros((1, obs_dim), jnp.float32)
        )["params"]


def numpy_forward(params, obs: np.ndarray):
    """Pure-numpy forward pass so CPU env runners act without importing a jax
    device runtime (reference: env runners hold a lightweight policy copy).
    Mirrors ActorCriticModule's architecture exactly."""
    x = obs.astype(np.float32)
    # numeric sort: flax auto-names are Dense_0..Dense_N and 'Dense_10'
    # sorts lexicographically before 'Dense_2'
    layers = sorted((k for k in params if k.startswith("Dense_")),
                    key=lambda k: int(k.rsplit("_", 1)[1]))
    for k in layers:
        x = np.tanh(x @ np.asarray(params[k]["kernel"])
                    + np.asarray(params[k]["bias"]))
    logits = x @ np.asarray(params["pi"]["kernel"]) + np.asarray(
        params["pi"]["bias"])
    v = x @ np.asarray(params["vf"]["kernel"]) + np.asarray(
        params["vf"]["bias"])
    return logits, v[:, 0]


class QModule(nn.Module):
    """MLP Q-network for discrete action spaces (DQN family)."""

    num_actions: int
    hidden: Sequence[int] = (64, 64)

    @nn.compact
    def __call__(self, obs):
        x = obs
        for h in self.hidden:
            x = nn.relu(nn.Dense(h)(x))
        return nn.Dense(self.num_actions, name="q")(x)

    def init_params(self, obs_dim: int, seed: int = 0):
        return self.init(
            jax.random.PRNGKey(seed), jnp.zeros((1, obs_dim), jnp.float32)
        )["params"]


def numpy_q_forward(params, obs: np.ndarray):
    """Numpy mirror of QModule for CPU env runners (relu hidden stack)."""
    x = obs.astype(np.float32)
    layers = sorted((k for k in params if k.startswith("Dense_")),
                    key=lambda k: int(k.rsplit("_", 1)[1]))
    for k in layers:
        x = np.maximum(
            x @ np.asarray(params[k]["kernel"]) + np.asarray(params[k]["bias"]),
            0.0,
        )
    return x @ np.asarray(params["q"]["kernel"]) + np.asarray(
        params["q"]["bias"])


def sample_actions(rng: np.random.Generator, logits: np.ndarray):
    """Categorical sample + log-prob, numpy."""
    z = logits - logits.max(axis=-1, keepdims=True)
    p = np.exp(z)
    p /= p.sum(axis=-1, keepdims=True)
    u = rng.random((len(p), 1))
    actions = (p.cumsum(axis=-1) > u).argmax(axis=-1)
    logp = np.log(p[np.arange(len(p)), actions] + 1e-12)
    return actions, logp


class SquashedGaussianModule(nn.Module):
    """Tanh-squashed Gaussian policy for continuous control (SAC actor;
    reference: rllib/algorithms/sac/sac_torch_model.py's policy head —
    re-designed as a flax module; squashing correction lives in the
    learner's jit)."""

    action_dim: int
    hidden: Sequence[int] = (256, 256)

    @nn.compact
    def __call__(self, obs):
        x = obs
        for h in self.hidden:
            x = nn.relu(nn.Dense(h)(x))
        mean = nn.Dense(self.action_dim, name="mean")(x)
        log_std = nn.Dense(self.action_dim, name="log_std")(x)
        log_std = jnp.clip(log_std, -20.0, 2.0)
        return mean, log_std

    def init_params(self, obs_dim: int, seed: int = 0):
        return self.init(
            jax.random.PRNGKey(seed), jnp.zeros((1, obs_dim), jnp.float32)
        )["params"]


class TwinQModule(nn.Module):
    """Two independent Q(s, a) critics (SAC's clipped double-Q;
    reference: sac.py twin_q=True default)."""

    hidden: Sequence[int] = (256, 256)

    @nn.compact
    def __call__(self, obs, act):
        x = jnp.concatenate([obs, act], axis=-1)
        qs = []
        for name in ("q1", "q2"):
            h = x
            for i, width in enumerate(self.hidden):
                h = nn.relu(nn.Dense(width, name=f"{name}_d{i}")(h))
            qs.append(nn.Dense(1, name=f"{name}_out")(h)[:, 0])
        return qs[0], qs[1]

    def init_params(self, obs_dim: int, action_dim: int, seed: int = 0):
        return self.init(
            jax.random.PRNGKey(seed),
            jnp.zeros((1, obs_dim), jnp.float32),
            jnp.zeros((1, action_dim), jnp.float32),
        )["params"]


def numpy_gaussian_forward(params, obs: np.ndarray):
    """Numpy mirror of SquashedGaussianModule for CPU env runners."""
    x = obs.astype(np.float32)
    layers = sorted((k for k in params if k.startswith("Dense_")),
                    key=lambda k: int(k.rsplit("_", 1)[1]))
    for k in layers:
        x = np.maximum(
            x @ np.asarray(params[k]["kernel"]) + np.asarray(params[k]["bias"]),
            0.0,
        )
    mean = x @ np.asarray(params["mean"]["kernel"]) + np.asarray(
        params["mean"]["bias"])
    log_std = x @ np.asarray(params["log_std"]["kernel"]) + np.asarray(
        params["log_std"]["bias"])
    return mean, np.clip(log_std, -20.0, 2.0)


def sample_squashed_actions(rng: np.random.Generator, mean, log_std,
                            low, high):
    """Sample tanh-squashed actions scaled into [low, high] (numpy)."""
    raw = mean + np.exp(log_std) * rng.standard_normal(mean.shape)
    squashed = np.tanh(raw)
    return low + (squashed + 1.0) * 0.5 * (high - low)
