"""IMPALA learner: V-trace off-policy actor-critic update, pjit-compiled
over the device mesh.

Reference: rllib/algorithms/impala/ (decoupled env runners stream
trajectories to a continuously-updating learner; staleness is corrected
with V-trace importance weighting, Espeholt et al. 2018). The torch/DDP
learner stack is re-designed jax-first: the whole update — forward over the
(T, N) sequence batch, v-trace via a reversed lax.scan, gradients, adam —
is ONE jit with the batch sharded on the env axis over `dp` and XLA
inserting the gradient psum.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np


def vtrace(rho, rewards, discounts, values, bootstrap_v, c):
    """V-trace targets vs and policy-gradient advantages (all (T, N)).

    rho/c are the already-clipped importance ratios min(rho_bar, pi/mu) /
    min(c_bar, pi/mu)."""
    import jax
    import jax.numpy as jnp

    next_values = jnp.concatenate([values[1:], bootstrap_v[None]], axis=0)
    deltas = rho * (rewards + discounts * next_values - values)

    def body(acc, xs):
        delta_t, disc_t, c_t = xs
        acc = delta_t + disc_t * c_t * acc
        return acc, acc

    _, vs_minus_v = jax.lax.scan(
        body, jnp.zeros_like(bootstrap_v), (deltas, discounts, c),
        reverse=True,
    )
    vs = values + vs_minus_v
    vs_next = jnp.concatenate([vs[1:], bootstrap_v[None]], axis=0)
    pg_adv = rho * (rewards + discounts * vs_next - values)
    return vs, pg_adv


def shard_time_major(mesh, batch_sharding, batch: Dict[str, np.ndarray]):
    """device_put a time-major (T, N) trajectory batch with the env axis
    padded to the mesh and sharded over dp (bootstrap_obs is (N, obs))."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    d = mesh.size
    n = batch["actions"].shape[1]
    pad = (-n) % d
    if pad:
        def pad_k(k, v):
            if k == "bootstrap_obs":  # (N, obs): env axis is 0
                return np.concatenate([v, np.repeat(v[-1:], pad, axis=0)])
            return np.concatenate(  # (T, N, ...): env axis is 1
                [v, np.repeat(v[:, -1:], pad, axis=1)], axis=1
            )

        batch = {k: pad_k(k, v) for k, v in batch.items()}
    shardings = {
        k: (NamedSharding(mesh, P("dp")) if k == "bootstrap_obs"
            else batch_sharding)
        for k in batch
    }
    return jax.device_put(batch, shardings)


class ImpalaLearner:
    """Owns params/optimizer on the mesh; one jit per update, consuming
    time-major trajectory batches from (possibly stale) behavior policies."""

    def __init__(self, obs_dim: int, num_actions: int, *,
                 lr: float = 5e-4, gamma: float = 0.99,
                 vf_coeff: float = 0.5, entropy_coeff: float = 0.01,
                 rho_bar: float = 1.0, c_bar: float = 1.0,
                 hidden=(64, 64), seed: int = 0,
                 mesh_devices: Optional[int] = None):
        import jax
        import jax.numpy as jnp
        import optax
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        from ray_tpu.rllib.core.rl_module import ActorCriticModule

        self.module = ActorCriticModule(num_actions=num_actions,
                                        hidden=tuple(hidden))
        self.params = self.module.init_params(obs_dim, seed)
        self.opt = optax.adam(lr)
        self.opt_state = self.opt.init(self.params)

        devices = jax.devices()[:mesh_devices] if mesh_devices else jax.devices()
        self.mesh = Mesh(np.array(devices), ("dp",))
        # time-major batches shard the ENV axis (axis 1) over dp
        self._batch_sharding = NamedSharding(self.mesh, P(None, "dp"))
        self._replicated = NamedSharding(self.mesh, P())
        module = self.module

        def loss_fn(params, batch):
            T, N = batch["actions"].shape
            flat_obs = batch["obs"].reshape((T * N,) + batch["obs"].shape[2:])
            logits, v = module.apply({"params": params}, flat_obs)
            logits = logits.reshape(T, N, -1)
            values = v.reshape(T, N)
            _, boot_v = module.apply({"params": params},
                                     batch["bootstrap_obs"])
            logp_all = jax.nn.log_softmax(logits)
            target_logp = jnp.take_along_axis(
                logp_all, batch["actions"][..., None], axis=-1
            )[..., 0]
            log_ratio = target_logp - batch["behavior_logp"]
            ratio = jnp.exp(log_ratio)
            rho = jnp.minimum(ratio, rho_bar)
            c = jnp.minimum(ratio, c_bar)
            discounts = gamma * (1.0 - batch["dones"])
            vs, pg_adv = vtrace(
                rho, batch["rewards"], discounts, values, boot_v, c
            )
            # autoreset rows (action ignored by the env) carry zero weight
            w = batch["valid"]
            wsum = jnp.maximum(jnp.sum(w), 1.0)
            pi_loss = -jnp.sum(
                w * target_logp * jax.lax.stop_gradient(pg_adv)
            ) / wsum
            vf_loss = 0.5 * jnp.sum(
                w * (values - jax.lax.stop_gradient(vs)) ** 2
            ) / wsum
            entropy = -jnp.sum(
                w * jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1)
            ) / wsum
            total = pi_loss + vf_coeff * vf_loss - entropy_coeff * entropy
            return total, {
                "pi_loss": pi_loss, "vf_loss": vf_loss, "entropy": entropy,
                "mean_rho": jnp.mean(rho),
            }

        def update_fn(params, opt_state, batch):
            (loss, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                params, batch
            )
            updates, opt_state = self.opt.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            aux["total_loss"] = loss
            return params, opt_state, aux

        self._update = jax.jit(
            update_fn,
            in_shardings=(
                self._replicated, self._replicated,
                {
                    "obs": self._batch_sharding,
                    "actions": self._batch_sharding,
                    "behavior_logp": self._batch_sharding,
                    "rewards": self._batch_sharding,
                    "dones": self._batch_sharding,
                    "valid": self._batch_sharding,
                    "bootstrap_obs": NamedSharding(self.mesh, P("dp")),
                },
            ),
            out_shardings=(self._replicated, self._replicated, None),
        )

    def _shard(self, batch: Dict[str, np.ndarray]):
        return shard_time_major(self.mesh, self._batch_sharding, batch)

    def update_from_trajectories(
        self, batch: Dict[str, np.ndarray]
    ) -> Dict[str, float]:
        """One v-trace gradient step on a time-major (T, N) batch."""
        batch = {k: v for k, v in batch.items() if k != "episode_returns"}
        self.params, self.opt_state, aux = self._update(
            self.params, self.opt_state, self._shard(batch)
        )
        return {k: float(v) for k, v in aux.items()}

    def get_weights(self):
        import jax

        return jax.tree.map(np.asarray, self.params)

    def set_weights(self, weights):
        import jax

        self.params = jax.device_put(weights, self._replicated)
        self.opt_state = self.opt.init(self.params)
        return True

    def num_devices(self) -> int:
        return self.mesh.size
