"""APPO learner: asynchronous PPO — IMPALA's decoupled engine with a
PPO-clipped surrogate computed against a periodically-synced target policy.

Reference: rllib/algorithms/appo/appo.py:277 + appo_torch_policy.py — APPO
runs IMPALA's async rollout plan, but the loss replaces the plain v-trace
policy gradient with the clipped surrogate: v-trace targets/advantages are
computed under the TARGET ("old") policy, the surrogate ratio is the
current/behavior ratio clamped through the old-policy importance ratio, and
an optional KL(old || current) regularizer bounds the policy lag. The
target network refreshes every `target_update_freq` updates
(reference: appo.py target_network_update_freq).

Design is jax-first like ImpalaLearner: the entire update is ONE jit over
the device mesh, batch sharded on the env axis.
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from ray_tpu.rllib.core.impala_learner import shard_time_major, vtrace


class AppoLearner:
    def __init__(self, obs_dim: int, num_actions: int, *,
                 lr: float = 5e-4, gamma: float = 0.99,
                 vf_coeff: float = 0.5, entropy_coeff: float = 0.01,
                 rho_bar: float = 1.0, c_bar: float = 1.0,
                 clip_param: float = 0.2,
                 use_kl_loss: bool = False, kl_coeff: float = 1.0,
                 target_update_freq: int = 8,
                 hidden=(64, 64), seed: int = 0,
                 mesh_devices: Optional[int] = None):
        import jax
        import jax.numpy as jnp
        import optax
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        from ray_tpu.rllib.core.rl_module import ActorCriticModule

        self.module = ActorCriticModule(num_actions=num_actions,
                                        hidden=tuple(hidden))
        self.params = self.module.init_params(obs_dim, seed)
        self.target_params = jax.tree.map(jnp.copy, self.params)
        self.opt = optax.adam(lr)
        self.opt_state = self.opt.init(self.params)
        self.target_update_freq = max(1, int(target_update_freq))
        self._updates = 0

        devices = jax.devices()[:mesh_devices] if mesh_devices else jax.devices()
        self.mesh = Mesh(np.array(devices), ("dp",))
        self._batch_sharding = NamedSharding(self.mesh, P(None, "dp"))
        self._replicated = NamedSharding(self.mesh, P())
        module = self.module

        def logp_and_values(params, batch):
            T, N = batch["actions"].shape
            flat = batch["obs"].reshape((T * N,) + batch["obs"].shape[2:])
            logits, v = module.apply({"params": params}, flat)
            logp_all = jax.nn.log_softmax(logits.reshape(T, N, -1))
            target_logp = jnp.take_along_axis(
                logp_all, batch["actions"][..., None], axis=-1)[..., 0]
            return logp_all, target_logp, v.reshape(T, N)

        def loss_fn(params, target_params, batch):
            logp_all, cur_logp, cur_values = logp_and_values(params, batch)
            # Value estimates and the v-trace correction come from the
            # TARGET network (reference: appo_torch_policy target_model
            # value_function + old_policy_behaviour_logits).
            old_logp_all, old_logp, old_values = logp_and_values(
                target_params, batch)
            old_logp_all = jax.lax.stop_gradient(old_logp_all)
            old_logp = jax.lax.stop_gradient(old_logp)
            old_values = jax.lax.stop_gradient(old_values)
            _, boot_v = module.apply({"params": target_params},
                                     batch["bootstrap_obs"])
            boot_v = jax.lax.stop_gradient(boot_v)

            old_ratio = jnp.exp(old_logp - batch["behavior_logp"])
            rho = jnp.minimum(old_ratio, rho_bar)
            c = jnp.minimum(old_ratio, c_bar)
            discounts = gamma * (1.0 - batch["dones"])
            vs, pg_adv = vtrace(
                rho, batch["rewards"], discounts, old_values, boot_v, c)
            pg_adv = jax.lax.stop_gradient(pg_adv)

            # Clipped surrogate: current/behavior ratio routed through the
            # old-policy importance ratio (reference: appo_torch_policy
            # is_ratio clamp [0, 2] * exp(curr - prev)).
            is_ratio = jnp.clip(
                jnp.exp(batch["behavior_logp"] - old_logp), 0.0, 2.0)
            logp_ratio = is_ratio * jnp.exp(cur_logp - batch["behavior_logp"])
            surr1 = pg_adv * logp_ratio
            surr2 = pg_adv * jnp.clip(
                logp_ratio, 1.0 - clip_param, 1.0 + clip_param)
            w = batch["valid"]
            wsum = jnp.maximum(jnp.sum(w), 1.0)
            pi_loss = -jnp.sum(w * jnp.minimum(surr1, surr2)) / wsum

            # Value function trains on the v-trace targets with the CURRENT
            # params (the target net only supplies the targets).
            vf_loss = 0.5 * jnp.sum(
                w * (cur_values - jax.lax.stop_gradient(vs)) ** 2) / wsum
            entropy = -jnp.sum(
                w * jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1)) / wsum
            kl = jnp.sum(
                w * jnp.sum(
                    jnp.exp(old_logp_all) * (old_logp_all - logp_all), axis=-1
                )) / wsum
            total = pi_loss + vf_coeff * vf_loss - entropy_coeff * entropy
            if use_kl_loss:
                total = total + kl_coeff * kl
            return total, {
                "pi_loss": pi_loss, "vf_loss": vf_loss, "entropy": entropy,
                "kl": kl, "mean_rho": jnp.mean(rho),
            }

        def update_fn(params, target_params, opt_state, batch):
            (loss, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                params, target_params, batch)
            updates, opt_state = self.opt.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            aux["total_loss"] = loss
            return params, opt_state, aux

        batch_shardings = {
            "obs": self._batch_sharding,
            "actions": self._batch_sharding,
            "behavior_logp": self._batch_sharding,
            "rewards": self._batch_sharding,
            "dones": self._batch_sharding,
            "valid": self._batch_sharding,
            "bootstrap_obs": NamedSharding(self.mesh, P("dp")),
        }
        self._update = jax.jit(
            update_fn,
            in_shardings=(self._replicated, self._replicated,
                          self._replicated, batch_shardings),
            out_shardings=(self._replicated, self._replicated, None),
        )

    def _shard(self, batch: Dict[str, np.ndarray]):
        return shard_time_major(self.mesh, self._batch_sharding, batch)

    def update_from_trajectories(
        self, batch: Dict[str, np.ndarray]
    ) -> Dict[str, float]:
        import jax

        batch = {k: v for k, v in batch.items() if k != "episode_returns"}
        self.params, self.opt_state, aux = self._update(
            self.params, self.target_params, self.opt_state,
            self._shard(batch))
        self._updates += 1
        if self._updates % self.target_update_freq == 0:
            self.target_params = jax.tree.map(lambda x: x, self.params)
        return {k: float(v) for k, v in aux.items()}

    def get_weights(self):
        import jax

        return jax.tree.map(np.asarray, self.params)

    def set_weights(self, weights):
        import jax

        self.params = jax.device_put(weights, self._replicated)
        self.target_params = jax.device_put(weights, self._replicated)
        self.opt_state = self.opt.init(self.params)
        return True

    def num_devices(self) -> int:
        return self.mesh.size
