"""APPO: asynchronous PPO on the IMPALA execution plan.

Reference: rllib/algorithms/appo/appo.py:277 — APPO subclasses IMPALA's
config/execution (decoupled env runners, continuous learner) and swaps the
loss for the target-network clipped surrogate (core/appo_learner.py). The
only engine-visible differences are the extra training knobs and the
learner class.
"""

from __future__ import annotations

from ray_tpu.rllib.algorithms.impala import IMPALA, IMPALAConfig


class APPOConfig(IMPALAConfig):
    def __init__(self):
        super().__init__()
        self.clip_param = 0.2
        self.use_kl_loss = False
        self.kl_coeff = 1.0
        # target net refresh cadence, in learner updates (reference:
        # appo.py target_network_update_freq, expressed there in env steps)
        self.target_update_freq = 8

    def training(self, *, clip_param=None, use_kl_loss=None, kl_coeff=None,
                 target_update_freq=None, **kwargs) -> "APPOConfig":
        super().training(**kwargs)
        for name, val in [
            ("clip_param", clip_param), ("use_kl_loss", use_kl_loss),
            ("kl_coeff", kl_coeff),
            ("target_update_freq", target_update_freq),
        ]:
            if val is not None:
                setattr(self, name, val)
        return self

    def _learner_path(self) -> str:
        return "ray_tpu.rllib.core.appo_learner:AppoLearner"

    def _extra_learner_kwargs(self) -> dict:
        return {
            "clip_param": self.clip_param,
            "use_kl_loss": self.use_kl_loss,
            "kl_coeff": self.kl_coeff,
            "target_update_freq": self.target_update_freq,
        }

    def build(self) -> "APPO":
        assert self.env_name, "call .environment(env_name) first"
        return APPO(self)


class APPO(IMPALA):
    pass
