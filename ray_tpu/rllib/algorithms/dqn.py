"""DQN (reference: rllib/algorithms/dqn/ — double-DQN with target network and
replay buffer, new-stack EnvRunner/Learner shape re-designed TPU-first: CPU
actors collect epsilon-greedy transitions with a numpy policy copy, the
learner's double-DQN update is one jit over the device mesh with the batch
sharded on dp, and the target-network sync is a pure pytree copy on device).
"""

from __future__ import annotations

import time
from collections import deque
from typing import Any, Dict, Optional

import numpy as np

import ray_tpu
from ray_tpu.rllib.core.rl_module import numpy_q_forward


class ReplayBuffer:
    """Uniform ring buffer of transitions (reference:
    rllib/utils/replay_buffers/ — the uniform subset)."""

    def __init__(self, capacity: int, obs_dim: int):
        self.capacity = capacity
        self.obs = np.zeros((capacity, obs_dim), np.float32)
        self.next_obs = np.zeros((capacity, obs_dim), np.float32)
        self.actions = np.zeros(capacity, np.int64)
        self.rewards = np.zeros(capacity, np.float32)
        self.dones = np.zeros(capacity, np.float32)
        self.size = 0
        self._pos = 0

    def add_batch(self, batch: Dict[str, np.ndarray]):
        n = len(batch["obs"])
        idx = (self._pos + np.arange(n)) % self.capacity
        self.obs[idx] = batch["obs"]
        self.next_obs[idx] = batch["next_obs"]
        self.actions[idx] = batch["actions"]
        self.rewards[idx] = batch["rewards"]
        self.dones[idx] = batch["dones"]
        self._pos = int((self._pos + n) % self.capacity)
        self.size = int(min(self.size + n, self.capacity))

    def sample(self, rng: np.random.Generator, n: int) -> Dict[str, np.ndarray]:
        idx = rng.integers(0, self.size, size=n)
        return {
            "obs": self.obs[idx],
            "next_obs": self.next_obs[idx],
            "actions": self.actions[idx],
            "rewards": self.rewards[idx],
            "dones": self.dones[idx],
        }


class DQNEnvRunner:
    """Epsilon-greedy transition collector (CPU actor, numpy policy)."""

    def __init__(self, env_name: str, num_envs: int, seed: int = 0):
        import gymnasium as gym

        self.envs = gym.make_vec(env_name, num_envs=num_envs,
                                 vectorization_mode="sync")
        self.num_envs = num_envs
        self.rng = np.random.default_rng(seed)
        self.obs, _ = self.envs.reset(seed=seed)
        self._episode_returns = np.zeros(num_envs)
        # gymnasium NEXT_STEP autoreset: the step after a done is a
        # fabricated transition (action ignored, reward 0) — mask it out
        self._autoreset = np.zeros(num_envs, bool)

    def obs_and_action_dims(self):
        return (int(np.prod(self.envs.single_observation_space.shape)),
                int(self.envs.single_action_space.n))

    def sample(self, params, rollout_len: int, epsilon: float
               ) -> Dict[str, np.ndarray]:
        T, N = rollout_len, self.num_envs
        obs_b = np.zeros((T, N) + self.obs.shape[1:], np.float32)
        nxt_b = np.zeros_like(obs_b)
        act_b = np.zeros((T, N), np.int64)
        rew_b = np.zeros((T, N), np.float32)
        done_b = np.zeros((T, N), np.float32)
        valid_b = np.ones((T, N), bool)
        completed = []
        for t in range(T):
            q = numpy_q_forward(params, self.obs)
            greedy = q.argmax(axis=-1)
            random = self.rng.integers(0, q.shape[-1], size=N)
            explore = self.rng.random(N) < epsilon
            actions = np.where(explore, random, greedy)
            valid_b[t] = ~self._autoreset
            nxt, rew, term, trunc, _ = self.envs.step(actions)
            done = np.logical_or(term, trunc)
            self._autoreset = done
            obs_b[t] = self.obs
            act_b[t] = actions
            rew_b[t] = rew
            # bootstrap through time-limit truncations, cut on terminations
            done_b[t] = term.astype(np.float32)
            nxt_b[t] = nxt
            self._episode_returns += rew
            for i in np.nonzero(done)[0]:
                completed.append(float(self._episode_returns[i]))
                self._episode_returns[i] = 0.0
            self.obs = nxt
        keep = valid_b.reshape(T * N)
        flat = lambda a: a.reshape((T * N,) + a.shape[2:])[keep]  # noqa: E731
        return {
            "obs": flat(obs_b),
            "next_obs": flat(nxt_b),
            "actions": flat(act_b),
            "rewards": flat(rew_b),
            "dones": flat(done_b),
            "episode_returns": np.asarray(completed, np.float32),
        }


class DQNLearner:
    """Double-DQN update compiled once over the device mesh."""

    def __init__(self, obs_dim: int, num_actions: int, *, lr: float = 1e-3,
                 gamma: float = 0.99, hidden=(64, 64), seed: int = 0,
                 mesh_devices: Optional[int] = None):
        import jax
        import jax.numpy as jnp
        import optax
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        from ray_tpu.rllib.core.rl_module import QModule

        self.module = QModule(num_actions=num_actions, hidden=tuple(hidden))
        self.params = self.module.init_params(obs_dim, seed)
        self.target_params = jax.tree.map(lambda x: x, self.params)
        self.opt = optax.adam(lr)
        self.opt_state = self.opt.init(self.params)

        devices = jax.devices()[:mesh_devices] if mesh_devices else jax.devices()
        self.mesh = Mesh(np.array(devices), ("dp",))
        self._batch_sharding = NamedSharding(self.mesh, P("dp"))
        self._replicated = NamedSharding(self.mesh, P())
        module = self.module

        def loss_fn(params, target_params, batch):
            q = module.apply({"params": params}, batch["obs"])
            q_sa = jnp.take_along_axis(
                q, batch["actions"][:, None], axis=-1)[:, 0]
            # double DQN: online net picks the argmax, target net scores it
            q_next_online = module.apply({"params": params},
                                         batch["next_obs"])
            best = jnp.argmax(q_next_online, axis=-1)
            q_next_target = module.apply({"params": target_params},
                                         batch["next_obs"])
            q_best = jnp.take_along_axis(
                q_next_target, best[:, None], axis=-1)[:, 0]
            target = batch["rewards"] + gamma * (1.0 - batch["dones"]) * (
                jax.lax.stop_gradient(q_best))
            td = q_sa - target
            # huber
            loss = jnp.mean(jnp.where(jnp.abs(td) < 1.0, 0.5 * td * td,
                                      jnp.abs(td) - 0.5))
            return loss, {"td_error_mean": jnp.mean(jnp.abs(td))}

        def update_fn(params, target_params, opt_state, batch):
            (loss, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                params, target_params, batch)
            updates, opt_state = self.opt.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            aux["total_loss"] = loss
            return params, opt_state, aux

        self._update = jax.jit(
            update_fn,
            in_shardings=(self._replicated, self._replicated,
                          self._replicated, self._batch_sharding),
            out_shardings=(self._replicated, self._replicated, None),
        )

    def _pad_to_devices(self, batch):
        import jax

        n = len(batch["obs"])
        d = self.mesh.size
        pad = (-n) % d
        if pad:
            batch = {
                k: np.concatenate([v, np.repeat(v[-1:], pad, axis=0)])
                for k, v in batch.items()
            }
        return jax.device_put(batch, self._batch_sharding)

    def update(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        self.params, self.opt_state, aux = self._update(
            self.params, self.target_params, self.opt_state,
            self._pad_to_devices(batch),
        )
        return {k: float(v) for k, v in aux.items()}

    def sync_target(self):
        import jax

        self.target_params = jax.tree.map(lambda x: x, self.params)

    def get_weights(self):
        import jax

        return jax.tree.map(np.asarray, self.params)

    def set_weights(self, weights, target_weights=None):
        import jax

        self.params = jax.device_put(weights, self._replicated)
        self.target_params = jax.device_put(
            target_weights if target_weights is not None else weights,
            self._replicated,
        )
        self.opt_state = self.opt.init(self.params)
        return True

    def get_target_weights(self):
        import jax

        return jax.tree.map(np.asarray, self.target_params)

    def num_devices(self) -> int:
        return self.mesh.size


class DQNConfig:
    def __init__(self):
        self.env_name: Optional[str] = None
        self.num_env_runners = 2
        self.num_envs_per_runner = 8
        self.rollout_fragment_length = 32
        self.lr = 1e-3
        self.gamma = 0.99
        self.buffer_capacity = 100_000
        self.train_batch_size = 256
        self.updates_per_iteration = 32
        self.target_update_freq = 4  # iterations between target syncs
        self.epsilon_start = 1.0
        self.epsilon_end = 0.05
        self.epsilon_decay_iters = 30
        self.learning_starts = 1_000
        self.hidden = (64, 64)
        self.seed = 0
        self.remote_learner = True

    def environment(self, env: str) -> "DQNConfig":
        self.env_name = env
        return self

    def env_runners(self, *, num_env_runners=None,
                    num_envs_per_env_runner=None,
                    rollout_fragment_length=None) -> "DQNConfig":
        if num_env_runners is not None:
            self.num_env_runners = num_env_runners
        if num_envs_per_env_runner is not None:
            self.num_envs_per_runner = num_envs_per_env_runner
        if rollout_fragment_length is not None:
            self.rollout_fragment_length = rollout_fragment_length
        return self

    def training(self, *, lr=None, gamma=None, buffer_capacity=None,
                 train_batch_size=None, updates_per_iteration=None,
                 target_update_freq=None, epsilon_decay_iters=None,
                 learning_starts=None, model_hidden=None) -> "DQNConfig":
        for name, val in [("lr", lr), ("gamma", gamma),
                          ("buffer_capacity", buffer_capacity),
                          ("train_batch_size", train_batch_size),
                          ("updates_per_iteration", updates_per_iteration),
                          ("target_update_freq", target_update_freq),
                          ("epsilon_decay_iters", epsilon_decay_iters),
                          ("learning_starts", learning_starts),
                          ("hidden", model_hidden)]:
            if val is not None:
                setattr(self, name, val)
        return self

    def debugging(self, *, seed: Optional[int] = None) -> "DQNConfig":
        if seed is not None:
            self.seed = seed
        return self

    def build(self) -> "DQN":
        assert self.env_name, "call .environment(env_name) first"
        return DQN(self)


class DQN:
    """Algorithm driver (Tune-trainable shape, like PPO)."""

    def __init__(self, config: DQNConfig):
        cfg = config
        self.config = cfg
        runner_cls = ray_tpu.remote(DQNEnvRunner)
        self.runners = [
            runner_cls.options(num_cpus=1).remote(
                cfg.env_name, cfg.num_envs_per_runner, seed=cfg.seed + 1000 * i)
            for i in range(cfg.num_env_runners)
        ]
        obs_dim, num_actions = ray_tpu.get(
            self.runners[0].obs_and_action_dims.remote(), timeout=120)
        kw = dict(lr=cfg.lr, gamma=cfg.gamma, hidden=cfg.hidden, seed=cfg.seed)
        if cfg.remote_learner:
            self._learner_actor = ray_tpu.remote(DQNLearner).options(
                num_cpus=1).remote(obs_dim, num_actions, **kw)
            self._learner = None
            self._weights = ray_tpu.get(
                self._learner_actor.get_weights.remote(), timeout=120)
        else:
            self._learner_actor = None
            self._learner = DQNLearner(obs_dim, num_actions, **kw)
            self._weights = self._learner.get_weights()
        self.buffer = ReplayBuffer(cfg.buffer_capacity, obs_dim)
        self.rng = np.random.default_rng(cfg.seed)
        self._iteration = 0
        self._timesteps = 0
        self._recent_returns: deque = deque(maxlen=100)

    def _epsilon(self) -> float:
        cfg = self.config
        frac = min(1.0, self._iteration / max(1, cfg.epsilon_decay_iters))
        return cfg.epsilon_start + frac * (cfg.epsilon_end - cfg.epsilon_start)

    def _learner_call(self, method, *args, **kw):
        if self._learner is not None:
            return getattr(self._learner, method)(*args, **kw)
        return ray_tpu.get(
            getattr(self._learner_actor, method).remote(*args, **kw),
            timeout=300)

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        eps = self._epsilon()
        refs = [r.sample.remote(self._weights, cfg.rollout_fragment_length, eps)
                for r in self.runners]
        batches = ray_tpu.get(refs, timeout=300)
        for b in batches:
            self._recent_returns.extend(b.pop("episode_returns").tolist())
            self._timesteps += len(b["obs"])
            self.buffer.add_batch(b)
        losses: Dict[str, float] = {}
        if self.buffer.size >= cfg.learning_starts:
            for _ in range(cfg.updates_per_iteration):
                mb = self.buffer.sample(self.rng, cfg.train_batch_size)
                losses = self._learner_call("update", mb)
            if self._iteration % cfg.target_update_freq == 0:
                self._learner_call("sync_target")
            self._weights = self._learner_call("get_weights")
        return losses

    def train(self) -> Dict[str, Any]:
        t0 = time.perf_counter()
        losses = self.training_step()
        self._iteration += 1
        mean_ret = (float(np.mean(self._recent_returns))
                    if self._recent_returns else 0.0)
        return {
            "training_iteration": self._iteration,
            "episode_return_mean": mean_ret,
            "num_env_steps_sampled_lifetime": self._timesteps,
            "epsilon": self._epsilon(),
            "time_this_iter_s": time.perf_counter() - t0,
            **{f"learner/{k}": v for k, v in losses.items()},
        }

    def get_weights(self):
        return self._weights

    def save(self, checkpoint_dir: Optional[str] = None) -> str:
        """Persist online+target weights, config and counters (reference:
        Algorithm.save / Checkpointable)."""
        import os
        import tempfile

        import cloudpickle

        path = checkpoint_dir or tempfile.mkdtemp(prefix="dqn_ckpt_")
        os.makedirs(path, exist_ok=True)
        target = self._learner_call("get_target_weights")
        with open(os.path.join(path, "algorithm_state.pkl"), "wb") as f:
            cloudpickle.dump({
                "algo": "DQN",
                "config": self.config,
                "weights": self._weights,
                "target_weights": target,
                "iteration": self._iteration,
                "timesteps": self._timesteps,
            }, f)
        return path

    def restore(self, checkpoint_path: str, _state: dict = None):
        import os

        import cloudpickle

        if _state is not None:
            state = _state
        else:
            with open(os.path.join(checkpoint_path, "algorithm_state.pkl"),
                      "rb") as f:
                state = cloudpickle.load(f)
        self._weights = state["weights"]
        self._iteration = state["iteration"]
        self._timesteps = state["timesteps"]
        self._learner_call("set_weights", state["weights"],
                           state.get("target_weights"))
        return self

    @classmethod
    def from_checkpoint(cls, checkpoint_path: str) -> "DQN":
        import os

        import cloudpickle

        with open(os.path.join(checkpoint_path, "algorithm_state.pkl"),
                  "rb") as f:
            state = cloudpickle.load(f)
        algo = cls(state["config"])
        return algo.restore(checkpoint_path, _state=state)

    def stop(self):
        for r in self.runners:
            try:
                ray_tpu.kill(r)
            except Exception:
                pass
        if self._learner_actor is not None:
            try:
                ray_tpu.kill(self._learner_actor)
            except Exception:
                pass
