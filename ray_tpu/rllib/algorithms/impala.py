"""IMPALA: decoupled async sampling + continuous v-trace learner.

Reference: rllib/algorithms/impala/ — env runner actors sample with
whatever weights they were last handed while the learner updates
continuously; the policy-lag is corrected by v-trace
(core/impala_learner.py). The async engine here is the idiomatic runtime
pattern: one in-flight sample_trajectory task per runner, `wait(...,
num_returns=1)` to consume whichever finishes first, and an immediate
redispatch carrying the LATEST weights — the learner never blocks on the
slowest runner (PPO's synchronous sample() does).
"""

from __future__ import annotations

import time
from collections import deque
from typing import Any, Dict, Optional

import numpy as np

import ray_tpu


class IMPALAConfig:
    def __init__(self):
        self.env_name: Optional[str] = None
        self.num_env_runners = 2
        self.num_envs_per_runner = 8
        self.rollout_fragment_length = 64
        self.lr = 5e-4
        self.gamma = 0.99
        self.vf_coeff = 0.5
        self.entropy_coeff = 0.01
        self.rho_bar = 1.0
        self.c_bar = 1.0
        self.hidden = (64, 64)
        self.seed = 0
        self.remote_learner = True
        # env steps consumed per train() iteration
        self.train_iter_env_steps = 4096

    def environment(self, env: str) -> "IMPALAConfig":
        self.env_name = env
        return self

    def env_runners(self, *, num_env_runners=None,
                    num_envs_per_env_runner=None,
                    rollout_fragment_length=None) -> "IMPALAConfig":
        if num_env_runners is not None:
            self.num_env_runners = num_env_runners
        if num_envs_per_env_runner is not None:
            self.num_envs_per_runner = num_envs_per_env_runner
        if rollout_fragment_length is not None:
            self.rollout_fragment_length = rollout_fragment_length
        return self

    def training(self, *, lr=None, gamma=None, vf_loss_coeff=None,
                 entropy_coeff=None, vtrace_clip_rho_threshold=None,
                 vtrace_clip_c_threshold=None, model_hidden=None,
                 train_iter_env_steps=None) -> "IMPALAConfig":
        for name, val in [
            ("lr", lr), ("gamma", gamma), ("vf_coeff", vf_loss_coeff),
            ("entropy_coeff", entropy_coeff),
            ("rho_bar", vtrace_clip_rho_threshold),
            ("c_bar", vtrace_clip_c_threshold), ("hidden", model_hidden),
            ("train_iter_env_steps", train_iter_env_steps),
        ]:
            if val is not None:
                setattr(self, name, val)
        return self

    def debugging(self, *, seed: Optional[int] = None) -> "IMPALAConfig":
        if seed is not None:
            self.seed = seed
        return self

    # Seam for IMPALA-engined variants (APPO): which learner class hosts
    # the update, and what extra kwargs it takes.
    def _learner_path(self) -> str:
        return "ray_tpu.rllib.core.impala_learner:ImpalaLearner"

    def _extra_learner_kwargs(self) -> dict:
        return {}

    def build(self) -> "IMPALA":
        assert self.env_name, "call .environment(env_name) first"
        return IMPALA(self)


def _load_learner_cls(path: str):
    import importlib

    mod, name = path.split(":")
    return getattr(importlib.import_module(mod), name)


class _LearnerActor:
    """Remote host for the learner (reference: learner_group.py:83)."""

    def __init__(self, obs_dim, num_actions, cfg, learner_path):
        cls = _load_learner_cls(learner_path)
        self.learner = cls(obs_dim, num_actions, **cfg)

    def update(self, batch):
        return self.learner.update_from_trajectories(batch)

    def get_weights(self):
        return self.learner.get_weights()

    def set_weights(self, w):
        return self.learner.set_weights(w)

    def num_devices(self):
        return self.learner.num_devices()


class IMPALA:
    def __init__(self, config: IMPALAConfig):
        from ray_tpu.rllib.env.env_runner import EnvRunnerGroup

        self.config = config
        self.env_runner_group = EnvRunnerGroup(
            config.env_name,
            num_runners=config.num_env_runners,
            num_envs_per_runner=config.num_envs_per_runner,
            gamma=config.gamma, lambda_=1.0, seed=config.seed,
        )
        obs_dim, num_actions = self.env_runner_group.obs_and_action_dims()
        learner_cfg = dict(
            lr=config.lr, gamma=config.gamma, vf_coeff=config.vf_coeff,
            entropy_coeff=config.entropy_coeff, rho_bar=config.rho_bar,
            c_bar=config.c_bar, hidden=config.hidden, seed=config.seed,
            **config._extra_learner_kwargs(),
        )
        learner_path = config._learner_path()
        if config.remote_learner:
            cls = ray_tpu.remote(_LearnerActor)
            self.learner = cls.options(num_cpus=1).remote(
                obs_dim, num_actions, learner_cfg, learner_path
            )
            self._remote = True
        else:
            self.learner = _load_learner_cls(learner_path)(
                obs_dim, num_actions, **learner_cfg)
            self._remote = False
        self._weights = self._learner_call("get_weights")
        self._iteration = 0
        self._recent_returns: deque = deque(maxlen=100)
        self._timesteps = 0
        self._updates = 0
        # async engine state: one in-flight rollout per runner
        self._inflight: Dict[Any, Any] = {}

    def _learner_call(self, method, *args):
        if self._remote:
            return ray_tpu.get(
                getattr(self.learner, method).remote(*args), timeout=300
            )
        from ray_tpu.rllib.core.impala_learner import ImpalaLearner  # noqa

        fn = {
            "get_weights": self.learner.get_weights,
            "set_weights": self.learner.set_weights,
            "update": self.learner.update_from_trajectories,
            "num_devices": self.learner.num_devices,
        }[method]
        return fn(*args)

    def num_devices(self):
        return self._learner_call("num_devices")

    def _dispatch(self, runner):
        ref = runner.sample_trajectory.remote(
            self._weights, self.config.rollout_fragment_length
        )
        self._inflight[ref] = runner

    def training_step(self) -> Dict[str, Any]:
        """Consume ~train_iter_env_steps env steps: learner updates on
        whichever rollout lands first; runners immediately redispatch with
        the freshest weights (policy lag <= one rollout per runner)."""
        cfg = self.config
        for runner in self.env_runner_group.runners:
            if runner not in self._inflight.values():
                self._dispatch(runner)
        consumed = 0
        losses: Dict[str, float] = {}
        t_update = 0.0
        while consumed < cfg.train_iter_env_steps:
            ready, _ = ray_tpu.wait(
                list(self._inflight), num_returns=1, timeout=300
            )
            if not ready:
                raise RuntimeError("no rollout arrived within 300s")
            ref = ready[0]
            runner = self._inflight.pop(ref)
            batch = ray_tpu.get(ref)
            self._dispatch(runner)  # keep the runner busy, newest weights
            self._recent_returns.extend(
                batch.pop("episode_returns").tolist()
            )
            n = batch["actions"].shape[0] * batch["actions"].shape[1]
            consumed += n
            self._timesteps += n
            t0 = time.perf_counter()
            losses = self._learner_call("update", batch)
            self._weights = self._learner_call("get_weights")
            t_update += time.perf_counter() - t0
            self._updates += 1
        losses["learner_env_steps_per_s"] = (
            consumed / t_update if t_update else 0.0
        )
        return losses

    def train(self) -> Dict[str, Any]:
        t0 = time.perf_counter()
        losses = self.training_step()
        self._iteration += 1
        mean_ret = (float(np.mean(self._recent_returns))
                    if self._recent_returns else 0.0)
        return {
            "training_iteration": self._iteration,
            "episode_return_mean": mean_ret,
            "num_env_steps_sampled_lifetime": self._timesteps,
            "num_learner_updates": self._updates,
            "time_this_iter_s": time.perf_counter() - t0,
            **{f"learner/{k}": v for k, v in losses.items()},
        }

    def get_weights(self):
        return self._weights

    def save(self, checkpoint_dir: Optional[str] = None) -> str:
        import os
        import tempfile

        import cloudpickle

        path = checkpoint_dir or tempfile.mkdtemp(prefix="impala_ckpt_")
        os.makedirs(path, exist_ok=True)
        with open(os.path.join(path, "algorithm_state.pkl"), "wb") as f:
            cloudpickle.dump({
                "algo": "IMPALA",
                "config": self.config,
                "weights": self._weights,
                "iteration": self._iteration,
                "timesteps": self._timesteps,
            }, f)
        return path

    def restore(self, checkpoint_path: str, _state: dict = None):
        import os

        import cloudpickle

        if _state is None:
            with open(os.path.join(checkpoint_path, "algorithm_state.pkl"),
                      "rb") as f:
                _state = cloudpickle.load(f)
        self._weights = _state["weights"]
        self._iteration = _state["iteration"]
        self._timesteps = _state["timesteps"]
        self._learner_call("set_weights", self._weights)
        return self

    @classmethod
    def from_checkpoint(cls, checkpoint_path: str) -> "IMPALA":
        import os

        import cloudpickle

        with open(os.path.join(checkpoint_path, "algorithm_state.pkl"),
                  "rb") as f:
            state = cloudpickle.load(f)
        algo = cls(state["config"])
        return algo.restore(checkpoint_path, _state=state)

    def stop(self):
        # drain in-flight rollouts so actor kills don't race them
        refs = list(self._inflight)
        self._inflight.clear()
        if refs:
            try:
                ray_tpu.wait(refs, num_returns=len(refs), timeout=30)
            except Exception:
                pass
        self.env_runner_group.shutdown()
        if self._remote:
            try:
                ray_tpu.kill(self.learner)
            except Exception:
                pass
