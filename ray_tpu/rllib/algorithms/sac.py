"""SAC — Soft Actor-Critic for continuous control (reference:
rllib/algorithms/sac/sac.py:407 + sac_learner/sac_torch_learner, new-stack
EnvRunner/Learner shape re-designed TPU-first: CPU actors collect
transitions with a numpy copy of the squashed-Gaussian policy; the whole
update — twin-critic TD, reparameterized actor, auto-tuned temperature,
polyak target sync — is ONE jit over the device mesh with the batch
sharded on dp).
"""

from __future__ import annotations

import time
from collections import deque
from typing import Any, Dict, Optional

import numpy as np

import ray_tpu
from ray_tpu.rllib.core.rl_module import (
    numpy_gaussian_forward,
    sample_squashed_actions,
)


class ContinuousReplayBuffer:
    """Uniform ring buffer of continuous-action transitions."""

    def __init__(self, capacity: int, obs_dim: int, action_dim: int):
        self.capacity = capacity
        self.obs = np.zeros((capacity, obs_dim), np.float32)
        self.next_obs = np.zeros((capacity, obs_dim), np.float32)
        self.actions = np.zeros((capacity, action_dim), np.float32)
        self.rewards = np.zeros(capacity, np.float32)
        self.dones = np.zeros(capacity, np.float32)
        self.size = 0
        self._pos = 0

    def add_batch(self, batch: Dict[str, np.ndarray]):
        n = len(batch["obs"])
        idx = (self._pos + np.arange(n)) % self.capacity
        self.obs[idx] = batch["obs"]
        self.next_obs[idx] = batch["next_obs"]
        self.actions[idx] = batch["actions"]
        self.rewards[idx] = batch["rewards"]
        self.dones[idx] = batch["dones"]
        self._pos = int((self._pos + n) % self.capacity)
        self.size = int(min(self.size + n, self.capacity))

    def sample(self, rng: np.random.Generator, n: int) -> Dict[str, np.ndarray]:
        idx = rng.integers(0, self.size, size=n)
        return {
            "obs": self.obs[idx],
            "next_obs": self.next_obs[idx],
            "actions": self.actions[idx],
            "rewards": self.rewards[idx],
            "dones": self.dones[idx],
        }


class SACEnvRunner:
    """Transition collector for continuous action spaces (CPU actor,
    numpy policy copy — never initializes a jax runtime)."""

    def __init__(self, env_name: str, num_envs: int, seed: int = 0):
        import gymnasium as gym

        self.envs = gym.make_vec(env_name, num_envs=num_envs,
                                 vectorization_mode="sync")
        self.num_envs = num_envs
        self.rng = np.random.default_rng(seed)
        self.obs, _ = self.envs.reset(seed=seed)
        space = self.envs.single_action_space
        self.low = np.asarray(space.low, np.float32)
        self.high = np.asarray(space.high, np.float32)
        self._episode_returns = np.zeros(num_envs)
        # gymnasium NEXT_STEP autoreset: mask the fabricated post-done step
        self._autoreset = np.zeros(num_envs, bool)

    def space_dims(self):
        return (
            int(np.prod(self.envs.single_observation_space.shape)),
            int(np.prod(self.envs.single_action_space.shape)),
            self.low.tolist(),
            self.high.tolist(),
        )

    def sample(self, actor_params, rollout_len: int, *,
               random: bool = False) -> Dict[str, np.ndarray]:
        """rollout_len steps per env; `random=True` collects warm-up
        transitions from the uniform policy (reference: SAC's
        num_steps_sampled_before_learning_starts)."""
        T, N = rollout_len, self.num_envs
        obs_b = np.zeros((T, N) + self.obs.shape[1:], np.float32)
        nxt_b = np.zeros_like(obs_b)
        act_b = np.zeros((T, N) + self.low.shape, np.float32)
        rew_b = np.zeros((T, N), np.float32)
        done_b = np.zeros((T, N), np.float32)
        valid_b = np.ones((T, N), bool)
        completed = []
        for t in range(T):
            valid_b[t] = ~self._autoreset
            if random:
                actions = self.rng.uniform(
                    self.low, self.high, size=(N,) + self.low.shape
                ).astype(np.float32)
            else:
                mean, log_std = numpy_gaussian_forward(actor_params, self.obs)
                actions = sample_squashed_actions(
                    self.rng, mean, log_std, self.low, self.high
                ).astype(np.float32)
            nxt, rew, term, trunc, _ = self.envs.step(actions)
            done = np.logical_or(term, trunc)
            self._autoreset = done
            obs_b[t] = self.obs
            act_b[t] = actions
            rew_b[t] = rew
            # bootstrap through time-limit truncations, cut on terminations
            done_b[t] = term.astype(np.float32)
            nxt_b[t] = nxt
            self._episode_returns += rew
            for i in np.nonzero(done)[0]:
                completed.append(float(self._episode_returns[i]))
                self._episode_returns[i] = 0.0
            self.obs = nxt
        keep = valid_b.reshape(T * N)
        flat = lambda a: a.reshape((T * N,) + a.shape[2:])[keep]  # noqa: E731
        return {
            "obs": flat(obs_b),
            "next_obs": flat(nxt_b),
            "actions": flat(act_b),
            "rewards": flat(rew_b),
            "dones": flat(done_b),
            "episode_returns": np.asarray(completed, np.float32),
        }


class SACLearner:
    """Twin-critic + reparameterized actor + auto-alpha, one jit.

    Actions are learned in squashed space scaled to the env bounds; the
    tanh log-det correction keeps the entropy term exact
    (reference: sac_torch_learner.compute_loss_for_module)."""

    def __init__(self, obs_dim: int, action_dim: int, low, high, *,
                 actor_lr: float = 3e-4, critic_lr: float = 3e-4,
                 alpha_lr: float = 3e-4, gamma: float = 0.99,
                 tau: float = 0.005, hidden=(256, 256), seed: int = 0,
                 target_entropy: Optional[float] = None,
                 mesh_devices: Optional[int] = None):
        import jax
        import jax.numpy as jnp
        import optax
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        from ray_tpu.rllib.core.rl_module import (
            SquashedGaussianModule,
            TwinQModule,
        )

        self.actor = SquashedGaussianModule(action_dim=action_dim,
                                            hidden=tuple(hidden))
        self.critic = TwinQModule(hidden=tuple(hidden))
        self.actor_params = self.actor.init_params(obs_dim, seed)
        self.critic_params = self.critic.init_params(obs_dim, action_dim,
                                                     seed + 1)
        self.target_params = jax.tree.map(lambda x: x, self.critic_params)
        self.log_alpha = jnp.zeros(())
        self.actor_opt = optax.adam(actor_lr)
        self.critic_opt = optax.adam(critic_lr)
        self.alpha_opt = optax.adam(alpha_lr)
        self.actor_opt_state = self.actor_opt.init(self.actor_params)
        self.critic_opt_state = self.critic_opt.init(self.critic_params)
        self.alpha_opt_state = self.alpha_opt.init(self.log_alpha)
        self._key = jax.random.PRNGKey(seed + 2)
        if target_entropy is None:
            target_entropy = -float(action_dim)  # reference default

        devices = (jax.devices()[:mesh_devices] if mesh_devices
                   else jax.devices())
        self.mesh = Mesh(np.array(devices), ("dp",))
        self._batch_sharding = NamedSharding(self.mesh, P("dp"))
        self._replicated = NamedSharding(self.mesh, P())

        actor_mod, critic_mod = self.actor, self.critic
        low_j = jnp.asarray(low, jnp.float32)
        high_j = jnp.asarray(high, jnp.float32)
        scale = (high_j - low_j) * 0.5
        center = (high_j + low_j) * 0.5

        def sample_action(params, obs, key):
            mean, log_std = actor_mod.apply({"params": params}, obs)
            std = jnp.exp(log_std)
            raw = mean + std * jax.random.normal(key, mean.shape)
            squashed = jnp.tanh(raw)
            action = center + scale * squashed
            # Gaussian logp minus tanh log-det minus the affine scale
            logp = (
                -0.5 * (((raw - mean) / std) ** 2
                        + 2.0 * log_std + jnp.log(2.0 * jnp.pi))
            ).sum(-1)
            logp -= jnp.log(
                scale * (1.0 - squashed ** 2) + 1e-6
            ).sum(-1)
            return action, logp

        def update_fn(actor_p, critic_p, target_p, log_alpha,
                      actor_os, critic_os, alpha_os, batch, key):
            k1, k2 = jax.random.split(key)
            alpha = jnp.exp(log_alpha)

            # --- critic: clipped double-Q soft target
            next_a, next_logp = sample_action(actor_p, batch["next_obs"], k1)
            tq1, tq2 = critic_mod.apply({"params": target_p},
                                        batch["next_obs"], next_a)
            target_q = batch["rewards"] + gamma * (1.0 - batch["dones"]) * (
                jnp.minimum(tq1, tq2) - alpha * next_logp
            )
            target_q = jax.lax.stop_gradient(target_q)

            def critic_loss_fn(p):
                q1, q2 = critic_mod.apply({"params": p}, batch["obs"],
                                          batch["actions"])
                return jnp.mean((q1 - target_q) ** 2
                                + (q2 - target_q) ** 2)

            critic_loss, cgrads = jax.value_and_grad(critic_loss_fn)(critic_p)
            cupd, critic_os = self.critic_opt.update(cgrads, critic_os,
                                                     critic_p)
            critic_p = optax.apply_updates(critic_p, cupd)

            # --- actor: maximize soft value under the fresh critics
            def actor_loss_fn(p):
                a, logp = sample_action(p, batch["obs"], k2)
                q1, q2 = critic_mod.apply({"params": critic_p},
                                          batch["obs"], a)
                return jnp.mean(alpha * logp - jnp.minimum(q1, q2)), logp

            (actor_loss, logp), agrads = jax.value_and_grad(
                actor_loss_fn, has_aux=True)(actor_p)
            aupd, actor_os = self.actor_opt.update(agrads, actor_os, actor_p)
            actor_p = optax.apply_updates(actor_p, aupd)

            # --- temperature: drive entropy toward the target
            def alpha_loss_fn(la):
                return -jnp.mean(
                    la * jax.lax.stop_gradient(logp + target_entropy)
                )

            alpha_loss, lgrads = jax.value_and_grad(alpha_loss_fn)(log_alpha)
            lupd, alpha_os = self.alpha_opt.update(lgrads, alpha_os,
                                                   log_alpha)
            log_alpha = optax.apply_updates(log_alpha, lupd)

            # --- polyak target sync, every step (tau-weighted)
            target_p = jax.tree.map(
                lambda t, o: (1.0 - tau) * t + tau * o, target_p, critic_p
            )
            aux = {
                "critic_loss": critic_loss,
                "actor_loss": actor_loss,
                "alpha_loss": alpha_loss,
                "alpha": alpha,
                "entropy": -jnp.mean(logp),
            }
            return (actor_p, critic_p, target_p, log_alpha,
                    actor_os, critic_os, alpha_os, aux)

        rep = self._replicated
        self._update = jax.jit(
            update_fn,
            in_shardings=(rep,) * 7 + (self._batch_sharding, rep),
            out_shardings=(rep,) * 7 + (None,),
        )

    def _pad_to_devices(self, batch):
        import jax

        n = len(batch["obs"])
        pad = (-n) % self.mesh.size
        if pad:
            batch = {
                k: np.concatenate([v, np.repeat(v[-1:], pad, axis=0)])
                for k, v in batch.items()
            }
        return jax.device_put(batch, self._batch_sharding)

    def update(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        import jax

        self._key, sub = jax.random.split(self._key)
        (self.actor_params, self.critic_params, self.target_params,
         self.log_alpha, self.actor_opt_state, self.critic_opt_state,
         self.alpha_opt_state, aux) = self._update(
            self.actor_params, self.critic_params, self.target_params,
            self.log_alpha, self.actor_opt_state, self.critic_opt_state,
            self.alpha_opt_state, self._pad_to_devices(batch), sub,
        )
        return {k: float(v) for k, v in aux.items()}

    def get_actor_weights(self):
        import jax

        return jax.tree.map(np.asarray, self.actor_params)


class SACConfig:
    def __init__(self):
        self.env_name: Optional[str] = None
        self.num_env_runners = 1
        self.num_envs_per_runner = 4
        self.rollout_fragment_length = 32
        self.actor_lr = 3e-4
        self.critic_lr = 3e-4
        self.alpha_lr = 3e-4
        self.gamma = 0.99
        self.tau = 0.005
        self.hidden = (256, 256)
        self.buffer_capacity = 200_000
        self.train_batch_size = 256
        self.learner_steps_per_iteration = 32
        self.learning_starts = 1_500
        self.target_entropy: Optional[float] = None
        self.seed = 0

    def environment(self, env: str) -> "SACConfig":
        self.env_name = env
        return self

    def env_runners(self, *, num_env_runners=None,
                    num_envs_per_env_runner=None,
                    rollout_fragment_length=None) -> "SACConfig":
        if num_env_runners is not None:
            self.num_env_runners = num_env_runners
        if num_envs_per_env_runner is not None:
            self.num_envs_per_runner = num_envs_per_env_runner
        if rollout_fragment_length is not None:
            self.rollout_fragment_length = rollout_fragment_length
        return self

    def training(self, *, actor_lr=None, critic_lr=None, alpha_lr=None,
                 gamma=None, tau=None, model_hidden=None,
                 buffer_capacity=None, train_batch_size=None,
                 learner_steps_per_iteration=None, learning_starts=None,
                 target_entropy=None) -> "SACConfig":
        for name, val in [
            ("actor_lr", actor_lr), ("critic_lr", critic_lr),
            ("alpha_lr", alpha_lr), ("gamma", gamma), ("tau", tau),
            ("hidden", model_hidden), ("buffer_capacity", buffer_capacity),
            ("train_batch_size", train_batch_size),
            ("learner_steps_per_iteration", learner_steps_per_iteration),
            ("learning_starts", learning_starts),
            ("target_entropy", target_entropy),
        ]:
            if val is not None:
                setattr(self, name, val)
        return self

    def debugging(self, *, seed: Optional[int] = None) -> "SACConfig":
        if seed is not None:
            self.seed = seed
        return self

    def build(self) -> "SAC":
        assert self.env_name, "call .environment(env_name) first"
        return SAC(self)


class SAC:
    """Algorithm driver (Tune-trainable shape: train() per iteration).

    Off-policy loop: runners push transitions into the driver-side
    replay buffer; `learner_steps_per_iteration` jit updates sample from
    it (reference: sac.py training_step)."""

    def __init__(self, config: SACConfig):
        cfg = config
        self.config = cfg
        runner_cls = ray_tpu.remote(SACEnvRunner)
        self.runners = [
            runner_cls.options(num_cpus=1).remote(
                cfg.env_name, cfg.num_envs_per_runner,
                seed=cfg.seed + 1000 * i,
            )
            for i in range(cfg.num_env_runners)
        ]
        obs_dim, act_dim, low, high = ray_tpu.get(
            self.runners[0].space_dims.remote(), timeout=120
        )
        self.learner = SACLearner(
            obs_dim, act_dim, low, high,
            actor_lr=cfg.actor_lr, critic_lr=cfg.critic_lr,
            alpha_lr=cfg.alpha_lr, gamma=cfg.gamma, tau=cfg.tau,
            hidden=cfg.hidden, seed=cfg.seed,
            target_entropy=cfg.target_entropy,
        )
        self.buffer = ContinuousReplayBuffer(cfg.buffer_capacity, obs_dim,
                                             act_dim)
        self.rng = np.random.default_rng(cfg.seed)
        self._weights = self.learner.get_actor_weights()
        self._iteration = 0
        self._timesteps = 0
        self._recent_returns: deque = deque(maxlen=50)

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        warmup = self.buffer.size < cfg.learning_starts
        refs = [
            r.sample.remote(self._weights, cfg.rollout_fragment_length,
                            random=warmup)
            for r in self.runners
        ]
        losses: Dict[str, float] = {}
        for b in ray_tpu.get(refs, timeout=300):
            self._recent_returns.extend(b.pop("episode_returns").tolist())
            self._timesteps += len(b["obs"])
            self.buffer.add_batch(b)
        if self.buffer.size >= cfg.learning_starts:
            for _ in range(cfg.learner_steps_per_iteration):
                mb = self.buffer.sample(self.rng, cfg.train_batch_size)
                losses = self.learner.update(mb)
            self._weights = self.learner.get_actor_weights()
        return losses

    def train(self) -> Dict[str, Any]:
        t0 = time.perf_counter()
        losses = self.training_step()
        self._iteration += 1
        mean_ret = (float(np.mean(self._recent_returns))
                    if self._recent_returns else 0.0)
        return {
            "training_iteration": self._iteration,
            "episode_return_mean": mean_ret,
            "num_env_steps_sampled_lifetime": self._timesteps,
            "time_this_iter_s": time.perf_counter() - t0,
            **{f"learner/{k}": v for k, v in losses.items()},
        }

    def get_weights(self):
        return self._weights

    def save(self, checkpoint_dir: Optional[str] = None) -> str:
        import os
        import tempfile

        import cloudpickle

        path = checkpoint_dir or tempfile.mkdtemp(prefix="sac_ckpt_")
        os.makedirs(path, exist_ok=True)
        with open(os.path.join(path, "algorithm_state.pkl"), "wb") as f:
            cloudpickle.dump({
                "algo": "SAC",
                "config": self.config,
                "weights": self._weights,
                "iteration": self._iteration,
                "timesteps": self._timesteps,
            }, f)
        return path

    def stop(self):
        for r in self.runners:
            try:
                ray_tpu.kill(r)
            except Exception:
                pass
