"""BC and MARWIL: offline RL from recorded experiences via ray_tpu.data.

Reference: rllib/algorithms/bc/bc.py + rllib/algorithms/marwil/marwil.py —
MARWIL (Wang et al. 2018) is exponentially advantage-weighted behavior
cloning; BC is its beta=0 special case (the reference literally subclasses
MARWIL for BC). Losses re-designed jax-first: one jit per minibatch update;
the advantage normalizer c^2 is the same running average of squared
advantages the reference keeps (marwil_torch_policy moving_average of
ma_adv_norm).

Data path: experiences load through ray_tpu.data.read_parquet (reference:
offline_data.py wraps ray.data the same way); each train() epoch reshuffles
block order and streams minibatches.
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from ray_tpu.rllib.offline import batch_to_numpy, read_experiences


class _MarwilLearner:
    """Advantage-weighted BC update, one jit (beta=0 degrades to pure BC)."""

    def __init__(self, obs_dim: int, num_actions: int, *, lr: float = 1e-3,
                 beta: float = 1.0, vf_coeff: float = 1.0,
                 hidden=(64, 64), seed: int = 0):
        import jax
        import jax.numpy as jnp
        import optax

        from ray_tpu.rllib.core.rl_module import ActorCriticModule

        self.module = ActorCriticModule(num_actions=num_actions,
                                        hidden=tuple(hidden))
        self.params = self.module.init_params(obs_dim, seed)
        self.opt = optax.adam(lr)
        self.opt_state = self.opt.init(self.params)
        self.beta = beta
        # running mean of squared advantages (reference: ma_adv_norm);
        # warm-started from the first batch — with a cold norm of 1 every
        # early weight saturates at the clip and the policy burns in on
        # uniformly-upweighted garbage before the normalizer catches up
        self.ma_adv_sq: Optional[float] = None
        module = self.module

        def loss_fn(params, batch, adv_norm):
            logits, values = module.apply({"params": params}, batch["obs"])
            logp_all = jax.nn.log_softmax(logits)
            logp = jnp.take_along_axis(
                logp_all, batch["action"][:, None], axis=-1)[:, 0]
            adv = batch["return_to_go"] - values
            if beta > 0.0:
                w = jnp.exp(beta * jax.lax.stop_gradient(adv) / adv_norm)
                # clip the exponential weights like the reference (1e8 cap
                # is its untuned default; 20 keeps fp32 sane)
                w = jnp.minimum(w, 20.0)
                vf_loss = jnp.mean(adv ** 2)
            else:
                w = jnp.ones_like(logp)
                vf_loss = 0.0
            pi_loss = -jnp.mean(w * logp)
            total = pi_loss + (vf_coeff * vf_loss if beta > 0.0 else 0.0)
            return total, {
                "pi_loss": pi_loss, "vf_loss": vf_loss,
                "mean_abs_adv": jnp.mean(jnp.abs(adv)),
                "mean_sq_adv": jnp.mean(adv ** 2),
                "mean_weight": jnp.mean(w),
                "mean_logp": jnp.mean(logp),
            }

        def update_fn(params, opt_state, batch, adv_norm):
            (loss, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                params, batch, adv_norm)
            updates, opt_state = self.opt.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            aux["total_loss"] = loss
            return params, opt_state, aux

        self._update = jax.jit(update_fn)

    def update(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        if self.ma_adv_sq is None:
            # warm start: one throwaway norm-estimation pass
            _, _, aux0 = self._update(
                self.params, self.opt_state, batch, 1e9)
            self.ma_adv_sq = max(float(aux0["mean_sq_adv"]), 1e-8)
        adv_norm = max(float(np.sqrt(self.ma_adv_sq)), 1e-4)
        self.params, self.opt_state, aux = self._update(
            self.params, self.opt_state, batch, adv_norm)
        # EMA of squared advantages, like the reference's moving-average
        # ma_adv_norm but fast enough to settle within a test-sized run
        self.ma_adv_sq += 0.05 * (float(aux["mean_sq_adv"]) - self.ma_adv_sq)
        return {k: float(v) for k, v in aux.items()}

    def get_weights(self):
        import jax

        return jax.tree.map(np.asarray, self.params)


class MARWILConfig:
    _default_beta = 1.0  # 0 = BC

    def __init__(self):
        self.env_name: Optional[str] = None
        self.offline_path = None
        self.lr = 1e-3
        self.beta = type(self)._default_beta
        self.vf_coeff = 1.0
        self.train_batch_size = 512
        self.minibatches_per_iter = 32
        self.hidden = (64, 64)
        self.seed = 0

    def environment(self, env: str):
        self.env_name = env
        return self

    def offline_data(self, path):
        """Parquet path(s) of recorded experiences (reference:
        config.offline_data(input_=...))."""
        self.offline_path = path
        return self

    def training(self, *, lr=None, beta=None, vf_coeff=None,
                 train_batch_size=None, minibatches_per_iter=None,
                 model_hidden=None):
        for name, val in [("lr", lr), ("beta", beta), ("vf_coeff", vf_coeff),
                          ("train_batch_size", train_batch_size),
                          ("minibatches_per_iter", minibatches_per_iter),
                          ("hidden", model_hidden)]:
            if val is not None:
                setattr(self, name, val)
        return self

    def debugging(self, *, seed=None):
        if seed is not None:
            self.seed = seed
        return self

    def build(self):
        assert self.offline_path, "call .offline_data(path) first"
        assert self.env_name, "call .environment(env) first"
        return MARWIL(self)


class BCConfig(MARWILConfig):
    _default_beta = 0.0


class MARWIL:
    def __init__(self, config: MARWILConfig):
        import gymnasium as gym

        self.config = config
        self.dataset = read_experiences(config.offline_path)
        spec = gym.make(config.env_name)
        obs_dim = int(np.prod(spec.observation_space.shape))
        num_actions = int(spec.action_space.n)
        spec.close()
        self.learner = _MarwilLearner(
            obs_dim, num_actions, lr=config.lr, beta=config.beta,
            vf_coeff=config.vf_coeff, hidden=config.hidden, seed=config.seed)
        self._iteration = 0
        self._epoch_iter = None
        # Dataset-level return statistics: the value head regresses the
        # STANDARDIZED return-to-go (raw CartPole-scale returns ~1e2 put a
        # ~1e4-scale vf gradient through the shared trunk and crush the
        # policy features; the reference's marwil keeps the scales sane via
        # its moving advantage norm — standardizing the target is the
        # batch-independent equivalent).
        if config.beta > 0:
            count, total, sq = 0, 0.0, 0.0
            for b in self.dataset.iter_batches(batch_size=4096):
                r = np.asarray(batch_to_numpy(b)["return_to_go"], np.float64)
                count += r.size
                total += float(r.sum())
                sq += float((r ** 2).sum())
            mu = total / max(count, 1)
            var = max(sq / max(count, 1) - mu * mu, 1e-6)
            self._rtg_stats = (mu, float(np.sqrt(var)))
        else:
            self._rtg_stats = (0.0, 1.0)

    def _next_batch(self):
        for _ in range(2):
            if self._epoch_iter is None:
                self._epoch_iter = self.dataset.random_shuffle(
                    seed=self.config.seed + self._iteration
                ).iter_batches(batch_size=self.config.train_batch_size)
            try:
                return next(self._epoch_iter)
            except StopIteration:
                self._epoch_iter = None
        raise RuntimeError("offline dataset is empty")

    def train(self) -> Dict[str, float]:
        metrics: Dict[str, float] = {}
        for _ in range(self.config.minibatches_per_iter):
            batch = batch_to_numpy(self._next_batch())
            mu, sigma = self._rtg_stats
            batch = {
                "obs": batch["obs"].astype(np.float32),
                "action": batch["action"].astype(np.int32),
                "return_to_go": (
                    (batch["return_to_go"].astype(np.float32) - mu) / sigma),
            }
            metrics = self.learner.update(batch)
        self._iteration += 1
        metrics["training_iteration"] = self._iteration
        return metrics

    def evaluate(self, num_episodes: int = 10, *, greedy: bool = True,
                 seed: int = 1000) -> Dict[str, float]:
        """Run the learned policy in the real env (reference: the
        evaluation workers offline algos attach for exactly this)."""
        import gymnasium as gym

        from ray_tpu.rllib.core.rl_module import numpy_forward

        params = self.learner.get_weights()
        env = gym.make(self.config.env_name)
        returns = []
        for ep in range(num_episodes):
            obs, _ = env.reset(seed=seed + ep)
            total, done = 0.0, False
            while not done:
                logits, _ = numpy_forward(params, np.asarray(obs)[None])
                if greedy:
                    action = int(np.argmax(logits[0]))
                else:
                    p = np.exp(logits[0] - logits[0].max())
                    action = int(np.random.choice(len(p), p=p / p.sum()))
                obs, reward, term, trunc, _ = env.step(action)
                total += float(reward)
                done = bool(term or trunc)
            returns.append(total)
        env.close()
        return {"episode_return_mean": float(np.mean(returns)),
                "episodes": num_episodes}

    def get_weights(self):
        return self.learner.get_weights()

    def stop(self):
        pass


class BC(MARWIL):
    """Behavior cloning = MARWIL with beta=0 (reference: bc.py subclasses
    MARWIL the same way)."""

    def __init__(self, config: BCConfig):
        super().__init__(config)
