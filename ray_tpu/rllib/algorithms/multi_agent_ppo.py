"""Multi-agent PPO: per-policy learners over multi-agent env runners
(reference: the multi_agent() axis of AlgorithmConfig —
rllib/algorithms/algorithm_config.py policies/policy_mapping_fn — driving
rllib/env/multi_agent_env_runner.py:55; each policy trains on exactly the
transitions its mapped agents produced).
"""

from __future__ import annotations

import time
from collections import deque
from typing import Any, Callable, Dict, Optional

import numpy as np

import ray_tpu
from ray_tpu.rllib.env.multi_agent import MultiAgentEnvRunner


class MultiAgentPPOConfig:
    def __init__(self):
        self.env_creator: Optional[Callable] = None
        self.policy_mapping_fn: Callable[[str], str] = lambda aid: aid
        self.num_env_runners = 2
        self.rollout_fragment_length = 256
        self.lr = 3e-4
        self.gamma = 0.99
        self.lambda_ = 0.95
        self.clip = 0.2
        self.vf_coeff = 0.5
        self.entropy_coeff = 0.01
        self.num_epochs = 4
        self.minibatch_size = 256
        self.hidden = (64, 64)
        self.seed = 0

    def environment(self, env_creator: Callable) -> "MultiAgentPPOConfig":
        self.env_creator = env_creator
        return self

    def multi_agent(self, *, policy_mapping_fn: Callable[[str], str]
                    ) -> "MultiAgentPPOConfig":
        self.policy_mapping_fn = policy_mapping_fn
        return self

    def env_runners(self, *, num_env_runners=None,
                    rollout_fragment_length=None) -> "MultiAgentPPOConfig":
        if num_env_runners is not None:
            self.num_env_runners = num_env_runners
        if rollout_fragment_length is not None:
            self.rollout_fragment_length = rollout_fragment_length
        return self

    def training(self, *, lr=None, gamma=None, lambda_=None, clip_param=None,
                 num_epochs=None, minibatch_size=None, model_hidden=None
                 ) -> "MultiAgentPPOConfig":
        for name, val in [("lr", lr), ("gamma", gamma), ("lambda_", lambda_),
                          ("clip", clip_param), ("num_epochs", num_epochs),
                          ("minibatch_size", minibatch_size),
                          ("hidden", model_hidden)]:
            if val is not None:
                setattr(self, name, val)
        return self

    def debugging(self, *, seed: Optional[int] = None
                  ) -> "MultiAgentPPOConfig":
        if seed is not None:
            self.seed = seed
        return self

    def build(self) -> "MultiAgentPPO":
        assert self.env_creator, "call .environment(env_creator) first"
        return MultiAgentPPO(self)


class MultiAgentPPO:
    def __init__(self, config: MultiAgentPPOConfig):
        from ray_tpu.rllib.core.learner import JaxLearner

        cfg = config
        self.config = cfg
        runner_cls = ray_tpu.remote(MultiAgentEnvRunner)
        self.runners = [
            runner_cls.options(num_cpus=1).remote(
                cfg.env_creator, cfg.policy_mapping_fn,
                gamma=cfg.gamma, lambda_=cfg.lambda_,
                seed=cfg.seed + 1000 * i,
            )
            for i in range(cfg.num_env_runners)
        ]
        spaces = ray_tpu.get(self.runners[0].spaces.remote(), timeout=120)
        self.learners: Dict[str, JaxLearner] = {
            pid: JaxLearner(
                obs_dim, n_act, lr=cfg.lr, clip=cfg.clip,
                vf_coeff=cfg.vf_coeff, entropy_coeff=cfg.entropy_coeff,
                # sorted-index seeds: str hash() is salted per process and
                # would defeat .debugging(seed=...) reproducibility
                hidden=cfg.hidden, seed=cfg.seed + idx,
            )
            for idx, (pid, (obs_dim, n_act)) in enumerate(
                sorted(spaces.items())
            )
        }
        self._weights = {
            pid: learner.get_weights() for pid, learner in self.learners.items()
        }
        self._iteration = 0
        self._timesteps = 0
        self._recent_returns: deque = deque(maxlen=100)

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        refs = [
            r.sample.remote(self._weights, cfg.rollout_fragment_length)
            for r in self.runners
        ]
        results = ray_tpu.get(refs, timeout=300)
        merged: Dict[str, Dict[str, list]] = {}
        for res in results:
            for pid, batch in res.items():
                self._recent_returns.extend(
                    batch.pop("episode_returns").tolist()
                )
                dest = merged.setdefault(pid, {k: [] for k in batch})
                for k, v in batch.items():
                    dest[k].append(v)
        losses: Dict[str, float] = {}
        for pid, parts in merged.items():
            batch = {k: np.concatenate(v) for k, v in parts.items()}
            self._timesteps += len(batch["obs"])
            aux = self.learners[pid].update_from_batch(
                batch, num_epochs=cfg.num_epochs,
                minibatch_size=cfg.minibatch_size,
                seed=cfg.seed + self._iteration,
            )
            losses.update({f"{pid}/{k}": v for k, v in aux.items()})
            self._weights[pid] = self.learners[pid].get_weights()
        return losses

    def train(self) -> Dict[str, Any]:
        t0 = time.perf_counter()
        losses = self.training_step()
        self._iteration += 1
        mean_ret = (float(np.mean(self._recent_returns))
                    if self._recent_returns else 0.0)
        return {
            "training_iteration": self._iteration,
            "episode_return_mean": mean_ret,
            "num_env_steps_sampled_lifetime": self._timesteps,
            "time_this_iter_s": time.perf_counter() - t0,
            **{f"learner/{k}": v for k, v in losses.items()},
        }

    def get_weights(self):
        return dict(self._weights)

    def stop(self):
        for r in self.runners:
            try:
                ray_tpu.kill(r)
            except Exception:
                pass
