"""SingleAgentEnvRunner + EnvRunnerGroup (reference:
rllib/env/single_agent_env_runner.py:61, env_runner_group.py:71): CPU actors
stepping gymnasium vector envs with a numpy copy of the policy, returning
GAE-processed rollout batches. The policy forward is pure numpy so runner
processes never initialize a jax device runtime.
"""

from __future__ import annotations

from typing import Dict, List

import numpy as np

import ray_tpu
from ray_tpu.rllib.core.rl_module import numpy_forward, sample_actions


class SingleAgentEnvRunner:
    def __init__(self, env_name: str, num_envs: int, *, gamma: float,
                 lambda_: float, seed: int = 0):
        import gymnasium as gym

        self.envs = gym.make_vec(env_name, num_envs=num_envs,
                                 vectorization_mode="sync")
        self.num_envs = num_envs
        self.gamma = gamma
        self.lambda_ = lambda_
        self.rng = np.random.default_rng(seed)
        self.obs, _ = self.envs.reset(seed=seed)
        self._episode_returns = np.zeros(num_envs)
        self._completed: List[float] = []
        # gymnasium NEXT_STEP autoreset: the step after a done ignores the
        # action and returns the reset obs with reward 0 — those fabricated
        # transitions must not be trained on
        self._autoreset = np.zeros(num_envs, bool)

    def obs_and_action_dims(self):
        return (int(np.prod(self.envs.single_observation_space.shape)),
                int(self.envs.single_action_space.n))

    def _rollout(self, params, rollout_len: int) -> Dict[str, np.ndarray]:
        """Shared env-stepping core: time-major buffers for rollout_len
        steps per env (policy forward, vector step, episode bookkeeping).
        Both the on-policy (GAE) and off-policy (v-trace) samplers build on
        this."""
        T, N = rollout_len, self.num_envs
        obs_buf = np.zeros((T, N) + self.obs.shape[1:], np.float32)
        act_buf = np.zeros((T, N), np.int64)
        logp_buf = np.zeros((T, N), np.float32)
        rew_buf = np.zeros((T, N), np.float32)
        val_buf = np.zeros((T, N), np.float32)
        done_buf = np.zeros((T, N), np.float32)
        valid_buf = np.ones((T, N), bool)
        self._completed = []
        for t in range(T):
            logits, v = numpy_forward(params, self.obs)
            actions, logp = sample_actions(self.rng, logits)
            valid_buf[t] = ~self._autoreset
            nxt, rew, term, trunc, _ = self.envs.step(actions)
            done = np.logical_or(term, trunc)
            self._autoreset = done
            obs_buf[t] = self.obs
            act_buf[t] = actions
            logp_buf[t] = logp
            rew_buf[t] = rew
            val_buf[t] = v
            done_buf[t] = done.astype(np.float32)
            self._episode_returns += rew
            for i in np.nonzero(done)[0]:
                self._completed.append(float(self._episode_returns[i]))
                self._episode_returns[i] = 0.0
            self.obs = nxt
        return {
            "obs": obs_buf, "actions": act_buf, "logp": logp_buf,
            "rewards": rew_buf, "values": val_buf, "dones": done_buf,
            "valid": valid_buf,
        }

    def sample(self, params, rollout_len: int) -> Dict[str, np.ndarray]:
        """Collect rollout_len steps per env; returns a flat batch with GAE
        advantages/returns plus completed-episode stats."""
        T, N = rollout_len, self.num_envs
        roll = self._rollout(params, rollout_len)
        obs_buf, act_buf, logp_buf = roll["obs"], roll["actions"], roll["logp"]
        rew_buf, val_buf, done_buf = (
            roll["rewards"], roll["values"], roll["dones"]
        )
        _, last_v = numpy_forward(params, self.obs)
        adv = np.zeros((T, N), np.float32)
        lastgae = np.zeros(N, np.float32)
        for t in reversed(range(T)):
            nonterminal = 1.0 - done_buf[t]
            next_v = val_buf[t + 1] if t + 1 < T else last_v
            delta = rew_buf[t] + self.gamma * next_v * nonterminal - val_buf[t]
            lastgae = delta + self.gamma * self.lambda_ * nonterminal * lastgae
            adv[t] = lastgae
        returns = adv + val_buf
        keep = roll["valid"].reshape(T * N)
        flat = lambda a: a.reshape((T * N,) + a.shape[2:])[keep]  # noqa: E731
        return {
            "obs": flat(obs_buf),
            "actions": flat(act_buf),
            "logp_old": flat(logp_buf),
            "advantages": flat(adv),
            "returns": flat(returns),
            "episode_returns": np.asarray(self._completed, np.float32),
        }


    def sample_trajectory(self, params, rollout_len: int) -> Dict[str, np.ndarray]:
        """Time-major trajectory WITHOUT advantage processing — the
        off-policy learner (IMPALA v-trace) needs raw sequences plus the
        behavior policy's log-probs (reference:
        rllib/algorithms/impala — decoupled sampling)."""
        roll = self._rollout(params, rollout_len)
        return {
            "obs": roll["obs"],
            "actions": roll["actions"],
            "behavior_logp": roll["logp"],
            "rewards": roll["rewards"],
            "dones": roll["dones"],
            # sequences must stay time-contiguous for v-trace, so invalid
            # (autoreset) rows are weighted out in the learner's loss
            "valid": roll["valid"].astype(np.float32),
            "bootstrap_obs": self.obs.astype(np.float32),
            "episode_returns": np.asarray(self._completed, np.float32),
        }


class EnvRunnerGroup:
    def __init__(self, env_name: str, *, num_runners: int,
                 num_envs_per_runner: int, gamma: float, lambda_: float,
                 seed: int = 0):
        runner_cls = ray_tpu.remote(SingleAgentEnvRunner)
        self.runners = [
            runner_cls.options(num_cpus=1).remote(
                env_name, num_envs_per_runner, gamma=gamma, lambda_=lambda_,
                seed=seed + 1000 * i,
            )
            for i in range(num_runners)
        ]

    def obs_and_action_dims(self):
        return ray_tpu.get(self.runners[0].obs_and_action_dims.remote(),
                           timeout=120)

    def sample(self, params, rollout_len: int) -> Dict[str, np.ndarray]:
        """Parallel rollouts; concatenated into one training batch."""
        refs = [r.sample.remote(params, rollout_len) for r in self.runners]
        batches = ray_tpu.get(refs, timeout=300)
        out = {
            k: np.concatenate([b[k] for b in batches])
            for k in batches[0]
        }
        return out

    def shutdown(self):
        for r in self.runners:
            try:
                ray_tpu.kill(r)
            except Exception:
                pass
