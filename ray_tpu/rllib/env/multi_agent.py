"""Multi-agent environments + runner (reference:
rllib/env/multi_agent_env.py:32 MultiAgentEnv,
rllib/env/multi_agent_env_runner.py:55 MultiAgentEnvRunner).

The env speaks per-agent dicts: reset() -> (obs_dict, info_dict);
step(action_dict) -> (obs, rewards, terminateds, truncateds, infos) dicts,
with terminateds/truncateds carrying the "__all__" key. A
policy_mapping_fn routes each agent id to a policy id; the runner
collects one GAE-processed batch PER POLICY so heterogeneous policies
train independently (shared policies simply map several agents to one
id).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ray_tpu.rllib.core.rl_module import numpy_forward, sample_actions


class MultiAgentEnv:
    """Base class for dict-of-agents environments."""

    #: ids of every agent that may ever appear
    possible_agents: List[str] = []

    def reset(self, *, seed: Optional[int] = None
              ) -> Tuple[Dict[str, np.ndarray], Dict[str, dict]]:
        raise NotImplementedError

    def step(self, action_dict: Dict[str, Any]) -> Tuple[
        Dict[str, np.ndarray], Dict[str, float], Dict[str, bool],
        Dict[str, bool], Dict[str, dict],
    ]:
        raise NotImplementedError

    def observation_space_shape(self, agent_id: str) -> tuple:
        raise NotImplementedError

    def action_space_n(self, agent_id: str) -> int:
        raise NotImplementedError


class MultiAgentCartPole(MultiAgentEnv):
    """N independent CartPoles under one multi-agent wrapper — the
    standard smoke env (reference: rllib/env/tests use the same shape).
    The episode ends (__all__) when every sub-episode has ended; finished
    agents stop emitting observations until the joint reset."""

    def __init__(self, num_agents: int = 2, seed: int = 0):
        import gymnasium as gym

        self.possible_agents = [f"agent_{i}" for i in range(num_agents)]
        self._envs = {
            aid: gym.make("CartPole-v1") for aid in self.possible_agents
        }
        self._done: Dict[str, bool] = {}
        self._seed = seed

    def reset(self, *, seed=None):
        obs, infos = {}, {}
        base = self._seed if seed is None else seed
        for i, (aid, env) in enumerate(self._envs.items()):
            o, info = env.reset(seed=base + i)
            obs[aid] = np.asarray(o, np.float32)
            infos[aid] = info
            self._done[aid] = False
        self._seed = base + len(self._envs)
        return obs, infos

    def step(self, action_dict):
        obs, rewards, terms, truncs, infos = {}, {}, {}, {}, {}
        for aid, action in action_dict.items():
            if self._done.get(aid):
                continue
            o, r, term, trunc, info = self._envs[aid].step(int(action))
            rewards[aid] = float(r)
            terms[aid] = bool(term)
            truncs[aid] = bool(trunc)
            infos[aid] = info
            if term or trunc:
                self._done[aid] = True
            else:
                obs[aid] = np.asarray(o, np.float32)
        all_done = all(self._done.values())
        terms["__all__"] = all_done
        truncs["__all__"] = False
        return obs, rewards, terms, truncs, infos

    def observation_space_shape(self, agent_id):
        return self._envs[agent_id].observation_space.shape

    def action_space_n(self, agent_id):
        return int(self._envs[agent_id].action_space.n)


class MultiAgentEnvRunner:
    """Steps one MultiAgentEnv, routing each agent's observations through
    its mapped policy and returning a GAE batch PER POLICY (reference:
    multi_agent_env_runner.py:55; GAE segmentation follows each agent's
    own episode boundaries)."""

    def __init__(self, env_creator: Callable[[], MultiAgentEnv],
                 policy_mapping_fn: Callable[[str], str], *,
                 gamma: float, lambda_: float, seed: int = 0):
        self.env = env_creator()
        self.policy_of = policy_mapping_fn
        self.gamma = gamma
        self.lambda_ = lambda_
        self.rng = np.random.default_rng(seed)
        self.obs, _ = self.env.reset(seed=seed)
        self._episode_return = 0.0
        self._completed: List[float] = []

    def spaces(self) -> Dict[str, tuple]:
        """policy_id -> (obs_dim, num_actions), derived from its agents."""
        out = {}
        for aid in self.env.possible_agents:
            pid = self.policy_of(aid)
            dims = (
                int(np.prod(self.env.observation_space_shape(aid))),
                self.env.action_space_n(aid),
            )
            if pid in out and out[pid] != dims:
                raise ValueError(
                    f"policy {pid!r} maps agents with different spaces"
                )
            out[pid] = dims
        return out

    def sample(self, params_by_policy: Dict[str, Any], rollout_len: int
               ) -> Dict[str, Dict[str, np.ndarray]]:
        # per-agent transition streams; flattened per policy at the end
        streams: Dict[str, Dict[str, list]] = {
            aid: {"obs": [], "actions": [], "logp": [], "rewards": [],
                  "values": [], "dones": []}
            for aid in self.env.possible_agents
        }
        self._completed = []
        for _ in range(rollout_len):
            live = list(self.obs.keys())
            if not live:
                self.obs, _ = self.env.reset()
                live = list(self.obs.keys())
            actions: Dict[str, int] = {}
            for aid in live:
                params = params_by_policy[self.policy_of(aid)]
                logits, v = numpy_forward(params, self.obs[aid][None])
                act, logp = sample_actions(self.rng, logits)
                actions[aid] = int(act[0])
                s = streams[aid]
                s["obs"].append(self.obs[aid])
                s["actions"].append(int(act[0]))
                s["logp"].append(float(logp[0]))
                s["values"].append(float(v[0]))
            next_obs, rewards, terms, truncs, _ = self.env.step(actions)
            for aid in live:
                done = terms.get(aid, False) or truncs.get(aid, False)
                streams[aid]["rewards"].append(rewards.get(aid, 0.0))
                streams[aid]["dones"].append(float(done))
                self._episode_return += rewards.get(aid, 0.0)
            if terms.get("__all__") or truncs.get("__all__"):
                self._completed.append(self._episode_return)
                self._episode_return = 0.0
                next_obs, _ = self.env.reset()
            self.obs = next_obs

        out: Dict[str, Dict[str, list]] = {}
        for aid, s in streams.items():
            if not s["obs"]:
                continue
            # bootstrap with V(s_T) when the agent's episode is still live
            last_v = 0.0
            if aid in self.obs and s["dones"] and not s["dones"][-1]:
                params = params_by_policy[self.policy_of(aid)]
                _, v = numpy_forward(params, self.obs[aid][None])
                last_v = float(v[0])
            batch = self._gae(s, last_v)
            pid = self.policy_of(aid)
            dest = out.setdefault(pid, {k: [] for k in batch})
            for k, v in batch.items():
                dest[k].append(v)
        result = {
            pid: {k: np.concatenate(v) for k, v in parts.items()}
            for pid, parts in out.items()
        }
        for pid in result:
            result[pid]["episode_returns"] = np.asarray(
                self._completed, np.float32
            )
        return result

    def _gae(self, s: Dict[str, list], last_v: float
             ) -> Dict[str, np.ndarray]:
        T = len(s["obs"])
        rew = np.asarray(s["rewards"], np.float32)
        val = np.asarray(s["values"], np.float32)
        done = np.asarray(s["dones"], np.float32)
        adv = np.zeros(T, np.float32)
        lastgae = 0.0
        for t in reversed(range(T)):
            nonterminal = 1.0 - done[t]
            next_v = val[t + 1] if t + 1 < T else last_v
            delta = rew[t] + self.gamma * next_v * nonterminal - val[t]
            lastgae = delta + self.gamma * self.lambda_ * nonterminal * lastgae
            adv[t] = lastgae
        return {
            "obs": np.asarray(s["obs"], np.float32),
            "actions": np.asarray(s["actions"], np.int64),
            "logp_old": np.asarray(s["logp"], np.float32),
            "advantages": adv,
            "returns": adv + val,
        }
