"""Offline RL data plane: record episodes to parquet, read them back as a
ray_tpu.data Dataset.

Reference: rllib/offline/offline_data.py:18 (OfflineData wraps
ray.data.read_* for offline algorithms), rllib/offline/json_writer.py /
output writers (we standardize on parquet — the columnar format the data
layer already reads with column/filter pushdown). Rows are per-STEP:
episode_id, t, obs (list<float>), action, reward, done, and the
discounted return-to-go the advantage-weighted algorithms train against
(reference: marwil computes cumulative discounted returns in its
postprocessing, postprocess_advantages).
"""

from __future__ import annotations

from typing import Callable, List, Optional

import numpy as np


def record_episodes(env_name: str, policy_fn: Callable[[np.ndarray], int],
                    num_episodes: int, path: str, *, gamma: float = 0.99,
                    seed: int = 0, max_steps: int = 1000) -> dict:
    """Roll `num_episodes` with policy_fn (obs -> action) and write one
    parquet dataset of per-step rows to `path`. Returns summary stats."""
    import gymnasium as gym

    from ray_tpu import data as rt_data

    env = gym.make(env_name)
    rows: List[dict] = []
    returns = []
    for ep in range(num_episodes):
        obs, _ = env.reset(seed=seed + ep)
        ep_rows = []
        done = False
        t = 0
        while not done and t < max_steps:
            action = int(policy_fn(np.asarray(obs)))
            nxt, reward, term, trunc, _ = env.step(action)
            done = bool(term or trunc)
            row = {
                "episode_id": ep, "t": t,
                "action": action, "reward": float(reward),
                "done": done,
            }
            # one scalar column per obs dim (obs_0..obs_{d-1}): parquet has
            # no 2-D columns and scalar columns keep filter pushdown usable
            for j, x in enumerate(np.asarray(obs, np.float32).ravel()):
                row[f"obs_{j}"] = float(x)
            ep_rows.append(row)
            obs = nxt
            t += 1
        # discounted return-to-go per step
        g = 0.0
        for row in reversed(ep_rows):
            g = row["reward"] + gamma * g
            row["return_to_go"] = g
        returns.append(sum(r["reward"] for r in ep_rows))
        rows.extend(ep_rows)
    env.close()
    ds = rt_data.from_items(rows)
    files = ds.write_parquet(path)
    return {
        "episodes": num_episodes, "steps": len(rows), "files": len(files),
        "mean_return": float(np.mean(returns)),
    }


def read_experiences(path, *, columns: Optional[List[str]] = None):
    """Offline experiences as a Dataset (reference: OfflineData.__init__
    ray.data.read_parquet)."""
    from ray_tpu import data as rt_data

    return rt_data.read_parquet(path, columns=columns)


def batch_to_numpy(batch: dict) -> dict:
    """Column batch -> dense numpy arrays; obs_0..obs_{d-1} scalar columns
    reassemble into one (B, d) "obs" matrix."""
    out = {}
    obs_cols = {}
    for k, v in batch.items():
        if k.startswith("obs_"):
            obs_cols[int(k[4:])] = np.asarray(v, np.float32)
        else:
            out[k] = np.asarray(v)
    if obs_cols:
        out["obs"] = np.stack(
            [obs_cols[i] for i in sorted(obs_cols)], axis=1)
    return out
