"""ray_tpu.rllib — RL training: CPU env-runner actors + jax mesh learners.

Reference: rllib/ (SURVEY.md §2.3) — the new-stack slice: EnvRunnerGroup,
LearnerGroup, PPO. The torch-DDP learner is re-designed as a pjit'd update
over a jax device mesh (north-star config 3: CPU rollouts + TPU learner).
"""

from ray_tpu.rllib.algorithms.appo import APPO, APPOConfig  # noqa: F401
from ray_tpu.rllib.algorithms.bc import BC, BCConfig, MARWIL, MARWILConfig  # noqa: F401
from ray_tpu.rllib.algorithms.dqn import DQN, DQNConfig  # noqa: F401
from ray_tpu.rllib.algorithms.impala import IMPALA, IMPALAConfig  # noqa: F401
from ray_tpu.rllib.algorithms.multi_agent_ppo import (  # noqa: F401
    MultiAgentPPO,
    MultiAgentPPOConfig,
)
from ray_tpu.rllib.algorithms.ppo import PPO, PPOConfig  # noqa: F401
from ray_tpu.rllib.algorithms.sac import SAC, SACConfig  # noqa: F401
from ray_tpu.rllib.env.multi_agent import (  # noqa: F401
    MultiAgentCartPole,
    MultiAgentEnv,
)
