"""@serve.batch — transparent request batching inside a replica.

Counterpart of the reference's batching (reference:
python/ray/serve/batching.py — queue individual calls, run the wrapped
method once per batch of up to max_batch_size after at most
batch_wait_timeout_s, scatter results back).

Queue lifetime: each (instance, method) pair owns one ``_BatchQueue``
stored ON the instance, so it dies with the replica — a global
``id(instance)``-keyed registry could cross-wire a new replica's calls
into a dead one's queue when CPython reuses the id. Timer hygiene: a
size-triggered flush cancels the pending timeout timer (armed for the
batch just drained); letting it live would flush the NEXT partial batch
early, before its own ``batch_wait_timeout_s``. Cancelled callers
(client disconnects while queued) are dropped from the batch before the
user function runs — no compute for results nobody will read.
"""

from __future__ import annotations

import asyncio
import functools
from typing import Any, Callable, List, Optional


class _BatchQueue:
    def __init__(self, fn, max_batch_size: int, batch_wait_timeout_s: float):
        self.fn = fn
        self.max_batch_size = max_batch_size
        self.batch_wait_timeout_s = batch_wait_timeout_s
        self.queue: List[tuple] = []  # (item, future)
        self._flusher: Optional[asyncio.Task] = None

    async def submit(self, instance, item) -> Any:
        fut = asyncio.get_running_loop().create_future()
        self.queue.append((item, fut))
        if len(self.queue) >= self.max_batch_size:
            await self._flush(instance)
        elif self._flusher is None or self._flusher.done():
            self._flusher = asyncio.ensure_future(self._delayed_flush(instance))
        return await fut

    async def _delayed_flush(self, instance):
        await asyncio.sleep(self.batch_wait_timeout_s)
        await self._flush(instance)

    async def _flush(self, instance):
        # A size-triggered flush drains the queue the pending timer was
        # armed for; the orphaned timer would otherwise fire later and
        # flush a NEWER partial batch before its batch_wait_timeout_s.
        flusher = self._flusher
        if (flusher is not None and not flusher.done()
                and flusher is not asyncio.current_task()):
            flusher.cancel()
        self._flusher = None
        # Drop entries whose waiter is already done — a cancelled caller
        # (client disconnect) must not cost a slot in the user batch.
        batch = [(i, f) for i, f in self.queue if not f.done()]
        self.queue = []
        if not batch:
            return
        items = [b[0] for b in batch]
        try:
            if instance is not None:
                results = await self.fn(instance, items)
            else:
                results = await self.fn(items)
            if len(results) != len(items):
                raise ValueError(
                    f"@serve.batch function returned {len(results)} results "
                    f"for a batch of {len(items)}"
                )
            for (_, fut), res in zip(batch, results):
                if not fut.done():
                    fut.set_result(res)
        except Exception as e:
            for _, fut in batch:
                if not fut.done():
                    fut.set_exception(e)


def batch(
    _fn: Optional[Callable] = None,
    *,
    max_batch_size: int = 8,
    batch_wait_timeout_s: float = 0.01,
):
    """Decorate an async method taking a LIST of items; individual calls
    are queued and executed as batches."""

    def wrap(fn):
        # per-(instance, method) queue lives on the instance itself (see
        # module docstring); function deployments get one closure queue
        attr = f"__serve_batch_queue_{fn.__name__}"
        holder: List[_BatchQueue] = []  # instance=None case
        fallback = {}  # instances rejecting setattr (__slots__): legacy map

        @functools.wraps(fn)
        async def wrapper(*args):
            if len(args) == 2:
                instance, item = args
            elif len(args) == 1:
                instance, item = None, args[0]
            else:
                raise TypeError("@serve.batch methods take exactly one argument")
            if instance is None:
                if not holder:
                    holder.append(
                        _BatchQueue(fn, max_batch_size, batch_wait_timeout_s))
                q = holder[0]
            else:
                q = getattr(instance, attr, None)
                if q is None:
                    q = _BatchQueue(fn, max_batch_size, batch_wait_timeout_s)
                    try:
                        setattr(instance, attr, q)
                    except AttributeError:
                        q = fallback.setdefault(id(instance), q)
            return await q.submit(instance, item)

        wrapper._is_serve_batch = True
        return wrapper

    if _fn is not None:
        return wrap(_fn)
    return wrap
