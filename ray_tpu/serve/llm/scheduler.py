"""Continuous-batching scheduler: prefill/decode separation + preemption.

Iteration-level scheduling (Orca's contribution, vLLM's scheduler shape):
the unit of work is ONE engine step, not one request. Every step the
scheduler

  1. reaps cancellations,
  2. makes sure each RUNNING sequence has a KV slot for the token this
     step will produce — preempting the youngest sequence back to the
     waiting queue (recompute-on-resume) when the cache is out of blocks,
  3. admits waiting prompts into spare batch slots while their prompt fits
     in the cache (these run as prefills this step) — admission is
     prefix-aware: the longest cached prefix is mapped read-only into the
     block table and only the tail is charged to the pool (and prefilled),

and returns a :class:`StepPlan`. The engine executes the plan against the
model adapter and calls :meth:`Scheduler.commit` with the sampled tokens;
commit applies the termination rules (EOS / max_tokens / cancel) and frees
finished sequences' blocks.

Deliberately model-free and clock-free: the only dependencies are the
cache's allocator interface and the order requests arrived in, so unit
tests drive it step by step with a fake model and byte-identical results.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Union

from ray_tpu.serve.llm.kv_cache import PagedKVCache

WAITING = "WAITING"
RUNNING = "RUNNING"
FINISHED = "FINISHED"

# finish reasons (surfaced to clients in the stream's final frame)
FINISH_EOS = "eos"
FINISH_LENGTH = "length"
FINISH_CANCELLED = "cancelled"

_seq_counter = itertools.count()


@dataclass
class Sequence:
    """One generation request as the scheduler sees it."""

    prompt: List[int]
    max_tokens: int = 16
    eos_id: Optional[int] = None
    seq_id: str = ""
    state: str = WAITING
    tokens: List[int] = field(default_factory=list)  # generated so far
    arrival: int = 0          # admission priority (FIFO; preemption victim
    #                           is the HIGHEST arrival = youngest)
    preemptions: int = 0
    cancelled: bool = False
    finish_reason: Optional[str] = None
    # context tokens whose KV the prefix cache already held at admission —
    # the engine prefills only context_tokens()[cached_len:]
    cached_len: int = 0
    # opaque slot for the engine (sampling state rides along)
    sampling: Optional[object] = None

    def __post_init__(self):
        if not self.seq_id:
            self.seq_id = f"seq-{next(_seq_counter)}"
        self.arrival = next(_seq_counter)

    @property
    def total_len(self) -> int:
        return len(self.prompt) + len(self.tokens)

    def context_tokens(self) -> List[int]:
        """What a (re)prefill must run over: prompt + everything generated
        before a preemption threw the KV away."""
        return self.prompt + self.tokens


@dataclass
class StepPlan:
    """What one engine step executes: ``prefills`` are sequences admitted
    this step (their context needs a full forward + cache write);
    ``decodes`` were already running and take one fused decode step."""

    prefills: List[Sequence] = field(default_factory=list)
    decodes: List[Sequence] = field(default_factory=list)
    # evicted back to waiting while building this plan (engine telemetry)
    preempted: List[Sequence] = field(default_factory=list)
    # cancelled sequences reaped while building this plan
    reaped: List[Sequence] = field(default_factory=list)

    @property
    def batch_size(self) -> int:
        return len(self.prefills) + len(self.decodes)


class Scheduler:
    def __init__(self, cache: PagedKVCache, max_batch_size: int = 32,
                 max_waiting: int = 512):
        self.cache = cache
        self.max_batch_size = int(max_batch_size)
        self.max_waiting = int(max_waiting)
        self.waiting: List[Sequence] = []   # FIFO (preempted re-enter at head)
        self.running: List[Sequence] = []
        self._by_id: Dict[str, Sequence] = {}
        self.preemptions_total = 0
        self.finished_total = 0

    # ------------------------------------------------------------- admission

    def queue_depth(self) -> int:
        return len(self.waiting) + len(self.running)

    def can_admit(self) -> bool:
        return len(self.waiting) < self.max_waiting

    def add(self, seq: Sequence) -> None:
        """Enqueue a request. Admission control (shedding past
        ``max_waiting``) is the engine's job — it owns the structured
        backpressure error; ``add`` never refuses."""
        seq.state = WAITING
        self._by_id[seq.seq_id] = seq
        self.waiting.append(seq)

    def get(self, seq_id: str) -> Optional[Sequence]:
        return self._by_id.get(seq_id)

    def cancel(self, seq_id: str) -> bool:
        """Mark a sequence cancelled. Waiting sequences finish (and leave)
        immediately; running ones are reaped — and their blocks freed — at
        the start of the next schedule()."""
        seq = self._by_id.get(seq_id)
        if seq is None or seq.state == FINISHED:
            return False
        seq.cancelled = True
        if seq.state == WAITING:
            self.waiting.remove(seq)
            self._finish(seq, FINISH_CANCELLED)
        return True

    # -------------------------------------------------------------- the step

    def schedule(self) -> StepPlan:
        """Build this step's plan (mutates queues + cache allocation)."""
        plan = StepPlan()
        # 1. reap cancellations that arrived mid-flight
        for seq in [s for s in self.running if s.cancelled]:
            self.running.remove(seq)
            self.cache.free(seq.seq_id)
            self._finish(seq, FINISH_CANCELLED)
            plan.reaped.append(seq)

        # 2. every running sequence needs one slot for this step's token;
        #    on exhaustion the YOUNGEST survivor is evicted (its blocks fund
        #    the older sequences), until everyone left can extend
        survivors = sorted(self.running, key=lambda s: s.arrival)
        i = 0
        while i < len(survivors):
            if self.cache.extend(survivors[i].seq_id, 1):
                i += 1
            else:
                victim = survivors.pop()
                self._preempt(victim)
                plan.preempted.append(victim)
        self.running = survivors

        # 3. admit prefills into spare slots while their context fits,
        #    +1 so the first decode step cannot immediately preempt them.
        #    allocate_cached maps the longest indexed prefix read-only into
        #    the block table and charges the pool only for the tail — the
        #    engine then prefills context_tokens()[cached_len:].
        plan.decodes = list(self.running)
        while (self.waiting
               and plan.batch_size < self.max_batch_size):
            seq = self.waiting[0]
            served = self.cache.allocate_cached(
                seq.seq_id, seq.context_tokens(), extra=1)
            if served is None:
                break  # head-of-line blocks: FIFO fairness over packing
            seq.cached_len = served
            self.waiting.pop(0)
            seq.state = RUNNING
            self.running.append(seq)
            plan.prefills.append(seq)
        return plan

    def _preempt(self, seq: Sequence) -> None:
        """Recompute-style preemption: drop the KV, requeue at the head of
        waiting with the generated tokens folded into the context."""
        self.cache.free(seq.seq_id)
        seq.state = WAITING
        seq.cached_len = 0
        seq.preemptions += 1
        self.preemptions_total += 1
        self.waiting.insert(0, seq)

    def requeue(self, seq: Sequence) -> None:
        """Return a just-admitted sequence to the head of waiting after its
        prefill was interrupted (KVCacheExhausted mid-admission). The
        engine has already freed the partial block hold — requeueing with
        it still allocated would leak pinned shared blocks."""
        if seq.seq_id in self.cache.block_tables:
            raise AssertionError(
                f"requeue({seq.seq_id!r}) with blocks still allocated")
        if seq in self.running:
            self.running.remove(seq)
        seq.state = WAITING
        seq.cached_len = 0
        self.waiting.insert(0, seq)

    def commit(self, tokens: Dict[str, Union[int, List[int]]]
               ) -> List[Sequence]:
        """Apply one step's sampled tokens (``seq_id -> token`` or, from a
        speculative-decode step, ``seq_id -> [tokens...]``) and the
        termination rules; returns the sequences that finished this step
        (their cache blocks already freed). A terminal token (EOS /
        max_tokens / cancel) stops the list early — accepted-but-post-EOS
        speculation is discarded, keeping the stream byte-equal to
        non-speculative decoding."""
        finished: List[Sequence] = []
        for seq_id, toks in tokens.items():
            seq = self._by_id.get(seq_id)
            if seq is None or seq.state != RUNNING:
                continue
            reason = None
            for tok in ([toks] if isinstance(toks, int) else toks):
                seq.tokens.append(int(tok))
                if seq.cancelled:
                    reason = FINISH_CANCELLED
                elif seq.eos_id is not None and int(tok) == seq.eos_id:
                    reason = FINISH_EOS
                elif len(seq.tokens) >= seq.max_tokens:
                    reason = FINISH_LENGTH
                if reason is not None:
                    break
            if reason is not None:
                self.running.remove(seq)
                self.cache.free(seq.seq_id)
                self._finish(seq, reason)
                finished.append(seq)
        return finished

    def _finish(self, seq: Sequence, reason: str) -> None:
        seq.state = FINISHED
        seq.finish_reason = reason
        self.finished_total += 1
        self._by_id.pop(seq.seq_id, None)

    def has_work(self) -> bool:
        return bool(self.running or self.waiting)
