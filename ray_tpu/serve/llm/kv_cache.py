"""Paged KV cache: fixed-size blocks + per-sequence block tables.

The vLLM insight applied to this engine: a sequence's KV never needs to be
contiguous — it lives in fixed-size blocks handed out from one shared pool,
so admitting a request costs exactly ``ceil(prompt_len / block_size)``
blocks instead of a max-context reservation, and a finished or cancelled
sequence returns its blocks to the pool immediately.

Storage is plain numpy (fp32), one (K, V) pair of
``[n_layers, num_blocks, block_size, n_kv_heads, head_dim]`` arrays: the
decode adapters (``adapters.py``) are numpy too, which keeps the whole
engine runnable on the CPU plane (``JAX_PLATFORMS=cpu``) where tier-1 and
the ``serve_llm_tokens_per_s`` bench exercise it. On a TPU replica the
same block-table bookkeeping would drive a pallas paged-attention kernel;
the allocator below is deliberately math-free so that swap stays local to
the adapter.

Thread-unsafe by design: the engine serializes all cache access behind its
step loop.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np


class KVCacheExhausted(RuntimeError):
    """Raised only by callers that choose to treat a failed allocation as
    fatal; the scheduler uses the boolean returns instead (preempting is
    its job, not the allocator's)."""


class PagedKVCache:
    """Block allocator + per-sequence block tables + the backing arrays.

    A sequence's logical KV layout: token position ``t`` lives at
    ``block_table[t // block_size]``, offset ``t % block_size``.
    """

    def __init__(
        self,
        num_blocks: int,
        block_size: int,
        n_layers: int,
        n_kv_heads: int,
        head_dim: int,
        dtype=np.float32,
    ):
        if num_blocks <= 0 or block_size <= 0:
            raise ValueError("num_blocks and block_size must be positive")
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        self.n_layers = int(n_layers)
        self.n_kv_heads = int(n_kv_heads)
        self.head_dim = int(head_dim)
        shape = (self.n_layers, self.num_blocks, self.block_size,
                 self.n_kv_heads, self.head_dim)
        self.k = np.zeros(shape, dtype=dtype)
        self.v = np.zeros(shape, dtype=dtype)
        # LIFO free list: recently freed blocks are cache-warm.
        self._free: List[int] = list(range(self.num_blocks - 1, -1, -1))
        self.block_tables: Dict[str, List[int]] = {}
        self.seq_lens: Dict[str, int] = {}

    # ------------------------------------------------------------ accounting

    @property
    def num_free_blocks(self) -> int:
        return len(self._free)

    @property
    def num_used_blocks(self) -> int:
        return self.num_blocks - len(self._free)

    def utilization(self) -> float:
        """Fraction of the pool currently allocated (the
        ``ray_tpu_llm_kv_utilization`` gauge)."""
        return self.num_used_blocks / self.num_blocks

    def blocks_needed(self, n_tokens: int) -> int:
        return -(-int(n_tokens) // self.block_size)

    def can_allocate(self, n_tokens: int) -> bool:
        return self.blocks_needed(n_tokens) <= len(self._free)

    # ------------------------------------------------------------ allocation

    def allocate(self, seq_id: str, n_tokens: int) -> bool:
        """Reserve blocks for a new sequence of ``n_tokens`` (its prompt).
        False (and no state change) when the pool cannot cover it."""
        if seq_id in self.block_tables:
            raise ValueError(f"sequence {seq_id!r} already allocated")
        need = self.blocks_needed(max(1, n_tokens))
        if need > len(self._free):
            return False
        self.block_tables[seq_id] = [self._free.pop() for _ in range(need)]
        self.seq_lens[seq_id] = 0
        return True

    def extend(self, seq_id: str, n_tokens: int = 1) -> bool:
        """Ensure capacity for ``n_tokens`` more positions, allocating new
        blocks at the table's tail when the last block is full. False when
        the pool is exhausted (caller preempts); partial growth is rolled
        back so a failed extend is side-effect free."""
        table = self.block_tables[seq_id]
        have = len(table) * self.block_size - self.seq_lens[seq_id]
        need_blocks = self.blocks_needed(max(0, n_tokens - have)) \
            if n_tokens > have else 0
        if need_blocks > len(self._free):
            return False
        for _ in range(need_blocks):
            table.append(self._free.pop())
        return True

    def free(self, seq_id: str) -> int:
        """Return the sequence's blocks to the pool; returns how many."""
        table = self.block_tables.pop(seq_id, None)
        self.seq_lens.pop(seq_id, None)
        if not table:
            return 0
        self._free.extend(reversed(table))
        return len(table)

    # ---------------------------------------------------------------- writes

    def _slots(self, seq_id: str, start: int, n: int):
        """(block_ids, offsets) arrays for logical positions [start, start+n)."""
        table = self.block_tables[seq_id]
        pos = np.arange(start, start + n)
        return np.asarray(table, dtype=np.int64)[pos // self.block_size], \
            pos % self.block_size

    def write_prefill(self, seq_id: str, k: np.ndarray, v: np.ndarray):
        """Copy-on-admit prefill write: ``k``/``v`` are
        ``[n_layers, T, n_kv_heads, head_dim]`` for the whole prompt; the
        copy into the paged arrays happens exactly once, here."""
        T = k.shape[1]
        if not self.extend(seq_id, T):
            raise KVCacheExhausted(f"prefill of {T} tokens does not fit")
        blocks, offs = self._slots(seq_id, self.seq_lens[seq_id], T)
        self.k[:, blocks, offs] = k
        self.v[:, blocks, offs] = v
        self.seq_lens[seq_id] += T

    def append(self, seq_id: str, k: np.ndarray, v: np.ndarray):
        """Write one decoded token's ``[n_layers, n_kv_heads, head_dim]``
        K/V at the sequence's current length. The slot must already exist
        (``extend`` ran in the schedule phase)."""
        pos = self.seq_lens[seq_id]
        table = self.block_tables[seq_id]
        block = table[pos // self.block_size]
        off = pos % self.block_size
        self.k[:, block, off] = k
        self.v[:, block, off] = v
        self.seq_lens[seq_id] = pos + 1

    # ---------------------------------------------------------------- reads

    def gather(self, seq_id: str) -> Tuple[np.ndarray, np.ndarray]:
        """Dense ``[n_layers, T, heads, dim]`` views of one sequence's KV
        (copies out of the paged arrays — the CPU analogue of what a paged
        attention kernel reads in place)."""
        T = self.seq_lens[seq_id]
        blocks, offs = self._slots(seq_id, 0, T)
        return self.k[:, blocks, offs], self.v[:, blocks, offs]

    def gather_batch(
        self, seq_ids: List[str], pad_to: Optional[int] = None
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Padded batch gather for the fused decode step: returns
        ``(k [B, L, Tmax, H, D], v same, lens [B])``; positions past a
        sequence's length are zero (the adapter masks by ``lens``)."""
        lens = np.asarray([self.seq_lens[s] for s in seq_ids], dtype=np.int32)
        tmax = max(int(lens.max(initial=0)), 1)
        if pad_to is not None:
            tmax = max(tmax, pad_to)
        B = len(seq_ids)
        # one vectorized fancy-index per array instead of a per-sequence
        # copy loop: build [B, Tmax] (block, offset) index grids (padding
        # positions point at block 0 and are masked by `lens` downstream)
        pos = np.arange(tmax)
        off = np.broadcast_to(pos % self.block_size, (B, tmax))
        blk = np.zeros((B, tmax), dtype=np.int64)
        for i, s in enumerate(seq_ids):
            t = int(lens[i])
            if t:
                blk[i, :t] = np.asarray(self.block_tables[s],
                                        dtype=np.int64)[pos[:t]
                                                        // self.block_size]
        # [L, B, T, H, D] -> [B, L, T, H, D]
        k = np.moveaxis(self.k[:, blk, off], 0, 1)
        v = np.moveaxis(self.v[:, blk, off], 0, 1)
        # padding rows beyond a sequence's length carry stale block-0 data;
        # the adapters mask attention by `lens`, so zeroing is unnecessary
        return k, v, lens
