"""Paged KV cache: fixed-size blocks + per-sequence block tables +
copy-on-write prefix sharing.

The vLLM insight applied to this engine: a sequence's KV never needs to be
contiguous — it lives in fixed-size blocks handed out from one shared pool,
so admitting a request costs exactly ``ceil(prompt_len / block_size)``
blocks instead of a max-context reservation, and a finished or cancelled
sequence returns its blocks to the pool immediately.

Prefix caching (``RTPU_llm_prefix_cache``) layers block *sharing* on top:
every block carries a reference count, and full, immutable prompt blocks
are indexed by a chained content hash (``hash(parent_hash, block_tokens)``
— the chain makes the key the whole token prefix, not just the chunk, so
two prompts share a block only when *everything* before it matches too).
``allocate_cached`` maps the longest cached prefix read-only into a new
sequence's block table and only charges fresh blocks for the tail; a
million users sharing one system prompt store one KV copy. Writes into a
shared (or still-indexed) block go through copy-on-write, and
``free``/``truncate`` only return a block to the pool when its last
reference drops. Blocks whose refcount reaches zero while indexed park in
an LRU "cached-free" pool: still matchable, first in line for eviction
when the allocator runs dry.

Storage is plain numpy (fp32), one (K, V) pair of
``[n_layers, num_blocks, block_size, n_kv_heads, head_dim]`` arrays: the
decode adapters (``adapters.py``) are numpy too, which keeps the whole
engine runnable on the CPU plane (``JAX_PLATFORMS=cpu``) where tier-1 and
the ``serve_llm_*`` bench rows exercise it. On a TPU replica the same
block-table bookkeeping would drive a pallas paged-attention kernel; the
allocator below is deliberately math-free so that swap stays local to the
adapter.

Thread-unsafe by design: the engine serializes all cache access behind its
step loop.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

import numpy as np


class KVCacheExhausted(RuntimeError):
    """Raised only by callers that choose to treat a failed allocation as
    fatal; the scheduler uses the boolean returns instead (preempting is
    its job, not the allocator's)."""


class PagedKVCache:
    """Block allocator + per-sequence block tables + the backing arrays.

    A sequence's logical KV layout: token position ``t`` lives at
    ``block_table[t // block_size]``, offset ``t % block_size``.
    """

    def __init__(
        self,
        num_blocks: int,
        block_size: int,
        n_layers: int,
        n_kv_heads: int,
        head_dim: int,
        dtype=np.float32,
        enable_prefix_cache: bool = False,
    ):
        if num_blocks <= 0 or block_size <= 0:
            raise ValueError("num_blocks and block_size must be positive")
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        self.n_layers = int(n_layers)
        self.n_kv_heads = int(n_kv_heads)
        self.head_dim = int(head_dim)
        self.enable_prefix_cache = bool(enable_prefix_cache)
        shape = (self.n_layers, self.num_blocks, self.block_size,
                 self.n_kv_heads, self.head_dim)
        self.k = np.zeros(shape, dtype=dtype)
        self.v = np.zeros(shape, dtype=dtype)
        # LIFO free list: recently freed blocks are cache-warm.
        self._free: List[int] = list(range(self.num_blocks - 1, -1, -1))
        self.block_tables: Dict[str, List[int]] = {}
        self.seq_lens: Dict[str, int] = {}
        # --- prefix-sharing state -------------------------------------
        # per-block reference count (0 = free or cached-free)
        self.ref_counts = np.zeros(self.num_blocks, dtype=np.int32)
        # chained content hash -> block id, and the inverse for eviction
        self._hash_to_block: Dict[int, int] = {}
        self._block_hash: Dict[int, int] = {}
        # refcount-0 blocks still in the index, oldest-first (LRU evict)
        self._cached_free: "OrderedDict[int, None]" = OrderedDict()
        # counters (the hit-rate gauge + bench rows read these)
        self.prefix_query_tokens = 0
        self.prefix_hit_tokens = 0
        self.cow_copies = 0
        self.prefix_evictions = 0

    # ------------------------------------------------------------ accounting

    @property
    def num_free_blocks(self) -> int:
        """Allocatable blocks: truly free + evictable cached-free."""
        return len(self._free) + len(self._cached_free)

    @property
    def num_used_blocks(self) -> int:
        return self.num_blocks - self.num_free_blocks

    @property
    def num_cached_blocks(self) -> int:
        """Indexed blocks kept warm for future prefix hits (refcount 0)."""
        return len(self._cached_free)

    def utilization(self) -> float:
        """Fraction of the pool currently allocated (the
        ``ray_tpu_llm_kv_utilization`` gauge). Cached-free blocks count as
        free: they are reclaimed on demand."""
        return self.num_used_blocks / self.num_blocks

    def hit_rate(self) -> float:
        """Cumulative fraction of looked-up prompt tokens served from the
        prefix index (the ``ray_tpu_llm_prefix_hit_rate`` gauge)."""
        if not self.prefix_query_tokens:
            return 0.0
        return self.prefix_hit_tokens / self.prefix_query_tokens

    def blocks_needed(self, n_tokens: int) -> int:
        return -(-int(n_tokens) // self.block_size)

    def can_allocate(self, n_tokens: int) -> bool:
        return self.blocks_needed(n_tokens) <= self.num_free_blocks

    # ------------------------------------------------------------ allocation

    def _pop_block(self) -> int:
        """Hand out one block, evicting the LRU cached-free block (and its
        index entry) when the true free list is empty. Raises
        KVCacheExhausted on an empty pool — only reachable from a
        copy-on-write (allocate/extend pre-check capacity)."""
        if self._free:
            return self._free.pop()
        if not self._cached_free:
            raise KVCacheExhausted("no free block for copy-on-write")
        block, _ = self._cached_free.popitem(last=False)
        self._unregister(block)
        self.prefix_evictions += 1
        return block

    def _release_block(self, block: int) -> None:
        """Refcount hit zero: park indexed blocks in the cached-free LRU
        (still matchable), return the rest to the free list."""
        if block in self._block_hash:
            self._cached_free[block] = None
        else:
            self._free.append(block)

    def _incref(self, block: int) -> None:
        if self.ref_counts[block] == 0:
            # resurrect a cached-free block: it is allocated again
            self._cached_free.pop(block, None)
        self.ref_counts[block] += 1

    def _decref(self, block: int) -> None:
        self.ref_counts[block] -= 1
        if self.ref_counts[block] == 0:
            self._release_block(block)

    def allocate(self, seq_id: str, n_tokens: int) -> bool:
        """Reserve blocks for a new sequence of ``n_tokens`` (its prompt).
        False (and no state change) when the pool cannot cover it."""
        if seq_id in self.block_tables:
            raise ValueError(f"sequence {seq_id!r} already allocated")
        need = self.blocks_needed(max(1, n_tokens))
        if need > self.num_free_blocks:
            return False
        table = [self._pop_block() for _ in range(need)]
        for b in table:
            self._incref(b)
        self.block_tables[seq_id] = table
        self.seq_lens[seq_id] = 0
        return True

    @staticmethod
    def _chain_hash(parent: int, chunk: Tuple[int, ...]) -> int:
        return hash((parent, chunk))

    def match_prefix(self, tokens: List[int]) -> Tuple[List[int], int]:
        """Longest indexed prefix of ``tokens``: returns (block ids, matched
        token count). The match is capped at ``len(tokens) - 1`` so the
        caller always has at least one tail token to prefill (the engine
        needs the last position's logits)."""
        if not self.enable_prefix_cache or len(tokens) < 2:
            return [], 0
        bs = self.block_size
        blocks: List[int] = []
        h = 0
        for i in range(len(tokens) // bs):
            h = self._chain_hash(h, tuple(tokens[i * bs:(i + 1) * bs]))
            b = self._hash_to_block.get(h)
            if b is None:
                break
            blocks.append(b)
        if not blocks:
            return [], 0
        # the cap may land mid-block: that last block maps shared anyway
        # and the tail prefill's write into it goes through copy-on-write
        return blocks, min(len(blocks) * bs, len(tokens) - 1)

    def allocate_cached(self, seq_id: str, tokens: List[int],
                        extra: int = 1) -> Optional[int]:
        """Prefix-aware allocation for a new sequence whose context is
        ``tokens`` (+``extra`` decode slots): map the longest indexed prefix
        read-only into the block table (refcount bump, zero copies) and
        charge fresh blocks only for the tail. Returns the number of prefix
        tokens served from cache (0 = cold), or None — with every partial
        hold rolled back — when the pool cannot cover the remainder.

        A non-block-aligned match (the last-token cap) maps the final
        shared block too; the tail prefill's write into it triggers
        copy-on-write, so the indexed copy stays immutable.
        """
        if seq_id in self.block_tables:
            raise ValueError(f"sequence {seq_id!r} already allocated")
        matched_blocks, matched_tokens = self.match_prefix(tokens)
        self.prefix_query_tokens += len(tokens)
        need = self.blocks_needed(max(1, len(tokens) + extra))
        fresh_needed = need - len(matched_blocks)
        # incref the hit first: a matched block may sit in cached-free, and
        # counting it free while also mapping it would double-book it
        for b in matched_blocks:
            self._incref(b)
        if fresh_needed > self.num_free_blocks:
            for b in matched_blocks:      # roll the partial hold back
                self._decref(b)
            return None
        table = matched_blocks + [self._pop_block()
                                  for _ in range(fresh_needed)]
        for b in table[len(matched_blocks):]:
            self._incref(b)
        self.block_tables[seq_id] = table
        self.seq_lens[seq_id] = matched_tokens
        self.prefix_hit_tokens += matched_tokens
        return matched_tokens

    def register_prefix(self, seq_id: str, tokens: List[int]) -> int:
        """Index the sequence's full, written blocks covering ``tokens``
        under their chained hashes (idempotent; blocks already indexed —
        its own shared prefix, or a twin admitted the same step — are
        skipped). Called by the engine once a (re)prefill lands; returns
        how many blocks were newly indexed."""
        if not self.enable_prefix_cache:
            return 0
        table = self.block_tables.get(seq_id)
        if table is None:
            return 0
        bs = self.block_size
        n_full = min(len(tokens), self.seq_lens[seq_id]) // bs
        added = 0
        h = 0
        for i in range(n_full):
            h = self._chain_hash(h, tuple(tokens[i * bs:(i + 1) * bs]))
            b = table[i]
            if b in self._block_hash or h in self._hash_to_block:
                continue
            self._hash_to_block[h] = b
            self._block_hash[b] = h
            added += 1
        return added

    def _unregister(self, block: int) -> None:
        h = self._block_hash.pop(block, None)
        if h is not None and self._hash_to_block.get(h) == block:
            self._hash_to_block.pop(h, None)

    def extend(self, seq_id: str, n_tokens: int = 1) -> bool:
        """Ensure capacity for ``n_tokens`` more positions, allocating new
        blocks at the table's tail when the last block is full. False when
        the pool is exhausted (caller preempts); partial growth is rolled
        back so a failed extend is side-effect free."""
        table = self.block_tables[seq_id]
        have = len(table) * self.block_size - self.seq_lens[seq_id]
        need_blocks = self.blocks_needed(max(0, n_tokens - have)) \
            if n_tokens > have else 0
        if need_blocks > self.num_free_blocks:
            return False
        for _ in range(need_blocks):
            b = self._pop_block()
            self._incref(b)
            table.append(b)
        return True

    def free(self, seq_id: str) -> int:
        """Drop the sequence's references; returns how many blocks its
        table held. A block only returns to the pool when its LAST
        reference drops — shared prefix blocks survive their originator
        (indexed ones stay matchable in the cached-free LRU)."""
        table = self.block_tables.pop(seq_id, None)
        self.seq_lens.pop(seq_id, None)
        if not table:
            return 0
        for b in reversed(table):
            self._decref(b)
        return len(table)

    def truncate(self, seq_id: str, n_tokens: int) -> None:
        """Shrink the sequence to ``n_tokens`` positions (speculative-decode
        rollback), dropping references to the now-unused tail blocks. A
        truncated-into block that is still shared/indexed is copy-on-write
        protected at the next write, so other readers never see the
        rollback."""
        cur = self.seq_lens[seq_id]
        n_tokens = int(n_tokens)
        if n_tokens > cur:
            raise ValueError(
                f"truncate({seq_id!r}) to {n_tokens} > current {cur}")
        table = self.block_tables[seq_id]
        keep = max(1, self.blocks_needed(max(1, n_tokens)))
        for b in reversed(table[keep:]):
            self._decref(b)
        del table[keep:]
        self.seq_lens[seq_id] = n_tokens

    # ---------------------------------------------------------------- writes

    def _slots(self, seq_id: str, start: int, n: int):
        """(block_ids, offsets) arrays for logical positions [start, start+n)."""
        table = self.block_tables[seq_id]
        pos = np.arange(start, start + n)
        return np.asarray(table, dtype=np.int64)[pos // self.block_size], \
            pos % self.block_size

    def _ensure_writable(self, seq_id: str, block_idx: int) -> None:
        """Copy-on-write guard: a block about to be written must be
        exclusively owned AND out of the prefix index (an indexed block's
        content is pinned by its hash). Shared -> copy into a fresh block;
        exclusively-owned-but-indexed -> just unindex it."""
        table = self.block_tables[seq_id]
        b = table[block_idx]
        if self.ref_counts[b] > 1:
            nb = self._pop_block()          # may evict LRU cached-free
            self.k[:, nb] = self.k[:, b]
            self.v[:, nb] = self.v[:, b]
            self._incref(nb)
            table[block_idx] = nb
            self._decref(b)
            self.cow_copies += 1
        elif b in self._block_hash:
            self._unregister(b)

    def write_prefill(self, seq_id: str, k: np.ndarray, v: np.ndarray):
        """Copy-on-admit prefill write: ``k``/``v`` are
        ``[n_layers, T, n_kv_heads, head_dim]`` for the un-cached tail of
        the context (the whole prompt when cold); the copy into the paged
        arrays happens exactly once, here. Raises KVCacheExhausted when the
        pool cannot hold the tail — the engine frees the partial hold and
        requeues the sequence."""
        T = k.shape[1]
        start = self.seq_lens[seq_id]
        if not self.extend(seq_id, T):
            raise KVCacheExhausted(f"prefill of {T} tokens does not fit")
        if self.enable_prefix_cache and T:
            for bi in range(start // self.block_size,
                            (start + T - 1) // self.block_size + 1):
                self._ensure_writable(seq_id, bi)
        blocks, offs = self._slots(seq_id, start, T)
        self.k[:, blocks, offs] = k
        self.v[:, blocks, offs] = v
        self.seq_lens[seq_id] = start + T

    def append(self, seq_id: str, k: np.ndarray, v: np.ndarray):
        """Write one decoded token's ``[n_layers, n_kv_heads, head_dim]``
        K/V at the sequence's current length. The slot must already exist
        (``extend`` ran in the schedule phase)."""
        pos = self.seq_lens[seq_id]
        if self.enable_prefix_cache:
            self._ensure_writable(seq_id, pos // self.block_size)
        table = self.block_tables[seq_id]
        block = table[pos // self.block_size]
        off = pos % self.block_size
        self.k[:, block, off] = k
        self.v[:, block, off] = v
        self.seq_lens[seq_id] = pos + 1

    # ---------------------------------------------------------------- reads

    def gather(self, seq_id: str) -> Tuple[np.ndarray, np.ndarray]:
        """Dense ``[n_layers, T, heads, dim]`` views of one sequence's KV
        (copies out of the paged arrays — the CPU analogue of what a paged
        attention kernel reads in place)."""
        T = self.seq_lens[seq_id]
        blocks, offs = self._slots(seq_id, 0, T)
        return self.k[:, blocks, offs], self.v[:, blocks, offs]

    def gather_batch(
        self, seq_ids: List[str], pad_to: Optional[int] = None
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Padded batch gather for the fused decode step: returns
        ``(k [B, L, Tmax, H, D], v same, lens [B])``; positions past a
        sequence's length are zero (the adapter masks by ``lens``)."""
        lens = np.asarray([self.seq_lens[s] for s in seq_ids], dtype=np.int32)
        tmax = max(int(lens.max(initial=0)), 1)
        if pad_to is not None:
            tmax = max(tmax, pad_to)
        B = len(seq_ids)
        # one vectorized fancy-index per array instead of a per-sequence
        # copy loop: build [B, Tmax] (block, offset) index grids (padding
        # positions point at block 0 and are masked by `lens` downstream)
        pos = np.arange(tmax)
        off = np.broadcast_to(pos % self.block_size, (B, tmax))
        blk = np.zeros((B, tmax), dtype=np.int64)
        for i, s in enumerate(seq_ids):
            t = int(lens[i])
            if t:
                blk[i, :t] = np.asarray(self.block_tables[s],
                                        dtype=np.int64)[pos[:t]
                                                        // self.block_size]
        # [L, B, T, H, D] -> [B, L, T, H, D]
        k = np.moveaxis(self.k[:, blk, off], 0, 1)
        v = np.moveaxis(self.v[:, blk, off], 0, 1)
        # padding rows beyond a sequence's length carry stale block-0 data;
        # the adapters mask attention by `lens`, so zeroing is unnecessary
        return k, v, lens

    # ------------------------------------------------------------ invariants

    def check_integrity(self) -> List[str]:
        """Cross-check every block against the refcount/index/free-list
        bookkeeping (the serve-plane analogue of the PR 7 object-leak
        sweep). Returns human-readable violations; empty = consistent.
        Tests assert emptiness after every failure-injection path so an
        interrupted admission or rollback can never strand a pinned
        block."""
        problems: List[str] = []
        mapped: Dict[int, int] = {}
        for sid, table in self.block_tables.items():
            for b in table:
                mapped[b] = mapped.get(b, 0) + 1
        free_set = set(self._free)
        for b in range(self.num_blocks):
            refs = int(self.ref_counts[b])
            if refs != mapped.get(b, 0):
                problems.append(
                    f"block {b}: refcount {refs} != {mapped.get(b, 0)} "
                    f"table references")
            in_free = b in free_set
            in_cached = b in self._cached_free
            if refs > 0 and (in_free or in_cached):
                problems.append(f"block {b}: referenced but on a free list")
            if refs == 0 and not (in_free or in_cached):
                problems.append(f"block {b}: leaked (refcount 0, not free)")
            if in_free and in_cached:
                problems.append(f"block {b}: on both free lists")
        for h, b in self._hash_to_block.items():
            if self._block_hash.get(b) != h:
                problems.append(f"index: hash {h} -> block {b} not inverse")
        for b in self._block_hash:
            if b in free_set:
                problems.append(f"block {b}: indexed but on the free list")
        return problems

    def assert_no_leaks(self) -> None:
        problems = self.check_integrity()
        if problems:
            raise AssertionError(
                "KV cache integrity violations:\n  " + "\n  ".join(problems))
