"""ray_tpu.serve.llm — continuous-batching LLM inference engine.

The piece that makes TPU serving survive real traffic (ROADMAP item 1): a
replica is no longer one-request-at-a-time but an iteration-level batching
engine in the vLLM/Orca mold —

  - a **paged KV cache** (``kv_cache.PagedKVCache``): fixed-size blocks,
    a block table per sequence, alloc on admit / free on finish or cancel,
    so fragmentation never strands HBM the way per-request max-length
    buffers do;
  - a **prefill/decode scheduler** (``scheduler.Scheduler``): each engine
    step admits new prompts into spare batch slots (prefill), runs ONE
    fused decode step for every active sequence, and preempts-and-requeues
    the youngest sequence when the cache runs out of blocks;
  - **prefix caching** (``RTPU_llm_prefix_cache``): full prompt blocks are
    indexed by chained content hash and shared copy-on-write between
    sequences, so a million users on one system prompt store one KV copy
    and only their unique tails are prefilled — byte-equal to the cold
    path, measured by the ``serve_llm_prefix_*`` bench rows;
  - **speculative decoding** (``RTPU_llm_draft_model`` +
    ``RTPU_llm_spec_k``): a tiny draft model proposes ``k`` tokens, the
    target verifies them in one fused forward and keeps the longest
    agreeing run (+1 bonus token) — greedy acceptance keeps the stream
    exactly what the target alone would produce;
  - **admission control**: past ``RTPU_llm_max_waiting`` queued prompts
    the engine sheds load with a structured ``LLMBackpressure`` error
    (carrying queue depth + KV utilization) instead of OOMing the cache;
  - **zero-copy token streaming**: token deltas ride the out-of-band RPC
    frames of the serve ingress (``ServeLlmOpen/Next/Cancel`` in
    ``serve/_proxy.py``) — the proxy forwards the replica's raw int32
    buffer into the client frame without re-serializing it.

Quick start (tokens in, tokens out; models come from ``ray_tpu/models``)::

    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.serve import llm

    ray_tpu.init()
    llm.deploy(model="gpt2-tiny", app_name="llm")
    for tok in llm.stream([1, 2, 3], app_name="llm", max_tokens=32):
        print(tok)

Everything runs on the CPU plane too (``JAX_PLATFORMS=cpu``): the decode
math lives in numpy adapters (``adapters.py``) so tier-1 tests and the
``serve_llm_tokens_per_s`` bench exercise the real engine chip-free.
"""

from __future__ import annotations

from typing import Any, List, Optional, Union

from ray_tpu.serve.llm.engine import (
    LLMBackpressure,
    LLMEngine,
    LLMReplica,
    SamplingParams,
)
from ray_tpu.serve.llm.kv_cache import PagedKVCache
from ray_tpu.serve.llm.scheduler import Scheduler, Sequence, StepPlan

__all__ = [
    "PagedKVCache",
    "Scheduler",
    "Sequence",
    "StepPlan",
    "LLMEngine",
    "LLMReplica",
    "LLMBackpressure",
    "SamplingParams",
    "deploy",
    "stream",
    "generate",
]


def deploy(
    model: str = "gpt2-tiny",
    *,
    app_name: str = "llm",
    route_prefix: Optional[str] = "/llm",
    num_replicas: int = 1,
    model_config: Optional[dict] = None,
    autoscaling_config: Optional[dict] = None,
    seed: int = 0,
    **engine_kwargs,
):
    """Deploy an ``LLMReplica`` application behind serve.

    ``model`` names a zoo entry (``gpt2-tiny``, ``gpt2``, ``llama-tiny``,
    ``llama-160m``, ``gpt2-moe-tiny``); ``model_config`` overrides config
    fields. ``engine_kwargs`` (``num_blocks``, ``block_size``,
    ``max_batch``, ``max_waiting``, ``prefix_cache``, ``draft_model``,
    ``draft_model_config``, ``spec_k``) override the ``RTPU_llm_*`` flags —
    e.g. ``draft_model="gpt2-tiny", spec_k=4`` turns on speculative
    decoding with that zoo model as the draft. Returns the app's
    DeploymentHandle.
    """
    from ray_tpu import serve

    dep = serve.deployment(
        name="LLMReplica",
        num_replicas=num_replicas,
        autoscaling_config=autoscaling_config,
        # The engine gates user load itself (admission control); the serve
        # concurrency cap only needs to cover the control-plane chatter
        # (submits, pulls, stats).
        max_ongoing_requests=64,
    )(LLMReplica)
    return serve.run(
        dep.bind(model=model, model_config=model_config, seed=seed,
                 **engine_kwargs),
        name=app_name,
        route_prefix=route_prefix,
    )


def stream(
    prompt: Union[str, List[int]],
    *,
    app_name: str = "llm",
    timeout: float = 300.0,
    **sampling: Any,
):
    """Stream generated tokens for ``prompt`` from a deployed llm app.

    Returns an ``LlmStream`` (iterable and async-iterable of int token
    ids) riding the binary serve ingress: the prompt goes up as one raw
    out-of-band frame and token deltas come back the same way, untouched
    by the proxy. ``sampling`` takes ``max_tokens``, ``temperature``,
    ``top_k``, ``eos_id``, ``seed``.
    """
    from ray_tpu import serve
    from ray_tpu.serve.rpc_ingress import RpcIngressClient

    port = serve.start_rpc_ingress()
    client = RpcIngressClient("127.0.0.1", port)
    s = client.llm_stream(prompt, app=app_name, timeout=timeout, **sampling)
    s._owns_client = True  # closing the stream closes this throwaway client
    return s


def generate(
    prompt: Union[str, List[int]],
    *,
    app_name: str = "llm",
    timeout: float = 300.0,
    **sampling: Any,
) -> List[int]:
    """One-shot generation: collect the whole stream (same engine path)."""
    return list(stream(prompt, app_name=app_name, timeout=timeout,
                       **sampling))
