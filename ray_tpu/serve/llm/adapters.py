"""Model adapters: the thin ``generate_step`` seam between the engine and
``ray_tpu/models``.

The zoo models (gpt2 / llama / gpt2_moe) are training-first flax modules
with no KV cache plumbing, so the adapters re-express their forward pass as
explicit numpy math over the raw param pytrees in two shapes the engine
needs:

  ``prefill(tokens)``       one sequence's full context: returns the last
                            position's logits plus per-layer K/V for every
                            position (the copy-on-admit cache write);
  ``prefill_ctx(...)``      the chunked form: run only the un-cached TAIL
                            of a context against KV the prefix cache
                            already holds (``prefill`` is the start=0
                            special case — the two share one code path so
                            a prefix hit cannot drift numerically);
  ``decode(...)``           ONE fused step for the whole running batch:
                            each sequence contributes one new token + its
                            gathered paged KV; returns next-token logits
                            and the new token's K/V to append;
  ``decode_chunk(...)``     the speculative-verify form: each sequence
                            contributes a short chunk (last sampled token
                            + the draft's proposals) scored in ONE fused
                            forward — logits for every chunk position, so
                            the engine can accept the longest agreeing
                            run and take the bonus token.

Everything is fp32 numpy — bit-for-bit deterministic, chip-free (tier-1
and the CPU-plane bench run the real engine), and byte-equivalent to the
flax forward for fp32 configs (tests/test_serve_llm.py pins gpt2 and llama
against ``models.*.forward``). On a TPU replica ``decode`` is the seam
where a pallas paged-attention kernel slots in; the engine never sees the
difference.

MoE note: serving uses dropless top-k routing (every token reaches all its
k experts). Train-time static capacity can drop tokens under load — a
nondeterministic-under-batching behavior a server must not have.
"""

from __future__ import annotations

import math
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

__all__ = ["ModelAdapter", "GPT2Adapter", "LlamaAdapter", "GPT2MoEAdapter",
           "FakeAdapter", "build_adapter", "MODEL_ZOO"]


def _np_tree(params) -> Dict[str, Any]:
    """Convert a (possibly jax) param pytree to fp32 numpy once, at adapter
    construction — the engine's hot path never touches jax after this."""
    if isinstance(params, dict):
        return {k: _np_tree(v) for k, v in params.items()}
    return np.asarray(params, dtype=np.float32)


def _layernorm(x: np.ndarray, p: Dict[str, np.ndarray],
               eps: float = 1e-6) -> np.ndarray:
    mu = x.mean(axis=-1, keepdims=True)
    var = ((x - mu) ** 2).mean(axis=-1, keepdims=True)
    return (x - mu) / np.sqrt(var + eps) * p["scale"] + p["bias"]


def _rmsnorm(x: np.ndarray, weight: np.ndarray, eps: float) -> np.ndarray:
    var = (x * x).mean(axis=-1, keepdims=True)
    return x / np.sqrt(var + eps) * weight


def _gelu(x: np.ndarray) -> np.ndarray:
    # tanh approximation — matches nn.gelu(approximate=True)
    return 0.5 * x * (1.0 + np.tanh(
        math.sqrt(2.0 / math.pi) * (x + 0.044715 * x ** 3)))


def _softmax(x: np.ndarray, axis: int = -1) -> np.ndarray:
    x = x - x.max(axis=axis, keepdims=True)
    e = np.exp(x)
    return e / e.sum(axis=axis, keepdims=True)


def _attend(q, k_ctx, v_ctx, lens, k_new, v_new):
    """Fused single-query attention over (paged-gathered context + self).

    q/k_new/v_new ``[B, H, D]``; k_ctx/v_ctx ``[B, Tmax, H, D]`` zero-padded
    past ``lens [B]``. Returns ``[B, H, D]``.
    """
    B, Tmax, H, D = k_ctx.shape
    scale = 1.0 / math.sqrt(D)
    s_ctx = np.einsum("bhd,bthd->bht", q, k_ctx) * scale
    mask = np.arange(Tmax)[None, :] >= lens[:, None]          # [B, Tmax]
    s_ctx = np.where(mask[:, None, :], -1e30, s_ctx)
    s_self = np.einsum("bhd,bhd->bh", q, k_new)[..., None] * scale
    probs = _softmax(np.concatenate([s_ctx, s_self], axis=-1))  # [B,H,T+1]
    out = np.einsum("bht,bthd->bhd", probs[..., :Tmax], v_ctx)
    return out + probs[..., Tmax:] * v_new


def _ctx_causal_attend(q, k_ctx, v_ctx, k_ch, v_ch):
    """Chunked prefill attention, one sequence: the chunk's queries
    ``q [T, H, D]`` attend to the already-cached context ``k_ctx/v_ctx
    [P, H, D]`` plus causally to the chunk itself (``k_ch/v_ch [T, H, D]``).
    With ``P == 0`` this is exactly full-prefill self-attention: the empty
    context contributes zero to both the softmax and the output, so
    ``prefill`` and a prefix-cache-hit tail prefill share one code path."""
    T, H, D = q.shape
    P = k_ctx.shape[0]
    s_ctx = np.einsum("thd,shd->hts", q, k_ctx) / math.sqrt(D)
    s_ch = np.einsum("thd,shd->hts", q, k_ch) / math.sqrt(D)
    s_ch = np.where(np.tril(np.ones((T, T), dtype=bool))[None], s_ch, -1e30)
    probs = _softmax(np.concatenate([s_ctx, s_ch], axis=-1))
    return np.einsum("hts,shd->thd", probs[..., :P], v_ctx) \
        + np.einsum("hts,shd->thd", probs[..., P:], v_ch)


def _chunk_attend(q, k_ctx, v_ctx, lens, k_ch, v_ch):
    """Fused multi-token verify attention over (paged-gathered context +
    causal chunk), the batched C>1 sibling of :func:`_attend`.

    q/k_ch/v_ch ``[B, C, H, D]``; k_ctx/v_ctx ``[B, Tmax, H, D]`` padded
    past ``lens [B]``. Returns ``[B, C, H, D]``.
    """
    B, Tmax, H, D = k_ctx.shape
    C = q.shape[1]
    scale = 1.0 / math.sqrt(D)
    s_ctx = np.einsum("bchd,bthd->bhct", q, k_ctx) * scale
    mask = np.arange(Tmax)[None, :] >= lens[:, None]          # [B, Tmax]
    s_ctx = np.where(mask[:, None, None, :], -1e30, s_ctx)
    s_ch = np.einsum("bchd,bshd->bhcs", q, k_ch) * scale
    causal = np.tril(np.ones((C, C), dtype=bool))
    s_ch = np.where(causal[None, None], s_ch, -1e30)
    probs = _softmax(np.concatenate([s_ctx, s_ch], axis=-1))
    out = np.einsum("bhct,bthd->bchd", probs[..., :Tmax], v_ctx)
    return out + np.einsum("bhcs,bshd->bchd", probs[..., Tmax:], v_ch)


def _repeat_kv(x: np.ndarray, rep: int) -> np.ndarray:
    """GQA broadcast: [..., Hkv, D] -> [..., Hkv*rep, D]."""
    if rep == 1:
        return x
    return np.repeat(x, rep, axis=-2)


class ModelAdapter:
    """Shape contract the engine sizes its cache from."""

    n_layers: int
    n_heads: int
    n_kv_heads: int
    head_dim: int
    vocab_size: int
    max_context: int

    def prefill(self, tokens: np.ndarray
                ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Full-context prefill == ``prefill_ctx`` with an empty cache."""
        L, H, D = self.n_layers, self.n_kv_heads, self.head_dim
        empty = np.zeros((L, 0, H, D), dtype=np.float32)
        return self.prefill_ctx(tokens, 0, empty, empty)

    def prefill_ctx(self, tokens: np.ndarray, start: int,
                    k_ctx: np.ndarray, v_ctx: np.ndarray
                    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Prefill the context TAIL ``tokens`` (positions ``start`` ..
        ``start+T``) against cached ``k_ctx/v_ctx [n_layers, start, H, D]``
        (a prefix-cache hit's gathered blocks). Returns the last position's
        logits plus the tail's per-layer K/V ``[n_layers, T, H, D]``."""
        raise NotImplementedError

    def decode(self, tokens: np.ndarray, positions: np.ndarray,
               k_ctx: np.ndarray, v_ctx: np.ndarray, lens: np.ndarray
               ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        raise NotImplementedError

    def decode_chunk(self, tokens: np.ndarray, positions: np.ndarray,
                     k_ctx: np.ndarray, v_ctx: np.ndarray, lens: np.ndarray
                     ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Speculative-verify forward: score a C-token chunk per sequence
        (``tokens [B, C]`` starting at ``positions [B]``) against the
        gathered paged context in ONE fused pass. Returns logits
        ``[B, C, vocab]`` and the chunk's K/V ``[B, n_layers, C, H, D]``;
        the engine writes only the accepted prefix back to the cache. On a
        TPU replica this (like ``decode``) is the pallas paged-attention
        seam."""
        raise NotImplementedError


# --------------------------------------------------------------------- GPT-2


class GPT2Adapter(ModelAdapter):
    """Numpy twin of ``models/gpt2.py`` (weight-tied head, learned
    positions, tanh-gelu MLP)."""

    def __init__(self, config, params):
        self.cfg = config
        self.p = _np_tree(params)
        self.n_layers = config.n_layer
        self.n_heads = self.n_kv_heads = config.n_head
        self.head_dim = config.n_embd // config.n_head
        self.vocab_size = config.vocab_size
        self.max_context = config.block_size

    # hook GPT2MoEAdapter overrides for its MoE blocks
    def _ffn(self, x: np.ndarray, lp: Dict[str, Any]) -> np.ndarray:
        h = _gelu(x @ lp["mlp"]["c_fc"]["kernel"] + lp["mlp"]["c_fc"]["bias"])
        return h @ lp["mlp"]["c_proj"]["kernel"] + lp["mlp"]["c_proj"]["bias"]

    def _qkv(self, h: np.ndarray, lp) -> Tuple[np.ndarray, ...]:
        qkv = h @ lp["attn"]["c_attn"]["kernel"] + lp["attn"]["c_attn"]["bias"]
        q, k, v = np.split(qkv, 3, axis=-1)
        shape = h.shape[:-1] + (self.n_heads, self.head_dim)
        return q.reshape(shape), k.reshape(shape), v.reshape(shape)

    def _logits(self, x: np.ndarray) -> np.ndarray:
        return _layernorm(x, self.p["ln_f"]) @ self.p["wte"]["embedding"].T

    def prefill_ctx(self, tokens, start, k_ctx, v_ctx):
        T = len(tokens)
        p = self.p
        x = p["wte"]["embedding"][tokens] \
            + p["wpe"]["embedding"][start:start + T]
        ks, vs = [], []
        for li in range(self.n_layers):
            lp = p[f"h_{li}"]
            q, k, v = self._qkv(_layernorm(x, lp["ln_1"]), lp)
            ks.append(k)
            vs.append(v)
            y = _ctx_causal_attend(q, k_ctx[li], v_ctx[li], k, v) \
                .reshape(T, -1)
            x = x + y @ lp["attn"]["c_proj"]["kernel"] \
                + lp["attn"]["c_proj"]["bias"]
            x = x + self._ffn(_layernorm(x, lp["ln_2"]), lp)
        return self._logits(x[-1]), np.stack(ks), np.stack(vs)

    def decode(self, tokens, positions, k_ctx, v_ctx, lens):
        p = self.p
        x = p["wte"]["embedding"][tokens] + p["wpe"]["embedding"][positions]
        k_news, v_news = [], []
        for li in range(self.n_layers):
            lp = p[f"h_{li}"]
            q, k, v = self._qkv(_layernorm(x, lp["ln_1"]), lp)
            k_news.append(k)
            v_news.append(v)
            y = _attend(q, k_ctx[:, li], v_ctx[:, li], lens, k, v)
            x = x + y.reshape(len(tokens), -1) \
                @ lp["attn"]["c_proj"]["kernel"] + lp["attn"]["c_proj"]["bias"]
            x = x + self._ffn(_layernorm(x, lp["ln_2"]), lp)
        return (self._logits(x),
                np.stack(k_news, axis=1), np.stack(v_news, axis=1))

    def decode_chunk(self, tokens, positions, k_ctx, v_ctx, lens):
        B, C = tokens.shape
        p = self.p
        pos = positions[:, None] + np.arange(C)[None, :]          # [B, C]
        x = p["wte"]["embedding"][tokens] + p["wpe"]["embedding"][pos]
        k_news, v_news = [], []
        for li in range(self.n_layers):
            lp = p[f"h_{li}"]
            q, k, v = self._qkv(_layernorm(x, lp["ln_1"]), lp)
            k_news.append(k)
            v_news.append(v)
            y = _chunk_attend(q, k_ctx[:, li], v_ctx[:, li], lens, k, v)
            x = x + y.reshape(B, C, -1) \
                @ lp["attn"]["c_proj"]["kernel"] + lp["attn"]["c_proj"]["bias"]
            x = x + self._ffn(_layernorm(x, lp["ln_2"]), lp)
        return (self._logits(x),
                np.stack(k_news, axis=1), np.stack(v_news, axis=1))


# ---------------------------------------------------------------------- MoE


class GPT2MoEAdapter(GPT2Adapter):
    """gpt2_moe: every ``moe_every``-th block routes its FFN through
    dropless top-k experts (see module docstring for the capacity note)."""

    def _ffn(self, x: np.ndarray, lp: Dict[str, Any]) -> np.ndarray:
        if "moe" not in lp:
            return super()._ffn(x, lp)
        mp = lp["moe"]
        cfg = self.cfg.moe
        probs = _softmax(x @ mp["router"]["kernel"] + mp["router"]["bias"])
        k = cfg.top_k
        idx = np.argsort(probs, axis=-1)[..., ::-1][..., :k]      # [T, k]
        gates = np.take_along_axis(probs, idx, axis=-1)
        gates = gates / np.maximum(gates.sum(axis=-1, keepdims=True), 1e-9)
        out = np.zeros_like(x)
        for j in range(k):
            for e in np.unique(idx[..., j]):
                rows = idx[..., j] == e
                h = _gelu(x[rows] @ mp["wi"][e]) @ mp["wo"][e]
                out[rows] += gates[rows, j:j + 1] * h
        return out


# --------------------------------------------------------------------- llama


class LlamaAdapter(ModelAdapter):
    """Numpy twin of ``models/llama.py``: RMSNorm, rotate-half RoPE (keys
    cached post-rotation, the standard trick), GQA, SwiGLU."""

    def __init__(self, config, params):
        self.cfg = config
        self.p = _np_tree(params)
        self.n_layers = config.n_layer
        self.n_heads = config.n_head
        self.n_kv_heads = config.n_kv_head
        self.head_dim = config.head_dim
        self.vocab_size = config.vocab_size
        self.max_context = config.block_size

    def _rope(self, x: np.ndarray, positions: np.ndarray) -> np.ndarray:
        """x [..., T?, H, D] with matching leading position axis."""
        D = self.head_dim
        inv = 1.0 / (self.cfg.rope_theta
                     ** (np.arange(0, D, 2, dtype=np.float32) / D))
        ang = positions.astype(np.float32)[..., None] * inv     # [T?, D/2]
        cos = np.cos(ang)[..., None, :]
        sin = np.sin(ang)[..., None, :]
        x1, x2 = np.split(x, 2, axis=-1)
        return np.concatenate([x1 * cos - x2 * sin,
                               x1 * sin + x2 * cos], axis=-1)

    def _proj(self, h, lp, name, heads):
        return (h @ lp["attn"][name]["kernel"]).reshape(
            h.shape[:-1] + (heads, self.head_dim))

    def _block_mlp(self, x, lp):
        g = x @ lp["mlp"]["gate"]["kernel"]
        return ((g / (1.0 + np.exp(-g))) * (x @ lp["mlp"]["up"]["kernel"])) \
            @ lp["mlp"]["down"]["kernel"]

    def _logits(self, x):
        return _rmsnorm(x, self.p["final_norm"]["weight"],
                        self.cfg.rms_eps) @ self.p["lm_head"]["kernel"]

    def prefill_ctx(self, tokens, start, k_ctx, v_ctx):
        cfg, p = self.cfg, self.p
        T = len(tokens)
        pos = np.arange(start, start + T)
        rep = cfg.n_head // cfg.n_kv_head
        x = p["tok_emb"]["embedding"][tokens]
        ks, vs = [], []
        for li in range(self.n_layers):
            lp = p[f"h_{li}"]
            h = _rmsnorm(x, lp["attn_norm"]["weight"], cfg.rms_eps)
            q = self._rope(self._proj(h, lp, "wq", cfg.n_head), pos)
            k = self._rope(self._proj(h, lp, "wk", cfg.n_kv_head), pos)
            v = self._proj(h, lp, "wv", cfg.n_kv_head)
            ks.append(k)
            vs.append(v)
            y = _ctx_causal_attend(q,
                                   _repeat_kv(k_ctx[li], rep),
                                   _repeat_kv(v_ctx[li], rep),
                                   _repeat_kv(k, rep), _repeat_kv(v, rep))
            x = x + y.reshape(T, -1) @ lp["attn"]["wo"]["kernel"]
            x = x + self._block_mlp(
                _rmsnorm(x, lp["mlp_norm"]["weight"], cfg.rms_eps), lp)
        return self._logits(x[-1]), np.stack(ks), np.stack(vs)

    def decode(self, tokens, positions, k_ctx, v_ctx, lens):
        cfg, p = self.cfg, self.p
        rep = cfg.n_head // cfg.n_kv_head
        x = p["tok_emb"]["embedding"][tokens]
        k_news, v_news = [], []
        for li in range(self.n_layers):
            lp = p[f"h_{li}"]
            h = _rmsnorm(x, lp["attn_norm"]["weight"], cfg.rms_eps)
            q = self._rope(self._proj(h, lp, "wq", cfg.n_head), positions)
            k = self._rope(self._proj(h, lp, "wk", cfg.n_kv_head), positions)
            v = self._proj(h, lp, "wv", cfg.n_kv_head)
            k_news.append(k)
            v_news.append(v)
            y = _attend(q,
                        _repeat_kv(k_ctx[:, li], rep),
                        _repeat_kv(v_ctx[:, li], rep),
                        lens, _repeat_kv(k, rep), _repeat_kv(v, rep))
            x = x + y.reshape(len(tokens), -1) @ lp["attn"]["wo"]["kernel"]
            x = x + self._block_mlp(
                _rmsnorm(x, lp["mlp_norm"]["weight"], cfg.rms_eps), lp)
        return (self._logits(x),
                np.stack(k_news, axis=1), np.stack(v_news, axis=1))

    def decode_chunk(self, tokens, positions, k_ctx, v_ctx, lens):
        cfg, p = self.cfg, self.p
        B, C = tokens.shape
        rep = cfg.n_head // cfg.n_kv_head
        pos = positions[:, None] + np.arange(C)[None, :]          # [B, C]
        x = p["tok_emb"]["embedding"][tokens]
        k_news, v_news = [], []
        for li in range(self.n_layers):
            lp = p[f"h_{li}"]
            h = _rmsnorm(x, lp["attn_norm"]["weight"], cfg.rms_eps)
            q = self._rope(self._proj(h, lp, "wq", cfg.n_head), pos)
            k = self._rope(self._proj(h, lp, "wk", cfg.n_kv_head), pos)
            v = self._proj(h, lp, "wv", cfg.n_kv_head)
            k_news.append(k)
            v_news.append(v)
            y = _chunk_attend(q,
                              _repeat_kv(k_ctx[:, li], rep),
                              _repeat_kv(v_ctx[:, li], rep),
                              lens, _repeat_kv(k, rep), _repeat_kv(v, rep))
            x = x + y.reshape(B, C, -1) @ lp["attn"]["wo"]["kernel"]
            x = x + self._block_mlp(
                _rmsnorm(x, lp["mlp_norm"]["weight"], cfg.rms_eps), lp)
        return (self._logits(x),
                np.stack(k_news, axis=1), np.stack(v_news, axis=1))


# ---------------------------------------------------------------------- fake


class FakeAdapter(ModelAdapter):
    """Model-free adapter for scheduler/engine tests and pure-batching
    benches. Deterministic: the next token is a function of the last token
    AND the KV cache contents (each position's K stores its token id), so a
    block-table bug or a bad gather changes the output stream.

    ``step_cost_s`` sleeps once per adapter CALL (a fused batch is one
    call, like one accelerator dispatch), so the spec-decode bench can
    model a target:draft cost ratio. ``disagree_every`` perturbs the next
    token whenever the true next token is divisible by it — used as the
    DRAFT in speculative tests/benches for a deterministic, partial
    acceptance rate (≈ 1 - 1/q) instead of the degenerate 0 or 1."""

    def __init__(self, vocab_size: int = 97, n_layers: int = 1,
                 n_kv_heads: int = 1, head_dim: int = 1,
                 max_context: int = 4096, step_cost_s: float = 0.0,
                 disagree_every: int = 0):
        self.vocab_size = vocab_size
        self.n_layers = n_layers
        self.n_heads = self.n_kv_heads = n_kv_heads
        self.head_dim = head_dim
        self.max_context = max_context
        self.step_cost_s = step_cost_s  # simulated model time per call
        self.disagree_every = int(disagree_every)

    def _sleep(self):
        if self.step_cost_s:
            import time
            time.sleep(self.step_cost_s)

    def _next(self, ctx_sum: np.ndarray, tokens: np.ndarray) -> np.ndarray:
        nxt = (np.asarray(ctx_sum).astype(np.int64)
               + tokens * 31 + 7) % self.vocab_size
        if self.disagree_every:
            nxt = np.where(nxt % self.disagree_every == 0,
                           (nxt + 1) % self.vocab_size, nxt)
        return nxt

    def _logits_for(self, nxt: np.ndarray) -> np.ndarray:
        out = np.zeros(nxt.shape + (self.vocab_size,), dtype=np.float32)
        np.put_along_axis(out, nxt[..., None], 1.0, axis=-1)
        return out

    def _kv(self, tokens: np.ndarray):
        kv = np.broadcast_to(
            tokens.astype(np.float32)[..., None, None, None],
            tokens.shape + (self.n_layers, self.n_kv_heads, self.head_dim),
        ).copy()
        return kv, kv.copy()

    def prefill_ctx(self, tokens, start, k_ctx, v_ctx):
        self._sleep()
        tokens = np.asarray(tokens)
        # same semantics as decode with cache = everything-but-last, input =
        # last (a preempted sequence's recompute must continue identically);
        # the cached prefix is read back THROUGH the gathered blocks so a
        # prefix-cache or COW bug changes the output
        ctx_sum = np.float64(k_ctx[0, :, 0, 0].sum()) \
            + np.float64(tokens[:-1].sum())
        nxt = self._next(ctx_sum, tokens[-1:])
        k, v = self._kv(tokens)  # [T, L, H, D] -> [L, T, H, D]
        return (self._logits_for(nxt)[0],
                np.moveaxis(k, 0, 1), np.moveaxis(v, 0, 1))

    def decode(self, tokens, positions, k_ctx, v_ctx, lens):
        self._sleep()
        # context read back THROUGH the gathered cache: [B, L, Tmax, H, D]
        # (masked by lens — padding slots may carry stale block data)
        valid = np.arange(k_ctx.shape[2])[None, :] < lens[:, None]
        ctx_sum = (k_ctx[:, 0, :, 0, 0] * valid).sum(axis=1)
        nxt = self._next(ctx_sum, np.asarray(tokens))
        k, v = self._kv(np.asarray(tokens))  # [B, L, H, D]
        return self._logits_for(nxt), k, v

    def decode_chunk(self, tokens, positions, k_ctx, v_ctx, lens):
        self._sleep()
        tokens = np.asarray(tokens)                               # [B, C]
        valid = np.arange(k_ctx.shape[2])[None, :] < lens[:, None]
        base = (k_ctx[:, 0, :, 0, 0] * valid).sum(axis=1)         # [B]
        # chunk position c additionally sees chunk tokens [0, c)
        csum = np.cumsum(tokens, axis=1) - tokens                 # exclusive
        nxt = self._next(base[:, None] + csum, tokens)            # [B, C]
        k, v = self._kv(tokens)               # [B, C, L, H, D] -> B,L,C,H,D
        return (self._logits_for(nxt),
                np.moveaxis(k, 1, 2), np.moveaxis(v, 1, 2))


# ----------------------------------------------------------------- model zoo


MODEL_ZOO = {
    "gpt2-tiny": ("gpt2", "tiny"),
    "gpt2": ("gpt2", "gpt2_124m"),
    "gpt2-moe-tiny": ("gpt2_moe", "tiny_moe"),
    "llama-tiny": ("llama", "tiny"),
    "llama-160m": ("llama", "llama_160m"),
    "fake": ("fake", None),
}


def build_adapter(model: str, model_config: Optional[dict] = None,
                  seed: int = 0) -> ModelAdapter:
    """Resolve a zoo name to (config, fresh params, adapter). Checkpoint
    loading is out of scope for this engine PR — params are seeded random,
    which is exactly what the bench and tests need. jax/flax imports stay
    inside this function so ``import ray_tpu.serve.llm`` is cheap."""
    if model == "fake":
        return FakeAdapter(**(model_config or {}))
    if model not in MODEL_ZOO:
        raise ValueError(
            f"unknown model {model!r}; zoo: {sorted(MODEL_ZOO)}")
    family, preset = MODEL_ZOO[model]
    kw = dict(model_config or {})
    import jax
    import jax.numpy as jnp

    kw.setdefault("dtype", jnp.float32)  # fp32: the adapters' native math
    rng = jax.random.PRNGKey(seed)
    if family == "gpt2":
        from ray_tpu.models import gpt2 as m

        cfg = getattr(m.GPT2Config, preset)(**kw)
        return GPT2Adapter(cfg, m.init_params(cfg, rng))
    if family == "gpt2_moe":
        from ray_tpu.models import gpt2_moe as m

        cfg = getattr(m.GPT2MoEConfig, preset)(**kw)
        return GPT2MoEAdapter(cfg, m.init_params(cfg, rng))
    from ray_tpu.models import llama as m

    cfg = getattr(m.LlamaConfig, preset)(**kw)
    return LlamaAdapter(cfg, m.init_params(cfg, rng))
