"""LLM engine + the ``LLMReplica`` deployment class.

``LLMEngine`` is the synchronous core: it owns the paged cache, the
continuous-batching scheduler and a model adapter, and advances the world
one :meth:`step` at a time (prefill the newly admitted, one fused decode
for everything running, commit + deliver tokens). It is thread-safe behind
one coarse lock and has no asyncio/ray dependencies — the bench and the
unit tests drive it directly.

``LLMReplica`` is the serve-facing wrapper: an async step loop pumps the
engine off the actor's event loop (model math runs in the default
executor so queue probes and pulls stay responsive), requests arrive as
``llm_submit``/``llm_pull``/``llm_cancel`` (the proxy's zero-copy OOB
path), ``generate``/``stream`` (plain handle + HTTP streaming paths), and
admission control sheds load with the structured :class:`LLMBackpressure`
error before the cache can OOM.

Per-step telemetry rides the PR 1 metrics path (names are a stability
contract, see ``util/metrics.py``):

  ray_tpu_llm_tokens_per_s        gauge, EMA of generated tokens/s
  ray_tpu_llm_kv_utilization      gauge, 0-1 fraction of KV blocks in use
  ray_tpu_llm_batch_size          gauge, sequences in the last step
  ray_tpu_llm_preemptions_total   counter

and the flight recorder gets ``llm.admit`` / ``llm.preempt`` /
``llm.finish`` events (PR 3 contract: cheap tuples, no formatting until
dump).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Union

import numpy as np

from ray_tpu._private.config import RTPU_CONFIG
from ray_tpu.serve.llm import scheduler as sched_mod
from ray_tpu.serve.llm.adapters import ModelAdapter, build_adapter
from ray_tpu.serve.llm.kv_cache import PagedKVCache
from ray_tpu.serve.llm.scheduler import Scheduler, Sequence


class LLMBackpressure(RuntimeError):
    """Structured admission rejection: the engine sheds load instead of
    OOMing the KV cache. Carries enough for a client (or the proxy) to
    make a real decision — queue elsewhere, back off, or surface a 429."""

    def __init__(self, queue_depth: int, max_waiting: int,
                 kv_utilization: float):
        self.queue_depth = int(queue_depth)
        self.max_waiting = int(max_waiting)
        self.kv_utilization = float(kv_utilization)
        super().__init__(
            f"llm admission rejected: queue_depth={queue_depth} >= "
            f"max_waiting={max_waiting} (kv_utilization="
            f"{kv_utilization:.2f}); back off and retry"
        )

    def __reduce__(self):
        # pickles across the actor boundary with its structure intact
        # (default Exception.__reduce__ would replay the message string
        # into the 3-arg __init__ and blow up at unpickle time)
        return (LLMBackpressure,
                (self.queue_depth, self.max_waiting, self.kv_utilization))

    def to_dict(self) -> dict:
        return {"backpressure": True, "queue_depth": self.queue_depth,
                "max_waiting": self.max_waiting,
                "kv_utilization": round(self.kv_utilization, 4)}


@dataclass
class SamplingParams:
    max_tokens: int = 16
    temperature: float = 0.0   # 0 = greedy
    top_k: int = 0             # 0 = full vocab
    eos_id: Optional[int] = None
    seed: Optional[int] = None


class _SeqSampling:
    """Per-sequence sampling state riding on Sequence.sampling."""

    __slots__ = ("params", "rng")

    def __init__(self, params: SamplingParams):
        self.params = params
        self.rng = (np.random.default_rng(params.seed)
                    if params.temperature > 0 else None)


_llm_metrics = None


def _metrics():
    global _llm_metrics
    if _llm_metrics is None:
        from ray_tpu.util.metrics import Counter, Gauge

        tags = ("deployment", "replica")
        _llm_metrics = {
            "tokens_per_s": Gauge(
                "ray_tpu_llm_tokens_per_s",
                "generated tokens/s per llm replica (EMA)", tag_keys=tags),
            "kv_util": Gauge(
                "ray_tpu_llm_kv_utilization",
                "fraction of paged KV blocks in use", tag_keys=tags),
            "batch": Gauge(
                "ray_tpu_llm_batch_size",
                "sequences in the last engine step", tag_keys=tags),
            "preempt": Counter(
                "ray_tpu_llm_preemptions_total",
                "sequences requeued on KV exhaustion", tag_keys=tags),
        }
    return _llm_metrics


class _OutBuffer:
    """Tokens produced but not yet pulled by the client."""

    __slots__ = ("tokens", "done", "finish_reason")

    def __init__(self):
        self.tokens: List[int] = []
        self.done = False
        self.finish_reason: Optional[str] = None


class LLMEngine:
    """Synchronous continuous-batching engine (see module docstring)."""

    def __init__(
        self,
        adapter: ModelAdapter,
        *,
        num_blocks: Optional[int] = None,
        block_size: Optional[int] = None,
        max_batch: Optional[int] = None,
        max_waiting: Optional[int] = None,
        name: str = "llm",
    ):
        self.adapter = adapter
        block_size = int(block_size or RTPU_CONFIG.llm_block_size)
        num_blocks = int(num_blocks or RTPU_CONFIG.llm_num_blocks)
        self.cache = PagedKVCache(
            num_blocks=num_blocks,
            block_size=block_size,
            n_layers=adapter.n_layers,
            n_kv_heads=adapter.n_kv_heads,
            head_dim=adapter.head_dim,
        )
        self.scheduler = Scheduler(
            self.cache,
            max_batch_size=int(max_batch or RTPU_CONFIG.llm_max_batch),
            max_waiting=int(max_waiting or RTPU_CONFIG.llm_max_waiting),
        )
        self._out: Dict[str, _OutBuffer] = {}
        self._lock = threading.RLock()
        self._tags = {"deployment": name, "replica": ""}
        self._tokens_per_s = 0.0  # EMA over steps
        self.steps_total = 0
        self.tokens_total = 0

    def set_identity(self, deployment: str, replica: str = ""):
        self._tags = {"deployment": deployment, "replica": replica}

    # ------------------------------------------------------------ submission

    def submit(self, prompt: List[int],
               sampling: Optional[SamplingParams] = None) -> str:
        """Admit a prompt; returns the request id. Raises
        :class:`LLMBackpressure` past ``max_waiting`` queued prompts and
        ``ValueError`` for prompts that can never fit the cache."""
        sampling = sampling or SamplingParams()
        prompt = [int(t) for t in prompt]
        if not prompt:
            raise ValueError("empty prompt")
        if any(t < 0 or t >= self.adapter.vocab_size for t in prompt):
            raise ValueError(
                f"prompt token out of range [0, {self.adapter.vocab_size})")
        limit = min(self.adapter.max_context,
                    self.cache.num_blocks * self.cache.block_size)
        if len(prompt) + 1 > limit:
            raise ValueError(
                f"prompt of {len(prompt)} tokens can never fit "
                f"(context limit {limit})")
        with self._lock:
            if not self.scheduler.can_admit():
                raise LLMBackpressure(
                    self.scheduler.queue_depth(),
                    self.scheduler.max_waiting,
                    self.cache.utilization(),
                )
            seq = Sequence(prompt=prompt, max_tokens=sampling.max_tokens,
                           eos_id=sampling.eos_id,
                           sampling=_SeqSampling(sampling))
            self.scheduler.add(seq)
            self._out[seq.seq_id] = _OutBuffer()
            return seq.seq_id

    def cancel(self, seq_id: str) -> bool:
        """Client abandoned the stream: stop generating and (for waiting
        sequences now, running ones at the next schedule) free the KV."""
        with self._lock:
            ok = self.scheduler.cancel(seq_id)
            buf = self._out.get(seq_id)
            if buf is not None and not buf.done:
                buf.done = True
                buf.finish_reason = sched_mod.FINISH_CANCELLED
            return ok

    def pull(self, seq_id: str, max_tokens: int = 0):
        """Drain up to ``max_tokens`` (0 = all) buffered tokens. Returns
        ``(tokens, done, finish_reason)``; ``done`` only once the buffer is
        empty AND the sequence finished. KeyError for unknown ids."""
        with self._lock:
            buf = self._out[seq_id]
            n = len(buf.tokens) if max_tokens <= 0 else int(max_tokens)
            out, buf.tokens = buf.tokens[:n], buf.tokens[n:]
            done = buf.done and not buf.tokens
            if done:
                self._out.pop(seq_id, None)
            return out, done, buf.finish_reason

    # --------------------------------------------------------------- the step

    def _sample(self, seq: Sequence, logits: np.ndarray) -> int:
        sp: _SeqSampling = seq.sampling
        p = sp.params
        if p.temperature <= 0 or sp.rng is None:
            return int(np.argmax(logits))
        z = logits.astype(np.float64) / p.temperature
        if p.top_k and p.top_k < len(z):
            kth = np.partition(z, -p.top_k)[-p.top_k]
            z = np.where(z < kth, -np.inf, z)
        z -= z.max()
        probs = np.exp(z)
        probs /= probs.sum()
        return int(sp.rng.choice(len(probs), p=probs))

    def step(self) -> Dict[str, Any]:
        """One engine iteration; returns step stats (also published as
        gauges). A no-op returning ``{"batch_size": 0}`` when idle."""
        from ray_tpu._private import flight_recorder as _fr

        with self._lock:
            t0 = time.perf_counter()
            plan = self.scheduler.schedule()
            for seq in plan.reaped:
                self._finish_buffer(seq)
            for seq in plan.preempted:
                _fr.record("llm.preempt", b"",
                           f"{seq.seq_id} ctx={seq.total_len}")
            if plan.batch_size == 0:
                self._publish(0, 0, 0.0)
                return {"batch_size": 0, "tokens": 0}

            sampled: Dict[str, int] = {}
            for seq in plan.prefills:
                ctx = np.asarray(seq.context_tokens(), dtype=np.int64)
                logits, k, v = self.adapter.prefill(ctx)
                self.cache.write_prefill(seq.seq_id, k, v)
                sampled[seq.seq_id] = self._sample(seq, logits)
                _fr.record("llm.admit", b"",
                           f"{seq.seq_id} prompt={len(ctx)} "
                           f"kv={self.cache.utilization():.2f}")
            if plan.decodes:
                ids = [s.seq_id for s in plan.decodes]
                toks = np.asarray([s.tokens[-1] for s in plan.decodes],
                                  dtype=np.int64)
                pos = np.asarray([self.cache.seq_lens[i] for i in ids],
                                 dtype=np.int64)
                k_ctx, v_ctx, lens = self.cache.gather_batch(ids)
                logits, k_new, v_new = self.adapter.decode(
                    toks, pos, k_ctx, v_ctx, lens)
                for i, seq in enumerate(plan.decodes):
                    self.cache.append(seq.seq_id, k_new[i], v_new[i])
                    sampled[seq.seq_id] = self._sample(seq, logits[i])

            finished = self.scheduler.commit(sampled)
            for seq_id, tok in sampled.items():
                buf = self._out.get(seq_id)
                if buf is not None and not buf.done:
                    buf.tokens.append(tok)
            for seq in finished:
                self._finish_buffer(seq)
                _fr.record("llm.finish", b"",
                           f"{seq.seq_id} reason={seq.finish_reason} "
                           f"tokens={len(seq.tokens)}")

            dt = max(time.perf_counter() - t0, 1e-9)
            n_tokens = len(sampled)
            self.steps_total += 1
            self.tokens_total += n_tokens
            inst = n_tokens / dt
            self._tokens_per_s = (inst if self._tokens_per_s == 0.0
                                  else 0.8 * self._tokens_per_s + 0.2 * inst)
            self._publish(plan.batch_size, len(plan.preempted), dt)
            return {
                "batch_size": plan.batch_size,
                "prefills": len(plan.prefills),
                "decodes": len(plan.decodes),
                "preempted": len(plan.preempted),
                "finished": len(finished),
                "finished_ids": [s.seq_id for s in finished],
                "tokens": n_tokens,
                "step_s": dt,
            }

    def _finish_buffer(self, seq: Sequence):
        buf = self._out.get(seq.seq_id)
        if buf is not None:
            buf.done = True
            buf.finish_reason = seq.finish_reason

    def _publish(self, batch: int, preempted: int, dt: float):
        try:
            m = _metrics()
            m["tokens_per_s"].set(self._tokens_per_s, tags=self._tags)
            m["kv_util"].set(self.cache.utilization(), tags=self._tags)
            m["batch"].set(batch, tags=self._tags)
            if preempted:
                m["preempt"].inc(preempted, tags=self._tags)
        except Exception:
            pass

    # ------------------------------------------------------------------ misc

    def has_work(self) -> bool:
        with self._lock:
            return self.scheduler.has_work()

    def load(self) -> int:
        """Waiting + running sequences — what the serve autoscaler keys on."""
        with self._lock:
            return self.scheduler.queue_depth()

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "waiting": len(self.scheduler.waiting),
                "running": len(self.scheduler.running),
                "kv_utilization": round(self.cache.utilization(), 4),
                "kv_free_blocks": self.cache.num_free_blocks,
                "tokens_per_s": round(self._tokens_per_s, 1),
                "tokens_total": self.tokens_total,
                "steps_total": self.steps_total,
                "preemptions_total": self.scheduler.preemptions_total,
                "finished_total": self.scheduler.finished_total,
            }

    def run_until_drained(self, max_steps: int = 1_000_000) -> int:
        """Drive the engine until no work remains (bench/test helper);
        returns steps executed."""
        steps = 0
        while self.has_work() and steps < max_steps:
            self.step()
            steps += 1
        return steps


def _normalize_prompt(prompt: Union[str, bytes, List[int]]) -> List[int]:
    """str prompts become UTF-8 byte ids (every zoo vocab is >= 256);
    token-id lists / arrays pass through."""
    if isinstance(prompt, str):
        return list(prompt.encode("utf-8"))
    if isinstance(prompt, (bytes, bytearray)):
        return list(np.frombuffer(bytes(prompt), dtype=np.int32))
    return [int(t) for t in prompt]


class LLMReplica:
    """The deployment class: ``serve.llm.deploy`` binds this behind serve.

    One background task pumps the engine; every request-facing method is
    async and cheap (the model math runs in the executor). Telemetry
    identity (deployment/replica tags) is injected by the hosting
    ``Replica`` via ``__serve_identity__``; the serve autoscaler reads the
    engine's queue depth via ``__serve_load__``.
    """

    def __init__(
        self,
        model: str = "gpt2-tiny",
        model_config: Optional[dict] = None,
        *,
        num_blocks: Optional[int] = None,
        block_size: Optional[int] = None,
        max_batch: Optional[int] = None,
        max_waiting: Optional[int] = None,
        seed: int = 0,
    ):
        adapter = build_adapter(model, model_config, seed=seed)
        self.engine = LLMEngine(
            adapter,
            num_blocks=num_blocks,
            block_size=block_size,
            max_batch=max_batch,
            max_waiting=max_waiting,
        )
        self.model = model
        self._loop_task = None
        self._tick = None          # asyncio.Event, re-armed every step
        self._wake = None          # set on submit while the loop is idle

    # hooks the serve Replica wrapper calls (see serve/_replica.py)
    def __serve_identity__(self, deployment: str, replica: str):
        self.engine.set_identity(deployment, replica)

    def __serve_load__(self) -> int:
        return self.engine.load()

    # ------------------------------------------------------------- step loop

    def _ensure_loop(self):
        import asyncio

        if self._loop_task is not None and not self._loop_task.done():
            return
        self._tick = asyncio.Event()
        self._wake = asyncio.Event()
        self._loop_task = asyncio.ensure_future(self._run_loop())

    async def _run_loop(self):
        import asyncio

        loop = asyncio.get_running_loop()
        while True:
            if self.engine.has_work():
                await loop.run_in_executor(None, self.engine.step)
                # wake every pull waiting on this step's tokens
                tick, self._tick = self._tick, asyncio.Event()
                tick.set()
            else:
                self._wake.clear()
                # wake promptly on submit; the timeout keeps the loop
                # resilient to a lost wake (cancelled submit etc.)
                try:
                    await asyncio.wait_for(self._wake.wait(), 1.0)
                except asyncio.TimeoutError:
                    pass

    @staticmethod
    async def _wait_event(ev, timeout: float):
        import asyncio

        try:
            await asyncio.wait_for(ev.wait(), timeout)
        except asyncio.TimeoutError:
            pass

    # -------------------------------------------------------- request surface

    def _submit(self, prompt, sampling: Optional[dict]) -> str:
        sp = SamplingParams(**(sampling or {}))
        rid = self.engine.submit(_normalize_prompt(prompt), sp)
        self._ensure_loop()
        self._wake.set()
        return rid

    async def llm_submit(self, prompt, sampling: Optional[dict] = None) -> dict:
        """OOB ingress entry: prompt may be raw int32 bytes (the frame's
        payload, untouched), a token-id list, or a string."""
        self._ensure_loop()
        return {"request_id": self._submit(prompt, sampling)}

    async def llm_pull(self, request_id: str, max_tokens: int = 0,
                       wait_s: Optional[float] = None) -> dict:
        """Long-poll pull: waits up to ``wait_s`` for at least one token
        (or completion), then returns ``{"tokens": <raw int32 bytes>,
        "done", "finish_reason"}`` — bytes, so the proxy can forward them
        as an OOB frame without re-serializing."""
        import time as _time

        self._ensure_loop()
        if wait_s is None:
            wait_s = float(RTPU_CONFIG.llm_pull_wait_s)
        deadline = _time.monotonic() + max(0.0, float(wait_s))
        while True:
            # grab the CURRENT tick event before reading the buffer: a step
            # landing between the read and the wait sets this very event,
            # so the wait below returns immediately instead of timing out
            ev = self._tick
            try:
                toks, done, reason = self.engine.pull(request_id, max_tokens)
            except KeyError:
                return {"tokens": b"", "done": True,
                        "finish_reason": "unknown"}
            if toks or done or _time.monotonic() >= deadline:
                return {
                    "tokens": np.asarray(toks, dtype=np.int32).tobytes(),
                    "done": done,
                    "finish_reason": reason,
                }
            await self._wait_event(ev, max(0.01,
                                           deadline - _time.monotonic()))

    async def llm_cancel(self, request_id: str) -> dict:
        ok = self.engine.cancel(request_id)
        if self._wake is not None:
            self._wake.set()  # let the loop reap + free the KV promptly
        return {"ok": ok}

    async def generate(self, prompt, **sampling) -> dict:
        """One-shot completion through the same continuous-batching path."""
        self._ensure_loop()
        rid = self._submit(prompt, sampling)
        tokens: List[int] = []
        while True:
            out = await self.llm_pull(rid, wait_s=30.0)
            tokens.extend(np.frombuffer(out["tokens"], dtype=np.int32)
                          .tolist())
            if out["done"]:
                return {"tokens": tokens,
                        "finish_reason": out["finish_reason"]}

    async def stream(self, prompt, **sampling):
        """Async generator of token ids — rides serve's generic streaming
        (handle ``options(stream=True)`` and the HTTP ``?stream=1`` path)."""
        self._ensure_loop()
        rid = self._submit(prompt, sampling)
        try:
            while True:
                out = await self.llm_pull(rid, wait_s=30.0)
                for t in np.frombuffer(out["tokens"], dtype=np.int32):
                    yield int(t)
                if out["done"]:
                    return
        finally:
            # generator abandoned mid-stream (client closed): free the KV
            self.engine.cancel(rid)

    async def __call__(self, prompt, **sampling) -> dict:
        return await self.generate(prompt, **sampling)

    async def stats(self) -> dict:
        return {"model": self.model, **self.engine.stats()}

    def check_health(self):
        if self._loop_task is not None and self._loop_task.done():
            exc = self._loop_task.exception()
            raise RuntimeError(f"llm step loop died: {exc!r}")
        return True
