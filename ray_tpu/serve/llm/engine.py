"""LLM engine + the ``LLMReplica`` deployment class.

``LLMEngine`` is the synchronous core: it owns the paged cache, the
continuous-batching scheduler and a model adapter, and advances the world
one :meth:`step` at a time (prefill the newly admitted, one fused decode
for everything running, commit + deliver tokens). It is thread-safe behind
one coarse lock and has no asyncio/ray dependencies — the bench and the
unit tests drive it directly.

Two serving optimizations ride the same step loop, both byte-equal to
plain greedy decoding:

  - **prefix caching** (``RTPU_llm_prefix_cache``): admission maps the
    longest indexed prompt prefix read-only into the new sequence's block
    table (see ``kv_cache.py``) and the engine prefills only the un-hit
    tail via the adapter's ``prefill_ctx``;
  - **speculative decoding** (``RTPU_llm_draft_model`` +
    ``RTPU_llm_spec_k``): a tiny draft model proposes ``k`` tokens through
    its own paged cache, the target verifies all of them in ONE fused
    ``decode_chunk`` forward, and the longest agreeing run (+1 bonus
    token) commits; the draft cache rolls back with a refcount-aware
    ``truncate``. Greedy acceptance means the stream is exactly what the
    target alone would have produced. Only temperature-0 sequences
    speculate; sampled ones take the plain fused decode.

``LLMReplica`` is the serve-facing wrapper: an async step loop pumps the
engine off the actor's event loop (model math runs in the default
executor so queue probes and pulls stay responsive), requests arrive as
``llm_submit``/``llm_pull``/``llm_cancel`` (the proxy's zero-copy OOB
path), ``generate``/``stream`` (plain handle + HTTP streaming paths), and
admission control sheds load with the structured :class:`LLMBackpressure`
error before the cache can OOM.

Per-step telemetry rides the PR 1 metrics path (names are a stability
contract, see ``util/metrics.py``):

  ray_tpu_llm_tokens_per_s        gauge, EMA of generated tokens/s
  ray_tpu_llm_kv_utilization      gauge, 0-1 fraction of KV blocks in use
  ray_tpu_llm_batch_size          gauge, sequences in the last step
  ray_tpu_llm_preemptions_total   counter
  ray_tpu_llm_prefix_hit_rate     gauge, cumulative fraction of looked-up
                                  prompt tokens served from the prefix
                                  cache
  ray_tpu_llm_spec_acceptance     gauge, cumulative fraction of proposed
                                  draft tokens the target accepted

and the flight recorder gets ``llm.admit`` / ``llm.preempt`` /
``llm.finish`` / ``llm.prefix_hit`` / ``llm.spec_verify`` events (PR 3
contract: cheap tuples, no formatting until dump).
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Union

import numpy as np

from ray_tpu._private.config import RTPU_CONFIG
from ray_tpu.serve.llm import scheduler as sched_mod
from ray_tpu.serve.llm.adapters import ModelAdapter, build_adapter
from ray_tpu.serve.llm.kv_cache import KVCacheExhausted, PagedKVCache
from ray_tpu.serve.llm.scheduler import Scheduler, Sequence


class LLMBackpressure(RuntimeError):
    """Structured admission rejection: the engine sheds load instead of
    OOMing the KV cache. Carries enough for a client (or the proxy) to
    make a real decision — queue elsewhere, back off, or surface a 429."""

    def __init__(self, queue_depth: int, max_waiting: int,
                 kv_utilization: float):
        self.queue_depth = int(queue_depth)
        self.max_waiting = int(max_waiting)
        self.kv_utilization = float(kv_utilization)
        super().__init__(
            f"llm admission rejected: queue_depth={queue_depth} >= "
            f"max_waiting={max_waiting} (kv_utilization="
            f"{kv_utilization:.2f}); back off and retry"
        )

    def __reduce__(self):
        # pickles across the actor boundary with its structure intact
        # (default Exception.__reduce__ would replay the message string
        # into the 3-arg __init__ and blow up at unpickle time)
        return (LLMBackpressure,
                (self.queue_depth, self.max_waiting, self.kv_utilization))

    def to_dict(self) -> dict:
        return {"backpressure": True, "queue_depth": self.queue_depth,
                "max_waiting": self.max_waiting,
                "kv_utilization": round(self.kv_utilization, 4)}


@dataclass
class SamplingParams:
    max_tokens: int = 16
    temperature: float = 0.0   # 0 = greedy
    top_k: int = 0             # 0 = full vocab
    eos_id: Optional[int] = None
    seed: Optional[int] = None


class _SeqSampling:
    """Per-sequence sampling state riding on Sequence.sampling."""

    __slots__ = ("params", "rng", "spec")

    def __init__(self, params: SamplingParams):
        self.params = params
        self.rng = (np.random.default_rng(params.seed)
                    if params.temperature > 0 else None)
        # set at prefill time: the draft cache admitted this sequence, so
        # it takes the speculative decode path (greedy sequences only)
        self.spec = False


_llm_metrics = None


def _metrics():
    global _llm_metrics
    if _llm_metrics is None:
        from ray_tpu.util.metrics import Counter, Gauge

        tags = ("deployment", "replica")
        _llm_metrics = {
            "tokens_per_s": Gauge(
                "ray_tpu_llm_tokens_per_s",
                "generated tokens/s per llm replica (EMA)", tag_keys=tags),
            "kv_util": Gauge(
                "ray_tpu_llm_kv_utilization",
                "fraction of paged KV blocks in use", tag_keys=tags),
            "batch": Gauge(
                "ray_tpu_llm_batch_size",
                "sequences in the last engine step", tag_keys=tags),
            "preempt": Counter(
                "ray_tpu_llm_preemptions_total",
                "sequences requeued on KV exhaustion", tag_keys=tags),
            "prefix_hit": Gauge(
                "ray_tpu_llm_prefix_hit_rate",
                "fraction of prompt tokens served from the prefix cache",
                tag_keys=tags),
            "spec_accept": Gauge(
                "ray_tpu_llm_spec_acceptance",
                "fraction of proposed draft tokens the target accepted",
                tag_keys=tags),
        }
    return _llm_metrics


class _OutBuffer:
    """Tokens produced but not yet pulled by the client."""

    __slots__ = ("tokens", "done", "finish_reason")

    def __init__(self):
        self.tokens: List[int] = []
        self.done = False
        self.finish_reason: Optional[str] = None


class LLMEngine:
    """Synchronous continuous-batching engine (see module docstring)."""

    def __init__(
        self,
        adapter: ModelAdapter,
        *,
        num_blocks: Optional[int] = None,
        block_size: Optional[int] = None,
        max_batch: Optional[int] = None,
        max_waiting: Optional[int] = None,
        name: str = "llm",
        prefix_cache: Optional[bool] = None,
        draft_adapter: Optional[ModelAdapter] = None,
        spec_k: Optional[int] = None,
    ):
        self.adapter = adapter
        block_size = int(block_size or RTPU_CONFIG.llm_block_size)
        num_blocks = int(num_blocks or RTPU_CONFIG.llm_num_blocks)
        self.prefix_cache_enabled = bool(
            RTPU_CONFIG.llm_prefix_cache if prefix_cache is None
            else prefix_cache)
        self.cache = PagedKVCache(
            num_blocks=num_blocks,
            block_size=block_size,
            n_layers=adapter.n_layers,
            n_kv_heads=adapter.n_kv_heads,
            head_dim=adapter.head_dim,
            enable_prefix_cache=self.prefix_cache_enabled,
        )
        self.scheduler = Scheduler(
            self.cache,
            max_batch_size=int(max_batch or RTPU_CONFIG.llm_max_batch),
            max_waiting=int(max_waiting or RTPU_CONFIG.llm_max_waiting),
        )
        self.spec_k = int(RTPU_CONFIG.llm_spec_k if spec_k is None
                          else spec_k)
        self.draft_adapter = draft_adapter if self.spec_k > 0 else None
        self.draft_cache: Optional[PagedKVCache] = None
        if self.draft_adapter is not None:
            if self.draft_adapter.vocab_size != adapter.vocab_size:
                raise ValueError(
                    f"draft vocab {self.draft_adapter.vocab_size} != "
                    f"target vocab {adapter.vocab_size}")
            self.draft_cache = PagedKVCache(
                num_blocks=num_blocks,
                block_size=block_size,
                n_layers=self.draft_adapter.n_layers,
                n_kv_heads=self.draft_adapter.n_kv_heads,
                head_dim=self.draft_adapter.head_dim,
                enable_prefix_cache=self.prefix_cache_enabled,
            )
        self._out: Dict[str, _OutBuffer] = {}
        # finish reasons of recently drained sequences: a re-pull of a
        # drained id gets its true terminal marker, not "unknown"
        self._done_reasons: "OrderedDict[str, str]" = OrderedDict()
        self._lock = threading.RLock()
        self._tags = {"deployment": name, "replica": ""}
        self._tokens_per_s = 0.0  # EMA over steps
        self.steps_total = 0
        self.tokens_total = 0
        self.spec_proposed_total = 0
        self.spec_accepted_total = 0
        self.spec_rounds_total = 0

    def set_identity(self, deployment: str, replica: str = ""):
        self._tags = {"deployment": deployment, "replica": replica}

    # ------------------------------------------------------------ submission

    def submit(self, prompt: List[int],
               sampling: Optional[SamplingParams] = None) -> str:
        """Admit a prompt; returns the request id. Raises
        :class:`LLMBackpressure` past ``max_waiting`` queued prompts and
        ``ValueError`` for prompts that can never fit the cache."""
        sampling = sampling or SamplingParams()
        prompt = [int(t) for t in prompt]
        if not prompt:
            raise ValueError("empty prompt")
        if any(t < 0 or t >= self.adapter.vocab_size for t in prompt):
            raise ValueError(
                f"prompt token out of range [0, {self.adapter.vocab_size})")
        limit = min(self.adapter.max_context,
                    self.cache.num_blocks * self.cache.block_size)
        if len(prompt) + 1 > limit:
            raise ValueError(
                f"prompt of {len(prompt)} tokens can never fit "
                f"(context limit {limit})")
        with self._lock:
            if not self.scheduler.can_admit():
                raise LLMBackpressure(
                    self.scheduler.queue_depth(),
                    self.scheduler.max_waiting,
                    self.cache.utilization(),
                )
            seq = Sequence(prompt=prompt, max_tokens=sampling.max_tokens,
                           eos_id=sampling.eos_id,
                           sampling=_SeqSampling(sampling))
            self.scheduler.add(seq)
            self._out[seq.seq_id] = _OutBuffer()
            return seq.seq_id

    def cancel(self, seq_id: str) -> bool:
        """Client abandoned the stream: stop generating and (for waiting
        sequences now, running ones at the next schedule) free the KV."""
        with self._lock:
            ok = self.scheduler.cancel(seq_id)
            buf = self._out.get(seq_id)
            if buf is not None and not buf.done:
                buf.done = True
                buf.finish_reason = sched_mod.FINISH_CANCELLED
            return ok

    def pull(self, seq_id: str, max_tokens: int = 0):
        """Drain up to ``max_tokens`` (0 = all) buffered tokens. Returns
        ``(tokens, done, finish_reason)``; ``done`` only once the buffer is
        empty AND the sequence finished.

        An unknown or already-finished-and-drained id returns a terminal
        marker (``([], True, reason)``) immediately — the replica's
        long-poll keys its wait on ``done``, so raising (or returning a
        not-done empty read) here would sleep a retried client out of its
        full ``RTPU_llm_pull_wait_s`` window for a sequence that can never
        produce another token. Recently drained ids keep their true finish
        reason in a bounded ring; everything older reports ``"unknown"``."""
        with self._lock:
            buf = self._out.get(seq_id)
            if buf is None:
                return [], True, self._done_reasons.get(seq_id, "unknown")
            n = len(buf.tokens) if max_tokens <= 0 else int(max_tokens)
            out, buf.tokens = buf.tokens[:n], buf.tokens[n:]
            done = buf.done and not buf.tokens
            if done:
                self._out.pop(seq_id, None)
                self._done_reasons[seq_id] = buf.finish_reason or "unknown"
                while len(self._done_reasons) > 1024:
                    self._done_reasons.popitem(last=False)
            return out, done, buf.finish_reason

    # --------------------------------------------------------------- the step

    def _sample(self, seq: Sequence, logits: np.ndarray) -> int:
        sp: _SeqSampling = seq.sampling
        p = sp.params
        if p.temperature <= 0 or sp.rng is None:
            return int(np.argmax(logits))
        z = logits.astype(np.float64) / p.temperature
        if p.top_k and p.top_k < len(z):
            kth = np.partition(z, -p.top_k)[-p.top_k]
            z = np.where(z < kth, -np.inf, z)
        z -= z.max()
        probs = np.exp(z)
        probs /= probs.sum()
        return int(sp.rng.choice(len(probs), p=probs))

    def _free_draft(self, seq_id: str) -> None:
        if self.draft_cache is not None:
            self.draft_cache.free(seq_id)

    def _prefill_seq(self, seq: Sequence) -> np.ndarray:
        """Run the (possibly tail-only) prefill for a just-admitted
        sequence, write + index its KV, and mirror it into the draft cache
        when speculating. Returns the last position's logits. Raises
        KVCacheExhausted if the target-side write cannot complete — the
        caller frees the partial hold and requeues."""
        from ray_tpu._private import flight_recorder as _fr

        ctx = seq.context_tokens()
        cached = min(seq.cached_len, len(ctx) - 1)
        if cached:
            k_ctx, v_ctx = self.cache.gather(seq.seq_id)
            logits, k, v = self.adapter.prefill_ctx(
                np.asarray(ctx[cached:], dtype=np.int64), cached,
                k_ctx, v_ctx)
            _fr.record("llm.prefix_hit", b"",
                       f"{seq.seq_id} hit={cached}/{len(ctx)}")
        else:
            logits, k, v = self.adapter.prefill(
                np.asarray(ctx, dtype=np.int64))
        self.cache.write_prefill(seq.seq_id, k, v)
        self.cache.register_prefix(seq.seq_id, ctx)
        sp: Optional[_SeqSampling] = seq.sampling
        if (self.draft_cache is not None and sp is not None
                and sp.params.temperature <= 0):
            sp.spec = self._draft_prefill(seq.seq_id, ctx)
        return logits

    def _draft_prefill(self, seq_id: str, ctx: List[int]) -> bool:
        """Mirror the context into the draft cache (prefix-aware too).
        Failure is not fatal — the sequence just decodes without
        speculation."""
        dc, da = self.draft_cache, self.draft_adapter
        dc.free(seq_id)  # defensive: re-admission after an interrupted try
        served = dc.allocate_cached(seq_id, ctx, extra=self.spec_k + 1)
        if served is None:
            return False
        try:
            if served:
                k_ctx, v_ctx = dc.gather(seq_id)
                _, k, v = da.prefill_ctx(
                    np.asarray(ctx[served:], dtype=np.int64), served,
                    k_ctx, v_ctx)
            else:
                _, k, v = da.prefill(np.asarray(ctx, dtype=np.int64))
            dc.write_prefill(seq_id, k, v)
            dc.register_prefix(seq_id, ctx)
        except KVCacheExhausted:
            dc.free(seq_id)
            return False
        return True

    def _draft_extend(self, seqs: List[Sequence], n: int) -> bool:
        for s in seqs:
            if not self.draft_cache.extend(s.seq_id, n):
                return False
        return True

    def _spec_decode(self, seqs: List[Sequence]
                     ) -> Optional[Dict[str, List[int]]]:
        """Speculative decode for one step's greedy sequences: the draft
        proposes up to ``spec_k`` tokens (fused over the batch through its
        own paged cache), the target scores the whole chunk in ONE fused
        ``decode_chunk`` forward, and each sequence keeps its longest
        agreeing run plus the bonus token — exactly the tokens sequential
        greedy decoding would have produced. Rejected draft positions roll
        the draft cache back via the refcount-aware ``truncate``. Returns
        None when the draft pool cannot even start a round (callers fall
        back to the plain fused decode this step)."""
        from ray_tpu._private import flight_recorder as _fr

        da, dc = self.draft_adapter, self.draft_cache
        ids = [s.seq_id for s in seqs]
        # 1. catch-up: the draft cache must cover exactly the positions the
        #    target cache holds (it runs one token behind after a fully
        #    accepted round; further behind is impossible by construction)
        while True:
            lag = [s for s in seqs
                   if dc.seq_lens[s.seq_id] < self.cache.seq_lens[s.seq_id]]
            if not lag:
                break
            if not self._draft_extend(lag, 1):
                return None
            toks = np.asarray(
                [s.context_tokens()[dc.seq_lens[s.seq_id]] for s in lag],
                dtype=np.int64)
            lag_ids = [s.seq_id for s in lag]
            pos = np.asarray([dc.seq_lens[i] for i in lag_ids],
                             dtype=np.int64)
            k_ctx, v_ctx, lens = dc.gather_batch(lag_ids)
            _, k_new, v_new = da.decode(toks, pos, k_ctx, v_ctx, lens)
            for i, s in enumerate(lag):
                dc.append(s.seq_id, k_new[i], v_new[i])

        # 2. propose: k fused draft decode steps
        B = len(seqs)
        last = np.asarray([s.tokens[-1] for s in seqs], dtype=np.int64)
        drafts = np.zeros((B, self.spec_k), dtype=np.int64)
        k_eff = 0
        cur = last
        for j in range(self.spec_k):
            if not self._draft_extend(seqs, 1):
                break
            pos = np.asarray([dc.seq_lens[i] for i in ids], dtype=np.int64)
            k_ctx, v_ctx, lens = dc.gather_batch(ids)
            logits, k_new, v_new = da.decode(cur, pos, k_ctx, v_ctx, lens)
            for i, s in enumerate(seqs):
                dc.append(s.seq_id, k_new[i], v_new[i])
            cur = np.argmax(logits, axis=-1).astype(np.int64)
            drafts[:, j] = cur
            k_eff = j + 1
        if k_eff == 0:
            return None

        # 3. verify: one fused target forward over [last, d0..d_{k-1}]
        chunk = np.concatenate([last[:, None], drafts[:, :k_eff]], axis=1)
        pos = np.asarray([self.cache.seq_lens[i] for i in ids],
                         dtype=np.int64)
        k_ctx, v_ctx, lens = self.cache.gather_batch(ids)
        logits, k_new, v_new = self.adapter.decode_chunk(
            chunk, pos, k_ctx, v_ctx, lens)
        greedy = np.argmax(logits, axis=-1)                    # [B, k_eff+1]

        bs = self.cache.block_size
        sampled: Dict[str, List[int]] = {}
        accepted_round = 0
        for i, s in enumerate(seqs):
            agree = 0
            while (agree < k_eff
                   and int(drafts[i, agree]) == int(greedy[i, agree])):
                agree += 1
            n_emit = agree + 1
            # clip to the sequence's budget, to EOS, and to what the pool
            # can still hold this step (>= 1 slot is pre-reserved by the
            # scheduler, so plain-decode progress is always possible)
            n_emit = min(n_emit, max(1, s.max_tokens - len(s.tokens)))
            emitted = [int(greedy[i, c]) for c in range(n_emit)]
            if s.eos_id is not None and s.eos_id in emitted:
                n_emit = emitted.index(s.eos_id) + 1
                emitted = emitted[:n_emit]
            sid = s.seq_id
            slack = (len(self.cache.block_tables[sid]) * bs
                     - self.cache.seq_lens[sid]
                     + self.cache.num_free_blocks * bs)
            if n_emit > slack:
                n_emit = max(1, slack)
                emitted = emitted[:n_emit]
            self.cache.write_prefill(
                sid, k_new[i, :, :n_emit], v_new[i, :, :n_emit])
            # roll the draft back to the accepted length; after a fully
            # accepted chunk it is one token SHORT instead (caught up at
            # the start of the next round)
            new_kv_len = int(pos[i]) + n_emit
            if dc.seq_lens[sid] > new_kv_len:
                dc.truncate(sid, new_kv_len)
            sampled[sid] = emitted
            accepted_round += n_emit - 1
        self.spec_rounds_total += 1
        self.spec_proposed_total += k_eff * B
        self.spec_accepted_total += accepted_round
        _fr.record("llm.spec_verify", b"",
                   f"batch={B} k={k_eff} accepted={accepted_round}")
        return sampled

    def step(self) -> Dict[str, Any]:
        """One engine iteration; returns step stats (also published as
        gauges). A no-op returning ``{"batch_size": 0}`` when idle."""
        from ray_tpu._private import flight_recorder as _fr

        with self._lock:
            t0 = time.perf_counter()
            plan = self.scheduler.schedule()
            for seq in plan.reaped:
                self._finish_buffer(seq)
                self._free_draft(seq.seq_id)
            for seq in plan.preempted:
                self._free_draft(seq.seq_id)
                _fr.record("llm.preempt", b"",
                           f"{seq.seq_id} ctx={seq.total_len}")
            if plan.batch_size == 0:
                self._publish(0, 0, 0.0)
                return {"batch_size": 0, "tokens": 0}

            sampled: Dict[str, Union[int, List[int]]] = {}
            for seq in plan.prefills:
                try:
                    logits = self._prefill_seq(seq)
                except KVCacheExhausted:
                    # admission interrupted mid-prefill (e.g. a
                    # copy-on-write with an empty pool): free the partial
                    # hold FIRST — requeueing with blocks still pinned
                    # would leak shared refcounts — then retry next step
                    self.cache.free(seq.seq_id)
                    self._free_draft(seq.seq_id)
                    self.scheduler.requeue(seq)
                    _fr.record("llm.preempt", b"",
                               f"{seq.seq_id} ctx={seq.total_len} admit")
                    continue
                sampled[seq.seq_id] = self._sample(seq, logits)
                _fr.record("llm.admit", b"",
                           f"{seq.seq_id} prompt={seq.total_len} "
                           f"hit={seq.cached_len} "
                           f"kv={self.cache.utilization():.2f}")
            if plan.decodes:
                spec_seqs = [
                    s for s in plan.decodes
                    if getattr(s.sampling, "spec", False)
                ] if self.draft_cache is not None else []
                spec_ids = {s.seq_id for s in spec_seqs}
                plain = [s for s in plan.decodes
                         if s.seq_id not in spec_ids]
                if spec_seqs:
                    out = self._spec_decode(spec_seqs)
                    if out is None:
                        plain = plain + spec_seqs
                    else:
                        sampled.update(out)
                if plain:
                    ids = [s.seq_id for s in plain]
                    toks = np.asarray([s.tokens[-1] for s in plain],
                                      dtype=np.int64)
                    pos = np.asarray([self.cache.seq_lens[i] for i in ids],
                                     dtype=np.int64)
                    k_ctx, v_ctx, lens = self.cache.gather_batch(ids)
                    logits, k_new, v_new = self.adapter.decode(
                        toks, pos, k_ctx, v_ctx, lens)
                    for i, seq in enumerate(plain):
                        self.cache.append(seq.seq_id, k_new[i], v_new[i])
                        sampled[seq.seq_id] = self._sample(seq, logits[i])

            by_id = {s.seq_id: s for s in plan.prefills + plan.decodes}
            before = {sid: len(by_id[sid].tokens) for sid in sampled}
            finished = self.scheduler.commit(sampled)
            n_tokens = 0
            for sid in sampled:
                seq = by_id[sid]
                committed = seq.tokens[before[sid]:]
                n_tokens += len(committed)
                buf = self._out.get(sid)
                if buf is not None and not buf.done:
                    buf.tokens.extend(committed)
            for seq in finished:
                self._finish_buffer(seq)
                self._free_draft(seq.seq_id)
                _fr.record("llm.finish", b"",
                           f"{seq.seq_id} reason={seq.finish_reason} "
                           f"tokens={len(seq.tokens)}")

            dt = max(time.perf_counter() - t0, 1e-9)
            self.steps_total += 1
            self.tokens_total += n_tokens
            inst = n_tokens / dt
            self._tokens_per_s = (inst if self._tokens_per_s == 0.0
                                  else 0.8 * self._tokens_per_s + 0.2 * inst)
            self._publish(plan.batch_size, len(plan.preempted), dt)
            return {
                "batch_size": plan.batch_size,
                "prefills": len(plan.prefills),
                "decodes": len(plan.decodes),
                "preempted": len(plan.preempted),
                "finished": len(finished),
                "finished_ids": [s.seq_id for s in finished],
                "tokens": n_tokens,
                "step_s": dt,
            }

    def _finish_buffer(self, seq: Sequence):
        buf = self._out.get(seq.seq_id)
        if buf is not None:
            buf.done = True
            buf.finish_reason = seq.finish_reason

    def spec_acceptance(self) -> float:
        """Cumulative fraction of proposed draft tokens the target
        accepted (the ``ray_tpu_llm_spec_acceptance`` gauge)."""
        if not self.spec_proposed_total:
            return 0.0
        return self.spec_accepted_total / self.spec_proposed_total

    def _publish(self, batch: int, preempted: int, dt: float):
        try:
            m = _metrics()
            m["tokens_per_s"].set(self._tokens_per_s, tags=self._tags)
            m["kv_util"].set(self.cache.utilization(), tags=self._tags)
            m["batch"].set(batch, tags=self._tags)
            if preempted:
                m["preempt"].inc(preempted, tags=self._tags)
            if self.prefix_cache_enabled:
                m["prefix_hit"].set(self.cache.hit_rate(), tags=self._tags)
            if self.draft_cache is not None:
                m["spec_accept"].set(self.spec_acceptance(),
                                     tags=self._tags)
        except Exception:
            pass

    # ------------------------------------------------------------------ misc

    def has_work(self) -> bool:
        with self._lock:
            return self.scheduler.has_work()

    def load(self) -> int:
        """Waiting + running sequences — what the serve autoscaler keys on."""
        with self._lock:
            return self.scheduler.queue_depth()

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            out = {
                "waiting": len(self.scheduler.waiting),
                "running": len(self.scheduler.running),
                "kv_utilization": round(self.cache.utilization(), 4),
                "kv_free_blocks": self.cache.num_free_blocks,
                "tokens_per_s": round(self._tokens_per_s, 1),
                "tokens_total": self.tokens_total,
                "steps_total": self.steps_total,
                "preemptions_total": self.scheduler.preemptions_total,
                "finished_total": self.scheduler.finished_total,
            }
            if self.prefix_cache_enabled:
                out.update({
                    "prefix_hit_rate": round(self.cache.hit_rate(), 4),
                    "kv_cached_blocks": self.cache.num_cached_blocks,
                    "cow_copies": self.cache.cow_copies,
                })
            if self.draft_cache is not None:
                out.update({
                    "spec_acceptance": round(self.spec_acceptance(), 4),
                    "spec_rounds_total": self.spec_rounds_total,
                })
            return out

    def run_until_drained(self, max_steps: int = 1_000_000) -> int:
        """Drive the engine until no work remains (bench/test helper);
        returns steps executed."""
        steps = 0
        while self.has_work() and steps < max_steps:
            self.step()
            steps += 1
        return steps


def _normalize_prompt(prompt: Union[str, bytes, List[int]]) -> List[int]:
    """str prompts become UTF-8 byte ids (every zoo vocab is >= 256);
    token-id lists / arrays pass through."""
    if isinstance(prompt, str):
        return list(prompt.encode("utf-8"))
    if isinstance(prompt, (bytes, bytearray)):
        return list(np.frombuffer(bytes(prompt), dtype=np.int32))
    return [int(t) for t in prompt]


class LLMReplica:
    """The deployment class: ``serve.llm.deploy`` binds this behind serve.

    One background task pumps the engine; every request-facing method is
    async and cheap (the model math runs in the executor). Telemetry
    identity (deployment/replica tags) is injected by the hosting
    ``Replica`` via ``__serve_identity__``; the serve autoscaler reads the
    engine's queue depth via ``__serve_load__``.
    """

    def __init__(
        self,
        model: str = "gpt2-tiny",
        model_config: Optional[dict] = None,
        *,
        num_blocks: Optional[int] = None,
        block_size: Optional[int] = None,
        max_batch: Optional[int] = None,
        max_waiting: Optional[int] = None,
        prefix_cache: Optional[bool] = None,
        draft_model: Optional[str] = None,
        draft_model_config: Optional[dict] = None,
        spec_k: Optional[int] = None,
        seed: int = 0,
    ):
        adapter = build_adapter(model, model_config, seed=seed)
        if draft_model is None:
            draft_model = str(RTPU_CONFIG.llm_draft_model or "")
        draft_adapter = (build_adapter(draft_model, draft_model_config,
                                       seed=seed)
                         if draft_model else None)
        self.engine = LLMEngine(
            adapter,
            num_blocks=num_blocks,
            block_size=block_size,
            max_batch=max_batch,
            max_waiting=max_waiting,
            prefix_cache=prefix_cache,
            draft_adapter=draft_adapter,
            spec_k=spec_k,
        )
        self.model = model
        self.draft_model = draft_model or None
        self._loop_task = None
        self._tick = None          # asyncio.Event, re-armed every step
        self._wake = None          # set on submit while the loop is idle

    # hooks the serve Replica wrapper calls (see serve/_replica.py)
    def __serve_identity__(self, deployment: str, replica: str):
        self.engine.set_identity(deployment, replica)

    def __serve_load__(self) -> int:
        return self.engine.load()

    # ------------------------------------------------------------- step loop

    def _ensure_loop(self):
        import asyncio

        if self._loop_task is not None and not self._loop_task.done():
            return
        self._tick = asyncio.Event()
        self._wake = asyncio.Event()
        self._loop_task = asyncio.ensure_future(self._run_loop())

    async def _run_loop(self):
        import asyncio

        from ray_tpu._private import chaos as _chaos

        loop = asyncio.get_running_loop()
        while True:
            if self.engine.has_work():
                stats = await loop.run_in_executor(None, self.engine.step)
                # wake every pull waiting on this step's tokens
                tick, self._tick = self._tick, asyncio.Event()
                tick.set()
                # Chaos site: fires only on PRODUCTIVE steps so
                # "after_steps" counts generation progress, deterministic
                # across replays (SIGKILL mid-stream, a hung step loop, a
                # step-loop crash are the faults the failover path and the
                # controller's health check must absorb).
                if _chaos.ARMED and stats.get("batch_size", 0) > 0:
                    act = _chaos.hit(
                        "replica.step",
                        deployment=self.engine._tags["deployment"],
                        replica=self.engine._tags["replica"])
                    if act is not None:
                        await self._apply_chaos(act)
            else:
                self._wake.clear()
                # wake promptly on submit; the timeout keeps the loop
                # resilient to a lost wake (cancelled submit etc.)
                try:
                    await asyncio.wait_for(self._wake.wait(), 1.0)
                except asyncio.TimeoutError:
                    pass

    @staticmethod
    async def _apply_chaos(act: dict):
        """Interpret a fired replica.step rule: kill (SIGKILL this
        process, flushing the flight ring first so the death report
        carries the tail), hang (stall the step loop — the controller's
        health staleness check replaces us), or error (step loop dies —
        check_health fails)."""
        import asyncio

        action = act["action"]
        if action == "kill":
            import os
            import signal

            from ray_tpu._private import flight_recorder as _fr

            _fr.flush_now()
            os.kill(os.getpid(), signal.SIGKILL)
        elif action == "hang":
            await asyncio.sleep(act["delay_s"] or 3600.0)
        elif action == "delay":
            await asyncio.sleep(act["delay_s"])
        elif action == "error":
            raise RuntimeError("chaos: replica step loop error (injected)")

    @staticmethod
    async def _wait_event(ev, timeout: float):
        import asyncio

        try:
            await asyncio.wait_for(ev.wait(), timeout)
        except asyncio.TimeoutError:
            pass

    # -------------------------------------------------------- request surface

    def _submit(self, prompt, sampling: Optional[dict]) -> str:
        sp = SamplingParams(**(sampling or {}))
        rid = self.engine.submit(_normalize_prompt(prompt), sp)
        self._ensure_loop()
        self._wake.set()
        return rid

    async def llm_submit(self, prompt, sampling: Optional[dict] = None) -> dict:
        """OOB ingress entry: prompt may be raw int32 bytes (the frame's
        payload, untouched), a token-id list, or a string."""
        self._ensure_loop()
        return {"request_id": self._submit(prompt, sampling)}

    async def llm_pull(self, request_id: str, max_tokens: int = 0,
                       wait_s: Optional[float] = None) -> dict:
        """Long-poll pull: waits up to ``wait_s`` for at least one token
        (or completion), then returns ``{"tokens": <raw int32 bytes>,
        "done", "finish_reason"}`` — bytes, so the proxy can forward them
        as an OOB frame without re-serializing."""
        import time as _time

        self._ensure_loop()
        if wait_s is None:
            wait_s = float(RTPU_CONFIG.llm_pull_wait_s)
        deadline = _time.monotonic() + max(0.0, float(wait_s))
        while True:
            # grab the CURRENT tick event before reading the buffer: a step
            # landing between the read and the wait sets this very event,
            # so the wait below returns immediately instead of timing out.
            # An unknown or already-drained id comes back from the engine
            # as a terminal marker (done=True), never a long-poll sleep.
            ev = self._tick
            toks, done, reason = self.engine.pull(request_id, max_tokens)
            if toks or done or _time.monotonic() >= deadline:
                return {
                    "tokens": np.asarray(toks, dtype=np.int32).tobytes(),
                    "done": done,
                    "finish_reason": reason,
                }
            await self._wait_event(ev, max(0.01,
                                           deadline - _time.monotonic()))

    async def llm_cancel(self, request_id: str) -> dict:
        ok = self.engine.cancel(request_id)
        if self._wake is not None:
            self._wake.set()  # let the loop reap + free the KV promptly
        return {"ok": ok}

    async def generate(self, prompt, **sampling) -> dict:
        """One-shot completion through the same continuous-batching path."""
        self._ensure_loop()
        rid = self._submit(prompt, sampling)
        tokens: List[int] = []
        while True:
            out = await self.llm_pull(rid, wait_s=30.0)
            tokens.extend(np.frombuffer(out["tokens"], dtype=np.int32)
                          .tolist())
            if out["done"]:
                return {"tokens": tokens,
                        "finish_reason": out["finish_reason"]}

    async def stream(self, prompt, **sampling):
        """Async generator of token ids — rides serve's generic streaming
        (handle ``options(stream=True)`` and the HTTP ``?stream=1`` path)."""
        self._ensure_loop()
        rid = self._submit(prompt, sampling)
        try:
            while True:
                out = await self.llm_pull(rid, wait_s=30.0)
                for t in np.frombuffer(out["tokens"], dtype=np.int32):
                    yield int(t)
                if out["done"]:
                    return
        finally:
            # generator abandoned mid-stream (client closed): free the KV
            self.engine.cancel(rid)

    async def __call__(self, prompt, **sampling) -> dict:
        return await self.generate(prompt, **sampling)

    async def stats(self) -> dict:
        return {"model": self.model, **self.engine.stats()}

    async def llm_integrity(self) -> dict:
        """Storm-survival invariant probe: cross-check every KV block
        (target AND draft cache) against the refcount/index/free-list
        bookkeeping. The chaos suite asserts ``problems == []`` and
        ``used_blocks == 0`` on every surviving replica after a storm —
        the serve-plane analogue of the PR 7 plasma leak sweep."""
        # lint: allow(sync-lock-in-async) -- the engine's documented
        # coarse lock; the probe runs between steps and never holds it
        # across an await
        with self.engine._lock:
            problems = list(self.engine.cache.check_integrity())
            used = self.engine.cache.num_used_blocks
            if self.engine.draft_cache is not None:
                problems += [f"draft: {p}" for p in
                             self.engine.draft_cache.check_integrity()]
                used += self.engine.draft_cache.num_used_blocks
            return {
                "problems": problems,
                "used_blocks": used,
                "waiting": len(self.engine.scheduler.waiting),
                "running": len(self.engine.scheduler.running),
            }

    def check_health(self):
        if self._loop_task is not None and self._loop_task.done():
            exc = self._loop_task.exception()
            raise RuntimeError(f"llm step loop died: {exc!r}")
        return True
