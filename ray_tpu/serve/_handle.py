"""DeploymentHandle: the client side of a deployment.

Counterpart of the reference's handle → router → replica-scheduler chain
(reference: python/ray/serve/handle.py:714 DeploymentHandle,
_private/router.py:320, _private/replica_scheduler/pow_2_scheduler.py:49
PowerOfTwoChoicesReplicaScheduler). Replica-set changes arrive by
LONG-POLL push from the controller (reference: _private/long_poll.py) — a
background updater holds a poll open and applies new sets the moment the
controller reconciles, so scale-downs re-route within one poll instead of
a TTL window. Each call picks two random replicas and PROBES their actual
queue depths (pow-2 with probes, like the reference's scheduler), falling
back to handle-local in-flight counts when a probe times out.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Any, Dict, List, Optional

CONTROLLER_NAME = "SERVE_CONTROLLER"
_POLL_TIMEOUT_S = 20.0
_PROBE_TIMEOUT_S = 0.5

_handle_metrics = None


def _metrics():
    """Caller-side serve metrics (lazy singleton). The handle lives in the
    caller's worker process, so these flush through THAT worker's
    util.metrics push — the latency here is the true end-to-end view
    (routing + queueing + execution + transport), complementing the
    replica-side ray_tpu_serve_request_latency_seconds."""
    global _handle_metrics
    if _handle_metrics is None:
        from ray_tpu.util.metrics import Counter, Histogram

        _handle_metrics = {
            "latency": Histogram(
                "ray_tpu_serve_handle_latency_seconds",
                "caller-observed end-to-end request latency",
                boundaries=(0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                            0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0),
                tag_keys=("deployment",)),
            "requests": Counter(
                "ray_tpu_serve_handle_requests_total",
                "requests dispatched through deployment handles",
                tag_keys=("deployment",)),
        }
    return _handle_metrics


class DeploymentResponse:
    """Future-like wrapper over the underlying ObjectRef
    (reference: serve/handle.py DeploymentResponse)."""

    def __init__(self, ref):
        self._ref = ref

    def result(self, timeout: Optional[float] = None):
        import ray_tpu

        return ray_tpu.get(self._ref, timeout=timeout)

    def _to_object_ref(self):
        return self._ref


class StreamingResponse:
    """Iterator over a streaming deployment call (reference:
    serve/handle.py DeploymentResponseGenerator): the replica runs the
    generator; items arrive in pulled batches."""

    def __init__(self, replica, stream_id: str, handle, idx: int):
        self._replica = replica
        self._stream_id = stream_id
        self._handle = handle
        self._idx = idx
        self._buf: List[Any] = []
        self._done = False

    def __iter__(self):
        return self

    def __next__(self):
        import ray_tpu

        while not self._buf:
            if self._done:
                self._finish()
                raise StopIteration
            reply = ray_tpu.get(
                self._replica.next_stream_items.remote(self._stream_id),
                timeout=120,
            )
            self._buf.extend(reply["items"])
            self._done = reply["done"]
        return self._buf.pop(0)

    def _finish(self):
        if self._handle is not None:
            self._handle._done(self._idx)
            self._handle = None

    def close(self):
        """Abandon the stream: frees the replica-side generator."""
        if not self._done:
            self._done = True
            try:
                self._replica.cancel_stream.remote(self._stream_id)
            except Exception:
                pass
        self._finish()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class DeploymentHandle:
    def __init__(self, deployment_name: str, method_name: str = "__call__",
                 multiplexed_model_id: str = "", stream: bool = False,
                 idempotent: bool = False):
        self.deployment_name = deployment_name
        self._method = method_name
        self._model_id = multiplexed_model_id
        self._stream = stream
        # idempotent methods opt into bounded ActorDiedError retry: a call
        # that dies with its replica is transparently re-dispatched to a
        # survivor (RTPU_serve_failover_retries, capped backoff)
        self._idempotent = idempotent
        self._lock = threading.Lock()
        self._replicas: List[Any] = []
        self._replica_names: List[str] = []
        self._version = -1
        self._inflight: Dict[str, int] = {}  # replica name -> in-flight
        self._poller: Optional[threading.Thread] = None
        self._closed = False

    def __reduce__(self):
        return (DeploymentHandle,
                (self.deployment_name, self._method, self._model_id,
                 self._stream, self._idempotent))

    def options(self, method_name: Optional[str] = None, *,
                multiplexed_model_id: Optional[str] = None,
                stream: Optional[bool] = None,
                idempotent: Optional[bool] = None) -> "DeploymentHandle":
        return DeploymentHandle(
            self.deployment_name,
            method_name if method_name is not None else self._method,
            multiplexed_model_id if multiplexed_model_id is not None
            else self._model_id,
            self._stream if stream is None else stream,
            self._idempotent if idempotent is None else idempotent,
        )

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        return DeploymentHandle(self.deployment_name, name, self._model_id,
                                self._stream, self._idempotent)

    def _apply_names(self, names: List[str], version: int):
        import ray_tpu

        replicas = []
        kept = []
        for n in names:
            try:
                replicas.append(ray_tpu.get_actor(n))
                kept.append(n)
            except Exception:
                pass
        with self._lock:
            self._replicas = replicas
            self._replica_names = kept
            self._version = version
            # in-flight counts keyed by NAME so surviving replicas keep
            # their counts across set changes
            self._inflight = {
                n: self._inflight.get(n, 0) for n in kept
            }

    def _poll_loop(self):
        """Background long-poll: applies replica-set changes the moment
        the controller publishes them. The thread is bound to ONE runtime
        session — after ray_tpu.shutdown (tests, notebooks) it retires
        instead of polling a dead or unrelated cluster; the next call on
        the handle starts a fresh poller in the new session."""
        import ray_tpu
        from ray_tpu._private import worker as worker_mod

        my_worker = worker_mod.global_worker
        try:
            while not self._closed:
                if worker_mod.global_worker is not my_worker:
                    return
                try:
                    controller = ray_tpu.get_actor(CONTROLLER_NAME)
                    r = ray_tpu.get(
                        controller.poll_replica_names.remote(
                            self.deployment_name, self._version,
                            _POLL_TIMEOUT_S,
                        ),
                        timeout=_POLL_TIMEOUT_S + 15,
                    )
                    if r["version"] != self._version or not self._replicas:
                        self._apply_names(r["names"], r["version"])
                except Exception:
                    for _ in range(10):
                        if (self._closed
                                or worker_mod.global_worker is not my_worker):
                            return
                        time.sleep(0.1)
        finally:
            with self._lock:
                if self._poller is threading.current_thread():
                    self._poller = None

    def _refresh_replicas(self, force: bool = False):
        with self._lock:
            if self._poller is None and not self._closed:
                self._poller = threading.Thread(
                    target=self._poll_loop, daemon=True,
                    name=f"serve-poll-{self.deployment_name}",
                )
                self._poller.start()
        if force or not self._replicas:
            import ray_tpu

            controller = ray_tpu.get_actor(CONTROLLER_NAME)
            r = ray_tpu.get(
                controller.poll_replica_names.remote(
                    self.deployment_name, -1, 0.0
                ),
                timeout=30,
            )
            self._apply_names(r["names"], r["version"])

    def _pick(self) -> tuple:
        """Power-of-two-choices with queue-length probes: two random
        candidates report their actual in-flight depth (reference:
        pow_2_scheduler.py:49); handle-local counts break probe failures
        and ties. Multiplexed requests get deterministic model→replica
        affinity so each model's weights stay warm on one replica."""
        with self._lock:
            n = len(self._replicas)
            if n == 0:
                raise RuntimeError(
                    f"no replicas for deployment '{self.deployment_name}'"
                )
            if n == 1:
                cand = [0]
            elif self._model_id:
                import zlib

                cand = [zlib.crc32(self._model_id.encode()) % n]
            else:
                cand = random.sample(range(n), 2)
            cand_named = [
                (i, self._replica_names[i], self._replicas[i]) for i in cand
            ]
        if len(cand_named) == 1:
            idx, name, replica = cand_named[0]
        else:
            import ray_tpu

            # probe candidates INDEPENDENTLY: one dead/slow replica must
            # neither discard the live candidate's answer nor stall the
            # request past the probe budget — an unanswered or failed
            # probe falls back to the local count, and a probe that
            # ERRORS (replica dead) is penalized so the live one wins
            refs = [r.queue_len.remote() for _, _, r in cand_named]
            try:
                ready, _ = ray_tpu.wait(
                    refs, num_returns=len(refs), timeout=_PROBE_TIMEOUT_S
                )
                ready_set = set(ready)
            except Exception:
                ready_set = set()
            depths = []
            for ref, (_i, nm, _r) in zip(refs, cand_named):
                if ref in ready_set:
                    try:
                        depths.append(ray_tpu.get(ref, timeout=1))
                        continue
                    except Exception:
                        depths.append(1 << 30)  # dead replica: avoid
                        continue
                with self._lock:
                    depths.append(self._inflight.get(nm, 0))
            pick = min(range(len(cand_named)), key=lambda i: depths[i])
            idx, name, replica = cand_named[pick]
        with self._lock:
            self._inflight[name] = self._inflight.get(name, 0) + 1
        return name, replica

    def _done(self, name: str):
        with self._lock:
            if self._inflight.get(name, 0) > 0:
                self._inflight[name] -= 1

    def close(self):
        self._closed = True

    def pick_replica(self) -> tuple:
        """Pick one replica (pow-2 probed, like remote()) and charge an
        in-flight slot to it; returns ``(replica_name, actor)``. The
        caller OWNS the slot and must call :meth:`release` when the
        pinned interaction ends — the proxy's llm stream path uses this
        to keep every pull of one token stream on the replica that holds
        its KV blocks."""
        self._refresh_replicas()
        return self._pick()

    def release(self, replica_name: str):
        """Return the in-flight slot taken by :meth:`pick_replica`."""
        self._done(replica_name)

    def remote(self, *args, **kwargs) -> DeploymentResponse:
        retries = 0
        if self._idempotent and not self._stream:
            from ray_tpu._private.config import RTPU_CONFIG

            retries = max(0, int(RTPU_CONFIG.serve_failover_retries))
        return self._remote(args, kwargs, retries)

    def _remote(self, args: tuple, kwargs: dict,
                died_retries: int = 0) -> DeploymentResponse:
        t0 = time.time()
        deadline = t0 + 60
        last_err: Optional[Exception] = None
        while time.time() < deadline:
            try:
                self._refresh_replicas()
                idx, replica = self._pick()
            except Exception as e:
                last_err = e
                time.sleep(0.25)
                continue
            try:
                if self._model_id:
                    kwargs = {**kwargs,
                              "__multiplexed_model_id": self._model_id}
                if self._stream:
                    import ray_tpu

                    sid = ray_tpu.get(
                        replica.start_stream.remote(
                            self._method, args, kwargs),
                        timeout=60,
                    )
                    try:
                        _metrics()["requests"].inc(
                            1, tags={"deployment": self.deployment_name})
                    except Exception:
                        pass
                    return StreamingResponse(replica, sid, self, idx)
                ref = replica.handle_request.remote(
                    self._method, args, kwargs
                )
                # decrement when the call resolves (best effort, piggybacks
                # on the ref's completion via a daemon thread-free path: the
                # response object decrements on result()).
                resp = DeploymentResponse(ref)
                _attach_done(resp, self, idx, t0, args=args, kwargs=kwargs,
                             died_retries=died_retries)
                try:
                    _metrics()["requests"].inc(
                        1, tags={"deployment": self.deployment_name})
                except Exception:
                    pass
                return resp
            except Exception as e:
                last_err = e
                # the pick's in-flight increment must not outlive a failed
                # dispatch (counts persist across set refreshes now)
                self._done(idx)
                self._refresh_replicas(force=True)
        raise RuntimeError(
            f"could not reach any replica of '{self.deployment_name}': {last_err}"
        )


def _is_actor_death(e: BaseException) -> bool:
    from ray_tpu.exceptions import (
        ActorDiedError,
        ActorUnavailableError,
        TaskError,
    )

    if isinstance(e, TaskError):
        e = e.cause
    return isinstance(e, (ActorDiedError, ActorUnavailableError))


def _attach_done(resp: DeploymentResponse, handle: DeploymentHandle, idx: int,
                 t0: Optional[float] = None, *, args: tuple = (),
                 kwargs: Optional[dict] = None, died_retries: int = 0):
    original = resp.result
    done = {"fired": False}
    deployment = handle.deployment_name

    def _settle():
        if not done["fired"]:
            done["fired"] = True
            handle._done(idx)
            if t0 is not None:
                # caller-observed e2e latency, observed once per request
                # at first resolution (repeat result() calls are reads)
                try:
                    _metrics()["latency"].observe(
                        time.time() - t0, tags={"deployment": deployment})
                except Exception:
                    pass

    def result(timeout: Optional[float] = None):
        try:
            out = original(timeout)
        except BaseException as e:
            if died_retries > 0 and _is_actor_death(e):
                # bounded retry for idempotent methods: the replica died
                # with our call in flight — back off (capped exponential:
                # replacements take seconds to appear), re-pick a survivor
                # and re-dispatch
                _settle()
                from ray_tpu._private.config import RTPU_CONFIG

                attempt = max(
                    0, int(RTPU_CONFIG.serve_failover_retries) - died_retries)
                time.sleep(min(RTPU_CONFIG.serve_failover_backoff_max_s,
                               RTPU_CONFIG.serve_failover_backoff_s
                               * (2 ** attempt))
                           * (0.5 + random.random() / 2))
                from ray_tpu.serve.rpc_ingress import _note_failover

                _note_failover(deployment)
                handle._refresh_replicas(force=True)
                return handle._remote(
                    args, dict(kwargs or {}), died_retries - 1
                ).result(timeout)
            _settle()
            raise
        _settle()
        return out

    resp.result = result
