"""DeploymentHandle: the client side of a deployment.

Counterpart of the reference's handle → router → replica-scheduler chain
(reference: python/ray/serve/handle.py:714 DeploymentHandle,
_private/router.py:320, _private/replica_scheduler/pow_2_scheduler.py:49
PowerOfTwoChoicesReplicaScheduler). Replica sets are fetched from the
controller and cached briefly; each call picks the less-loaded of two
random replicas using handle-local in-flight counts (the reference's
client-side queue-length view).
"""

from __future__ import annotations

import random
import threading
import time
from typing import Any, Dict, List, Optional

CONTROLLER_NAME = "SERVE_CONTROLLER"
_REPLICA_CACHE_TTL_S = 1.0


class DeploymentResponse:
    """Future-like wrapper over the underlying ObjectRef
    (reference: serve/handle.py DeploymentResponse)."""

    def __init__(self, ref):
        self._ref = ref

    def result(self, timeout: Optional[float] = None):
        import ray_tpu

        return ray_tpu.get(self._ref, timeout=timeout)

    def _to_object_ref(self):
        return self._ref


class StreamingResponse:
    """Iterator over a streaming deployment call (reference:
    serve/handle.py DeploymentResponseGenerator): the replica runs the
    generator; items arrive in pulled batches."""

    def __init__(self, replica, stream_id: str, handle, idx: int):
        self._replica = replica
        self._stream_id = stream_id
        self._handle = handle
        self._idx = idx
        self._buf: List[Any] = []
        self._done = False

    def __iter__(self):
        return self

    def __next__(self):
        import ray_tpu

        while not self._buf:
            if self._done:
                self._finish()
                raise StopIteration
            reply = ray_tpu.get(
                self._replica.next_stream_items.remote(self._stream_id),
                timeout=120,
            )
            self._buf.extend(reply["items"])
            self._done = reply["done"]
        return self._buf.pop(0)

    def _finish(self):
        if self._handle is not None:
            self._handle._done(self._idx)
            self._handle = None

    def close(self):
        """Abandon the stream: frees the replica-side generator."""
        if not self._done:
            self._done = True
            try:
                self._replica.cancel_stream.remote(self._stream_id)
            except Exception:
                pass
        self._finish()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class DeploymentHandle:
    def __init__(self, deployment_name: str, method_name: str = "__call__",
                 multiplexed_model_id: str = "", stream: bool = False):
        self.deployment_name = deployment_name
        self._method = method_name
        self._model_id = multiplexed_model_id
        self._stream = stream
        self._lock = threading.Lock()
        self._replicas: List[Any] = []
        self._fetched_at = 0.0
        self._inflight: Dict[int, int] = {}  # replica index -> in-flight

    def __reduce__(self):
        return (DeploymentHandle,
                (self.deployment_name, self._method, self._model_id,
                 self._stream))

    def options(self, method_name: Optional[str] = None, *,
                multiplexed_model_id: Optional[str] = None,
                stream: Optional[bool] = None) -> "DeploymentHandle":
        return DeploymentHandle(
            self.deployment_name,
            method_name if method_name is not None else self._method,
            multiplexed_model_id if multiplexed_model_id is not None
            else self._model_id,
            self._stream if stream is None else stream,
        )

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        return DeploymentHandle(self.deployment_name, name, self._model_id,
                                self._stream)

    def _refresh_replicas(self, force: bool = False):
        now = time.time()
        with self._lock:
            if not force and self._replicas and now - self._fetched_at < _REPLICA_CACHE_TTL_S:
                return
        import ray_tpu

        controller = ray_tpu.get_actor(CONTROLLER_NAME)
        names = ray_tpu.get(
            controller.get_replica_names.remote(self.deployment_name), timeout=30
        )
        replicas = []
        for n in names:
            try:
                replicas.append(ray_tpu.get_actor(n))
            except Exception:
                pass
        with self._lock:
            self._replicas = replicas
            self._fetched_at = now
            self._inflight = {i: 0 for i in range(len(replicas))}

    def _pick(self) -> tuple:
        """Power-of-two-choices on handle-local in-flight counts; requests
        tagged with a multiplexed model id get deterministic model→replica
        affinity instead, so each model's weights stay warm on one replica
        (reference: pow_2_scheduler.py multiplexed-model ranking)."""
        with self._lock:
            n = len(self._replicas)
            if n == 0:
                raise RuntimeError(
                    f"no replicas for deployment '{self.deployment_name}'"
                )
            if n == 1:
                idx = 0
            elif self._model_id:
                import zlib

                idx = zlib.crc32(self._model_id.encode()) % n
            else:
                a, b = random.sample(range(n), 2)
                idx = a if self._inflight.get(a, 0) <= self._inflight.get(b, 0) else b
            self._inflight[idx] = self._inflight.get(idx, 0) + 1
            return idx, self._replicas[idx]

    def _done(self, idx: int):
        with self._lock:
            if idx in self._inflight and self._inflight[idx] > 0:
                self._inflight[idx] -= 1

    def remote(self, *args, **kwargs) -> DeploymentResponse:
        deadline = time.time() + 60
        last_err: Optional[Exception] = None
        while time.time() < deadline:
            try:
                self._refresh_replicas()
                idx, replica = self._pick()
            except Exception as e:
                last_err = e
                time.sleep(0.25)
                continue
            try:
                if self._model_id:
                    kwargs = {**kwargs,
                              "__multiplexed_model_id": self._model_id}
                if self._stream:
                    import ray_tpu

                    sid = ray_tpu.get(
                        replica.start_stream.remote(
                            self._method, args, kwargs),
                        timeout=60,
                    )
                    return StreamingResponse(replica, sid, self, idx)
                ref = replica.handle_request.remote(
                    self._method, args, kwargs
                )
                # decrement when the call resolves (best effort, piggybacks
                # on the ref's completion via a daemon thread-free path: the
                # response object decrements on result()).
                resp = DeploymentResponse(ref)
                _attach_done(resp, self, idx)
                return resp
            except Exception as e:
                last_err = e
                self._refresh_replicas(force=True)
        raise RuntimeError(
            f"could not reach any replica of '{self.deployment_name}': {last_err}"
        )


def _attach_done(resp: DeploymentResponse, handle: DeploymentHandle, idx: int):
    original = resp.result
    done = {"fired": False}

    def result(timeout: Optional[float] = None):
        try:
            return original(timeout)
        finally:
            if not done["fired"]:
                done["fired"] = True
                handle._done(idx)

    resp.result = result
