"""ServeController: the reconciling control plane of Serve.

Counterpart of the reference's controller actor
(reference: python/ray/serve/_private/controller.py:86 with the
application/deployment state machines deployment_state.py and autoscaling
autoscaling_state.py / autoscaling_policy.py). One detached actor owns the
replica actors; a reconcile loop converges actual replicas to target
counts, restarts failed replicas, and applies request-based autoscaling.
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Dict, List, Optional

logger = logging.getLogger("ray_tpu.serve.controller")


class ServeController:
    def __init__(self):
        # deployment name -> state dict
        self._deployments: Dict[str, dict] = {}
        # app name -> {"route_prefix": str, "ingress": deployment name}
        self._apps: Dict[str, dict] = {}
        self._proxy = None
        self._proxy_port = 0
        self._proxy_lock = None  # created lazily on the actor loop
        self._loop_task = None
        # replica name -> (last push ts, meta) — pushed by the replicas
        self._metrics: Dict[str, tuple] = {}
        # long-poll config push (reference: serve/_private/long_poll.py):
        # handles block on poll_replica_names until the replica set changes
        self._versions: Dict[str, int] = {}
        self._change_events: Dict[str, asyncio.Event] = {}
        self._last_sets: Dict[str, tuple] = {}

    def _bump_version(self, dep_name: str):
        self._versions[dep_name] = self._versions.get(dep_name, 0) + 1
        ev = self._change_events.pop(dep_name, None)
        if ev is not None:
            ev.set()

    def _notify_changes(self):
        """Detect replica-set changes and wake long-pollers."""
        seen = set()
        for dep_name, st in self._deployments.items():
            seen.add(dep_name)
            cur = tuple(sorted(st["replicas"].keys()))
            if cur != self._last_sets.get(dep_name):
                self._last_sets[dep_name] = cur
                self._bump_version(dep_name)
        for dep_name in list(self._last_sets):
            if dep_name not in seen:
                del self._last_sets[dep_name]
                self._bump_version(dep_name)

    def _ensure_loop(self):
        if self._loop_task is None:
            self._loop_task = asyncio.ensure_future(self._reconcile_loop())

    # ------------------------------------------------------------ deploy API

    async def deploy_application(
        self,
        name: str,
        route_prefix: Optional[str],
        deployments: List[dict],
    ) -> str:
        """deployments: [{name, callable(bytes), init_args, init_kwargs,
        num_replicas, max_ongoing_requests, ray_actor_options,
        autoscaling_config}] — last entry is the ingress."""
        import hashlib

        self._ensure_loop()
        for spec in deployments:
            dep_name = spec["name"]
            st = self._deployments.get(dep_name)
            target = spec["num_replicas"]
            if spec.get("autoscaling_config"):
                target = max(
                    spec["autoscaling_config"].get("min_replicas", 1), 1
                )
            # Version = hash of code + config: redeploying changed code
            # rolls replicas (reference: deployment_state version-based
            # rollout).
            h = hashlib.sha1(spec["callable"])
            h.update(repr((spec.get("init_args"), spec.get("init_kwargs"),
                           spec.get("ray_actor_options"),
                           spec.get("max_ongoing_requests"))).encode())
            spec["version"] = h.hexdigest()
            # Idempotent redeploy of an unchanged autoscaled version keeps
            # the scaled-up target: resetting to min would kill loaded
            # replicas and force a re-climb.
            if (
                st is not None
                and spec.get("autoscaling_config")
                and st["spec"].get("version") == spec["version"]
            ):
                cfg = spec["autoscaling_config"]
                target = min(
                    max(st["target"], cfg.get("min_replicas", 1)),
                    cfg.get("max_replicas", 4),
                )
            self._deployments[dep_name] = {
                "spec": spec,
                "target": target,
                "replicas": (st or {}).get("replicas", {}),  # name -> rec
                "draining": (st or {}).get("draining", {}),
                "next_id": (st or {}).get("next_id", 0),
                "overload_since": None,
                "underload_since": None,
            }
        ingress = deployments[-1]["name"]
        self._apps[name] = {
            "route_prefix": route_prefix,
            "ingress": ingress,
            "deployments": [d["name"] for d in deployments],
        }
        await self._reconcile_once()
        return ingress

    async def delete_application(self, name: str):
        app = self._apps.pop(name, None)
        if app is None:
            return
        # Tear down only THIS app's deployments, and only those no
        # remaining app (ingress or inner) still references.
        in_use = set()
        for a in self._apps.values():
            in_use.update(a.get("deployments", [a["ingress"]]))
        import ray_tpu

        for dep_name in app.get("deployments", [app["ingress"]]):
            st = self._deployments.get(dep_name)
            if st is None or dep_name in in_use:
                continue
            for rname, rec in {
                **st["replicas"], **st.get("draining", {})
            }.items():
                self._metrics.pop(rname, None)
                try:
                    ray_tpu.kill(rec["handle"])
                except Exception:
                    pass
            del self._deployments[dep_name]
        self._notify_changes()

    async def report_replica_metrics(self, dep_name: str, replica_name: str, meta: dict):
        self._metrics[replica_name] = (time.time(), meta)

    # -------------------------------------------------------------- queries

    async def get_replica_names(self, deployment_name: str) -> List[str]:
        st = self._deployments.get(deployment_name)
        if st is None:
            return []
        return list(st["replicas"].keys())

    async def poll_replica_names(self, deployment_name: str,
                                 known_version: int = -1,
                                 timeout: float = 25.0) -> dict:
        """Long-poll: reply immediately when the caller's view is stale,
        otherwise hold the call until the replica set changes (or the
        timeout passes) — handles track replica churn push-style instead
        of polling a TTL cache (reference: serve/_private/long_poll.py)."""
        deadline = time.time() + timeout
        while True:
            v = self._versions.get(deployment_name, 0)
            names = await self.get_replica_names(deployment_name)
            if v != known_version:
                return {"version": v, "names": names}
            left = deadline - time.time()
            if left <= 0:
                return {"version": v, "names": names}
            ev = self._change_events.setdefault(
                deployment_name, asyncio.Event()
            )
            try:
                await asyncio.wait_for(ev.wait(), left)
            except asyncio.TimeoutError:
                pass

    async def get_app_info(self, name: str) -> Optional[dict]:
        return self._apps.get(name)

    async def list_apps(self) -> Dict[str, dict]:
        return dict(self._apps)

    async def get_proxy_port(self) -> int:
        return self._proxy_port

    async def ensure_rpc_ingress(self, port: int = 0) -> int:
        """Binary (msgpack-RPC) ingress beside the HTTP proxy (reference:
        the gRPC proxy, serve/_private/proxy.py:540)."""
        import ray_tpu

        await self.ensure_proxy(0)
        return await asyncio.get_running_loop().run_in_executor(
            None,
            lambda: ray_tpu.get(
                self._proxy.start_rpc_ingress.remote(port), timeout=60
            ),
        )

    async def ensure_proxy(self, port: int = 0) -> int:
        # Serialize concurrent callers: the second must await the first's
        # startup, not read a not-yet-assigned port 0.
        if self._proxy_lock is None:
            self._proxy_lock = asyncio.Lock()
        async with self._proxy_lock:
            if self._proxy is not None:
                return self._proxy_port
            import ray_tpu
            from ray_tpu.serve._proxy import ProxyActor

            proxy = (
                ray_tpu.remote(ProxyActor)
                .options(name="SERVE_PROXY", max_concurrency=64, num_cpus=0)
                .remote()
            )
            self._proxy_port = await asyncio.get_running_loop().run_in_executor(
                None, lambda: ray_tpu.get(proxy.start.remote(port), timeout=60)
            )
            self._proxy = proxy
            return self._proxy_port

    # ------------------------------------------------------------ reconcile

    async def _reconcile_loop(self):
        while True:
            try:
                await self._reconcile_once()
                await self._autoscale_once()
            except Exception:
                logger.exception("reconcile error")
            await asyncio.sleep(0.5)

    async def _reconcile_once(self):
        import ray_tpu
        from ray_tpu.serve._replica import Replica

        now = time.time()
        for dep_name, st in self._deployments.items():
            spec = st["spec"]
            # Version rollout: replicas of an older spec are replaced.
            for rname in list(st["replicas"]):
                rec = st["replicas"][rname]
                if rec.get("version") != spec["version"]:
                    logger.info("replica %s outdated; rolling", rname)
                    st["replicas"].pop(rname, None)
                    self._metrics.pop(rname, None)
                    try:
                        ray_tpu.kill(rec["handle"])
                    except Exception:
                        pass
            # Health = freshness of the replica's metric pushes. A pull-based
            # probe would queue behind user requests and mark busy replicas
            # dead; pushes keep flowing even under full load.
            for rname in list(st["replicas"]):
                rec = st["replicas"][rname]
                pushed = self._metrics.get(rname)
                stale = (
                    (pushed is None and now - rec["created"] > 20.0)
                    or (pushed is not None and now - pushed[0] > 6.0)
                    or (pushed is not None and not pushed[1].get("healthy", True))
                )
                if stale and pushed is None and self._actor_pending(rname):
                    # Still waiting for resources (e.g. the cluster
                    # autoscaler is booting a node): not a failure — killing
                    # it would flap the pending demand forever.
                    continue
                if stale:
                    logger.warning("replica %s unhealthy; replacing", rname)
                    st["replicas"].pop(rname, None)
                    self._metrics.pop(rname, None)
                    try:
                        ray_tpu.kill(rec["handle"])
                    except Exception:
                        pass
            while len(st["replicas"]) < st["target"]:
                rid = st["next_id"]
                st["next_id"] += 1
                rname = f"SERVE_REPLICA::{dep_name}::{rid}"
                opts = dict(spec.get("ray_actor_options") or {})
                opts.setdefault("num_cpus", 1)
                handle = (
                    ray_tpu.remote(Replica)
                    .options(
                        name=rname,
                        # +8 headroom over the user-request cap (which the
                        # replica self-gates): queue_len probes and metrics
                        # answer instantly even at saturation
                        max_concurrency=spec.get("max_ongoing_requests", 8) + 8,
                        **opts,
                    )
                    .remote(
                        {"callable": spec["callable"], "name": dep_name,
                         "max_ongoing": spec.get("max_ongoing_requests", 8)},
                        spec.get("init_args", ()),
                        spec.get("init_kwargs", {}),
                    )
                )
                handle.start_metrics_push.remote(
                    rname, spec.get("health_check_period_s", 2.0)
                )
                st["replicas"][rname] = {
                    "handle": handle,
                    "created": now,
                    "version": spec["version"],
                }
            # Scale-down drains gracefully: the replica leaves the
            # advertised set FIRST (long-pollers re-route within one poll),
            # then dies once its in-flight requests finish (or after a
            # 30 s grace) — a scale-down must not fail live requests.
            draining = st.setdefault("draining", {})
            while len(st["replicas"]) > st["target"]:
                rname = next(iter(st["replicas"]))
                rec = st["replicas"].pop(rname)
                rec["drain_started"] = now
                rec["drain_deadline"] = now + 30.0
                draining[rname] = rec
            for rname in list(draining):
                rec = draining[rname]
                pushed = self._metrics.get(rname)
                # Idle only counts from a push that POSTDATES the drain
                # start by a push period: a pre-drain ongoing=0 snapshot
                # says nothing about requests dispatched by handles that
                # had not yet seen the set change.
                idle = (
                    pushed is not None
                    and pushed[0] > rec["drain_started"] + 2.5
                    and pushed[1].get("ongoing", 1) == 0
                )
                if idle or now > rec["drain_deadline"]:
                    draining.pop(rname)
                    self._metrics.pop(rname, None)
                    try:
                        ray_tpu.kill(rec["handle"])
                    except Exception:
                        pass
        self._notify_changes()

    @staticmethod
    def _actor_pending(replica_name: str) -> bool:
        """True while the named replica actor is still awaiting placement."""
        try:
            from ray_tpu._private import worker as worker_mod

            r = worker_mod.global_worker.gcs.call(
                "GetActorByName", {"name": replica_name, "namespace": ""},
                timeout=5,
            )
            return bool(r.get("found")) and r["actor"]["state"] in (
                "PENDING_CREATION",
                "RESTARTING",
            )
        except Exception:
            return False

    async def _autoscale_once(self):
        """Request-based autoscaling (reference: autoscaling_policy.py —
        scale toward total_ongoing / target_ongoing_requests, bounded by
        min/max, with up/down delays)."""
        now = time.time()
        for dep_name, st in self._deployments.items():
            cfg = st["spec"].get("autoscaling_config")
            if not cfg or not st["replicas"]:
                continue
            ongoing = 0
            for rname in st["replicas"]:
                pushed = self._metrics.get(rname)
                if pushed is not None and now - pushed[0] < 3.0:
                    # "load" folds in engine-internal queues (serve.llm
                    # sequences waiting+running) on top of the request-level
                    # in-flight count; older replicas only push "ongoing"
                    meta = pushed[1]
                    ongoing += meta.get("load", meta.get("ongoing", 0))
            import math

            target_per = max(cfg.get("target_ongoing_requests", 2.0), 0.1)
            desired = max(
                cfg.get("min_replicas", 1),
                min(
                    cfg.get("max_replicas", 4),
                    math.ceil(ongoing / target_per)
                    if ongoing
                    else cfg.get("min_replicas", 1),
                ),
            )
            cur = st["target"]
            if desired > cur:
                if st["overload_since"] is None:
                    st["overload_since"] = now
                if now - st["overload_since"] >= cfg.get("upscale_delay_s", 2.0):
                    st["target"] = desired
                    st["overload_since"] = None
                    logger.info("autoscale %s: %d -> %d", dep_name, cur, desired)
            else:
                st["overload_since"] = None
            if desired < cur:
                if st["underload_since"] is None:
                    st["underload_since"] = now
                if now - st["underload_since"] >= cfg.get("downscale_delay_s", 10.0):
                    st["target"] = desired
                    st["underload_since"] = None
                    logger.info("autoscale %s: %d -> %d", dep_name, cur, desired)
            else:
                st["underload_since"] = None
