"""Client for the serve binary RPC ingress (the gRPC-ingress analogue,
reference: serve/_private/proxy.py:540 gRPC proxy + generated stubs).

    from ray_tpu import serve
    from ray_tpu.serve.rpc_ingress import RpcIngressClient

    port = serve.start_rpc_ingress()
    client = RpcIngressClient("127.0.0.1", port)
    out = client.call("default", arg1, method="predict", kw=2)
    client.close()

One persistent multiplexed connection; arbitrary python payloads ride
cloudpickle both ways; application errors surface as RpcIngressError.
"""

from __future__ import annotations

from typing import Any

import cloudpickle

from ray_tpu._private.rpc import IoThread, RpcClient


class RpcIngressError(RuntimeError):
    pass


class RpcBackpressureError(RpcIngressError):
    """Admission rejected by the llm engine (structured shed-load reply,
    serve/llm admission control): carries the numbers a client needs to
    back off sensibly instead of hammering a saturated replica."""

    def __init__(self, message: str, queue_depth: int = 0,
                 max_waiting: int = 0, kv_utilization: float = 0.0):
        super().__init__(message)
        self.queue_depth = queue_depth
        self.max_waiting = max_waiting
        self.kv_utilization = kv_utilization


class RpcIngressClient:
    def __init__(self, host: str, port: int):
        self._io = IoThread.current()
        self._client = RpcClient(host, port)
        self._io.run(self._client.connect())

    def call(self, app: str, *args, method: str = "__call__",
             timeout: float = 300.0, **kwargs) -> Any:
        req = {
            "app": app,
            "method": method,
            "timeout": timeout,
            "args": cloudpickle.dumps(args) if args else b"",
            "kwargs": cloudpickle.dumps(kwargs) if kwargs else b"",
        }
        reply = self._io.run(
            self._client.call("ServeCall", req, timeout=timeout),
            timeout=timeout + 10,
        )
        if reply.get("error"):
            raise RpcIngressError(reply["error"])
        return cloudpickle.loads(reply["result"])

    def call_streaming(self, app: str, *args, method: str = "__call__",
                       timeout: float = 300.0, max_items_per_pull: int = 16,
                       **kwargs) -> "RpcStream":
        """Call a generator deployment; returns an iterator that pulls
        chunks over the multiplexed connection. Pull-based: a slow consumer
        backpressures the replica-side generator (it only advances when
        pulled). Mirrors the reference's gRPC streaming proxy
        (serve/_private/proxy.py:540)."""
        req = {
            "app": app,
            "method": method,
            "timeout": timeout,
            "stream": True,
            "args": cloudpickle.dumps(args) if args else b"",
            "kwargs": cloudpickle.dumps(kwargs) if kwargs else b"",
        }
        reply = self._io.run(
            self._client.call("ServeCall", req, timeout=timeout),
            timeout=timeout + 10,
        )
        if reply.get("error"):
            raise RpcIngressError(reply["error"])
        return RpcStream(self, reply["stream_id"], timeout,
                         max_items_per_pull)

    def llm_stream(self, prompt, *, app: str = "llm", timeout: float = 300.0,
                   max_tokens_per_pull: int = 0, **sampling) -> "LlmStream":
        """Open a continuous-batching generation stream (serve/llm).

        The prompt ships as ONE raw out-of-band frame of int32 token ids
        (str prompts become UTF-8 byte ids) and token deltas come back the
        same way — the proxy never re-serializes either direction.
        ``sampling``: max_tokens, temperature, top_k, eos_id, seed.
        Raises :class:`RpcBackpressureError` when admission is shed.
        """
        import numpy as np

        if isinstance(prompt, str):
            ids = np.asarray(list(prompt.encode("utf-8")), dtype=np.int32)
        else:
            ids = np.asarray(list(prompt), dtype=np.int32)
        req = {"app": app, "timeout": timeout, "sampling": sampling}
        reply = self._io.run(
            self._client.call("ServeLlmOpen", req, timeout=timeout,
                              oob=ids.tobytes()),
            timeout=timeout + 10,
        )
        if reply.get("error"):
            if reply.get("backpressure"):
                raise RpcBackpressureError(
                    reply["error"],
                    queue_depth=reply.get("queue_depth", 0),
                    max_waiting=reply.get("max_waiting", 0),
                    kv_utilization=reply.get("kv_utilization", 0.0),
                )
            raise RpcIngressError(reply["error"])
        return LlmStream(self, reply["stream_id"], timeout,
                         max_tokens_per_pull)

    def close(self):
        try:
            self._io.run(self._client.close())
        except Exception:
            pass


class RpcStream:
    """Client side of a streaming ingress call."""

    def __init__(self, client: RpcIngressClient, stream_id: str,
                 timeout: float, max_items: int):
        self._client = client
        self._sid = stream_id
        self._timeout = timeout
        self._max_items = max_items
        self._buf: list = []
        self._done = False

    def __iter__(self):
        return self

    def __next__(self) -> Any:
        while not self._buf:
            if self._done:
                raise StopIteration
            reply = self._client._io.run(
                self._client._client.call(
                    "ServeStreamNext",
                    {"stream_id": self._sid,
                     "max_items": self._max_items,
                     "timeout": self._timeout},
                    timeout=self._timeout,
                ),
                timeout=self._timeout + 10,
            )
            if reply.get("error"):
                self._done = True
                raise RpcIngressError(reply["error"])
            self._buf.extend(reply["items"])
            self._done = reply["done"]
        return cloudpickle.loads(self._buf.pop(0))

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def close(self):
        """Abandon the stream (frees the proxy + replica state)."""
        if self._done:
            return
        self._done = True
        try:
            self._client._io.run(
                self._client._client.call(
                    "ServeStreamCancel", {"stream_id": self._sid}, timeout=10
                ),
                timeout=15,
            )
        except Exception:
            pass


class LlmStream:
    """Client side of a serve/llm token stream: iterate (or async-iterate)
    int token ids. Each pull is one ``ServeLlmNext`` round-trip whose token
    payload arrives as a raw out-of-band frame (int32 little-endian) —
    decoded here with one ``np.frombuffer``, zero copies upstream of the
    socket. ``finish_reason`` is set once the stream ends."""

    def __init__(self, client: RpcIngressClient, stream_id: str,
                 timeout: float, max_tokens_per_pull: int = 0):
        self._client = client
        self._sid = stream_id
        self._timeout = timeout
        self._max_tokens = max_tokens_per_pull
        self._buf: list = []
        self._done = False
        self._owns_client = False
        self.finish_reason: str | None = None

    def __iter__(self):
        return self

    def __next__(self) -> int:
        import numpy as np

        while not self._buf:
            if self._done:
                self._finish()
                raise StopIteration
            reply = self._client._io.run(
                self._client._client.call(
                    "ServeLlmNext",
                    {"stream_id": self._sid,
                     "max_tokens": self._max_tokens},
                    timeout=self._timeout,
                ),
                timeout=self._timeout + 10,
            )
            if reply.get("error"):
                self._done = True
                self._finish()
                raise RpcIngressError(reply["error"])
            raw = reply.get("_oob") or b""
            self._buf.extend(np.frombuffer(bytes(raw), dtype=np.int32)
                             .tolist())
            self._done = reply["done"]
            if self._done:
                self.finish_reason = reply.get("finish_reason")
        return self._buf.pop(0)

    # async iteration: the blocking pull runs in the default executor so
    # `async for tok in serve.llm.stream(...)` works from an event loop
    def __aiter__(self):
        return self

    async def __anext__(self) -> int:
        import asyncio

        try:
            return await asyncio.get_running_loop().run_in_executor(
                None, self.__next__)
        except StopIteration:
            raise StopAsyncIteration from None

    def _finish(self):
        if self._owns_client and self._client is not None:
            try:
                self._client.close()
            except Exception:
                pass
            self._client = None

    def close(self):
        """Abandon mid-stream: the proxy cancels the sequence so its KV
        blocks return to the pool immediately."""
        if not self._done:
            self._done = True
            try:
                self._client._io.run(
                    self._client._client.call(
                        "ServeLlmCancel", {"stream_id": self._sid},
                        timeout=10),
                    timeout=15,
                )
            except Exception:
                pass
        self._finish()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
