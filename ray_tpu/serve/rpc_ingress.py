"""Client for the serve binary RPC ingress (the gRPC-ingress analogue,
reference: serve/_private/proxy.py:540 gRPC proxy + generated stubs).

    from ray_tpu import serve
    from ray_tpu.serve.rpc_ingress import RpcIngressClient

    port = serve.start_rpc_ingress()
    client = RpcIngressClient("127.0.0.1", port)
    out = client.call("default", arg1, method="predict", kw=2)
    client.close()

One persistent multiplexed connection; arbitrary python payloads ride
cloudpickle both ways; application errors surface as RpcIngressError.
"""

from __future__ import annotations

from typing import Any

import cloudpickle

from ray_tpu._private.rpc import IoThread, RpcClient


class RpcIngressError(RuntimeError):
    pass


class RpcIngressClient:
    def __init__(self, host: str, port: int):
        self._io = IoThread.current()
        self._client = RpcClient(host, port)
        self._io.run(self._client.connect())

    def call(self, app: str, *args, method: str = "__call__",
             timeout: float = 300.0, **kwargs) -> Any:
        req = {
            "app": app,
            "method": method,
            "timeout": timeout,
            "args": cloudpickle.dumps(args) if args else b"",
            "kwargs": cloudpickle.dumps(kwargs) if kwargs else b"",
        }
        reply = self._io.run(
            self._client.call("ServeCall", req, timeout=timeout),
            timeout=timeout + 10,
        )
        if reply.get("error"):
            raise RpcIngressError(reply["error"])
        return cloudpickle.loads(reply["result"])

    def call_streaming(self, app: str, *args, method: str = "__call__",
                       timeout: float = 300.0, max_items_per_pull: int = 16,
                       **kwargs) -> "RpcStream":
        """Call a generator deployment; returns an iterator that pulls
        chunks over the multiplexed connection. Pull-based: a slow consumer
        backpressures the replica-side generator (it only advances when
        pulled). Mirrors the reference's gRPC streaming proxy
        (serve/_private/proxy.py:540)."""
        req = {
            "app": app,
            "method": method,
            "timeout": timeout,
            "stream": True,
            "args": cloudpickle.dumps(args) if args else b"",
            "kwargs": cloudpickle.dumps(kwargs) if kwargs else b"",
        }
        reply = self._io.run(
            self._client.call("ServeCall", req, timeout=timeout),
            timeout=timeout + 10,
        )
        if reply.get("error"):
            raise RpcIngressError(reply["error"])
        return RpcStream(self, reply["stream_id"], timeout,
                         max_items_per_pull)

    def close(self):
        try:
            self._io.run(self._client.close())
        except Exception:
            pass


class RpcStream:
    """Client side of a streaming ingress call."""

    def __init__(self, client: RpcIngressClient, stream_id: str,
                 timeout: float, max_items: int):
        self._client = client
        self._sid = stream_id
        self._timeout = timeout
        self._max_items = max_items
        self._buf: list = []
        self._done = False

    def __iter__(self):
        return self

    def __next__(self) -> Any:
        while not self._buf:
            if self._done:
                raise StopIteration
            reply = self._client._io.run(
                self._client._client.call(
                    "ServeStreamNext",
                    {"stream_id": self._sid,
                     "max_items": self._max_items,
                     "timeout": self._timeout},
                    timeout=self._timeout,
                ),
                timeout=self._timeout + 10,
            )
            if reply.get("error"):
                self._done = True
                raise RpcIngressError(reply["error"])
            self._buf.extend(reply["items"])
            self._done = reply["done"]
        return cloudpickle.loads(self._buf.pop(0))

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def close(self):
        """Abandon the stream (frees the proxy + replica state)."""
        if self._done:
            return
        self._done = True
        try:
            self._client._io.run(
                self._client._client.call(
                    "ServeStreamCancel", {"stream_id": self._sid}, timeout=10
                ),
                timeout=15,
            )
        except Exception:
            pass
