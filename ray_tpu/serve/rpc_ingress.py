"""Client for the serve binary RPC ingress (the gRPC-ingress analogue,
reference: serve/_private/proxy.py:540 gRPC proxy + generated stubs).

    from ray_tpu import serve
    from ray_tpu.serve.rpc_ingress import RpcIngressClient

    port = serve.start_rpc_ingress()
    client = RpcIngressClient("127.0.0.1", port)
    out = client.call("default", arg1, method="predict", kw=2)
    client.close()

One persistent multiplexed connection; arbitrary python payloads ride
cloudpickle both ways; application errors surface as RpcIngressError.
"""

from __future__ import annotations

from typing import Any

import cloudpickle

from ray_tpu._private.rpc import IoThread, RpcClient


class RpcIngressError(RuntimeError):
    pass


class RpcIngressClient:
    def __init__(self, host: str, port: int):
        self._io = IoThread.current()
        self._client = RpcClient(host, port)
        self._io.run(self._client.connect())

    def call(self, app: str, *args, method: str = "__call__",
             timeout: float = 300.0, **kwargs) -> Any:
        req = {
            "app": app,
            "method": method,
            "timeout": timeout,
            "args": cloudpickle.dumps(args) if args else b"",
            "kwargs": cloudpickle.dumps(kwargs) if kwargs else b"",
        }
        reply = self._io.run(
            self._client.call("ServeCall", req, timeout=timeout),
            timeout=timeout + 10,
        )
        if reply.get("error"):
            raise RpcIngressError(reply["error"])
        return cloudpickle.loads(reply["result"])

    def close(self):
        try:
            self._io.run(self._client.close())
        except Exception:
            pass
