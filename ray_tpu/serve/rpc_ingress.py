"""Client for the serve binary RPC ingress (the gRPC-ingress analogue,
reference: serve/_private/proxy.py:540 gRPC proxy + generated stubs).

    from ray_tpu import serve
    from ray_tpu.serve.rpc_ingress import RpcIngressClient

    port = serve.start_rpc_ingress()
    client = RpcIngressClient("127.0.0.1", port)
    out = client.call("default", arg1, method="predict", kw=2)
    client.close()

One persistent multiplexed connection; arbitrary python payloads ride
cloudpickle both ways; application errors surface as RpcIngressError.
"""

from __future__ import annotations

from typing import Any

import cloudpickle

from ray_tpu._private.rpc import IoThread, RpcClient


class RpcIngressError(RuntimeError):
    pass


class RpcBackpressureError(RpcIngressError):
    """Admission rejected by the llm engine (structured shed-load reply,
    serve/llm admission control): carries the numbers a client needs to
    back off sensibly instead of hammering a saturated replica."""

    def __init__(self, message: str, queue_depth: int = 0,
                 max_waiting: int = 0, kv_utilization: float = 0.0):
        super().__init__(message)
        self.queue_depth = queue_depth
        self.max_waiting = max_waiting
        self.kv_utilization = kv_utilization


class ReplicaDiedMidStreamError(RpcIngressError):
    """The replica pinned to this llm stream died and failover was
    disabled or exhausted its retry budget. Carries everything a caller
    needs to resume by hand: the stream id and the tokens generated so
    far (resubmit ``prompt + tokens_generated`` with the remaining
    budget — exactly what the built-in failover does automatically)."""

    def __init__(self, message: str, stream_id: str = "",
                 tokens_generated=None):
        super().__init__(message)
        self.stream_id = stream_id
        self.tokens_generated = list(tokens_generated or [])


class LlmStreamTimeoutError(RpcIngressError, TimeoutError):
    """A token pull exceeded ``RTPU_llm_stream_timeout_s`` (stability
    contract flag). Structured — stream id + tokens received — instead of
    the raw transport timeout, so callers can tell a stalled stream from
    a dead connection and decide whether the partial output is usable."""

    def __init__(self, message: str, stream_id: str = "",
                 tokens_received: int = 0, timeout_s: float = 0.0):
        super().__init__(message)
        self.stream_id = stream_id
        self.tokens_received = tokens_received
        self.timeout_s = timeout_s


_failover_counter = None


def _note_failover(deployment: str):
    """Bump ``ray_tpu_serve_failovers_total`` (stability contract,
    util/metrics.py) — one per successful mid-stream resubmission or
    idempotent-handle ActorDiedError retry."""
    global _failover_counter
    try:
        from ray_tpu.util.metrics import Counter

        if _failover_counter is None:
            _failover_counter = Counter(
                "ray_tpu_serve_failovers_total",
                "mid-stream llm failovers + idempotent handle retries",
                tag_keys=("deployment",))
        _failover_counter.inc(1, tags={"deployment": deployment})
    except Exception:
        pass


class RpcIngressClient:
    def __init__(self, host: str, port: int):
        self._io = IoThread.current()
        self._client = RpcClient(host, port)
        self._io.run(self._client.connect())

    def call(self, app: str, *args, method: str = "__call__",
             timeout: float = 300.0, **kwargs) -> Any:
        req = {
            "app": app,
            "method": method,
            "timeout": timeout,
            "args": cloudpickle.dumps(args) if args else b"",
            "kwargs": cloudpickle.dumps(kwargs) if kwargs else b"",
        }
        reply = self._io.run(
            self._client.call("ServeCall", req, timeout=timeout),
            timeout=timeout + 10,
        )
        if reply.get("error"):
            raise RpcIngressError(reply["error"])
        return cloudpickle.loads(reply["result"])

    def call_streaming(self, app: str, *args, method: str = "__call__",
                       timeout: float = 300.0, max_items_per_pull: int = 16,
                       **kwargs) -> "RpcStream":
        """Call a generator deployment; returns an iterator that pulls
        chunks over the multiplexed connection. Pull-based: a slow consumer
        backpressures the replica-side generator (it only advances when
        pulled). Mirrors the reference's gRPC streaming proxy
        (serve/_private/proxy.py:540)."""
        req = {
            "app": app,
            "method": method,
            "timeout": timeout,
            "stream": True,
            "args": cloudpickle.dumps(args) if args else b"",
            "kwargs": cloudpickle.dumps(kwargs) if kwargs else b"",
        }
        reply = self._io.run(
            self._client.call("ServeCall", req, timeout=timeout),
            timeout=timeout + 10,
        )
        if reply.get("error"):
            raise RpcIngressError(reply["error"])
        return RpcStream(self, reply["stream_id"], timeout,
                         max_items_per_pull)

    def llm_stream(self, prompt, *, app: str = "llm", timeout: float = 300.0,
                   max_tokens_per_pull: int = 0, **sampling) -> "LlmStream":
        """Open a continuous-batching generation stream (serve/llm).

        The prompt ships as ONE raw out-of-band frame of int32 token ids
        (str prompts become UTF-8 byte ids) and token deltas come back the
        same way — the proxy never re-serializes either direction.
        ``sampling``: max_tokens, temperature, top_k, eos_id, seed.
        Raises :class:`RpcBackpressureError` when admission is shed.
        """
        if isinstance(prompt, str):
            ids = list(prompt.encode("utf-8"))
        else:
            ids = [int(t) for t in prompt]
        reply = self._llm_open(app, ids, sampling, timeout)
        return LlmStream(self, reply["stream_id"], timeout,
                         max_tokens_per_pull, app=app, prompt_ids=ids,
                         sampling=sampling)

    def _llm_open(self, app: str, ids, sampling: dict,
                  timeout: float) -> dict:
        """One ``ServeLlmOpen`` round-trip (prompt as a raw OOB frame);
        raises the structured admission/ingress errors. Shared by the
        initial open and the failover resubmission path."""
        import numpy as np

        req = {"app": app, "timeout": timeout, "sampling": sampling}
        reply = self._io.run(
            self._client.call(
                "ServeLlmOpen", req, timeout=timeout,
                oob=np.asarray(ids, dtype=np.int32).tobytes()),
            timeout=timeout + 10,
        )
        if reply.get("error"):
            if reply.get("backpressure"):
                raise RpcBackpressureError(
                    reply["error"],
                    queue_depth=reply.get("queue_depth", 0),
                    max_waiting=reply.get("max_waiting", 0),
                    kv_utilization=reply.get("kv_utilization", 0.0),
                )
            raise RpcIngressError(reply["error"])
        return reply

    def close(self):
        try:
            self._io.run(self._client.close())
        except Exception:
            pass


class RpcStream:
    """Client side of a streaming ingress call."""

    def __init__(self, client: RpcIngressClient, stream_id: str,
                 timeout: float, max_items: int):
        self._client = client
        self._sid = stream_id
        self._timeout = timeout
        self._max_items = max_items
        self._buf: list = []
        self._done = False

    def __iter__(self):
        return self

    def __next__(self) -> Any:
        while not self._buf:
            if self._done:
                raise StopIteration
            reply = self._client._io.run(
                self._client._client.call(
                    "ServeStreamNext",
                    {"stream_id": self._sid,
                     "max_items": self._max_items,
                     "timeout": self._timeout},
                    timeout=self._timeout,
                ),
                timeout=self._timeout + 10,
            )
            if reply.get("error"):
                self._done = True
                raise RpcIngressError(reply["error"])
            self._buf.extend(reply["items"])
            self._done = reply["done"]
        return cloudpickle.loads(self._buf.pop(0))

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def close(self):
        """Abandon the stream (frees the proxy + replica state)."""
        if self._done:
            return
        self._done = True
        try:
            self._client._io.run(
                self._client._client.call(
                    "ServeStreamCancel", {"stream_id": self._sid}, timeout=10
                ),
                timeout=15,
            )
        except Exception:
            pass


class LlmStream:
    """Client side of a serve/llm token stream: iterate (or async-iterate)
    int token ids. Each pull is one ``ServeLlmNext`` round-trip whose token
    payload arrives as a raw out-of-band frame (int32 little-endian) —
    decoded here with one ``np.frombuffer``, zero copies upstream of the
    socket. ``finish_reason`` is set once the stream ends.

    **Failover**: when the pinned replica dies mid-stream (the proxy
    replies with ``replica_died``), the remaining generation is
    transparently resubmitted to a surviving replica with capped
    exponential backoff + jitter — the resubmitted prompt is
    ``prompt + tokens_generated_so_far``, so recovery rides the prefix
    cache and only re-prefills the un-shared tail, and greedy streams stay
    byte-equal to a fault-free run (the engine's recompute-equivalence
    property). Budget: ``RTPU_serve_failover_retries`` attempts per death;
    exhaustion raises :class:`ReplicaDiedMidStreamError` carrying the
    tokens generated so far. Pulls are bounded by
    ``RTPU_llm_stream_timeout_s`` and raise a structured
    :class:`LlmStreamTimeoutError` on expiry."""

    def __init__(self, client: RpcIngressClient, stream_id: str,
                 timeout: float, max_tokens_per_pull: int = 0, *,
                 app: str | None = None, prompt_ids=None,
                 sampling: dict | None = None):
        self._client = client
        self._sid = stream_id
        self._timeout = timeout
        self._max_tokens = max_tokens_per_pull
        self._buf: list = []
        self._done = False
        self._owns_client = False
        self._app = app
        self._prompt = list(prompt_ids) if prompt_ids is not None else None
        self._sampling = dict(sampling or {})
        self._received: list = []  # all tokens this stream has produced
        self.failovers = 0
        self.finish_reason: str | None = None

    def __iter__(self):
        return self

    def __next__(self) -> int:
        import asyncio
        import concurrent.futures

        import numpy as np

        from ray_tpu._private.config import RTPU_CONFIG

        while not self._buf:
            if self._done:
                self._finish()
                raise StopIteration
            pull_timeout = min(float(RTPU_CONFIG.llm_stream_timeout_s),
                               self._timeout)
            try:
                reply = self._client._io.run(
                    self._client._client.call(
                        "ServeLlmNext",
                        {"stream_id": self._sid,
                         "max_tokens": self._max_tokens},
                        timeout=pull_timeout,
                    ),
                    timeout=pull_timeout + 10,
                )
            except (asyncio.TimeoutError,
                    concurrent.futures.TimeoutError) as e:
                self._done = True
                self._finish()
                raise LlmStreamTimeoutError(
                    f"llm stream {self._sid} pull exceeded "
                    f"{pull_timeout:.0f}s (RTPU_llm_stream_timeout_s) after "
                    f"{len(self._received)} tokens",
                    stream_id=self._sid,
                    tokens_received=len(self._received),
                    timeout_s=pull_timeout,
                ) from e
            if reply.get("error"):
                if reply.get("replica_died") and self._failover():
                    continue  # resubmitted on a surviving replica
                self._done = True
                self._finish()
                if reply.get("replica_died"):
                    raise ReplicaDiedMidStreamError(
                        f"replica died mid-stream after "
                        f"{len(self._received)} tokens: {reply['error']}",
                        stream_id=self._sid,
                        tokens_generated=self._received,
                    )
                raise RpcIngressError(reply["error"])
            raw = reply.get("_oob") or b""
            toks = np.frombuffer(bytes(raw), dtype=np.int32).tolist()
            self._buf.extend(toks)
            self._received.extend(toks)
            self._done = reply["done"]
            if self._done:
                self.finish_reason = reply.get("finish_reason")
        return self._buf.pop(0)

    def _failover(self) -> bool:
        """Resubmit the remaining generation to a surviving replica.
        Returns True when a new stream is open (the pull loop continues
        against it); False when failover is impossible or exhausted."""
        import random
        import time

        from ray_tpu._private import flight_recorder as _fr
        from ray_tpu._private.config import RTPU_CONFIG

        retries = int(RTPU_CONFIG.serve_failover_retries)
        if self._prompt is None or self._app is None or retries <= 0:
            return False
        sampling = dict(self._sampling)
        max_tokens = int(sampling.get("max_tokens", 0) or 0)
        if max_tokens:
            remaining = max_tokens - len(self._received)
            if remaining <= 0:
                # the death raced the final pull: everything was generated
                self._done = True
                self.finish_reason = "length"
                return True
            sampling["max_tokens"] = remaining
        # prompt + generated-so-far: the surviving replica re-prefills only
        # the blocks the prefix cache does not already share
        prompt = list(self._prompt) + [int(t) for t in self._received]
        base = float(RTPU_CONFIG.serve_failover_backoff_s)
        cap = float(RTPU_CONFIG.serve_failover_backoff_max_s)
        last: Exception | None = None
        for attempt in range(retries):
            # capped exponential backoff with +/-50% jitter: replacement
            # replicas take seconds to boot, and a storm of failing-over
            # clients must not synchronize into retry waves
            time.sleep(min(cap, base * (2 ** attempt))
                       * (0.5 + random.random() / 2))
            try:
                reply = self._client._llm_open(
                    self._app, prompt, sampling, self._timeout)
            except Exception as e:  # noqa: BLE001 — includes backpressure
                last = e            # and no-replicas-yet; retry with backoff
                continue
            old = self._sid
            self._sid = reply["stream_id"]
            self.failovers += 1
            _fr.record("serve.failover", b"",
                       f"{self._app} {old}->{self._sid} "
                       f"tokens={len(self._received)} attempt={attempt + 1}")
            _note_failover(self._app)
            return True
        self._done = True
        self._finish()
        raise ReplicaDiedMidStreamError(
            f"replica died mid-stream after {len(self._received)} tokens "
            f"and failover exhausted {retries} attempts: {last}",
            stream_id=self._sid,
            tokens_generated=self._received,
        )

    # async iteration: the blocking pull runs in the default executor so
    # `async for tok in serve.llm.stream(...)` works from an event loop
    def __aiter__(self):
        return self

    async def __anext__(self) -> int:
        import asyncio

        try:
            return await asyncio.get_running_loop().run_in_executor(
                None, self.__next__)
        except StopIteration:
            raise StopAsyncIteration from None

    def _finish(self):
        if self._owns_client and self._client is not None:
            try:
                self._client.close()
            except Exception:
                pass
            self._client = None

    def close(self):
        """Abandon mid-stream: the proxy cancels the sequence so its KV
        blocks return to the pool immediately."""
        if not self._done:
            self._done = True
            try:
                self._client._io.run(
                    self._client._client.call(
                        "ServeLlmCancel", {"stream_id": self._sid},
                        timeout=10),
                    timeout=15,
                )
            except Exception:
                pass
        self._finish()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
