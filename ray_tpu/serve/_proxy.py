"""HTTP proxy: routes requests to deployment handles.

Counterpart of the reference's ProxyActor
(reference: python/ray/serve/_private/proxy.py:1130 — per-node HTTP
ingress; uvicorn there, a dependency-free asyncio HTTP/1.1 listener here).
Routing: longest matching route_prefix wins
(reference: proxy_router.py). Bodies are passed to the ingress deployment:
JSON bodies decode to Python values, anything else arrives as bytes.
"""

from __future__ import annotations

import asyncio
import json
import logging
from typing import Optional, Tuple

logger = logging.getLogger("ray_tpu.serve.proxy")


class ProxyActor:
    _ROUTE_TTL_S = 1.0

    def __init__(self):
        from concurrent.futures import ThreadPoolExecutor

        self._server: Optional[asyncio.AbstractServer] = None
        self._port = 0
        self._routes: dict = {}  # app name -> info
        self._routes_at = 0.0
        self._handles: dict = {}  # ingress name -> DeploymentHandle
        # Dedicated pool: the default loop executor caps at ~min(32, cpus+4)
        # threads, which would head-of-line-block cheap requests (and route
        # refreshes) behind slow ones.
        self._pool = ThreadPoolExecutor(max_workers=64, thread_name_prefix="proxy")
        # Streams block a thread between item pulls (up to the whole
        # response lifetime): give them their own pool so slow streams can
        # never starve routing/non-streaming traffic out of self._pool.
        self._stream_pool = ThreadPoolExecutor(
            max_workers=32, thread_name_prefix="proxy-stream")
        self._stream_handles: dict = {}  # ingress name -> streaming handle

    async def start(self, port: int = 0) -> int:
        self._server = await asyncio.start_server(self._handle, "127.0.0.1", port)
        self._port = self._server.sockets[0].getsockname()[1]
        logger.info("serve proxy listening on %d", self._port)
        return self._port

    async def start_rpc_ingress(self, port: int = 0) -> int:
        """Binary ingress on the framework's msgpack-RPC framing — the
        counterpart of the reference's gRPC proxy (serve/_private/
        proxy.py:540): non-HTTP clients call deployments with binary
        payloads and typed errors, multiplexed over one connection.
        Method: ServeCall {app, method?, args(pickled), kwargs(pickled)}
        -> {result: pickled} | {error, app_error}."""
        # serialize concurrent starters (async actors interleave): the
        # second caller must await the first's startup, not read an
        # unassigned port
        if getattr(self, "_rpc_lock", None) is None:
            self._rpc_lock = asyncio.Lock()
        async with self._rpc_lock:
            if getattr(self, "_rpc_server", None) is not None:
                return self._rpc_port
            from ray_tpu._private.rpc import RpcServer

            srv = RpcServer("127.0.0.1")
            srv.register("ServeCall", self._handle_rpc_call)
            srv.register("ServeStreamNext", self._handle_rpc_stream_next)
            srv.register("ServeStreamCancel", self._handle_rpc_stream_cancel)
            # llm token streaming (serve/llm): prompts arrive as raw OOB
            # frames, token deltas leave as raw OOB frames — the proxy
            # forwards the replica's int32 buffer without re-serializing
            srv.register("ServeLlmOpen", self._handle_llm_open)
            srv.register("ServeLlmNext", self._handle_llm_next)
            srv.register("ServeLlmCancel", self._handle_llm_cancel)
            self._rpc_port = await srv.start(port)
            self._rpc_server = srv
            logger.info("serve rpc ingress on %d", self._rpc_port)
            return self._rpc_port

    async def _handle_rpc_call(self, req):
        import cloudpickle

        self._sweep_rpc_streams()
        app = req.get("app")
        info = None
        if app is not None:
            # refresh via _route's TTL machinery, then resolve by app name
            await self._route("/")
            info = self._routes.get(app)
        if info is None:
            return {"error": f"no such application {app!r}", "app_error": False}
        ingress = info["ingress"]
        from ray_tpu.serve._handle import DeploymentHandle

        method = req.get("method") or "__call__"
        # cache per (ingress, method, stream): a fresh handle per request
        # would leak a long-poll thread each time and reset the p2c state
        stream = bool(req.get("stream"))
        if not hasattr(self, "_rpc_handles"):
            self._rpc_handles = {}
        handle = self._rpc_handles.get((ingress, method, stream))
        if handle is None:
            handle = DeploymentHandle(ingress, method_name=method)
            if stream:
                handle = handle.options(stream=True)
            self._rpc_handles[(ingress, method, stream)] = handle
        args = cloudpickle.loads(req["args"]) if req.get("args") else ()
        kwargs = cloudpickle.loads(req["kwargs"]) if req.get("kwargs") else {}
        # honor the client's deadline (capped): a hung replica must not
        # pin a shared proxy-pool thread for 300s when the caller gave up
        # after 10
        timeout = min(float(req.get("timeout") or 300.0), 300.0)
        loop = asyncio.get_running_loop()

        if stream:
            # Streaming over the multiplexed connection (reference:
            # serve/_private/proxy.py:540 gRPCProxy streaming): the call
            # opens a replica-side generator; the CLIENT pulls batches via
            # ServeStreamNext at its own pace — pull-based, so a slow
            # consumer naturally backpressures the replica (it only
            # advances when pulled).
            def _open():
                return handle.remote(*args, **kwargs)

            # the dedicated stream pool: slow streams must never starve
            # routing/non-streaming traffic out of self._pool. Submit the
            # CONCURRENT future (not run_in_executor) so a timeout can
            # still observe the late result and close it — wait_for's
            # cancellation never reaches a running pool thread.
            fut = self._stream_pool.submit(_open)
            try:
                resp = await asyncio.wait_for(
                    asyncio.wrap_future(fut), timeout + 10)
            except (asyncio.TimeoutError, asyncio.CancelledError):
                # an open that completes after the client gave up would
                # hold its replica in-flight slot forever: close it
                def _abandon(f):
                    try:
                        f.result().close()
                    except BaseException:
                        pass

                fut.add_done_callback(_abandon)
                return {"error": "timed out opening stream",
                        "app_error": True}
            except Exception as e:  # noqa: BLE001
                return {"error": str(e), "app_error": True}
            import threading as _threading
            import time as _time
            import uuid as _uuid

            if not hasattr(self, "_rpc_streams"):
                self._rpc_streams = {}
            sid = _uuid.uuid4().hex
            self._rpc_streams[sid] = {"it": resp, "ts": _time.time(),
                                      "lock": _threading.Lock()}
            return {"stream_id": sid}

        def _call():
            return handle.remote(*args, **kwargs).result(timeout=timeout)

        try:
            result = await loop.run_in_executor(self._pool, _call)
        except Exception as e:  # noqa: BLE001 — typed back to the client
            return {"error": str(e), "app_error": True}
        return {"result": cloudpickle.dumps(result)}

    def _sweep_rpc_streams(self, idle_s: float = 600.0):
        '''Drop streams an absent client stopped pulling (their
        replica-side generators are cancelled).'''
        import time as _time

        now = _time.time()
        for sid, rec in list(getattr(self, "_rpc_streams", {}).items()):
            if now - rec["ts"] > idle_s:
                self._rpc_streams.pop(sid, None)
                self._close_stream_record(rec)

    def _close_stream_record(self, rec):
        """Close off the io loop: StreamingResponse.close does a remote
        cancel round-trip and must release the handle's in-flight slot."""
        def _close():
            try:
                rec["it"].close()
            except Exception:
                pass

        try:
            self._stream_pool.submit(_close)
        except Exception:
            pass

    async def _handle_rpc_stream_next(self, req):
        import cloudpickle

        rec = getattr(self, "_rpc_streams", {}).get(req["stream_id"])
        if rec is None:
            return {"error": "unknown stream %r" % req["stream_id"],
                    "app_error": False}
        import time as _time

        rec["ts"] = _time.time()
        max_items = max(1, min(int(req.get("max_items") or 16), 256))
        timeout = min(float(req.get("timeout") or 300.0), 300.0)
        loop = asyncio.get_running_loop()

        def _pull():
            # per-stream lock: a client retry after its own timeout must
            # not run next() concurrently with the still-blocked pull
            # (StreamingResponse is not thread-safe)
            if not rec["lock"].acquire(timeout=timeout):
                raise TimeoutError("previous pull still in flight")
            try:
                items, done = [], False
                try:
                    for _ in range(max_items):
                        items.append(next(rec["it"]))
                except StopIteration:
                    done = True
                return items, done
            finally:
                rec["lock"].release()

        try:
            items, done = await asyncio.wait_for(
                loop.run_in_executor(self._stream_pool, _pull), timeout + 10
            )
        except Exception as e:  # noqa: BLE001 — generator raised / timeout
            self._rpc_streams.pop(req["stream_id"], None)
            # release the p2c in-flight slot + replica-side generator
            self._close_stream_record(rec)
            return {"error": str(e), "app_error": True}
        if done:
            self._rpc_streams.pop(req["stream_id"], None)
        return {"items": [cloudpickle.dumps(i) for i in items],
                "done": done}

    async def _handle_rpc_stream_cancel(self, req):
        rec = getattr(self, "_rpc_streams", {}).pop(req.get("stream_id"), None)
        if rec is not None:
            self._close_stream_record(rec)
        return {"ok": True}

    # ------------------------------------------------------- llm OOB streams
    # The continuous-batching engine's zero-copy egress (serve/llm): one
    # stream = one sequence pinned to the replica holding its KV blocks.
    # Open/Next/Cancel mirror the generic stream verbs, but the payloads are
    # raw int32 token buffers carried in out-of-band frames: the prompt's
    # "_oob" bytes go to the replica untouched, and ServeLlmNext wraps the
    # replica's token bytes in an OobPayload — straight to the client
    # socket, never through cloudpickle in this process.

    async def _handle_llm_open(self, req):
        self._sweep_llm_streams()
        app = req.get("app")
        await self._route("/")
        info = self._routes.get(app)
        if info is None:
            # cache miss may just be a fresh deploy inside the TTL window:
            # force one refresh before declaring the app unknown
            self._routes_at = 0.0
            await self._route("/")
            info = self._routes.get(app)
        if info is None:
            return {"error": f"no such application {app!r}",
                    "app_error": False}
        from ray_tpu.serve._handle import DeploymentHandle

        ingress = info["ingress"]
        if not hasattr(self, "_llm_handles"):
            self._llm_handles = {}
            self._llm_streams = {}
        handle = self._llm_handles.get(ingress)
        if handle is None:
            handle = self._llm_handles[ingress] = DeploymentHandle(ingress)
        prompt = req.get("_oob")
        if prompt is not None:
            prompt = bytes(prompt)  # raw int32 token ids from the frame
        else:
            prompt = req.get("prompt")
        sampling = req.get("sampling") or {}
        timeout = min(float(req.get("timeout") or 60.0), 300.0)

        def _open():
            import ray_tpu

            name, replica = handle.pick_replica()
            try:
                out = ray_tpu.get(
                    replica.llm_call.remote(
                        "llm_submit", (prompt,), {"sampling": sampling}),
                    timeout=timeout,
                )
                return name, replica, out["request_id"]
            except BaseException:
                # death/timeout between pick_replica and registration: the
                # p2c in-flight slot must come back exactly once (here —
                # the stream record that would normally own it was never
                # created)
                handle.release(name)
                raise

        fut = self._stream_pool.submit(_open)
        try:
            name, replica, rid = await asyncio.wait_for(
                asyncio.wrap_future(fut), timeout + 10)
        except (asyncio.TimeoutError, asyncio.CancelledError):
            # the pool thread may still be mid-open; if it eventually
            # succeeds, nobody will ever pull this stream — release the
            # slot and cancel the submitted sequence so its KV frees
            def _abandon(f):
                try:
                    name, replica, rid = f.result()
                except BaseException:
                    return  # _open released on its own failure path
                handle.release(name)
                try:
                    replica.llm_call.remote("llm_cancel", (rid,), {})
                except Exception:
                    pass

            fut.add_done_callback(_abandon)
            return {"error": "timed out opening llm stream",
                    "app_error": True}
        except Exception as e:  # noqa: BLE001
            return self._llm_error(e)
        import time as _time
        import uuid as _uuid

        sid = _uuid.uuid4().hex
        self._llm_streams[sid] = {
            "replica": replica, "name": name, "rid": rid,
            "ingress": ingress, "ts": _time.time(),
        }
        return {"stream_id": sid}

    @staticmethod
    def _llm_error(e) -> dict:
        """Typed error reply; admission rejections stay structured so the
        client can distinguish backpressure (retry with backoff / route
        elsewhere) from a real failure, and replica deaths are tagged so
        the client's failover path can resubmit instead of surfacing a raw
        ActorDiedError."""
        from ray_tpu.exceptions import (
            ActorDiedError,
            ActorUnavailableError,
            TaskError,
        )

        cause = e.cause if isinstance(e, TaskError) else e
        out = {"error": str(cause), "app_error": True}
        if isinstance(cause, (ActorDiedError, ActorUnavailableError)):
            out["replica_died"] = True
        to_dict = getattr(cause, "to_dict", None)
        if callable(to_dict) and getattr(cause, "queue_depth", None) is not None:
            out.update(to_dict())
        return out

    async def _handle_llm_next(self, req):
        rec = getattr(self, "_llm_streams", {}).get(req.get("stream_id"))
        if rec is None:
            return {"error": "unknown llm stream %r" % req.get("stream_id"),
                    "app_error": False}
        import time as _time

        rec["ts"] = _time.time()
        from ray_tpu._private.config import RTPU_CONFIG

        max_tokens = max(0, int(req.get("max_tokens") or 0))
        wait_s = min(float(req.get("wait_s")
                           or RTPU_CONFIG.llm_pull_wait_s), 30.0)
        loop = asyncio.get_running_loop()

        def _pull():
            import ray_tpu

            return ray_tpu.get(
                rec["replica"].llm_call.remote(
                    "llm_pull", (rec["rid"],),
                    {"max_tokens": max_tokens, "wait_s": wait_s}),
                timeout=wait_s + 30,
            )

        try:
            out = await asyncio.wait_for(
                loop.run_in_executor(self._stream_pool, _pull), wait_s + 40)
        except Exception as e:  # noqa: BLE001
            err = self._llm_error(e)
            # replica death: the stream record goes (slot released exactly
            # once via the pop in _drop_llm_stream) but there is nothing
            # left to cancel — the sequence died with the replica
            self._drop_llm_stream(req.get("stream_id"),
                                  cancel=not err.get("replica_died"))
            return err
        if out["done"]:
            self._drop_llm_stream(req.get("stream_id"), cancel=False)
        from ray_tpu._private.rpc import OobPayload

        data = out["tokens"] or b""
        return OobPayload(
            {"done": out["done"], "finish_reason": out.get("finish_reason"),
             "n": len(data) // 4},
            data,
        )

    async def _handle_llm_cancel(self, req):
        self._drop_llm_stream(req.get("stream_id"), cancel=True)
        return {"ok": True}

    def _drop_llm_stream(self, sid, cancel: bool):
        rec = getattr(self, "_llm_streams", {}).pop(sid, None)
        if rec is None:
            return
        handle = getattr(self, "_llm_handles", {}).get(rec["ingress"])
        if handle is not None:
            handle.release(rec["name"])
        if cancel:
            def _cancel():
                try:
                    rec["replica"].llm_call.remote(
                        "llm_cancel", (rec["rid"],), {})
                except Exception:
                    pass

            try:
                self._stream_pool.submit(_cancel)
            except Exception:
                pass

    def _sweep_llm_streams(self, idle_s: float = 600.0):
        """Free streams an absent client stopped pulling: their sequences
        are cancelled on the replica so the KV blocks return to the pool."""
        import time as _time

        now = _time.time()
        for sid, rec in list(getattr(self, "_llm_streams", {}).items()):
            if now - rec["ts"] > idle_s:
                self._drop_llm_stream(sid, cancel=True)

    async def _route(self, path: str):
        """Longest route_prefix match. The route table refreshes on a short
        TTL and handles are cached per ingress, so the p2c router's
        in-flight view survives across requests (a fresh handle per request
        would degenerate to uniform random and pay three control-plane
        round-trips on every call)."""
        import ray_tpu
        from ray_tpu.serve._handle import CONTROLLER_NAME, DeploymentHandle

        import time as _time

        now = _time.time()
        if now - self._routes_at > self._ROUTE_TTL_S:
            controller = ray_tpu.get_actor(CONTROLLER_NAME)
            loop = asyncio.get_running_loop()
            self._routes = await loop.run_in_executor(
                self._pool,
                lambda: ray_tpu.get(controller.list_apps.remote(), timeout=10),
            )
            self._routes_at = now
        best: Tuple[int, Optional[str]] = (-1, None)
        for name, info in self._routes.items():
            prefix = info.get("route_prefix")
            if prefix is None:
                continue
            norm = prefix.rstrip("/") or ""
            if path == norm or path.startswith(norm + "/") or norm == "":
                if len(norm) > best[0]:
                    best = (len(norm), info["ingress"])
        if best[1] is None:
            return None
        handle = self._handles.get(best[1])
        if handle is None:
            handle = self._handles[best[1]] = DeploymentHandle(best[1])
        return handle

    async def _handle(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        try:
            request_line = await asyncio.wait_for(reader.readline(), 10)
            parts = request_line.decode("latin-1").split()
            if len(parts) < 2:
                return
            method, path = parts[0], parts[1]
            headers = {}
            while True:
                line = await asyncio.wait_for(reader.readline(), 10)
                if line in (b"\r\n", b"\n", b""):
                    break
                k, _, v = line.decode("latin-1").partition(":")
                headers[k.strip().lower()] = v.strip()
            body = b""
            length = int(headers.get("content-length", 0) or 0)
            if length:
                body = await reader.readexactly(length)

            raw_path, _, query = path.partition("?")
            handle = await self._route(raw_path)
            if handle is None:
                await self._respond(writer, 404, b'{"error": "no route"}')
                return
            arg: object = body
            ctype = headers.get("content-type", "")
            if body and ("application/json" in ctype or not ctype):
                try:
                    arg = json.loads(body)
                except Exception:
                    arg = body
            loop = asyncio.get_running_loop()

            # ?stream=1 → chunked transfer, one chunk per generator item
            # (reference: serve streaming responses over HTTP, proxy.py).
            # Exact param match: substring matching would catch ?upstream=1.
            if "stream=1" in query.split("&"):
                await self._stream_response(
                    writer, loop, handle, method, body, arg
                )
                return

            def _call():
                if method == "GET" and not body:
                    resp = handle.remote()
                else:
                    resp = handle.remote(arg)
                return resp.result(timeout=60)

            try:
                result = await loop.run_in_executor(self._pool, _call)
            except Exception as e:
                await self._respond(
                    writer, 500, json.dumps({"error": str(e)}).encode()
                )
                return
            if isinstance(result, (bytes, bytearray)):
                out = bytes(result)
                ctype_out = "application/octet-stream"
            elif isinstance(result, str):
                out = result.encode()
                ctype_out = "text/plain; charset=utf-8"
            else:
                out = json.dumps(result).encode()
                ctype_out = "application/json"
            await self._respond(writer, 200, out, ctype_out)
        except Exception:
            logger.exception("proxy request failed")
        finally:
            try:
                writer.close()
            except Exception:
                pass

    async def _stream_response(self, writer, loop, handle, method, body, arg):
        """HTTP chunked transfer: each generator item becomes one chunk
        (newline-delimited; JSON for non-str/bytes items). The first item is
        pulled BEFORE committing the status line, so an immediately-failing
        generator still gets a 500 like the non-streaming path."""
        # cached per ingress: a fresh handle per request would re-fetch
        # replicas from the controller and reset the p2c in-flight view
        h = self._stream_handles.get(handle.deployment_name)
        if h is None:
            h = handle.options(stream=True)
            self._stream_handles[handle.deployment_name] = h

        _END = object()
        state = {}

        def _start_and_first():
            stream = (h.remote() if (method == "GET" and not body)
                      else h.remote(arg))
            state["stream"] = stream
            try:
                return next(stream)
            except StopIteration:
                return _END

        def _next():
            try:
                return next(state["stream"])
            except StopIteration:
                return _END

        try:
            item = await loop.run_in_executor(
                self._stream_pool, _start_and_first)
        except Exception as e:
            await self._respond(
                writer, 500, json.dumps({"error": str(e)}).encode())
            return
        writer.write(
            b"HTTP/1.1 200 OK\r\n"
            b"Content-Type: text/plain; charset=utf-8\r\n"
            b"Transfer-Encoding: chunked\r\nConnection: close\r\n\r\n"
        )
        await writer.drain()
        try:
            while item is not _END:
                if isinstance(item, (bytes, bytearray)):
                    data = bytes(item)
                elif isinstance(item, str):
                    data = item.encode()
                else:
                    data = json.dumps(item).encode()
                data += b"\n"
                writer.write(f"{len(data):x}\r\n".encode() + data + b"\r\n")
                await writer.drain()
                item = await loop.run_in_executor(self._stream_pool, _next)
            writer.write(b"0\r\n\r\n")
            await writer.drain()
        except Exception:
            logger.exception("streaming response failed")
            try:
                state["stream"].close()
            except Exception:
                pass

    @staticmethod
    async def _respond(writer, status: int, body: bytes, ctype="application/json"):
        reason = {200: "OK", 404: "Not Found", 500: "Internal Server Error"}.get(
            status, "OK"
        )
        writer.write(
            f"HTTP/1.1 {status} {reason}\r\n"
            f"Content-Type: {ctype}\r\n"
            f"Content-Length: {len(body)}\r\n"
            "Connection: close\r\n\r\n".encode() + body
        )
        await writer.drain()
