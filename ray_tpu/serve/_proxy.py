"""HTTP proxy: routes requests to deployment handles.

Counterpart of the reference's ProxyActor
(reference: python/ray/serve/_private/proxy.py:1130 — per-node HTTP
ingress; uvicorn there, a dependency-free asyncio HTTP/1.1 listener here).
Routing: longest matching route_prefix wins
(reference: proxy_router.py). Bodies are passed to the ingress deployment:
JSON bodies decode to Python values, anything else arrives as bytes.
"""

from __future__ import annotations

import asyncio
import json
import logging
from typing import Optional, Tuple

logger = logging.getLogger("ray_tpu.serve.proxy")


class ProxyActor:
    _ROUTE_TTL_S = 1.0

    def __init__(self):
        from concurrent.futures import ThreadPoolExecutor

        self._server: Optional[asyncio.AbstractServer] = None
        self._port = 0
        self._routes: dict = {}  # app name -> info
        self._routes_at = 0.0
        self._handles: dict = {}  # ingress name -> DeploymentHandle
        # Dedicated pool: the default loop executor caps at ~min(32, cpus+4)
        # threads, which would head-of-line-block cheap requests (and route
        # refreshes) behind slow ones.
        self._pool = ThreadPoolExecutor(max_workers=64, thread_name_prefix="proxy")
        # Streams block a thread between item pulls (up to the whole
        # response lifetime): give them their own pool so slow streams can
        # never starve routing/non-streaming traffic out of self._pool.
        self._stream_pool = ThreadPoolExecutor(
            max_workers=32, thread_name_prefix="proxy-stream")
        self._stream_handles: dict = {}  # ingress name -> streaming handle

    async def start(self, port: int = 0) -> int:
        self._server = await asyncio.start_server(self._handle, "127.0.0.1", port)
        self._port = self._server.sockets[0].getsockname()[1]
        logger.info("serve proxy listening on %d", self._port)
        return self._port

    async def start_rpc_ingress(self, port: int = 0) -> int:
        """Binary ingress on the framework's msgpack-RPC framing — the
        counterpart of the reference's gRPC proxy (serve/_private/
        proxy.py:540): non-HTTP clients call deployments with binary
        payloads and typed errors, multiplexed over one connection.
        Method: ServeCall {app, method?, args(pickled), kwargs(pickled)}
        -> {result: pickled} | {error, app_error}."""
        # serialize concurrent starters (async actors interleave): the
        # second caller must await the first's startup, not read an
        # unassigned port
        if getattr(self, "_rpc_lock", None) is None:
            self._rpc_lock = asyncio.Lock()
        async with self._rpc_lock:
            if getattr(self, "_rpc_server", None) is not None:
                return self._rpc_port
            from ray_tpu._private.rpc import RpcServer

            srv = RpcServer("127.0.0.1")
            srv.register("ServeCall", self._handle_rpc_call)
            srv.register("ServeStreamNext", self._handle_rpc_stream_next)
            srv.register("ServeStreamCancel", self._handle_rpc_stream_cancel)
            self._rpc_port = await srv.start(port)
            self._rpc_server = srv
            logger.info("serve rpc ingress on %d", self._rpc_port)
            return self._rpc_port

    async def _handle_rpc_call(self, req):
        import cloudpickle

        self._sweep_rpc_streams()
        app = req.get("app")
        info = None
        if app is not None:
            # refresh via _route's TTL machinery, then resolve by app name
            await self._route("/")
            info = self._routes.get(app)
        if info is None:
            return {"error": f"no such application {app!r}", "app_error": False}
        ingress = info["ingress"]
        from ray_tpu.serve._handle import DeploymentHandle

        method = req.get("method") or "__call__"
        # cache per (ingress, method, stream): a fresh handle per request
        # would leak a long-poll thread each time and reset the p2c state
        stream = bool(req.get("stream"))
        if not hasattr(self, "_rpc_handles"):
            self._rpc_handles = {}
        handle = self._rpc_handles.get((ingress, method, stream))
        if handle is None:
            handle = DeploymentHandle(ingress, method_name=method)
            if stream:
                handle = handle.options(stream=True)
            self._rpc_handles[(ingress, method, stream)] = handle
        args = cloudpickle.loads(req["args"]) if req.get("args") else ()
        kwargs = cloudpickle.loads(req["kwargs"]) if req.get("kwargs") else {}
        # honor the client's deadline (capped): a hung replica must not
        # pin a shared proxy-pool thread for 300s when the caller gave up
        # after 10
        timeout = min(float(req.get("timeout") or 300.0), 300.0)
        loop = asyncio.get_running_loop()

        if stream:
            # Streaming over the multiplexed connection (reference:
            # serve/_private/proxy.py:540 gRPCProxy streaming): the call
            # opens a replica-side generator; the CLIENT pulls batches via
            # ServeStreamNext at its own pace — pull-based, so a slow
            # consumer naturally backpressures the replica (it only
            # advances when pulled).
            def _open():
                return handle.remote(*args, **kwargs)

            try:
                # the dedicated stream pool: slow streams must never starve
                # routing/non-streaming traffic out of self._pool
                resp = await asyncio.wait_for(
                    loop.run_in_executor(self._stream_pool, _open),
                    timeout + 10,
                )
            except Exception as e:  # noqa: BLE001
                return {"error": str(e), "app_error": True}
            import threading as _threading
            import time as _time
            import uuid as _uuid

            if not hasattr(self, "_rpc_streams"):
                self._rpc_streams = {}
            sid = _uuid.uuid4().hex
            self._rpc_streams[sid] = {"it": resp, "ts": _time.time(),
                                      "lock": _threading.Lock()}
            return {"stream_id": sid}

        def _call():
            return handle.remote(*args, **kwargs).result(timeout=timeout)

        try:
            result = await loop.run_in_executor(self._pool, _call)
        except Exception as e:  # noqa: BLE001 — typed back to the client
            return {"error": str(e), "app_error": True}
        return {"result": cloudpickle.dumps(result)}

    def _sweep_rpc_streams(self, idle_s: float = 600.0):
        '''Drop streams an absent client stopped pulling (their
        replica-side generators are cancelled).'''
        import time as _time

        now = _time.time()
        for sid, rec in list(getattr(self, "_rpc_streams", {}).items()):
            if now - rec["ts"] > idle_s:
                self._rpc_streams.pop(sid, None)
                self._close_stream_record(rec)

    def _close_stream_record(self, rec):
        """Close off the io loop: StreamingResponse.close does a remote
        cancel round-trip and must release the handle's in-flight slot."""
        def _close():
            try:
                rec["it"].close()
            except Exception:
                pass

        try:
            self._stream_pool.submit(_close)
        except Exception:
            pass

    async def _handle_rpc_stream_next(self, req):
        import cloudpickle

        rec = getattr(self, "_rpc_streams", {}).get(req["stream_id"])
        if rec is None:
            return {"error": "unknown stream %r" % req["stream_id"],
                    "app_error": False}
        import time as _time

        rec["ts"] = _time.time()
        max_items = max(1, min(int(req.get("max_items") or 16), 256))
        timeout = min(float(req.get("timeout") or 300.0), 300.0)
        loop = asyncio.get_running_loop()

        def _pull():
            # per-stream lock: a client retry after its own timeout must
            # not run next() concurrently with the still-blocked pull
            # (StreamingResponse is not thread-safe)
            if not rec["lock"].acquire(timeout=timeout):
                raise TimeoutError("previous pull still in flight")
            try:
                items, done = [], False
                try:
                    for _ in range(max_items):
                        items.append(next(rec["it"]))
                except StopIteration:
                    done = True
                return items, done
            finally:
                rec["lock"].release()

        try:
            items, done = await asyncio.wait_for(
                loop.run_in_executor(self._stream_pool, _pull), timeout + 10
            )
        except Exception as e:  # noqa: BLE001 — generator raised / timeout
            self._rpc_streams.pop(req["stream_id"], None)
            # release the p2c in-flight slot + replica-side generator
            self._close_stream_record(rec)
            return {"error": str(e), "app_error": True}
        if done:
            self._rpc_streams.pop(req["stream_id"], None)
        return {"items": [cloudpickle.dumps(i) for i in items],
                "done": done}

    async def _handle_rpc_stream_cancel(self, req):
        rec = getattr(self, "_rpc_streams", {}).pop(req.get("stream_id"), None)
        if rec is not None:
            self._close_stream_record(rec)
        return {"ok": True}

    async def _route(self, path: str):
        """Longest route_prefix match. The route table refreshes on a short
        TTL and handles are cached per ingress, so the p2c router's
        in-flight view survives across requests (a fresh handle per request
        would degenerate to uniform random and pay three control-plane
        round-trips on every call)."""
        import ray_tpu
        from ray_tpu.serve._handle import CONTROLLER_NAME, DeploymentHandle

        import time as _time

        now = _time.time()
        if now - self._routes_at > self._ROUTE_TTL_S:
            controller = ray_tpu.get_actor(CONTROLLER_NAME)
            loop = asyncio.get_running_loop()
            self._routes = await loop.run_in_executor(
                self._pool,
                lambda: ray_tpu.get(controller.list_apps.remote(), timeout=10),
            )
            self._routes_at = now
        best: Tuple[int, Optional[str]] = (-1, None)
        for name, info in self._routes.items():
            prefix = info.get("route_prefix")
            if prefix is None:
                continue
            norm = prefix.rstrip("/") or ""
            if path == norm or path.startswith(norm + "/") or norm == "":
                if len(norm) > best[0]:
                    best = (len(norm), info["ingress"])
        if best[1] is None:
            return None
        handle = self._handles.get(best[1])
        if handle is None:
            handle = self._handles[best[1]] = DeploymentHandle(best[1])
        return handle

    async def _handle(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        try:
            request_line = await asyncio.wait_for(reader.readline(), 10)
            parts = request_line.decode("latin-1").split()
            if len(parts) < 2:
                return
            method, path = parts[0], parts[1]
            headers = {}
            while True:
                line = await asyncio.wait_for(reader.readline(), 10)
                if line in (b"\r\n", b"\n", b""):
                    break
                k, _, v = line.decode("latin-1").partition(":")
                headers[k.strip().lower()] = v.strip()
            body = b""
            length = int(headers.get("content-length", 0) or 0)
            if length:
                body = await reader.readexactly(length)

            raw_path, _, query = path.partition("?")
            handle = await self._route(raw_path)
            if handle is None:
                await self._respond(writer, 404, b'{"error": "no route"}')
                return
            arg: object = body
            ctype = headers.get("content-type", "")
            if body and ("application/json" in ctype or not ctype):
                try:
                    arg = json.loads(body)
                except Exception:
                    arg = body
            loop = asyncio.get_running_loop()

            # ?stream=1 → chunked transfer, one chunk per generator item
            # (reference: serve streaming responses over HTTP, proxy.py).
            # Exact param match: substring matching would catch ?upstream=1.
            if "stream=1" in query.split("&"):
                await self._stream_response(
                    writer, loop, handle, method, body, arg
                )
                return

            def _call():
                if method == "GET" and not body:
                    resp = handle.remote()
                else:
                    resp = handle.remote(arg)
                return resp.result(timeout=60)

            try:
                result = await loop.run_in_executor(self._pool, _call)
            except Exception as e:
                await self._respond(
                    writer, 500, json.dumps({"error": str(e)}).encode()
                )
                return
            if isinstance(result, (bytes, bytearray)):
                out = bytes(result)
                ctype_out = "application/octet-stream"
            elif isinstance(result, str):
                out = result.encode()
                ctype_out = "text/plain; charset=utf-8"
            else:
                out = json.dumps(result).encode()
                ctype_out = "application/json"
            await self._respond(writer, 200, out, ctype_out)
        except Exception:
            logger.exception("proxy request failed")
        finally:
            try:
                writer.close()
            except Exception:
                pass

    async def _stream_response(self, writer, loop, handle, method, body, arg):
        """HTTP chunked transfer: each generator item becomes one chunk
        (newline-delimited; JSON for non-str/bytes items). The first item is
        pulled BEFORE committing the status line, so an immediately-failing
        generator still gets a 500 like the non-streaming path."""
        # cached per ingress: a fresh handle per request would re-fetch
        # replicas from the controller and reset the p2c in-flight view
        h = self._stream_handles.get(handle.deployment_name)
        if h is None:
            h = handle.options(stream=True)
            self._stream_handles[handle.deployment_name] = h

        _END = object()
        state = {}

        def _start_and_first():
            stream = (h.remote() if (method == "GET" and not body)
                      else h.remote(arg))
            state["stream"] = stream
            try:
                return next(stream)
            except StopIteration:
                return _END

        def _next():
            try:
                return next(state["stream"])
            except StopIteration:
                return _END

        try:
            item = await loop.run_in_executor(
                self._stream_pool, _start_and_first)
        except Exception as e:
            await self._respond(
                writer, 500, json.dumps({"error": str(e)}).encode())
            return
        writer.write(
            b"HTTP/1.1 200 OK\r\n"
            b"Content-Type: text/plain; charset=utf-8\r\n"
            b"Transfer-Encoding: chunked\r\nConnection: close\r\n\r\n"
        )
        await writer.drain()
        try:
            while item is not _END:
                if isinstance(item, (bytes, bytearray)):
                    data = bytes(item)
                elif isinstance(item, str):
                    data = item.encode()
                else:
                    data = json.dumps(item).encode()
                data += b"\n"
                writer.write(f"{len(data):x}\r\n".encode() + data + b"\r\n")
                await writer.drain()
                item = await loop.run_in_executor(self._stream_pool, _next)
            writer.write(b"0\r\n\r\n")
            await writer.drain()
        except Exception:
            logger.exception("streaming response failed")
            try:
                state["stream"].close()
            except Exception:
                pass

    @staticmethod
    async def _respond(writer, status: int, body: bytes, ctype="application/json"):
        reason = {200: "OK", 404: "Not Found", 500: "Internal Server Error"}.get(
            status, "OK"
        )
        writer.write(
            f"HTTP/1.1 {status} {reason}\r\n"
            f"Content-Type: {ctype}\r\n"
            f"Content-Length: {len(body)}\r\n"
            "Connection: close\r\n\r\n".encode() + body
        )
        await writer.drain()
