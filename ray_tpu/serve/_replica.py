"""Replica actor: hosts one copy of a deployment's callable.

Counterpart of the reference's ReplicaActor
(reference: python/ray/serve/_private/replica.py:231 — wraps the user
callable, enforces max_ongoing_requests, exposes queue length for the
router and health checks for the controller).
"""

from __future__ import annotations

import inspect
from typing import Any

_LATENCY_BOUNDARIES = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0,
)

_metrics = None


def _serve_metrics():
    """Replica-side request metrics (lazy singleton: one set of records per
    replica process). They ride the worker's util.metrics flush → GCS
    aggregation → Prometheus /metrics path — zero new transport. Metric
    names are a stability contract (see ray_tpu/util/metrics.py)."""
    global _metrics
    if _metrics is None:
        from ray_tpu.util.metrics import Counter, Gauge, Histogram

        tags = ("deployment", "replica")
        _metrics = {
            "requests": Counter(
                "ray_tpu_serve_requests_total",
                "requests handled per deployment replica", tag_keys=tags),
            "errors": Counter(
                "ray_tpu_serve_request_errors_total",
                "requests that raised per deployment replica",
                tag_keys=tags),
            "inflight": Gauge(
                "ray_tpu_serve_inflight_requests",
                "requests currently executing user code", tag_keys=tags),
            "queue": Gauge(
                "ray_tpu_serve_queue_depth",
                "requests queued + executing (the router's probe depth)",
                tag_keys=tags),
            "latency": Histogram(
                "ray_tpu_serve_request_latency_seconds",
                "replica-side request latency: queue wait + execution",
                boundaries=_LATENCY_BOUNDARIES, tag_keys=tags),
        }
    return _metrics


class Replica:
    """Instantiated inside a dedicated (async, max_concurrency) actor."""

    def __init__(self, serialized: dict, init_args: tuple, init_kwargs: dict):
        import cloudpickle

        from ray_tpu.serve._deployment import _HandleRef
        from ray_tpu.serve._handle import DeploymentHandle

        func_or_class = cloudpickle.loads(serialized["callable"])
        self._name = serialized["name"]
        init_args = tuple(
            DeploymentHandle(a.deployment_name) if isinstance(a, _HandleRef) else a
            for a in init_args
        )
        init_kwargs = {
            k: DeploymentHandle(v.deployment_name) if isinstance(v, _HandleRef) else v
            for k, v in init_kwargs.items()
        }
        if inspect.isclass(func_or_class):
            self._callable = func_or_class(*init_args, **init_kwargs)
            self._is_function = False
        else:
            self._callable = func_or_class
            self._is_function = True
        self._ongoing = 0
        self._running = 0  # executing user code (vs queued on the gate)
        self._handled = 0
        self._replica_tag = ""  # actor name, set by start_metrics_push
        # User-request concurrency is self-gated so the actor's
        # max_concurrency can carry headroom for control-plane methods
        # (queue_len probes, metrics) — a saturated replica must still
        # answer probes instantly (reference: pow_2_scheduler probes).
        self._max_ongoing = serialized.get("max_ongoing", 8)
        self._sem = None  # lazy: created on the actor loop
        # Identity hook: engine-style callables (serve.llm.LLMReplica) tag
        # their own telemetry series with the deployment/replica labels.
        self._push_identity()

    def _metric_tags(self) -> dict:
        return {"deployment": self._name, "replica": self._replica_tag}

    def _push_identity(self):
        hook = getattr(self._callable, "__serve_identity__", None)
        if callable(hook):
            try:
                hook(self._name, self._replica_tag)
            except Exception:
                pass

    def _extra_load(self) -> int:
        """Engine-style callables report internal load (e.g. the llm
        engine's waiting+running sequences) beyond the request-level
        _ongoing count — the autoscaler and queue gauge fold it in."""
        hook = getattr(self._callable, "__serve_load__", None)
        if callable(hook):
            try:
                return max(0, int(hook()))
            except Exception:
                return 0
        return 0

    async def llm_call(self, method: str, args: tuple, kwargs: dict):
        """Direct dispatch for llm control-plane calls (submit / pull /
        cancel / stats from the proxy's OOB stream path). Deliberately NOT
        gated by the max_ongoing semaphore: the engine applies its own
        admission control, and a pull must never queue behind the user
        requests whose tokens it is draining."""
        target = getattr(self._callable, method)
        result = target(*args, **kwargs)
        if inspect.iscoroutine(result):
            result = await result
        return result

    async def handle_request(self, method: str, args: tuple, kwargs: dict) -> Any:
        import asyncio
        import functools
        import time as _time

        if self._sem is None:
            self._sem = asyncio.Semaphore(self._max_ongoing)
        model_id = kwargs.pop("__multiplexed_model_id", "")
        if model_id:
            from ray_tpu.serve.multiplex import _set_current_model_id

            _set_current_model_id(model_id)
        t0 = _time.perf_counter()
        metrics = _serve_metrics()
        tags = self._metric_tags()
        # _ongoing counts queued + running: the probe's notion of depth
        self._ongoing += 1
        try:
            await self._sem.acquire()
        except BaseException:
            self._ongoing -= 1
            raise
        self._running += 1
        try:
            if self._is_function:
                target = self._callable
            else:
                target = getattr(self._callable, method or "__call__")
            if inspect.iscoroutinefunction(target) or getattr(
                target, "_is_serve_batch", False
            ):
                return await target(*args, **kwargs)
            # Sync callables run in the thread pool so max_ongoing_requests
            # gives real concurrency and metadata/health stay responsive
            # (reference: replica.py runs sync user methods off-loop). The
            # request context (multiplexed model id) is copied into the
            # worker thread explicitly — run_in_executor does not.
            import contextvars

            loop = asyncio.get_running_loop()
            ctx = contextvars.copy_context()
            result = await loop.run_in_executor(
                None, ctx.run, functools.partial(target, *args, **kwargs)
            )
            if inspect.iscoroutine(result):
                result = await result
            return result
        except BaseException:
            metrics["errors"].inc(1, tags=tags)
            raise
        finally:
            self._sem.release()
            self._running -= 1
            self._ongoing -= 1
            self._handled += 1
            # Replica-side end-to-end latency: queue wait + execution
            # (the handle records the caller-side view separately).
            dt = _time.perf_counter() - t0
            metrics["requests"].inc(1, tags=tags)
            metrics["latency"].observe(dt, tags=tags)
            from ray_tpu._private import flight_recorder as _fr

            _fr.record("serve.request", b"", f"{self._name} {dt:.4f}s")

    # ------------------------------------------------------------ streaming

    async def start_stream(self, method: str, args: tuple, kwargs: dict) -> str:
        """Begin a streaming call: the target returns a (sync or async)
        generator; items are pulled in batches via next_stream_items
        (reference: serve's streaming responses, replica.py generator
        handling)."""
        import asyncio
        import uuid

        import time as _time

        # streams count against max_ongoing_requests for their whole
        # lifetime (slot released in _drop_stream) — the actor-level
        # concurrency cap no longer enforces this since it carries probe
        # headroom
        if self._sem is None:
            self._sem = asyncio.Semaphore(self._max_ongoing)
        self._ongoing += 1
        try:
            await self._sem.acquire()
        except BaseException:
            self._ongoing -= 1
            raise
        try:
            model_id = kwargs.pop("__multiplexed_model_id", "")
            if model_id:
                from ray_tpu.serve.multiplex import _set_current_model_id

                _set_current_model_id(model_id)
            target = (self._callable if self._is_function
                      else getattr(self._callable, method or "__call__"))
            gen = target(*args, **kwargs)
            if inspect.iscoroutine(gen):
                gen = await gen
            sid = uuid.uuid4().hex
            if not hasattr(self, "_streams"):
                self._streams = {}
            # model_id stored with the stream: the generator body executes
            # in next_stream_items' task context, not this one
            self._streams[sid] = {"gen": gen, "model_id": model_id,
                                  "last_pull": _time.time()}
            _serve_metrics()["requests"].inc(1, tags=self._metric_tags())
            return sid
        except BaseException:
            self._sem.release()
            self._ongoing -= 1
            raise

    def _release_slot(self):
        if self._sem is not None:
            self._sem.release()

    async def cancel_stream(self, stream_id: str):
        """Client-side abandonment (StreamingResponse.close/__del__)."""
        self._drop_stream(stream_id)
        return True

    def _drop_stream(self, stream_id: str):
        rec = getattr(self, "_streams", {}).pop(stream_id, None)
        if rec is not None:
            self._release_slot()
            self._ongoing -= 1
            self._handled += 1

    def _reap_idle_streams(self, max_idle_s: float = 300.0):
        """Abandoned streams (client died mid-iteration) must not pin
        _ongoing/memory forever; called from the metrics push loop."""
        import time as _time

        now = _time.time()
        for sid, rec in list(getattr(self, "_streams", {}).items()):
            if now - rec["last_pull"] > max_idle_s:
                self._drop_stream(sid)

    async def next_stream_items(self, stream_id: str,
                                max_items: int = 16) -> dict:
        """Pull up to max_items from the stream; done=True ends it."""
        import time as _time

        rec = getattr(self, "_streams", {}).get(stream_id)
        if rec is None:
            return {"items": [], "done": True}
        rec["last_pull"] = _time.time()
        gen = rec["gen"]
        if rec["model_id"]:
            from ray_tpu.serve.multiplex import _set_current_model_id

            _set_current_model_id(rec["model_id"])
        items = []
        done = False
        try:
            if inspect.isasyncgen(gen):
                for _ in range(max_items):
                    try:
                        items.append(await gen.__anext__())
                    except StopAsyncIteration:
                        done = True
                        break
            else:
                import asyncio as _asyncio
                import contextvars as _cv
                import functools as _functools

                def pull():
                    out = []
                    for _ in range(max_items):
                        try:
                            out.append(next(gen))
                        except StopIteration:
                            return out, True
                    return out, False

                loop = _asyncio.get_running_loop()
                ctx = _cv.copy_context()  # carries the model id
                items, done = await loop.run_in_executor(
                    None, ctx.run, _functools.partial(pull))
        except Exception:
            self._drop_stream(stream_id)
            raise
        if done:
            self._drop_stream(stream_id)
        return {"items": items, "done": done}

    def get_metadata(self) -> dict:
        return {"ongoing": self._ongoing, "handled": self._handled}

    async def queue_len(self) -> int:
        """Current in-flight count, probed by pow-2 routing (reference:
        replica_scheduler/pow_2_scheduler.py:49 queue-length probes)."""
        return self._ongoing

    async def start_metrics_push(
        self, replica_name: str, health_check_period_s: float = 2.0
    ):
        """Controller calls this once after creation: push ongoing-request
        stats every 0.5s (reference: replicas push autoscaling metrics to
        the controller, serve/_private/autoscaling_state.py — a pull would
        queue FIFO behind user requests and always observe a drained
        queue). The user's check_health() runs on its own period and rides
        the same push: a failing check marks the replica unhealthy and the
        controller replaces it."""
        import asyncio
        import time as _time

        if getattr(self, "_push_task", None) is not None:
            return
        self._replica_name = replica_name
        # short tag: SERVE_REPLICA::<dep>::<id> -> <dep>#<id> keeps the
        # Prometheus label readable and the series cardinality = replicas
        self._replica_tag = replica_name.split("::")[-1]
        self._push_identity()  # now with the real replica tag

        async def _loop():
            import ray_tpu
            from ray_tpu.serve._handle import CONTROLLER_NAME

            controller = None
            healthy = True
            last_health_check = 0.0
            while True:
                now = _time.time()
                try:
                    self._reap_idle_streams()
                except Exception:
                    pass
                if now - last_health_check >= health_check_period_s:
                    last_health_check = now
                    try:
                        await self.check_health()
                        healthy = True
                    except Exception:
                        healthy = False
                extra = self._extra_load()
                try:
                    # queue/in-flight gauges ride the same 0.5s cadence as
                    # the controller push; exported via the worker's
                    # util.metrics flush → GCS → Prometheus
                    m = _serve_metrics()
                    tags = self._metric_tags()
                    m["queue"].set(self._ongoing + extra, tags=tags)
                    m["inflight"].set(self._running, tags=tags)
                except Exception:
                    pass
                try:
                    if controller is None:
                        controller = ray_tpu.get_actor(CONTROLLER_NAME)
                    controller.report_replica_metrics.remote(
                        self._name,
                        replica_name,
                        {
                            "ongoing": self._ongoing,
                            # autoscaling signal: request-level in-flight
                            # plus the callable's own queue (llm engine
                            # sequences waiting+running)
                            "load": self._ongoing + extra,
                            "handled": self._handled,
                            "healthy": healthy,
                        },
                    )
                except Exception:
                    controller = None
                await asyncio.sleep(0.5)

        self._push_task = asyncio.ensure_future(_loop())

    async def check_health(self) -> bool:
        user_check = getattr(self._callable, "check_health", None)
        if user_check is not None:
            result = user_check()
            if inspect.iscoroutine(result):
                await result
        return True
