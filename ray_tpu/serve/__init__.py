"""ray_tpu.serve — model serving on the actor runtime.

Counterpart of Ray Serve's public API (reference: python/ray/serve/api.py —
serve.run :535, @serve.deployment, handles serve/handle.py:714). Minimal
but real: a detached controller reconciles replica actors per deployment,
an HTTP proxy routes by route_prefix, DeploymentHandles load-balance with
power-of-two-choices, composition passes handles for bound sub-apps, and
request-based autoscaling adjusts replica counts.
"""

from __future__ import annotations

import time
from typing import Optional

import cloudpickle

from ray_tpu.serve._deployment import (
    Application,
    AutoscalingConfig,
    Deployment,
    deployment,
)
from ray_tpu.serve._handle import CONTROLLER_NAME, DeploymentHandle, DeploymentResponse
from ray_tpu.serve.batching import batch
from ray_tpu.serve.multiplex import get_multiplexed_model_id, multiplexed
from ray_tpu.serve import llm  # noqa: F401 — serve.llm.* public surface

__all__ = [
    "deployment",
    "llm",
    "run",
    "start",
    "shutdown",
    "delete",
    "get_app_handle",
    "get_deployment_handle",
    "status",
    "batch",
    "multiplexed",
    "get_multiplexed_model_id",
    "Application",
    "AutoscalingConfig",
    "Deployment",
    "DeploymentHandle",
    "DeploymentResponse",
]


def _get_or_create_controller():
    import ray_tpu
    from ray_tpu.serve._controller import ServeController

    try:
        return ray_tpu.get_actor(CONTROLLER_NAME)
    except Exception:
        pass
    try:
        return (
            ray_tpu.remote(ServeController)
            .options(
                name=CONTROLLER_NAME,
                lifetime="detached",
                max_concurrency=16,
                num_cpus=0,
            )
            .remote()
        )
    except Exception:
        # Raced another creator for the name.
        return ray_tpu.get_actor(CONTROLLER_NAME)


def start(http_port: int = 0) -> int:
    """Ensure the controller (and HTTP proxy) are running; returns the
    proxy port."""
    import ray_tpu

    controller = _get_or_create_controller()
    return ray_tpu.get(controller.ensure_proxy.remote(http_port), timeout=120)


def start_rpc_ingress(port: int = 0) -> int:
    """Start the binary msgpack-RPC ingress beside the HTTP proxy
    (the gRPC-ingress analogue, reference: serve/_private/proxy.py:540);
    returns its port. Consume with serve.rpc_ingress.RpcIngressClient."""
    import ray_tpu

    controller = _get_or_create_controller()
    return ray_tpu.get(
        controller.ensure_rpc_ingress.remote(port), timeout=120
    )


def run(
    app: Application,
    *,
    name: str = "default",
    route_prefix: Optional[str] = "/",
    _blocking_ready_timeout_s: float = 60.0,
) -> DeploymentHandle:
    """Deploy an application; returns a handle to its ingress deployment."""
    import ray_tpu

    if not isinstance(app, Application):
        raise TypeError("serve.run expects a bound Application (use .bind())")
    controller = _get_or_create_controller()
    specs = []
    from ray_tpu.serve._deployment import _HandleRef

    def scope(v):
        # Deployments are app-scoped (reference namespaces deployment names
        # per application): two apps may both have a 'Model' without
        # clobbering each other.
        if isinstance(v, _HandleRef):
            return _HandleRef(f"{name}#{v.deployment_name}")
        return v

    for dep, init_args, init_kwargs in app.flatten():
        specs.append(
            {
                "name": f"{name}#{dep.name}",
                "callable": cloudpickle.dumps(dep.func_or_class),
                "init_args": tuple(scope(a) for a in init_args),
                "init_kwargs": {k: scope(v) for k, v in init_kwargs.items()},
                "num_replicas": dep.num_replicas,
                "max_ongoing_requests": dep.max_ongoing_requests,
                "ray_actor_options": dep.ray_actor_options,
                "autoscaling_config": dep.autoscaling_config,
                "health_check_period_s": dep.health_check_period_s,
            }
        )
    ingress = ray_tpu.get(
        controller.deploy_application.remote(name, route_prefix, specs),
        timeout=120,
    )
    handle = DeploymentHandle(ingress)
    # Wait until at least one ingress replica answers (reference: serve.run
    # blocks until the application is RUNNING).
    deadline = time.time() + _blocking_ready_timeout_s
    last = None
    while time.time() < deadline:
        try:
            names = ray_tpu.get(
                controller.get_replica_names.remote(ingress), timeout=30
            )
            if names:
                replica = ray_tpu.get_actor(names[0])
                ray_tpu.get(replica.get_metadata.remote(), timeout=30)
                return handle
        except Exception as e:
            last = e
        time.sleep(0.25)
    raise TimeoutError(f"application '{name}' did not become ready: {last}")


def delete(name: str):
    import ray_tpu

    try:
        controller = ray_tpu.get_actor(CONTROLLER_NAME)
    except Exception:
        return
    ray_tpu.get(controller.delete_application.remote(name), timeout=60)


def get_app_handle(name: str = "default") -> DeploymentHandle:
    import ray_tpu

    controller = ray_tpu.get_actor(CONTROLLER_NAME)
    info = ray_tpu.get(controller.get_app_info.remote(name), timeout=30)
    if info is None:
        raise ValueError(f"no application named '{name}'")
    return DeploymentHandle(info["ingress"])


def get_deployment_handle(
    deployment_name: str, app_name: str = "default"
) -> DeploymentHandle:
    return DeploymentHandle(f"{app_name}#{deployment_name}")


def status() -> dict:
    import ray_tpu

    controller = ray_tpu.get_actor(CONTROLLER_NAME)
    return ray_tpu.get(controller.list_apps.remote(), timeout=30)


def shutdown():
    """Tear down all applications, replicas, the proxy and controller."""
    import ray_tpu

    try:
        controller = ray_tpu.get_actor(CONTROLLER_NAME)
    except Exception:
        return
    try:
        apps = ray_tpu.get(controller.list_apps.remote(), timeout=30)
        for name in apps:
            ray_tpu.get(controller.delete_application.remote(name), timeout=60)
    except Exception:
        pass
    try:
        proxy = ray_tpu.get_actor("SERVE_PROXY")
        ray_tpu.kill(proxy)
    except Exception:
        pass
    try:
        ray_tpu.kill(controller)
    except Exception:
        pass
