"""ray_tpu.data — streaming datasets over the distributed object store.

Reference: python/ray/data (the streaming-executor subset per SURVEY.md §2.3:
read/from_items → map_batches → iter_batches with operator pools and
backpressure). Blocks are plasma objects; map stages are task/actor pools;
iteration overlaps ingest with downstream compute.
"""

from __future__ import annotations

import builtins
from typing import Any, Dict, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.data.block import Block  # noqa: F401
from ray_tpu.data.dataset import Dataset

DEFAULT_BLOCK_ROWS = 1024


def from_items(items: List[Any], *, override_num_blocks: Optional[int] = None
               ) -> Dataset:
    """Create a dataset from a python list (reference: data.from_items)."""
    from ray_tpu.data._streaming import _rows_to_block

    n = len(items)
    if n == 0:
        return Dataset([])
    nblocks = override_num_blocks or max(1, min(32, n // DEFAULT_BLOCK_ROWS or 1))
    per = max(1, (n + nblocks - 1) // nblocks)
    refs = []
    for i in builtins.range(0, n, per):
        chunk = list(items[i:i + per])
        refs.append(ray_tpu.put(_rows_to_block(chunk)))
    return Dataset(refs)


def range(n: int, *, override_num_blocks: Optional[int] = None) -> Dataset:  # noqa: A001
    if n == 0:
        return Dataset([])
    nblocks = override_num_blocks or max(1, min(32, n // DEFAULT_BLOCK_ROWS or 1))
    per = max(1, (n + nblocks - 1) // nblocks)
    refs = [
        ray_tpu.put({"id": np.arange(i, min(n, i + per), dtype=np.int64)})
        for i in builtins.range(0, n, per)
    ]
    return Dataset(refs)


def from_numpy(arr, column: str = "data",
               override_num_blocks: Optional[int] = None) -> Dataset:
    arr = np.asarray(arr)
    if len(arr) == 0:
        return Dataset([])
    nblocks = override_num_blocks or max(1, min(32, len(arr) // DEFAULT_BLOCK_ROWS or 1))
    per = max(1, (len(arr) + nblocks - 1) // nblocks)
    refs = [
        ray_tpu.put({column: arr[i:i + per]})
        for i in builtins.range(0, len(arr), per)
    ]
    return Dataset(refs)


@ray_tpu.remote
def _read_parquet_task(path: str, columns, filters):
    import pyarrow.parquet as pq

    # columns + filters push down into the parquet reader: row groups
    # whose statistics exclude the predicate never leave disk
    # (reference: datasource/parquet_datasource filter pushdown)
    table = pq.read_table(path, columns=columns, filters=filters)
    return {
        name: table.column(name).to_numpy(zero_copy_only=False)
        for name in table.column_names
    }


def read_parquet(paths, *, columns: Optional[List[str]] = None,
                 filter: Optional[list] = None) -> Dataset:
    """One block per parquet file, read in parallel by tasks
    (reference: data.read_parquet / datasource/parquet_datasource).
    `filter` takes pyarrow DNF filters, e.g. [("x", ">", 5)] — pushed
    down to row-group pruning."""
    refs = [
        _read_parquet_task.remote(f, columns, filter)
        for f in _expand_files(paths, ".parquet")
    ]
    return Dataset(refs)


@ray_tpu.remote
def _read_csv_task(path: str):
    import pyarrow.csv as pcsv

    table = pcsv.read_csv(path)
    return {
        name: table.column(name).to_numpy(zero_copy_only=False)
        for name in table.column_names
    }


def read_csv(paths) -> Dataset:
    refs = [_read_csv_task.remote(f) for f in _expand_files(paths, ".csv")]
    return Dataset(refs)


def _expand_files(paths, suffix: str) -> List[str]:
    import glob
    import os

    if isinstance(paths, str):
        paths = [paths]
    files: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            files.extend(sorted(glob.glob(os.path.join(p, f"*{suffix}"))))
        else:
            files.extend(sorted(glob.glob(p)) or [p])
    if not files:
        raise FileNotFoundError(f"no {suffix} files under {paths}")
    return files


@ray_tpu.remote
def _read_json_task(path: str):
    import pyarrow.json as pjson

    table = pjson.read_json(path)
    return {
        name: table.column(name).to_numpy(zero_copy_only=False)
        for name in table.column_names
    }


def read_json(paths) -> Dataset:
    """Newline-delimited JSON, one block per file
    (reference: data.read_json / datasource/json_datasource)."""
    refs = [_read_json_task.remote(f) for f in _expand_files(paths, ".json")]
    return Dataset(refs)


@ray_tpu.remote
def _read_text_task(path: str):
    with open(path) as f:
        return {"text": np.asarray([ln.rstrip("\n") for ln in f], dtype=object)}


def read_text(paths) -> Dataset:
    """One row per line (reference: data.read_text)."""
    refs = [_read_text_task.remote(f) for f in _expand_files(paths, ".txt")]
    return Dataset(refs)


@ray_tpu.remote
def _read_binary_task(path: str, include_paths: bool):
    with open(path, "rb") as f:
        data = f.read()
    block = {"bytes": np.asarray([data], dtype=object)}
    if include_paths:
        block["path"] = np.asarray([path], dtype=object)
    return block


def _expand_by_extensions(paths, extensions: List[str]) -> List[str]:
    """Expand paths and keep only real FILES with one of the extensions
    (applied to explicit paths and glob matches too, not just directory
    listings — and a bare '*' listing must never hand a subdirectory to a
    reader task)."""
    import os

    exts = tuple(
        e if e.startswith(".") else "." + e for e in extensions)
    files: List[str] = []
    for ext in exts:
        try:
            files.extend(_expand_files(paths, ext))
        except FileNotFoundError:
            pass
    files = [f for f in sorted(set(files))
             if f.endswith(exts) and os.path.isfile(f)]
    if not files:
        raise FileNotFoundError(f"no {list(exts)} files under {paths}")
    return files


def read_binary_files(paths, *, include_paths: bool = False,
                      file_extensions: Optional[List[str]] = None) -> Dataset:
    """One row per file with its raw bytes (reference: data.
    read_binary_files / datasource/binary_datasource)."""
    import os

    if file_extensions:
        files = _expand_by_extensions(paths, file_extensions)
    else:
        files = [f for f in _expand_files(paths, "") if os.path.isfile(f)]
        if not files:
            raise FileNotFoundError(f"no files under {paths}")
    refs = [_read_binary_task.remote(f, include_paths) for f in files]
    return Dataset(refs)


@ray_tpu.remote
def _read_image_task(paths: List[str], size, mode):
    from PIL import Image

    images = []
    for path in paths:
        img = Image.open(path)
        if mode:
            img = img.convert(mode)
        if size:
            img = img.resize((size[1], size[0]))
        images.append(np.asarray(img))
    if size:
        arr = np.stack(images)
    else:
        # a 1-D object array of per-image ndarrays — np.asarray(...,
        # dtype=object) on same-shaped images would instead box every
        # PIXEL as a Python object (an ~8x memory blow-up)
        arr = np.empty(len(images), dtype=object)
        arr[:] = images
    return {"image": arr, "path": np.asarray(paths, dtype=object)}


IMAGE_EXTENSIONS = (".png", ".jpg", ".jpeg", ".bmp", ".gif", ".webp")


def read_images(paths, *, size=None, mode: Optional[str] = None,
                files_per_block: int = 64) -> Dataset:
    """Decode images into an "image" ndarray column (reference:
    data.read_images / datasource/image_datasource — PIL decode in tasks).
    `size=(h, w)` resizes so blocks stack dense; `mode` converts e.g.
    "RGB"/"L" (with `size` it defaults to "RGB" — mixed channel counts
    cannot stack)."""
    if size and not mode:
        mode = "RGB"
    files = _expand_by_extensions(paths, list(IMAGE_EXTENSIONS))
    refs = [
        _read_image_task.remote(files[i:i + files_per_block], size, mode)
        for i in builtins.range(0, len(files), files_per_block)
    ]
    return Dataset(refs)


def from_pandas(dfs) -> Dataset:
    """One block per DataFrame (reference: data.from_pandas)."""
    if not isinstance(dfs, (list, tuple)):
        dfs = [dfs]
    refs = [
        ray_tpu.put({c: df[c].to_numpy() for c in df.columns}) for df in dfs
    ]
    return Dataset(refs)


def from_arrow(tables) -> Dataset:
    if not isinstance(tables, (list, tuple)):
        tables = [tables]
    refs = [
        ray_tpu.put({
            name: t.column(name).to_numpy(zero_copy_only=False)
            for name in t.column_names
        })
        for t in tables
    ]
    return Dataset(refs)
