"""ray_tpu.data — streaming datasets over the distributed object store.

Reference: python/ray/data (the streaming-executor subset per SURVEY.md §2.3:
read/from_items → map_batches → iter_batches with operator pools and
backpressure). Blocks are plasma objects; map stages are task/actor pools;
iteration overlaps ingest with downstream compute.
"""

from __future__ import annotations

import builtins
from typing import Any, Dict, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.data.block import Block  # noqa: F401
from ray_tpu.data.dataset import Dataset

DEFAULT_BLOCK_ROWS = 1024


def from_items(items: List[Any], *, override_num_blocks: Optional[int] = None
               ) -> Dataset:
    """Create a dataset from a python list (reference: data.from_items)."""
    from ray_tpu.data._streaming import _rows_to_block

    n = len(items)
    if n == 0:
        return Dataset([])
    nblocks = override_num_blocks or max(1, min(32, n // DEFAULT_BLOCK_ROWS or 1))
    per = max(1, (n + nblocks - 1) // nblocks)
    refs = []
    for i in builtins.range(0, n, per):
        chunk = list(items[i:i + per])
        refs.append(ray_tpu.put(_rows_to_block(chunk)))
    return Dataset(refs)


def range(n: int, *, override_num_blocks: Optional[int] = None) -> Dataset:  # noqa: A001
    if n == 0:
        return Dataset([])
    nblocks = override_num_blocks or max(1, min(32, n // DEFAULT_BLOCK_ROWS or 1))
    per = max(1, (n + nblocks - 1) // nblocks)
    refs = [
        ray_tpu.put({"id": np.arange(i, min(n, i + per), dtype=np.int64)})
        for i in builtins.range(0, n, per)
    ]
    return Dataset(refs)


def from_numpy(arr, column: str = "data",
               override_num_blocks: Optional[int] = None) -> Dataset:
    arr = np.asarray(arr)
    if len(arr) == 0:
        return Dataset([])
    nblocks = override_num_blocks or max(1, min(32, len(arr) // DEFAULT_BLOCK_ROWS or 1))
    per = max(1, (len(arr) + nblocks - 1) // nblocks)
    refs = [
        ray_tpu.put({column: arr[i:i + per]})
        for i in builtins.range(0, len(arr), per)
    ]
    return Dataset(refs)


@ray_tpu.remote
def _read_parquet_task(path: str, columns):
    import pyarrow.parquet as pq

    table = pq.read_table(path, columns=columns)
    return {
        name: table.column(name).to_numpy(zero_copy_only=False)
        for name in table.column_names
    }


def read_parquet(paths, *, columns: Optional[List[str]] = None) -> Dataset:
    """One block per parquet file, read in parallel by tasks
    (reference: data.read_parquet / datasource/parquet_datasource)."""
    import glob
    import os

    if isinstance(paths, str):
        paths = [paths]
    files: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            files.extend(sorted(glob.glob(os.path.join(p, "*.parquet"))))
        else:
            files.extend(sorted(glob.glob(p)) or [p])
    if not files:
        raise FileNotFoundError(f"no parquet files under {paths}")
    refs = [_read_parquet_task.remote(f, columns) for f in files]
    return Dataset(refs)


@ray_tpu.remote
def _read_csv_task(path: str):
    import pyarrow.csv as pcsv

    table = pcsv.read_csv(path)
    return {
        name: table.column(name).to_numpy(zero_copy_only=False)
        for name in table.column_names
    }


def read_csv(paths) -> Dataset:
    import glob
    import os

    if isinstance(paths, str):
        paths = [paths]
    files: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            files.extend(sorted(glob.glob(os.path.join(p, "*.csv"))))
        else:
            files.extend(sorted(glob.glob(p)) or [p])
    if not files:
        raise FileNotFoundError(f"no csv files under {paths}")
    refs = [_read_csv_task.remote(f) for f in files]
    return Dataset(refs)
