"""ray_tpu.data — streaming datasets over the distributed object store.

Reference: python/ray/data (the streaming-executor subset per SURVEY.md §2.3:
read/from_items → map_batches → iter_batches with operator pools and
backpressure). Blocks are plasma objects; map stages are task/actor pools;
iteration overlaps ingest with downstream compute.
"""

from __future__ import annotations

import builtins
from typing import Any, Dict, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.data.block import Block  # noqa: F401
from ray_tpu.data.dataset import Dataset

DEFAULT_BLOCK_ROWS = 1024


def from_items(items: List[Any], *, override_num_blocks: Optional[int] = None
               ) -> Dataset:
    """Create a dataset from a python list (reference: data.from_items)."""
    from ray_tpu.data._streaming import _rows_to_block

    n = len(items)
    if n == 0:
        return Dataset([])
    nblocks = override_num_blocks or max(1, min(32, n // DEFAULT_BLOCK_ROWS or 1))
    per = max(1, (n + nblocks - 1) // nblocks)
    refs = []
    for i in builtins.range(0, n, per):
        chunk = list(items[i:i + per])
        refs.append(ray_tpu.put(_rows_to_block(chunk)))
    return Dataset(refs)


def range(n: int, *, override_num_blocks: Optional[int] = None) -> Dataset:  # noqa: A001
    if n == 0:
        return Dataset([])
    nblocks = override_num_blocks or max(1, min(32, n // DEFAULT_BLOCK_ROWS or 1))
    per = max(1, (n + nblocks - 1) // nblocks)
    refs = [
        ray_tpu.put({"id": np.arange(i, min(n, i + per), dtype=np.int64)})
        for i in builtins.range(0, n, per)
    ]
    return Dataset(refs)


def from_numpy(arr, column: str = "data",
               override_num_blocks: Optional[int] = None) -> Dataset:
    arr = np.asarray(arr)
    if len(arr) == 0:
        return Dataset([])
    nblocks = override_num_blocks or max(1, min(32, len(arr) // DEFAULT_BLOCK_ROWS or 1))
    per = max(1, (len(arr) + nblocks - 1) // nblocks)
    refs = [
        ray_tpu.put({column: arr[i:i + per]})
        for i in builtins.range(0, len(arr), per)
    ]
    return Dataset(refs)


@ray_tpu.remote
def _read_parquet_task(path: str, columns, filters):
    import pyarrow.parquet as pq

    # columns + filters push down into the parquet reader: row groups
    # whose statistics exclude the predicate never leave disk
    # (reference: datasource/parquet_datasource filter pushdown)
    table = pq.read_table(path, columns=columns, filters=filters)
    return {
        name: table.column(name).to_numpy(zero_copy_only=False)
        for name in table.column_names
    }


def read_parquet(paths, *, columns: Optional[List[str]] = None,
                 filter: Optional[list] = None) -> Dataset:
    """One block per parquet file, read in parallel by tasks
    (reference: data.read_parquet / datasource/parquet_datasource).
    `filter` takes pyarrow DNF filters, e.g. [("x", ">", 5)] — pushed
    down to row-group pruning."""
    refs = [
        _read_parquet_task.remote(f, columns, filter)
        for f in _expand_files(paths, ".parquet")
    ]
    return Dataset(refs)


@ray_tpu.remote
def _read_csv_task(path: str):
    import pyarrow.csv as pcsv

    table = pcsv.read_csv(path)
    return {
        name: table.column(name).to_numpy(zero_copy_only=False)
        for name in table.column_names
    }


def read_csv(paths) -> Dataset:
    refs = [_read_csv_task.remote(f) for f in _expand_files(paths, ".csv")]
    return Dataset(refs)


def _expand_files(paths, suffix: str) -> List[str]:
    import glob
    import os

    if isinstance(paths, str):
        paths = [paths]
    files: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            files.extend(sorted(glob.glob(os.path.join(p, f"*{suffix}"))))
        else:
            files.extend(sorted(glob.glob(p)) or [p])
    if not files:
        raise FileNotFoundError(f"no {suffix} files under {paths}")
    return files


@ray_tpu.remote
def _read_json_task(path: str):
    import pyarrow.json as pjson

    table = pjson.read_json(path)
    return {
        name: table.column(name).to_numpy(zero_copy_only=False)
        for name in table.column_names
    }


def read_json(paths) -> Dataset:
    """Newline-delimited JSON, one block per file
    (reference: data.read_json / datasource/json_datasource)."""
    refs = [_read_json_task.remote(f) for f in _expand_files(paths, ".json")]
    return Dataset(refs)


@ray_tpu.remote
def _read_text_task(path: str):
    with open(path) as f:
        return {"text": np.asarray([ln.rstrip("\n") for ln in f], dtype=object)}


def read_text(paths) -> Dataset:
    """One row per line (reference: data.read_text)."""
    refs = [_read_text_task.remote(f) for f in _expand_files(paths, ".txt")]
    return Dataset(refs)


def from_pandas(dfs) -> Dataset:
    """One block per DataFrame (reference: data.from_pandas)."""
    if not isinstance(dfs, (list, tuple)):
        dfs = [dfs]
    refs = [
        ray_tpu.put({c: df[c].to_numpy() for c in df.columns}) for df in dfs
    ]
    return Dataset(refs)


def from_arrow(tables) -> Dataset:
    if not isinstance(tables, (list, tuple)):
        tables = [tables]
    refs = [
        ray_tpu.put({
            name: t.column(name).to_numpy(zero_copy_only=False)
            for name in t.column_names
        })
        for t in tables
    ]
    return Dataset(refs)
