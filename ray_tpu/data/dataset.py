"""Dataset: lazy logical plan over object-store blocks
(reference: python/ray/data/dataset.py:139 — the streaming subset).

A Dataset is (source block refs, chain of map operators). Transformations
append operators; consumption (iter_batches/take/count/materialize) runs the
streaming executor. Blocks live in plasma; workers read them zero-copy.
"""

from __future__ import annotations

import logging
from typing import Any, Callable, Dict, Iterator, List, Optional, Union

import numpy as np

import ray_tpu
from ray_tpu.data._streaming import (
    DEFAULT_MAX_IN_FLIGHT,
    MapOperator,
    execute_plan,
    iter_batches_from_stream,
)
from ray_tpu.data.block import (
    Block,
    block_num_rows,
    block_schema,
    concat_blocks,
    rows_of,
    slice_block,
)

logger = logging.getLogger("ray_tpu.data")


@ray_tpu.remote(num_cpus=0.05)
def _block_num_rows_task(block):
    return block_num_rows(block)


@ray_tpu.remote(num_cpus=0.05)
def _slice_block_task(block, start: int, end: int):
    return slice_block(block, start, end)


class Dataset:
    def __init__(self, source_refs: List[Any],
                 operators: Optional[List[MapOperator]] = None,
                 extra_legs: Optional[List["Dataset"]] = None):
        self._source_refs = list(source_refs)
        self._operators = list(operators or [])
        # union() legs: independent (refs, ops) plans appended lazily
        self._extra_legs: List["Dataset"] = list(extra_legs or [])

    # ---------------------------------------------------------- transforms

    def _with_op(self, op) -> "Dataset":
        return Dataset(
            self._source_refs, self._operators + [op],
            [leg._with_op(op) for leg in self._extra_legs],
        )

    def map_batches(
        self,
        fn: Union[Callable, type],
        *,
        batch_size: Optional[int] = None,
        concurrency: Optional[int] = None,
        fn_constructor_args: tuple = (),
        num_cpus: float = 1.0,
        max_in_flight: int = DEFAULT_MAX_IN_FLIGHT,
    ) -> "Dataset":
        """Apply fn to whole blocks (reference: Dataset.map_batches). A class
        fn runs on an actor pool of `concurrency` actors; a plain function
        runs as tasks. batch_size=None maps entire blocks (recommended — the
        executor already sizes blocks)."""
        is_class = isinstance(fn, type)
        op = MapOperator(
            fn,
            is_batch_fn=True,
            compute_actors=(concurrency or 2) if is_class else 0,
            fn_constructor_args=fn_constructor_args,
            num_cpus=num_cpus,
            max_in_flight=(concurrency or max_in_flight)
            if not is_class else max_in_flight,
            name=getattr(fn, "__name__", "MapBatches"),
        )
        ds = self
        if batch_size is not None:
            from ray_tpu.data._streaming import RechunkOperator

            ds = ds._with_op(RechunkOperator(batch_size))
        return ds._with_op(op)

    def map(self, fn: Callable, *, num_cpus: float = 1.0,
            max_in_flight: int = DEFAULT_MAX_IN_FLIGHT) -> "Dataset":
        return self._with_op(MapOperator(
            fn, is_batch_fn=False, num_cpus=num_cpus,
            max_in_flight=max_in_flight, name="Map",
        ))

    def flat_map(self, fn: Callable) -> "Dataset":
        def batch_fn(block):
            out = []
            for row in rows_of(block):
                out.extend(fn(row))
            from ray_tpu.data._streaming import _rows_to_block

            return _rows_to_block(out)

        return self._with_op(
            MapOperator(batch_fn, is_batch_fn=True, name="FlatMap")
        )

    def filter(self, fn: Callable) -> "Dataset":
        def batch_fn(block):
            if isinstance(block, dict):
                keep = [i for i, row in enumerate(rows_of(block)) if fn(row)]
                return {k: np.asarray(v)[keep] for k, v in block.items()}
            return [r for r in block if fn(r)]

        return self._with_op(
            MapOperator(batch_fn, is_batch_fn=True, name="Filter")
        )

    # --------------------------------------------------------- re-chunking

    def repartition(self, num_blocks: int) -> "Dataset":
        """Materializing re-chunk into num_blocks equal-ish blocks."""
        blocks = [ray_tpu.get(r) for r in self._iter_block_refs()]
        whole = concat_blocks(blocks)
        n = block_num_rows(whole)
        per = max(1, (n + num_blocks - 1) // num_blocks)
        refs = [
            ray_tpu.put(slice_block(whole, i * per, min(n, (i + 1) * per)))
            for i in range(min(num_blocks, (n + per - 1) // per))
        ]
        return Dataset(refs)

    def repartition_by_rows(self, rows_per_block: int) -> "Dataset":
        return self.repartition(
            max(1, (self.count() + rows_per_block - 1) // rows_per_block)
        )

    def random_shuffle(self, seed: Optional[int] = None, *,
                       num_parts: Optional[int] = None) -> "Dataset":
        """Distributed two-stage shuffle (reference: Dataset.random_shuffle
        via the shuffle exchange): scatter tasks split every block's rows
        uniformly across partitions, merge tasks permute within each — the
        driver only routes refs, never block data. `num_parts` sets the
        output block count (default: input block count, capped) — raise it
        for very large datasets so each merge task's partition stays
        worker-memory-sized."""
        from ray_tpu.data._exchange import distributed_random_shuffle

        refs = list(self._iter_block_refs())
        return Dataset(distributed_random_shuffle(refs, seed,
                                                  num_parts=num_parts))

    def split(self, n: int, equal: bool = True) -> List["Dataset"]:
        """Ref-level row-exact split (reference: Dataset.split, which
        plans over block metadata and never materializes on the driver).
        The driver sees only per-block ROW COUNTS; whole blocks move into
        shards by reference, and only the blocks straddling a shard
        boundary are re-sliced — by tasks, where the data lives.
        equal=True gives identical shard sizes, dropping up to n-1
        trailing rows (like the reference); equal=False balances
        floor/ceil sizes with no rows dropped."""
        refs = list(self._iter_block_refs())
        counts = ray_tpu.get([_block_num_rows_task.remote(r) for r in refs])
        total = sum(counts)
        if equal:
            per = total // n
            if per == 0:
                raise ValueError(
                    f"cannot split {total} rows into {n} equal non-empty "
                    "shards"
                )
            sizes = [per] * n
        else:
            base, rem = divmod(total, n)
            sizes = [base + (1 if i < rem else 0) for i in range(n)]
        shards: List[Dataset] = []
        bi, offset = 0, 0  # cursor: current block, rows already consumed
        for size in sizes:
            parts, need = [], size
            while need > 0:
                avail = counts[bi] - offset
                take = min(avail, need)
                if take == counts[bi] and offset == 0:
                    parts.append(refs[bi])  # whole block: zero-copy move
                else:
                    parts.append(_slice_block_task.remote(
                        refs[bi], offset, offset + take))
                offset += take
                need -= take
                if offset == counts[bi]:
                    bi += 1
                    offset = 0
            shards.append(Dataset(parts))
        return shards

    def split_blocks(self, n: int) -> List["Dataset"]:
        """Lazy block-granular split: shard i keeps source blocks i::n and
        the SAME pending operator chain, so per-shard streaming (and
        ingest/compute overlap) is preserved. Row counts are equal only up
        to block granularity — the Train ingest path uses this (reference:
        streaming_split keeps sharding lazy the same way)."""
        leg_shards = [leg.split_blocks(n) for leg in self._extra_legs]
        shards: List[Dataset] = []
        for i in range(n):
            shard = Dataset(self._source_refs[i::n], self._operators)
            for per_leg in leg_shards:
                shard = shard.union(per_leg[i])
            shards.append(shard)
        return shards

    def union(self, other: "Dataset") -> "Dataset":
        """Lazy concatenation: both plans stay pending until consumption."""
        return Dataset(
            self._source_refs, self._operators,
            self._extra_legs + [other],
        )

    def limit(self, n: int) -> "Dataset":
        """Materializing head-n (reference: Dataset.limit)."""
        rows = self.take(n)
        from ray_tpu.data._streaming import _rows_to_block

        return Dataset([ray_tpu.put(_rows_to_block(rows))] if rows else [])

    def sort(self, key: Union[str, Callable, None] = None,
             descending: bool = False) -> "Dataset":
        """Distributed sample-sort (reference: Dataset.sort via
        data/_internal/planner/exchange/sort_task_spec.py): sample keys →
        range-partition map tasks → per-partition sort-merge tasks. The
        driver handles only key samples and boundary values, so datasets
        larger than driver memory sort fine."""
        from ray_tpu.data._exchange import distributed_sort

        refs = list(self._iter_block_refs())
        return Dataset(distributed_sort(refs, key, descending))

    def unique(self, column: str) -> List[Any]:
        vals = set()
        for block in self.iter_batches(batch_size=None):
            if isinstance(block, dict):
                vals.update(np.asarray(block[column]).tolist())
            else:
                vals.update(r[column] for r in block)
        return sorted(vals)

    def zip(self, other: "Dataset") -> "Dataset":
        """Materializing columnar zip of equal-length datasets
        (reference: Dataset.zip)."""
        a = concat_blocks([ray_tpu.get(r) for r in self._iter_block_refs()])
        b = concat_blocks([ray_tpu.get(r) for r in other._iter_block_refs()])
        if block_num_rows(a) != block_num_rows(b):
            raise ValueError("zip requires equal row counts")
        if block_num_rows(a) == 0:
            return Dataset([])
        if not (isinstance(a, dict) and isinstance(b, dict)):
            raise TypeError("zip requires column blocks")
        merged = dict(a)
        for k, v in b.items():
            merged[k if k not in merged else f"{k}_1"] = v
        return Dataset([ray_tpu.put(merged)])

    def groupby(self, key: str) -> "GroupedData":
        return GroupedData(self, key)

    def to_arrow(self):
        """Materialize as a list of pyarrow Tables, one per block
        (reference: Dataset.to_arrow_refs — the Arrow bridge out)."""
        import pyarrow as pa

        out = []
        for ref in self._iter_block_refs():
            block = ray_tpu.get(ref)
            if isinstance(block, dict):
                out.append(pa.table({k: np.asarray(v) for k, v in block.items()}))
            else:
                out.append(pa.Table.from_pylist(list(block)))
        return out

    # ---------------------------------------------------- simple aggregates

    def _column(self, column: str) -> np.ndarray:
        parts = [
            np.asarray(b[column])
            for b in self.iter_batches(batch_size=None)
            if block_num_rows(b)
        ]
        return np.concatenate(parts) if parts else np.array([])

    def sum(self, column: str):
        return self._column(column).sum().item()

    def mean(self, column: str):
        return self._column(column).mean().item()

    def min(self, column: str):
        return self._column(column).min().item()

    def max(self, column: str):
        return self._column(column).max().item()

    def std(self, column: str, ddof: int = 1):
        return self._column(column).std(ddof=ddof).item()

    # -------------------------------------------------------------- writes

    def _column_blocks(self):
        for i, ref in enumerate(self._iter_block_refs()):
            block = ray_tpu.get(ref)
            if not isinstance(block, dict):
                from ray_tpu.data._streaming import _rows_to_block

                block = _rows_to_block(list(rows_of(block)))
                if not isinstance(block, dict):
                    block = {"value": np.asarray(block, dtype=object)}
            yield i, block

    def write_parquet(self, path: str) -> List[str]:
        """One file per block under `path`
        (reference: Dataset.write_parquet)."""
        import os

        import pyarrow as pa
        import pyarrow.parquet as pq

        os.makedirs(path, exist_ok=True)
        out = []
        for i, block in self._column_blocks():
            fp = os.path.join(path, f"part-{i:05d}.parquet")
            pq.write_table(pa.table(dict(block)), fp)
            out.append(fp)
        return out

    def write_csv(self, path: str) -> List[str]:
        import os

        import pyarrow as pa
        import pyarrow.csv as pcsv

        os.makedirs(path, exist_ok=True)
        out = []
        for i, block in self._column_blocks():
            fp = os.path.join(path, f"part-{i:05d}.csv")
            pcsv.write_csv(pa.table(dict(block)), fp)
            out.append(fp)
        return out

    def write_json(self, path: str) -> List[str]:
        import json
        import os

        os.makedirs(path, exist_ok=True)
        out = []
        for i, ref in enumerate(self._iter_block_refs()):
            fp = os.path.join(path, f"part-{i:05d}.json")
            with open(fp, "w") as f:
                for row in rows_of(ray_tpu.get(ref)):
                    if isinstance(row, dict):
                        row = {
                            k: v.item() if isinstance(v, np.generic) else v
                            for k, v in row.items()
                        }
                    f.write(json.dumps(row) + "\n")
            out.append(fp)
        return out

    def to_pandas(self):
        import pandas as pd

        whole = concat_blocks(
            [ray_tpu.get(r) for r in self._iter_block_refs()]
        )
        if isinstance(whole, dict):
            return pd.DataFrame(dict(whole))
        return pd.DataFrame({"value": list(whole)})

    # ---------------------------------------------------------- consumption

    def _iter_block_refs(self) -> Iterator[Any]:
        import itertools

        return itertools.chain(
            execute_plan(self._source_refs, self._operators),
            *(leg._iter_block_refs() for leg in self._extra_legs),
        )

    def iter_batches(self, *, batch_size: Optional[int] = 256,
                     prefetch_blocks: int = 2) -> Iterator[Block]:
        """Streaming iteration: upstream map stages keep working while the
        consumer processes the current batch (ingest/compute overlap)."""
        return iter_batches_from_stream(
            self._iter_block_refs(), batch_size, prefetch_blocks
        )

    def iter_rows(self) -> Iterator[Any]:
        for block in self.iter_batches(batch_size=None):
            yield from rows_of(block)

    def iter_jax_batches(self, *, batch_size: Optional[int] = 256,
                         sharding=None, dtypes=None, drop_last: bool = False,
                         prefetch_blocks: int = 2) -> Iterator[Dict[str, Any]]:
        """iter_batches with each column placed on device as a jax array
        (reference: iterator.iter_torch_batches — the jax-first analogue).
        `sharding` is an optional jax.sharding.Sharding (e.g. a batch
        NamedSharding over a mesh's dp axis) applied by device_put; ingest
        of the NEXT batch overlaps with the caller's step on the current
        one via the streaming executor."""
        import jax
        import jax.numpy as jnp

        n_shards = 1
        if sharding is not None:
            n_shards = getattr(sharding, "num_devices", None) or len(
                getattr(sharding, "device_set", [1]))
        for block in self.iter_batches(batch_size=batch_size,
                                       prefetch_blocks=prefetch_blocks):
            if not isinstance(block, dict):
                raise TypeError("iter_jax_batches requires column blocks")
            rows = block_num_rows(block)
            if sharding is not None and rows % n_shards:
                # a partial final batch can't be laid out on the mesh axis
                if drop_last:
                    continue
                raise ValueError(
                    f"final batch of {rows} rows is not divisible by the "
                    f"{n_shards}-way sharding; pass drop_last=True (or a "
                    "batch_size divisible by the mesh axis)"
                )
            out = {}
            for k, v in block.items():
                arr = np.asarray(v)
                if dtypes and k in dtypes:
                    arr = arr.astype(dtypes[k])
                out[k] = (jax.device_put(arr, sharding)
                          if sharding is not None else jnp.asarray(arr))
            yield out

    def iter_torch_batches(self, *, batch_size: Optional[int] = 256,
                           dtypes=None, prefetch_blocks: int = 2
                           ) -> Iterator[Dict[str, Any]]:
        """iter_batches as dicts of torch tensors
        (reference: data/iterator.py iter_torch_batches)."""
        import torch

        for block in self.iter_batches(batch_size=batch_size,
                                       prefetch_blocks=prefetch_blocks):
            if not isinstance(block, dict):
                raise TypeError("iter_torch_batches requires column blocks")
            out = {}
            for k, v in block.items():
                arr = np.ascontiguousarray(v)
                t = torch.from_numpy(arr)
                if dtypes and k in dtypes:
                    t = t.to(dtypes[k])
                out[k] = t
            yield out

    def streaming_split(self, n: int) -> List["Dataset"]:
        """Reference-named alias of split_blocks: n lazy shards that keep
        streaming through the pending operator chain (reference:
        Dataset.streaming_split — Train ingest path)."""
        return self.split_blocks(n)

    def take(self, n: int = 20) -> List[Any]:
        out: List[Any] = []
        for row in self.iter_rows():
            out.append(row)
            if len(out) >= n:
                break
        return out

    def take_all(self) -> List[Any]:
        return list(self.iter_rows())

    def count(self) -> int:
        if not self._operators and not self._extra_legs:
            if not self._source_refs:
                return 0
            return sum(
                block_num_rows(b)
                for b in ray_tpu.get(list(self._source_refs))
            )
        return sum(
            block_num_rows(b) for b in self.iter_batches(batch_size=None)
        )

    def schema(self):
        for r in self._iter_block_refs():
            return block_schema(ray_tpu.get(r))
        return None

    def materialize(self) -> "Dataset":
        """Run the plan now; the result holds only materialized blocks."""
        return Dataset(list(self._iter_block_refs()))

    def num_blocks(self) -> int:
        return len(self._source_refs) + sum(
            leg.num_blocks() for leg in self._extra_legs
        )

    def __repr__(self):
        ops = " -> ".join(op.name for op in self._operators) or "source"
        return (f"Dataset(num_blocks={len(self._source_refs)}, "
                f"plan={ops})")


class GroupedData:
    """Group aggregation over the distributed sample-sort exchange
    (reference: python/ray/data/grouped_data.py over
    exchange/sort_task_spec.py): range-partitioning by the group key puts
    every row of a key into exactly one partition, so per-partition
    aggregation tasks are exact and nothing materializes on the driver."""

    def __init__(self, ds: Dataset, key: str):
        self._ds = ds
        self._key = key

    def _agg(self, column: Optional[str], how: str) -> Dataset:
        from ray_tpu.data._exchange import distributed_group_agg

        refs = list(self._ds._iter_block_refs())
        if not refs:
            name = f"{how}({column})" if column else f"{how}()"
            return Dataset([ray_tpu.put({
                self._key: np.array([]), name: np.array([]),
            })])
        return Dataset(
            distributed_group_agg(refs, self._key, column, how)
        )

    def count(self) -> Dataset:
        return self._agg(None, "count")

    def sum(self, column: str) -> Dataset:
        return self._agg(column, "sum")

    def mean(self, column: str) -> Dataset:
        return self._agg(column, "mean")

    def min(self, column: str) -> Dataset:
        return self._agg(column, "min")

    def max(self, column: str) -> Dataset:
        return self._agg(column, "max")

    def std(self, column: str) -> Dataset:
        return self._agg(column, "std")

    def map_groups(self, fn: Callable) -> Dataset:
        """Apply fn to each group's sub-block; concat per partition
        (groups never split across partitions)."""
        from ray_tpu.data._exchange import distributed_group_map

        refs = list(self._ds._iter_block_refs())
        if not refs:
            return Dataset([])
        return Dataset(distributed_group_map(refs, self._key, fn))
