"""Pluggable streaming-executor backpressure policies.

Reference: data/_internal/execution/backpressure_policy/ — the streaming
executor consults a policy chain before launching more block tasks, so
memory pressure (not just a fixed window) can throttle ingest. The
default chain caps per-operator concurrency at the operator's
max_in_flight; ObjectStoreMemoryBackpressurePolicy additionally holds
launches while the local plasma store is nearly full (letting the
consumer + spiller drain it). Policies are process-wide via DataContext:

    from ray_tpu.data.backpressure import (
        DataContext, ObjectStoreMemoryBackpressurePolicy)

    DataContext.get_current().backpressure_policies.append(
        ObjectStoreMemoryBackpressurePolicy(0.7))
"""

from __future__ import annotations

from typing import List, Optional


class BackpressurePolicy:
    """Decides whether an operator may launch one more block task.
    Called with the operator and its current in-flight count; returning
    False holds the launch until an outstanding block completes (the
    executor always retains progress: an empty window may always
    launch)."""

    def can_add_input(self, op, in_flight: int) -> bool:
        raise NotImplementedError


class ConcurrencyCapBackpressurePolicy(BackpressurePolicy):
    """The default: per-operator in-flight window (the operator's
    max_in_flight, or a global cap if given)."""

    def __init__(self, cap: Optional[int] = None):
        self.cap = cap

    def can_add_input(self, op, in_flight: int) -> bool:
        cap = self.cap or getattr(op, "max_in_flight", 4)
        return in_flight < cap


class ObjectStoreMemoryBackpressurePolicy(BackpressurePolicy):
    """Hold launches while local plasma usage exceeds a fraction of
    capacity — intermediate blocks otherwise race the spiller and evict
    hot objects (reference: backpressure based on object-store memory)."""

    def __init__(self, fraction: float = 0.8):
        self.fraction = fraction

    def can_add_input(self, op, in_flight: int) -> bool:
        try:
            from ray_tpu._private.worker import get_global_worker

            stats = get_global_worker().plasma.stats()
            cap = stats.get("capacity_bytes") or 0
            if not cap:
                return True
            return stats["used_bytes"] < self.fraction * cap
        except Exception:
            return True


class DataContext:
    """Process-wide execution options (reference: data/context.py
    DataContext.get_current())."""

    _current: Optional["DataContext"] = None

    def __init__(self):
        self.backpressure_policies: List[BackpressurePolicy] = [
            ConcurrencyCapBackpressurePolicy()
        ]

    @classmethod
    def get_current(cls) -> "DataContext":
        if cls._current is None:
            cls._current = DataContext()
        return cls._current
