"""Streaming executor: pull-based operator pipeline with bounded in-flight
work per stage (reference: data/_internal/execution/streaming_executor.py:48,
physical_operator.py:139, backpressure_policy/).

Each map stage keeps at most ``max_in_flight`` block tasks outstanding and
yields output refs as they complete, pulling from its upstream lazily — so a
downstream consumer (e.g. a training loop) overlaps ingest with compute and
memory stays bounded at stage_depth x block_size instead of dataset_size.
"""

from __future__ import annotations

import logging
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import ray_tpu
from ray_tpu.data.block import Block, block_num_rows, concat_blocks, slice_block

logger = logging.getLogger("ray_tpu.data")

DEFAULT_MAX_IN_FLIGHT = 8


@ray_tpu.remote
def _map_block_task(fn_payload, block, *, is_batch_fn: bool):
    import cloudpickle

    fn = cloudpickle.loads(fn_payload)
    return _apply(fn, block, is_batch_fn)


def _apply(fn, block: Block, is_batch_fn: bool) -> Block:
    from ray_tpu.data.block import rows_of

    if is_batch_fn:
        return fn(block)
    out = [fn(r) for r in rows_of(block)]
    return _rows_to_block(out)


def _rows_to_block(rows: List[Any]) -> Block:
    import numpy as np

    if rows and isinstance(rows[0], dict) and all(
        isinstance(r, dict) for r in rows
    ):
        keys = rows[0].keys()
        if all(r.keys() == keys for r in rows):
            try:
                return {k: np.asarray([r[k] for r in rows]) for k in keys}
            except Exception:
                return rows
    return rows


class MapOperator:
    """One logical map_batches/map/filter stage."""

    def __init__(self, fn: Callable, *, is_batch_fn: bool,
                 compute_actors: int = 0, fn_constructor_args: tuple = (),
                 num_cpus: float = 1.0,
                 max_in_flight: int = DEFAULT_MAX_IN_FLIGHT,
                 name: str = "Map"):
        self.fn = fn
        self.is_batch_fn = is_batch_fn
        self.compute_actors = compute_actors
        self.fn_constructor_args = fn_constructor_args
        self.num_cpus = num_cpus
        self.max_in_flight = max_in_flight
        self.name = name

    # ------------------------------------------------------------- execution

    def stream(self, upstream: Iterator[Any]) -> Iterator[Any]:
        if self.compute_actors:
            yield from self._stream_actors(upstream)
        else:
            yield from self._stream_tasks(upstream)

    def _stream_tasks(self, upstream: Iterator[Any]) -> Iterator[Any]:
        import collections

        import cloudpickle

        from ray_tpu.data.backpressure import DataContext

        payload = cloudpickle.dumps(self.fn)
        policies = DataContext.get_current().backpressure_policies
        # Yield in INPUT order (completion order would make block order — and
        # therefore take()/iter_batches contents — nondeterministic): block
        # on the oldest outstanding task whenever the policy chain (default:
        # the max_in_flight window; optionally object-store pressure) holds
        # the next launch.
        in_flight: "collections.deque" = collections.deque()
        # carry the logical stage name into the task spec: the timeline /
        # task events then show "Data[MapBatches(fn)+Filter]" instead of an
        # anonymous _map_block_task (reference: data tasks named per op)
        task = _map_block_task.options(
            num_cpus=self.num_cpus, name=f"Data[{self.name}]"
        )

        def may_launch():
            return all(p.can_add_input(self, len(in_flight)) for p in policies)

        for ref in upstream:
            while in_flight and not may_launch():
                yield in_flight.popleft()
            if not in_flight and not may_launch():
                # resource-pressure hold with an empty window: give the
                # consumer/spiller a bounded drain window, then proceed
                # (progress beats a perfect cap)
                import time as _time

                deadline = _time.time() + 10
                while not may_launch() and _time.time() < deadline:
                    _time.sleep(0.05)
            in_flight.append(
                task.remote(payload, ref, is_batch_fn=self.is_batch_fn)
            )
        while in_flight:
            yield in_flight.popleft()

    def _stream_actors(self, upstream: Iterator[Any]) -> Iterator[Any]:
        """Class-based UDF on a pool of actors (reference: ActorPoolStrategy).
        The callable is constructed once per actor and reused per block."""

        @ray_tpu.remote
        class _MapWorker:
            def __init__(self, fn_payload, ctor_args):
                import cloudpickle

                cls = cloudpickle.loads(fn_payload)
                self.callable = cls(*ctor_args)

            def apply(self, block, is_batch_fn):
                return _apply(self.callable, block, is_batch_fn)

        import cloudpickle

        payload = cloudpickle.dumps(self.fn)
        pool = [
            _MapWorker.options(num_cpus=self.num_cpus).remote(
                payload, self.fn_constructor_args
            )
            for _ in range(self.compute_actors)
        ]
        import collections

        from ray_tpu.data.backpressure import DataContext

        policies = DataContext.get_current().backpressure_policies
        per_actor_cap = max(2, self.max_in_flight // len(pool))
        in_flight: "collections.deque" = collections.deque()  # (ref, idx)
        load = [0] * len(pool)

        def may_launch():
            # the actor path honors the same policy chain as the task
            # path (memory pressure etc.); the pool window is an
            # additional per-actor cap
            return all(
                p.can_add_input(self, sum(load)) for p in policies
            )

        produced: List[Any] = []
        try:
            for ref in upstream:
                while in_flight and not may_launch():
                    done_ref, done_idx = in_flight.popleft()
                    load[done_idx] -= 1
                    yield done_ref
                while sum(load) >= per_actor_cap * len(pool):
                    done_ref, done_idx = in_flight.popleft()
                    load[done_idx] -= 1
                    yield done_ref  # input order preserved
                idx = min(range(len(pool)), key=lambda i: load[i])
                out = pool[idx].apply.remote(ref, self.is_batch_fn)
                in_flight.append((out, idx))
                load[idx] += 1
                produced.append(out)
                if len(produced) >= 32:
                    # prune resolved refs: holding every output ref for the
                    # stage's lifetime would pin the stage's entire output
                    # in the store (the streaming window must stay bounded)
                    _, produced = ray_tpu.wait(
                        produced, num_returns=len(produced), timeout=0
                    )
            while in_flight:
                done_ref, done_idx = in_flight.popleft()
                load[done_idx] -= 1
                yield done_ref
        finally:
            # The stage yields refs as soon as they're submitted; a
            # downstream stage may not have RESOLVED them yet. Wait until
            # every produced block is computed before killing the pool, or
            # consumers see ActorDiedError on perfectly good refs.
            if produced:
                try:
                    # only still-unresolved refs remain after pruning
                    ray_tpu.wait(produced, num_returns=len(produced),
                                 timeout=60)
                except Exception:
                    pass
            for a in pool:
                try:
                    ray_tpu.kill(a)
                except Exception:
                    pass


def rechunk_blocks(blocks: Iterator[Block], rows: int) -> Iterator[Block]:
    """Re-chunk a stream of blocks to exactly `rows` per block (short tail),
    with bounded memory: the current accumulation plus one upstream block."""
    pending: Optional[Block] = None
    for block in blocks:
        if pending is not None:
            block = concat_blocks([pending, block])
            pending = None
        n = block_num_rows(block)
        off = 0
        while n - off >= rows:
            yield slice_block(block, off, off + rows)
            off += rows
        if off < n:
            pending = slice_block(block, off, n)
    if pending is not None and block_num_rows(pending):
        yield pending


class RechunkOperator:
    """Lazy in-stream re-chunking to a fixed rows-per-block. Used by
    map_batches(batch_size=N) so the plan is never executed twice."""

    def __init__(self, rows_per_block: int):
        self.rows = rows_per_block
        self.name = f"Rechunk({rows_per_block})"

    def stream(self, upstream: Iterator[Any]) -> Iterator[Any]:
        blocks = (ray_tpu.get(r) for r in upstream)
        for out in rechunk_blocks(blocks, self.rows):
            yield ray_tpu.put(out)


class FusedMapOperator(MapOperator):
    """Several adjacent task-based map stages collapsed into one task per
    block (reference: data/_internal/logical/rules/operator_fusion.py —
    MapFusionRule): a map->filter->map chain costs one task launch and one
    block materialization instead of three."""

    def __init__(self, ops: List[MapOperator]):
        chain = [(op.fn, op.is_batch_fn) for op in ops]

        def fused(block, _chain=chain):
            for fn, is_batch in _chain:
                block = _apply(fn, block, is_batch)
            return block

        super().__init__(
            fused,
            is_batch_fn=True,
            num_cpus=max(op.num_cpus for op in ops),
            max_in_flight=min(op.max_in_flight for op in ops),
            name="+".join(op.name for op in ops),
        )


def fuse_operators(operators: List[Any]) -> List[Any]:
    """Plan rewrite: merge runs of adjacent task-based MapOperators.
    Actor-pool stages (stateful UDF construction) and Rechunk stages
    (block-shape barriers) break a run."""
    out: List[Any] = []
    run: List[MapOperator] = []

    def flush():
        if len(run) > 1:
            out.append(FusedMapOperator(run))
        elif run:
            out.append(run[0])
        run.clear()

    for op in operators:
        fusable = (
            isinstance(op, MapOperator)
            and not isinstance(op, FusedMapOperator)
            and not op.compute_actors
        )
        if fusable:
            run.append(op)
        else:
            flush()
            out.append(op)
    flush()
    return out


def execute_plan(source_refs: List[Any],
                 operators: List[MapOperator]) -> Iterator[Any]:
    """Chain the stages into one lazy pull pipeline of block refs (after
    the fusion rewrite)."""
    stream: Iterator[Any] = iter(source_refs)
    for op in fuse_operators(operators):
        stream = op.stream(stream)
    return stream


def iter_batches_from_stream(
    ref_stream: Iterator[Any],
    batch_size: Optional[int],
    prefetch_blocks: int = 2,
) -> Iterator[Block]:
    """Materialize blocks with bounded prefetch and re-chunk to batch_size."""
    import collections

    window: "collections.deque" = collections.deque()

    def blocks():
        while True:
            while len(window) < max(1, prefetch_blocks):
                try:
                    window.append(next(ref_stream))
                except StopIteration:
                    break
            if not window:
                return
            yield ray_tpu.get(window.popleft())

    if batch_size is None:
        yield from blocks()
        return
    yield from rechunk_blocks(blocks(), batch_size)
