"""Distributed sample-sort exchange for sort/groupby.

Reference: data/_internal/planner/exchange/sort_task_spec.py — the three
stage shuffle: (1) sample each block's keys, (2) range-partition every
block into P outputs with boundaries cut from the pooled sample, (3) per
partition, a sort-merge task combines its parts. The driver touches ONLY
the key samples and the boundary values — blocks move block-store ref to
ref between tasks, so datasets larger than driver RAM sort fine. Groupby
rides the same exchange: range partitioning by the group key puts every
row of a key into exactly one partition, so per-partition aggregation is
exact with no cross-partition combine step.
"""

from __future__ import annotations

import operator
from typing import Any, List, Optional, Tuple

import numpy as np

import ray_tpu
from ray_tpu.data.block import Block, block_num_rows, concat_blocks

_SAMPLES_PER_BLOCK = 32
MAX_PARTITIONS = 32


def _as_1d_key_array(vals: list) -> np.ndarray:
    """1-D key array; composite keys (tuples, mixed types) become a 1-D
    object array so argsort/searchsorted compare element-wise with Python
    semantics instead of building an accidental 2-D array."""
    if not vals:
        return np.asarray([])
    try:
        arr = np.asarray(vals)
        if arr.ndim == 1:
            return arr
    except Exception:
        pass
    arr = np.empty(len(vals), dtype=object)
    arr[:] = vals
    return arr


def _key_array(block: Block, key) -> np.ndarray:
    """Extract the sort/group key column of a block as a 1-D array."""
    if isinstance(block, dict):
        if callable(key):
            from ray_tpu.data.block import rows_of

            return _as_1d_key_array([key(r) for r in rows_of(block)])
        if key is None:
            key = next(iter(block))
        return np.asarray(block[key])
    if not block:
        return np.asarray([])
    if callable(key):
        return _as_1d_key_array([key(r) for r in block])
    if key is None and isinstance(block[0], dict):
        key = next(iter(block[0]))
    if key is None:
        return _as_1d_key_array(list(block))
    getter = operator.itemgetter(key)
    return _as_1d_key_array([getter(r) for r in block])


def _take(block: Block, idx: np.ndarray) -> Block:
    if isinstance(block, dict):
        return {k: np.asarray(v)[idx] for k, v in block.items()}
    return [block[i] for i in idx]


@ray_tpu.remote
def _sample_block(block, key, k: int):
    kv = _key_array(block, key)
    if len(kv) <= k:
        return kv
    idx = np.random.RandomState(0xDA7A).choice(len(kv), size=k, replace=False)
    return kv[idx]


def _scatter(block, part_ids: np.ndarray, P: int):
    """Split a block into P parts by a per-row partition-id array; the
    return shape matches num_returns=P task semantics (list for P>1)."""
    if len(part_ids) == 0:
        empty = {k: np.asarray(v)[:0] for k, v in block.items()} \
            if isinstance(block, dict) else []
        return [empty] * P if P > 1 else empty
    out = [_take(block, np.nonzero(part_ids == p)[0]) for p in range(P)]
    return out if P > 1 else out[0]


@ray_tpu.remote
def _range_partition(block, key, boundaries):
    """Split a block into len(boundaries)+1 parts by key range."""
    kv = _key_array(block, key)
    P = len(boundaries) + 1
    if len(kv) == 0:
        return _scatter(block, np.asarray([]), P)
    part = np.searchsorted(_as_1d_key_array(list(boundaries)), kv,
                           side="right")
    return _scatter(block, part, P)


@ray_tpu.remote
def _sort_merge(key, descending, *parts):
    """Concat one partition's parts and sort within it."""
    whole = concat_blocks(list(parts))
    n = block_num_rows(whole)
    if n == 0:
        return whole
    kv = _key_array(whole, key)
    order = np.argsort(kv, kind="stable")
    if descending:
        order = order[::-1]
    return _take(whole, order)


_AGGS = {
    "count": lambda v: len(v),
    "sum": lambda v: np.sum(v).item(),
    "mean": lambda v: np.mean(v).item(),
    "min": lambda v: np.min(v).item(),
    "max": lambda v: np.max(v).item(),
    "std": lambda v: np.std(v, ddof=1).item() if len(v) > 1 else 0.0,
}


@ray_tpu.remote
def _group_agg(key, column, how, *parts):
    """Aggregate one partition's groups (exact: range partitioning puts a
    key's every row in this partition)."""
    whole = concat_blocks(list(parts))
    name = f"{how}({column})" if column else f"{how}()"
    if block_num_rows(whole) == 0:
        return {key: np.asarray([]), name: np.asarray([])}
    keys = np.asarray(whole[key])
    order = np.argsort(keys, kind="stable")
    sorted_keys = keys[order]
    uniq, starts = np.unique(sorted_keys, return_index=True)
    bounds = list(starts) + [len(sorted_keys)]
    if how == "count":
        out = [bounds[i + 1] - bounds[i] for i in range(len(uniq))]
    else:
        vals = np.asarray(whole[column])[order]
        fn = _AGGS[how]
        out = [fn(vals[bounds[i]:bounds[i + 1]]) for i in range(len(uniq))]
    return {key: uniq, name: np.asarray(out)}


@ray_tpu.remote
def _group_map(key, fn, *parts):
    """map_groups over one partition."""
    whole = concat_blocks(list(parts))
    if block_num_rows(whole) == 0:
        return []
    keys = np.asarray(whole[key])
    order = np.argsort(keys, kind="stable")
    sorted_block = {k: np.asarray(v)[order] for k, v in whole.items()}
    sorted_keys = keys[order]
    uniq, starts = np.unique(sorted_keys, return_index=True)
    bounds = list(starts) + [len(sorted_keys)]
    outs = []
    for i in range(len(uniq)):
        sub = {k: v[bounds[i]:bounds[i + 1]] for k, v in sorted_block.items()}
        outs.append(fn(sub))
    return concat_blocks(outs)


def _boundaries(samples: List[np.ndarray], num_parts: int):
    pooled = np.concatenate([s for s in samples if len(s)]) \
        if any(len(s) for s in samples) else np.asarray([])
    if len(pooled) == 0 or num_parts <= 1:
        return []
    pooled = np.sort(pooled)
    cuts = [
        pooled[(len(pooled) * i) // num_parts] for i in range(1, num_parts)
    ]
    # dedupe (heavily skewed samples can repeat a cut — empty partitions
    # are fine, duplicate boundaries are not)
    out = []
    for c in cuts:
        if not out or c > out[-1]:
            out.append(c)
    return out


def exchange_partitions(
    refs: List[Any], key, num_parts: Optional[int] = None
) -> Tuple[List[List[Any]], int]:
    """Common front half: sample keys, cut boundaries, range-partition
    every block. Returns (parts_by_partition, P): parts_by_partition[p]
    is the list of per-block refs for partition p."""
    if not refs:
        return [], 0
    if num_parts is None:
        num_parts = min(len(refs), MAX_PARTITIONS)
    samples = ray_tpu.get(
        [_sample_block.remote(r, key, _SAMPLES_PER_BLOCK) for r in refs]
    )
    bounds = _boundaries(samples, num_parts)
    P = len(bounds) + 1
    part_refs = [
        _range_partition.options(num_returns=P).remote(r, key, bounds)
        for r in refs
    ]
    if P == 1:
        by_part = [[pr for pr in part_refs]]
    else:
        by_part = [
            [block_parts[p] for block_parts in part_refs] for p in range(P)
        ]
    return by_part, P


@ray_tpu.remote
def _random_partition(block, P: int, seed: int):
    """Scatter a block's rows uniformly into P partitions."""
    n = block_num_rows(block)
    if n == 0:
        return _scatter(block, np.asarray([]), P)
    part = np.random.default_rng(seed).integers(0, P, size=n)
    return _scatter(block, part, P)


@ray_tpu.remote
def _shuffle_merge(seed: int, *parts):
    """Concat one partition's parts and permute within it."""
    whole = concat_blocks(list(parts))
    n = block_num_rows(whole)
    if n == 0:
        return whole
    perm = np.random.default_rng(seed).permutation(n)
    return _take(whole, perm)


def distributed_random_shuffle(
    refs: List[Any], seed: Optional[int] = None,
    num_parts: Optional[int] = None,
) -> List[Any]:
    """Two-stage distributed shuffle (reference:
    data/_internal/planner/exchange/shuffle_task_spec.py): every block
    scatters its rows uniformly across P partitions, then each partition
    concat+permutes its parts. The driver holds ONLY refs — blocks move
    store-to-store between tasks, so datasets larger than driver memory
    shuffle fine (the old implementation materialized the whole dataset
    in the driver)."""
    if not refs:
        return []
    # default: preserve the input block count (capped — P scatter outputs
    # exist PER BLOCK, so P*blocks part-objects; beyond the cap pass
    # num_parts explicitly and budget worker memory at dataset/P per merge)
    P = num_parts or min(len(refs), 128)
    base = int(np.random.default_rng(seed).integers(0, 2**31))
    if P == 1:
        # single partition: the scatter stage would only copy blocks
        return [_shuffle_merge.remote(base, *refs)]
    part_refs = [
        _random_partition.options(num_returns=P).remote(r, P, base + i)
        for i, r in enumerate(refs)
    ]
    by_part = [
        [block_parts[p] for block_parts in part_refs] for p in range(P)
    ]
    return [
        _shuffle_merge.remote(base + 1_000_003 + p, *parts)
        for p, parts in enumerate(by_part)
    ]


def distributed_sort(refs: List[Any], key, descending: bool) -> List[Any]:
    """Sample-sort: returns refs of globally-sorted blocks (partition p
    holds keys <= partition p+1's; each block internally sorted)."""
    by_part, P = exchange_partitions(refs, key)
    if P == 0:
        return []
    merged = [
        _sort_merge.remote(key, descending, *parts) for parts in by_part
    ]
    return list(reversed(merged)) if descending else merged


def distributed_group_agg(
    refs: List[Any], key: str, column: Optional[str], how: str
) -> List[Any]:
    by_part, P = exchange_partitions(refs, key)
    if P == 0:
        return []
    return [
        _group_agg.remote(key, column, how, *parts) for parts in by_part
    ]


def distributed_group_map(refs: List[Any], key: str, fn) -> List[Any]:
    by_part, P = exchange_partitions(refs, key)
    if P == 0:
        return []
    return [_group_map.remote(key, fn, *parts) for parts in by_part]
