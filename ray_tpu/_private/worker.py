"""CoreWorker: the runtime inside every worker and driver process.

Counterpart of the reference's CoreWorker
(reference: src/ray/core_worker/core_worker.h:295 — SubmitTask
core_worker.cc:2166, Get :1552, HandlePushTask :3483) plus the
NormalTaskSubmitter lease/push pipeline
(reference: transport/normal_task_submitter.cc:24,:299,:547) and the
ActorTaskSubmitter ordered queues (reference: transport/actor_task_submitter.h:73).

Threading model: one background asyncio IO loop per process runs every RPC
(client and server). Synchronous user threads (driver API, task execution
threads) post coroutines to it and block on futures. Serialization and plasma
reads/writes happen on user threads to keep the IO loop responsive.
"""

from __future__ import annotations

import asyncio
import os
import socket
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

import msgpack

from ray_tpu._native.plasma import PlasmaClient, PlasmaOOM
from ray_tpu._private import chaos as _chaos
from ray_tpu._private import flight_recorder as _fr
from ray_tpu._private import runtime_env as renv, serialization, task_spec as ts
from ray_tpu._private.config import RTPU_CONFIG
from ray_tpu._private.executor import Executor
from ray_tpu._private.function_manager import FunctionManager
from ray_tpu._private.gcs.client import GcsAioClient, GcsClient
from ray_tpu._private.ids import ActorID, JobID, NodeID, ObjectID, TaskID, WorkerID
from ray_tpu._private.memory_report import callsite as _mem_callsite
from ray_tpu._private.memory_store import InPlasma, MemoryStore
from ray_tpu._private.object_ref import ObjectRef, set_worker_hooks
from ray_tpu._private.reference_counter import ReferenceCounter
from ray_tpu._private.rpc import ClientPool, ConnectionLost, IoThread, RemoteError, RpcClient, RpcServer
from ray_tpu.exceptions import (
    ActorDiedError,
    GetTimeoutError,
    ObjectLostError,
    OutOfMemoryError,
    OwnerDiedError,
    RayTpuError,
    TaskCancelledError,
    TaskError,
    WorkerCrashedError,
)

MODE_DRIVER = "driver"
MODE_WORKER = "worker"

_INLINE = "inline"
_ERR = "err"

# Task-state -> flight-recorder event names, precomputed so the hot path
# pays one dict lookup instead of a str.lower() allocation per transition.
_FR_TASK_STATES = {
    "PENDING": "task.pending",
    "SUBMITTED": "task.submitted",
    "RUNNING": "task.running",
    "FINISHED": "task.finished",
    "FAILED": "task.failed",
    "RETRY": "task.retry",
}


def _pinned_buffer(mv: memoryview, handle: "_PinHandle"):
    """Out-of-band buffer tying a plasma pin to value lifetime.

    Arrays deserialized zero-copy from plasma keep a reference to their
    buffer; when the last buffer of an object dies, the shared handle
    releases the plasma pin so the store may reclaim the memory (matches
    the reference plasma client's buffer refcounting, plasma/client.cc).

    The finalizer must sit on an object the deserialized value actually
    RETAINS. numpy does NOT keep the pickle.PickleBuffer it is handed — it
    re-exports the underlying buffer, so the deep base chain is
    ndarray -> memoryview -> <root exporter>, and a finalizer on the
    PickleBuffer fires as soon as unpickling returns, dropping the pin
    while the value still aliases store memory (under store churn the
    region gets reused and the value silently corrupts). A ctypes array
    created with from_buffer(mv) IS the root exporter of everything built
    on top of it — the retained memoryview's .obj — so a finalizer on it
    fires exactly when the last aliasing view dies. pickle.PickleBuffer
    wraps it for the unpickler (C-level buffer protocol on every supported
    Python; a pure-Python __buffer__ wrapper needs PEP 688, 3.12+).
    """
    import ctypes
    import pickle
    import weakref

    carr = (ctypes.c_char * mv.nbytes).from_buffer(mv)
    handle.count += 1
    weakref.finalize(carr, handle.dec)
    return pickle.PickleBuffer(carr)


class _PinHandle:
    __slots__ = ("count", "_release")

    def __init__(self, release):
        self.count = 0
        self._release = release

    def dec(self):
        self.count -= 1
        if self.count <= 0 and self._release is not None:
            release, self._release = self._release, None
            try:
                release()
            except Exception:
                pass


class TaskEventBuffer:
    """Buffered task state transitions flushed to the GCS task-event sink
    (reference: src/ray/core_worker/task_event_buffer.h:206)."""

    def __init__(self, core):
        self.core = core
        self._events: List[dict] = []
        self._lock = threading.Lock()
        # record() runs twice per task on the hot path — snapshot what never
        # changes for this worker's lifetime
        self._max_buffer = RTPU_CONFIG.task_events_max_buffer
        self._worker_hex = core.worker_id.hex()
        self._node_hex = ""

    def record(self, spec: dict, state: str, error: str = ""):
        # Hot path (2+ calls per task): capture only the small id fields in
        # a tuple (holding the whole spec would pin its inline args until
        # the next drain) and defer the dict build + hex conversions to
        # drain() — the flush loop runs once a second, the submit path runs
        # thousands of times a second.
        fr_event = _FR_TASK_STATES.get(state)
        if fr_event is not None:
            _fr.record(fr_event, spec["task_id"], spec.get("name", ""))
        if state == "RUNNING":
            # live-RUNNING registry: the raylet's stall watchdog probes it
            # via GetCoreWorkerStats to find tasks stuck in execution
            self.core.running_tasks[spec["task_id"]] = (
                spec.get("name", ""), time.time())
        elif state in ("FINISHED", "FAILED"):
            self.core.running_tasks.pop(spec["task_id"], None)
        ev = (
            spec["task_id"], spec.get("name", ""), spec.get("job_id", b""),
            spec.get("actor_id"), state, time.time(), error,
        )
        with self._lock:
            self._events.append(ev)
            if len(self._events) > self._max_buffer:
                del self._events[: len(self._events) // 2]

    def _materialize(self, ev) -> dict:
        if isinstance(ev, dict):  # span records are pre-built
            return ev
        task_id, name, job_id, actor_id, state, ts, error = ev
        if not self._node_hex and self.core.node_id:
            self._node_hex = self.core.node_id.hex()
        return {
            "task_id": task_id.hex() if isinstance(task_id, bytes) else task_id,
            "name": name,
            "job_id": job_id.hex() if isinstance(job_id, bytes) else "",
            "state": state,
            "ts": ts,
            "node_id": self._node_hex,
            "worker_id": self._worker_hex,
            "error": error,
            "actor_id": actor_id.hex() if actor_id else "",
        }

    def record_span(
        self, name: str, start: float, end: float, ctx: dict,
        attributes: dict, error: str = "",
    ):
        """User/tracing span (ray_tpu.util.tracing) — rides the same buffer
        and GCS sink as task state events; rendered by timeline()."""
        ev = {
            "task_id": ctx.get("span_id", ""),
            "name": name,
            "job_id": self.core.job_id.hex() if self.core.job_id else "",
            "state": "SPAN",
            "ts": start,
            "dur": end - start,
            "node_id": self.core.node_id.hex() if self.core.node_id else "",
            "worker_id": self.core.worker_id.hex(),
            "error": error,
            "actor_id": "",
            "trace_id": ctx.get("trace_id", ""),
            "parent_span_id": ctx.get("parent_span_id", ""),
            "attributes": {str(k): str(v) for k, v in attributes.items()},
        }
        with self._lock:
            self._events.append(ev)
            if len(self._events) > RTPU_CONFIG.task_events_max_buffer:
                del self._events[: len(self._events) // 2]

    def drain(self) -> List[dict]:
        with self._lock:
            out, self._events = self._events, []
        return [self._materialize(ev) for ev in out]


class _LeaseState:
    __slots__ = ("idle", "queue", "requests_in_flight", "all_leases")

    def __init__(self):
        self.idle: deque = deque()   # lease dicts ready for reuse
        self.queue: deque = deque()  # task specs waiting for a lease
        self.requests_in_flight = 0
        self.all_leases: set = set()


class _ActorSubmitter:
    __slots__ = (
        "actor_id", "state", "addr", "seq", "buffer", "inflight", "watched",
        "death_cause", "creation_refs", "push_queue", "pushing", "epoch",
        "direct_pending_switch",
    )

    def __init__(self, actor_id: bytes):
        self.actor_id = actor_id
        self.direct_pending_switch = False
        self.state = "UNKNOWN"
        self.addr: Optional[Tuple[str, int]] = None
        self.seq = 0
        self.buffer: deque = deque()  # specs waiting for ALIVE
        self.push_queue: deque = deque()  # specs ready to push (actor ALIVE)
        self.pushing = 0  # in-flight push batches awaiting their replies
        self.epoch = 0  # bumped on restart; stale batch accounting ignores
        self.inflight: Dict[bytes, dict] = {}  # task_id -> spec
        self.watched = False
        self.death_cause = ""


class CoreWorker:
    def __init__(
        self,
        mode: str,
        gcs_address: str,
        raylet_addr: Tuple[str, int],
        job_id: JobID,
        startup_token: int = -1,
        session_dir: str = "",
        host: str = "127.0.0.1",
        driver_sys_path: Optional[List[str]] = None,
        node_id_hex: str = "",
        plasma_name: str = "",
        pre_register=None,
    ):
        self.mode = mode
        # None = unknown (fetch via GetJob at connect); a list (possibly
        # empty) = the raylet already resolved it into the spawn message.
        self._driver_sys_path = driver_sys_path
        # Node identity/plasma handed through the spawn message: the worker
        # can attach the object store and run `pre_register` (spawn-time
        # actor creation) BEFORE the RegisterWorker round-trip, letting the
        # creation result ride the registration request itself.
        self._node_id_hint = node_id_hex
        self._plasma_name_hint = plasma_name
        self._pre_register = pre_register
        self.job_id = job_id
        self.worker_id = WorkerID.from_random()
        self.host = host
        self.session_dir = session_dir
        self.io = IoThread.current()
        self.inline_threshold = RTPU_CONFIG.max_direct_call_object_size
        # hot-path config snapshot (each RTPU_CONFIG read is an os.environ
        # probe, ~12 µs — these are read multiple times per task)
        self._cfg_push_batch = RTPU_CONFIG.task_push_max_batch
        self._cfg_lease_inflight = RTPU_CONFIG.max_lease_requests_in_flight
        self._cfg_actor_inflight = RTPU_CONFIG.actor_push_max_inflight
        self._cfg_direct = RTPU_CONFIG.direct_channels

        self.server = RpcServer(host)
        from ray_tpu._private import schema as _schema

        self.server.set_validator(_schema.make_validator(_schema.WORKER_SCHEMAS))
        self.pool = ClientPool()
        self.gcs_address = gcs_address
        gcs_host, gcs_port = gcs_address.rsplit(":", 1)
        self.gcs_aio = GcsAioClient(gcs_host, int(gcs_port))
        self.gcs = GcsClient(gcs_host, int(gcs_port), self.io)
        self.functions = FunctionManager(self.gcs.kv_put, self.gcs.kv_get)

        self.memory_store = MemoryStore()
        # Dependency-gated dispatch (reference: raylet task_dependency_
        # manager): a normal task whose OWNED arg refs are still pending
        # parks here instead of occupying a lease while blocked on its
        # upstream — without this, pipelines deeper than the CPU count can
        # deadlock (every lease held by a task waiting on a task that can't
        # get a lease). oid bytes -> [specs waiting on it].
        self._arg_waiting: Dict[bytes, List[dict]] = {}
        self.memory_store.on_ready = self._on_object_ready
        self.refs = ReferenceCounter(self._on_ref_zero)
        self.executor = Executor(self)
        self.task_events = TaskEventBuffer(self)

        self.node_id: Optional[NodeID] = None
        self.plasma: Optional[PlasmaClient] = None
        self.raylet: Optional[RpcClient] = None
        self._raylet_addr = raylet_addr
        self._startup_token = startup_token

        # plasma-backed submit ring (_private/submit_ring.py): eligible
        # tiny-task specs bypass the RPC submit path via shared memory.
        # All state IO-loop only; the ring is attached lazily on the first
        # eligible submit and every failure falls back to RPC.
        self._ring = None
        self._ring_oid: Optional[bytes] = None
        self._ring_dead = False
        self._ring_attach_state = 0  # 0 = never tried, 1 = tried/attaching
        self._ring_attach_t = 0.0
        self._ring_pending: Dict[bytes, dict] = {}  # task_id -> spec
        self._ring_submitted = 0  # counter for tests/introspection
        self._cfg_ring_slots = RTPU_CONFIG.submit_ring_slots
        self._cfg_ring_dead_s = RTPU_CONFIG.submit_ring_dead_s

        # ownership / submission state (IO-loop only)
        self._leases: Dict[tuple, _LeaseState] = {}
        self._pending_tasks: Dict[bytes, dict] = {}  # task_id -> record
        self._actor_submitters: Dict[bytes, _ActorSubmitter] = {}
        self._subscribed_channels: set = set()
        self._pubsub_task = None  # started lazily on first subscription
        self._working_dir_uris: Dict[tuple, str] = {}  # (path, signature) -> kv uri
        self._running_async: Dict[bytes, Any] = {}  # task_id -> cancellable future
        self._object_locations: Dict[bytes, set] = {}  # owned plasma obj -> node ids
        self._node_cache: Dict[bytes, dict] = {}
        self._node_cache_time = 0.0
        self._pg_node_cache: Dict[tuple, bytes] = {}  # (pg_id, idx) -> node_id
        self._lineage: Dict[bytes, dict] = {}  # task_id -> spec (for reconstruction)
        self._lineage_bytes = 0

        # Batched thread->loop handoff: submits/frees/notifies append here
        # and wake the io loop once per burst (a call_soon_threadsafe each
        # costs ~0.1 ms of self-pipe + GIL churn; per-task wakeups capped
        # submission at ~3k tasks/s — reference analogue: the Cython layer
        # posts into the asio io_service without a per-call thread switch).
        self._loop_work: deque = deque()
        self._loop_work_lock = threading.Lock()
        self._loop_work_scheduled = False
        # executor-side reply streaming for batched actor-task pushes
        self._reply_bufs: Dict[tuple, list] = {}
        self._reply_flush_scheduled: set = set()

        # task context for the executing thread
        self._ctx = threading.local()
        self._put_index_lock = threading.Lock()
        self._put_index = 0
        self._driver_task_id = TaskID.for_task(job_id)

        self.actor_id: Optional[bytes] = None
        self._actor_spec: Optional[dict] = None
        self.is_shutdown = False
        # Monotonic completion counter for the stall watchdog: incremented
        # on every task reply; "work pending but this hasn't moved" is the
        # cheap no-progress signal (watchdog.py).
        self.tasks_completed = 0
        self._watchdog = None
        # task_id -> (name, start wall time) while executing here
        # (maintained by TaskEventBuffer.record on RUNNING/terminal)
        self.running_tasks: Dict[bytes, tuple] = {}
        # memory observability: periodic on-disk ledger snapshot throttle
        self._mem_snapshot_period = RTPU_CONFIG.memory_snapshot_period_s
        self._last_mem_snapshot = 0.0

        # Direct call channels (direct_channel.py): caller-side manager +
        # the actor-worker-side server behind a connection upgrade.
        from ray_tpu._private import direct_channel as _dc

        self._direct = _dc.DirectManager(self) if self._cfg_direct else None
        self._direct_server = _dc.WorkerDirectServer(self)
        self.server.set_upgrade_hook(
            _dc.HANDSHAKE_METHOD, self._direct_upgrade)

        set_worker_hooks(self)
        # Publish as the global worker BEFORE the RPC server can receive a
        # task: the raylet may lease this worker the instant registration
        # lands, and the pushed task's user code calls get_global_worker()
        # — assigning the global only after __init__ returned (as every
        # construction site does) was a startup race. Any post-connect
        # setup below widens that window, so close it here.
        set_global_worker(self)
        # Connect (blocking): start server, register with raylet, attach plasma.
        try:
            self._finish_init()
        except BaseException:
            set_global_worker(None)
            set_worker_hooks(None)
            raise

    def _finish_init(self):
        self.io.run(self._connect())
        # Chaos plane: drivers publish their env plan to GCS KV so the
        # whole cluster replays one schedule; workers arm from the env or
        # the published plan when they join.
        try:
            _chaos.sync_with_gcs(self.gcs, publish=(self.mode == MODE_DRIVER))
        except Exception:
            pass
        if self.session_dir:
            # Flight-recorder forensics file: incrementally appended by the
            # flush loop so the tail survives SIGKILL; the raylet attaches
            # it to this worker's death report (keyed by pid). Drivers get
            # the file + atexit flush but keep their SIGTERM disposition.
            try:
                path = os.path.join(
                    self.session_dir, "logs",
                    f"flight_{self.mode}-{os.getpid()}.jsonl")
                if self.mode == MODE_WORKER:
                    _fr.install_exit_dump(path)
                else:
                    import atexit

                    _fr.set_dump_path(path)
                    atexit.register(_fr.flush_now)
            except Exception:
                pass
        if RTPU_CONFIG.watchdog_interval_s > 0:
            # Drivers watch their own submitted tasks; workers additionally
            # carry the train-step-stall check (the StepRecorder lives in
            # the train worker process, not the driver).
            from ray_tpu._private.watchdog import StallWatchdog

            self._watchdog = StallWatchdog(self)
            self._watchdog.start()

    # ------------------------------------------------------------- connect

    async def _connect(self):
        self.server.register_all(self)
        self.port = await self.server.start(0)
        if self.mode == MODE_WORKER:
            # Adopt the driver's sys.path BEFORE the raylet can hand us a
            # task: by-reference-pickled functions live in modules the driver
            # can import, and fork-server children don't inherit the driver's
            # path (reference: job_config code-search-path propagation).
            # The raylet resolves it once per job and passes it through the
            # spawn message; only fall back to GetJob when it didn't.
            paths = self._driver_sys_path
            if paths is None:
                try:
                    reply = await self.gcs_aio.call(
                        "GetJob", {"job_id": self.job_id.binary()}
                    )
                    paths = reply.get("job", {}).get("driver_sys_path", [])
                except Exception:
                    paths = []
            import sys as _sys

            for p in paths:
                if p not in _sys.path:
                    _sys.path.append(p)
        self.raylet = RpcClient(*self._raylet_addr)
        await self.raylet.connect()
        self.address = (self.host, self.port)
        register_req = {
            "worker_id": self.worker_id.binary(),
            "port": self.port,
            "pid": os.getpid(),
            "startup_token": self._startup_token,
            "job_id": self.job_id.binary(),
        }
        if self._node_id_hint and self._plasma_name_hint:
            # Spawn message already identified the node: attach plasma now so
            # spawn-time actor creation can resolve plasma args, and fold the
            # creation result into the registration round-trip.
            self.node_id = NodeID.from_hex(self._node_id_hint)
            self.plasma = PlasmaClient(self._plasma_name_hint)
            if self._pre_register is not None:
                register_req["actor_result"] = await self._pre_register(self)
                # single-use: drop the closure (it pins the spec + b64 class
                # blob for the worker's whole lifetime otherwise)
                self._pre_register = None
            reply = await self.raylet.call("RegisterWorker", register_req)
        else:
            reply = await self.raylet.call("RegisterWorker", register_req)
            self.node_id = NodeID(reply["node_id"])
            self.plasma = PlasmaClient(reply["plasma_name"])
        self._flush_task = asyncio.ensure_future(self._task_event_flush_loop())
        if self.mode == MODE_WORKER:
            asyncio.ensure_future(self._watch_raylet())

    async def _watch_raylet(self):
        """Workers die with their raylet (reference: worker <-> raylet
        socket). 2s cadence: at 1000-worker scale every idle per-worker
        timer is a process wakeup stealing the core from real work."""
        while True:
            await asyncio.sleep(2.0)
            if not self.raylet.is_connected():
                _fr.record("worker.death", self.worker_id.binary(),
                           "raylet connection lost")
                _fr.flush_now()
                os._exit(1)
            if os.getppid() == 1:
                _fr.record("worker.death", self.worker_id.binary(),
                           "orphaned (parent died)")
                _fr.flush_now()
                os._exit(1)

    async def _task_event_flush_loop(self):
        period = RTPU_CONFIG.task_events_flush_period_ms / 1000.0
        # Metrics ride this loop but on their own contract cadence
        # (RTPU_metrics_report_period_ms): a busy worker flushing task
        # events every second must not also re-push every gauge that often.
        metrics_period = RTPU_CONFIG.metrics_report_period_ms / 1000.0
        last_metrics_flush = 0.0
        idle_period = period
        while True:
            await asyncio.sleep(idle_period)
            if self.is_shutdown:
                # A worker outliving its cluster (init/shutdown cycles in
                # one process — the io loop is a process singleton) must
                # not keep draining the PROCESS-GLOBAL util.metrics
                # records: its push would fail against the dead GCS and
                # restore_records would re-merge, racing the live
                # worker's flush for the same deltas — metrics then only
                # export when the live worker happens to win the race.
                return
            events = self.task_events.drain()
            if events:
                idle_period = period
                try:
                    await self.gcs_aio.notify("AddTaskEvents", {"events": events})
                except Exception:
                    pass
            else:
                # Idle worker: back off (cap 8x) — a fleet of parked actors
                # shouldn't generate a constant wakeup storm.
                idle_period = min(idle_period * 2, period * 8)
            now = time.time()
            if now - last_metrics_flush >= metrics_period:
                last_metrics_flush = now
                self._flush_user_metrics()
            # Keep the on-disk flight tail current (incremental append):
            # this is what lets the raylet read a SIGKILLed worker's last
            # events — no exit handler ever runs for SIGKILL.
            _fr.flush_to_file()
            # Same SIGKILL-safety for memory state: a compact ledger
            # snapshot on disk is what OOM forensics attaches to this
            # worker's death report if it dies without warning.
            self._maybe_write_memory_snapshot()

    def _maybe_write_memory_snapshot(self):
        period = self._mem_snapshot_period
        if period <= 0 or self.mode != MODE_WORKER or not self.session_dir:
            return
        now = time.time()
        if now - self._last_mem_snapshot < period:
            return
        self._last_mem_snapshot = now
        try:
            from ray_tpu._private import memory_report as _mr

            _mr.write_snapshot(self)
        except Exception:
            pass

    def _drain_stamped_user_metrics(self):
        """Drain ray_tpu.util.metrics records (if that module is in use),
        stamped with worker/job labels so series from different workers
        never collide. Returns (module, records)."""
        import sys as _sys

        mod = _sys.modules.get("ray_tpu.util.metrics")
        if mod is None:
            return None, []
        try:
            records = mod.drain_records()
        except Exception:
            return mod, []
        if not records:
            return mod, []
        wid = self.worker_id.hex()[:12]
        jid = self.job_id.hex()
        for rec in records:
            rec["labels"] = {**rec["labels"], "WorkerId": wid, "JobId": jid}
        return mod, records

    def flush_user_metrics_sync(self, timeout: float = 5.0):
        """Blocking metrics + task-event flush for end-of-workload barriers
        (a train worker's final step deltas and step SPAN events must not
        race the worker-group kill)."""
        try:
            events = self.task_events.drain()
            if events:
                self.gcs.call("AddTaskEvents", {"events": events},
                              timeout=timeout)
        except Exception:
            pass
        mod, records = self._drain_stamped_user_metrics()
        if not records:
            return
        try:
            self.gcs.call("ReportUserMetrics", {"records": records},
                          timeout=timeout)
        except Exception:
            try:
                mod.restore_records(records)
            except Exception:
                pass

    def _flush_user_metrics(self):
        """Push ray_tpu.util.metrics records to the GCS aggregator (async,
        from the task-event flush loop)."""
        mod, records = self._drain_stamped_user_metrics()
        if not records:
            return

        async def _push():
            try:
                await self.gcs_aio.call(
                    "ReportUserMetrics", {"records": records}, timeout=10
                )
            except Exception:
                # Re-merge the drained deltas: a GCS blip must not lose
                # counter increments.
                try:
                    mod.restore_records(records)
                except Exception:
                    pass

        asyncio.ensure_future(_push())

    # ------------------------------------------------ ObjectRef hooks (sync)

    def add_local_ref(self, ref: ObjectRef):
        oid = ref.object_id()
        if self.refs.owns(oid):
            self.refs.add_local_ref(oid)
        else:
            first = self.refs.add_borrowed_ref(oid, ref.owner_address)
            if first and ref.owner_address and tuple(ref.owner_address) != self.address:
                self._post_owner_notify(
                    ref.owner_address,
                    "AddBorrowerRef",
                    {"object_id": oid.binary(), "borrower": list(self.address)},
                )

    def remove_local_ref(self, ref: ObjectRef):
        if self.is_shutdown:
            return
        oid = ref.object_id()
        if self.refs.owns(oid):
            self.refs.remove_local_ref(oid)
        else:
            owner = self.refs.remove_borrowed_ref(oid)
            if owner and tuple(owner) != self.address:
                self._post_owner_notify(
                    owner,
                    "RemoveBorrowerRef",
                    {"object_id": oid.binary(), "borrower": list(self.address)},
                )

    def _post_batched(self, kind: str, item):
        """Queue loop-side work from a foreign thread with one io-loop
        wakeup per burst instead of one run_coroutine_threadsafe per call."""
        with self._loop_work_lock:
            self._loop_work.append((kind, item))
            if self._loop_work_scheduled:
                return
            self._loop_work_scheduled = True
        try:
            self.io.loop.call_soon_threadsafe(self._drain_loop_work)
        except RuntimeError:
            pass  # loop closed (shutdown)

    def _drain_loop_work(self):
        """Runs on the io loop: route every queued item, then kick each
        touched pump exactly once."""
        with self._loop_work_lock:
            work = self._loop_work
            self._loop_work = deque()
            self._loop_work_scheduled = False
        normal_states: Dict[tuple, _LeaseState] = {}
        actor_subs: Dict[bytes, _ActorSubmitter] = {}
        frees: list = []
        actor_regs: list = []
        for kind, item in work:
            if kind == "normal":
                blocker = self._unready_owned_arg(item)
                if blocker is not None:
                    self._arg_waiting.setdefault(blocker, []).append(item)
                    continue
                if self._ring_submit(item):
                    continue  # rode the shared-memory submit ring
                key = ts.scheduling_key(item)
                state = self._leases.setdefault(key, _LeaseState())
                state.queue.append(item)
                normal_states[key] = state
            elif kind == "register_actor":
                actor_regs.append(item)
            elif kind == "actor":
                actor_id, spec = item
                sub = self._route_actor_spec(actor_id, spec)
                if sub is not None:
                    actor_subs[actor_id] = sub
            elif kind == "free":
                frees.append(item)
            elif kind == "direct_switch":
                if self._direct is not None:
                    self._direct.on_switch_request(item)
            elif kind == "direct_replies":
                if self._direct is not None:
                    self._direct.process_replies(item)
            elif kind == "direct_down":
                if self._direct is not None:
                    self._direct.on_channel_down(item[0], item[1])
            else:  # notify
                owner_addr, method, payload = item
                asyncio.ensure_future(
                    self._notify_owner(owner_addr, method, payload)
                )
        for key, state in normal_states.items():
            asyncio.ensure_future(self._pump_leases(key, state))
        for sub in actor_subs.values():
            self._pump_actor(sub)
        if frees:
            asyncio.ensure_future(self._free_refs_batch(frees))
        if actor_regs:
            asyncio.ensure_future(self._register_actors_batch(actor_regs))

    def _unready_owned_arg(self, spec: dict):
        """First arg ref owned by US that is still pending, else None.
        Borrowed refs (other owners) are not gated — the executing worker
        awaits them as before (the owner will have applied its own gating
        to the producing task)."""
        for _kind, _key, wire in spec["args"]:
            ref = wire.get("ref") if isinstance(wire, dict) else None
            if not ref:
                continue
            id_bytes, owner = ref
            if owner and tuple(owner) == self.address and \
                    self.memory_store.is_pending(ObjectID(id_bytes)):
                return id_bytes
        return None

    def _on_object_ready(self, oid: ObjectID):
        """io-loop: an owned object resolved — re-dispatch tasks parked on
        it (each re-checks its remaining args and may park again)."""
        waiters = self._arg_waiting.pop(oid.binary(), None)
        if not waiters:
            return
        states: Dict[tuple, _LeaseState] = {}
        for spec in waiters:
            blocker = self._unready_owned_arg(spec)
            if blocker is not None:
                self._arg_waiting.setdefault(blocker, []).append(spec)
                continue
            if self._ring_submit(spec):
                continue
            key = ts.scheduling_key(spec)
            state = self._leases.setdefault(key, _LeaseState())
            state.queue.append(spec)
            states[key] = state
        for key, state in states.items():
            asyncio.ensure_future(self._pump_leases(key, state))

    async def _register_actors_batch(self, items):
        """One SubscribeMany + one RegisterActors round-trip for a burst of
        anonymous actor creations. Subscribing first closes the
        missed-publish window without a per-actor state refresh."""
        channels = []
        for actor_id, _payload in items:
            ch = f"actor:{actor_id.hex()}"
            self._subscribed_channels.add(ch)
            channels.append(ch)
        self._ensure_pubsub()
        # Retry: registration is server-side idempotent, so a dropped reply
        # or GCS failover must not double-jeopardize actors the GCS already
        # registered (persisted + scheduled) by declaring them DEAD here.
        last_err = None
        for attempt in range(3):
            if attempt:
                await asyncio.sleep(1.0 * attempt)
            try:
                await self.gcs_aio.call(
                    "SubscribeMany",
                    {"sub_id": self.worker_id.binary(), "channels": channels},
                )
                await self.gcs_aio.call(
                    "RegisterActors", {"items": [p for _, p in items]}
                )
                return
            except Exception as e:
                last_err = e
        for actor_id, _payload in items:
            sub = self._actor_submitters.get(actor_id)
            if sub is not None:
                rec = {"state": "DEAD", "addr": None,
                       "death_cause": f"actor registration failed: {last_err}"}
                await self._apply_actor_state(sub, rec)

    async def _notify_owner(self, owner_addr, method, payload):
        try:
            client = await self.pool.get(owner_addr[0], owner_addr[1])
            await client.notify(method, payload)
        except Exception:
            pass

    def _post_owner_notify(self, owner_addr, method, payload):
        self._post_batched("notify", (owner_addr, method, payload))

    def as_future(self, ref: ObjectRef):
        import concurrent.futures

        out: concurrent.futures.Future = concurrent.futures.Future()

        def done(task):
            try:
                out.set_result(self.get([ref], timeout=None)[0])
            except Exception as e:
                out.set_exception(e)

        f = self.io.post(self._async_resolve(ref, None))
        f.add_done_callback(done)
        return out

    async def await_ref(self, ref: ObjectRef):
        res = await self._async_resolve(ref, None)
        value = self._materialize(ref.object_id(), res)
        if isinstance(value, Exception):
            raise value
        return value

    def _on_ref_zero(self, oid: ObjectID):
        """Owned object's refcount hit zero: free it everywhere."""
        if self._direct is not None:
            self._direct.discard_object(oid.binary())
        self._post_batched("free", oid)

    async def _free_refs_batch(self, oids):
        """Free a burst of dead objects: local stores synchronously, then
        one FreeObjects notify per holding node for the whole batch."""
        by_node: Dict[bytes, list] = {}
        for oid in oids:
            entry = self.memory_store.get_if_exists(oid)
            self.memory_store.free(oid)
            locations = self._object_locations.pop(oid.binary(), set())
            if isinstance(entry, InPlasma):
                locations |= entry.locations
            for node_id in locations:
                by_node.setdefault(node_id, []).append(oid.binary())
        for node_id, ids in by_node.items():
            info = await self._node_info(node_id)
            if info is None:
                continue
            try:
                client = await self.pool.get(info["ip"], info["raylet_port"])
                await client.notify("FreeObjects", {"ids": ids})
            except Exception:
                pass

    async def _node_info(self, node_id: bytes) -> Optional[dict]:
        now = time.time()
        if node_id not in self._node_cache or now - self._node_cache_time > 5.0:
            try:
                nodes = await self.gcs_aio.get_all_node_info()
                self._node_cache = {n["node_id"]: n for n in nodes}
                self._node_cache_time = now
            except Exception:
                pass
        return self._node_cache.get(node_id)

    # ------------------------------------------------------------ put / get

    def _next_put_id(self) -> ObjectID:
        with self._put_index_lock:
            self._put_index += 1
            idx = self._put_index
        return ObjectID.for_put(self.current_task_id(), idx)

    def current_task_id(self) -> TaskID:
        spec = getattr(self._ctx, "spec", None)
        if spec is not None:
            return TaskID(spec["task_id"])
        return self._driver_task_id

    def put(self, value: Any, _owner_hint=None) -> ObjectRef:
        """Store a value, return an owned ref (reference: worker.py:2691 ray.put).

        Plasma-bound values keep the RAW protocol-5 buffer views from
        serialize() all the way into write_blob, which streams them straight
        into the mapped shm destination — one copy total. Only the inline
        path (small values that ride msgpack frames) materializes bytes.
        """
        oid = self._next_put_id()
        p, bufs, _refs = serialization.serialize(value)
        size = len(p) + serialization.buffers_nbytes(bufs)
        self.refs.add_owned(
            oid, size=size, callsite=_mem_callsite(),
            task_id=self.current_task_id().binary())
        if size <= self.inline_threshold:
            payload = serialization.inline_payload(p, bufs)
            self.io.run(self._store_inline(oid, payload))
        else:
            nbytes = self._plasma_put_payload(oid, p, bufs)
            self.io.run(self._register_plasma_primary(oid, nbytes))
        _fr.record("obj.put", oid.binary(), size)
        return ObjectRef(oid, self.address)

    async def _store_inline(self, oid: ObjectID, payload):
        self.memory_store.put(oid, (_INLINE, payload, None))

    def _plasma_put_payload(self, oid: ObjectID, pickle_bytes: bytes,
                            buffers: list) -> int:
        """Serialize straight into the shared-memory buffer: one copy total
        (reference plasma clients do the same via Create+mutable buffer,
        plasma/client.cc). `buffers` are the raw out-of-band views from
        serialize() — never pre-materialized bytes. Returns the object's
        byte size."""
        if _chaos.ARMED:
            act = _chaos.hit("plasma.write")
            if act is not None:
                if act["action"] == "delay":
                    time.sleep(act["delay_s"])
                elif act["action"] in ("error", "fail"):
                    raise OSError("chaos: plasma write failed (injected)")
        size = serialization.blob_size(pickle_bytes, buffers)
        try:
            dest = self.plasma.create(oid, size)
        except FileExistsError:
            if self.plasma.contains(oid):
                return size  # already sealed by an earlier attempt
            # Unsealed leftover from a crashed/failed writer: readers would
            # block on it forever. Reclaim and rewrite.
            self.plasma.abort(oid)
            dest = self.plasma.create(oid, size)
        except PlasmaOOM:
            # Make room: evict unpinned secondaries, then ask the raylet to
            # spill pinned primaries to disk (reference: CreateRequestQueue
            # retries + LocalObjectManager spilling). Spilled memory may free
            # only after concurrent readers release their views, so retry
            # with backoff before giving up.
            dest = None
            for attempt in range(6):
                self.plasma.evict(size)
                try:
                    dest = self.plasma.create(oid, size)
                    break
                except PlasmaOOM:
                    try:
                        self.io.run(
                            self.raylet.call(
                                "SpillObjects", {"bytes": size}, timeout=60
                            )
                        )
                    except Exception:
                        pass
                    time.sleep(0.1 * (attempt + 1))
            if dest is None:
                dest = self.plasma.create(oid, size)  # raise the real OOM
        try:
            serialization.write_blob(dest, pickle_bytes, buffers)
            dest.release()
            self.plasma.seal(oid)
        except BaseException:
            # Never leave a created-but-unsealed object behind.
            try:
                dest.release()
            except Exception:
                pass
            self.plasma.abort(oid)
            raise
        return size

    async def _register_plasma_primary(self, oid: ObjectID, size: int):
        node = self.node_id.binary()
        self.memory_store.put(oid, InPlasma(size, {node}))
        self._object_locations.setdefault(oid.binary(), set()).add(node)
        self.refs.note_size(oid, size, plasma=True)
        try:
            # Synchronous: until the pin lands, a concurrent put's evict()
            # could reclaim this primary and lose the object.
            await self.raylet.call(
                "PinObject",
                {"object_id": oid.binary(), "owner_addr": list(self.address),
                 "meta": self._pin_meta(oid, size)},
                timeout=30,
            )
        except Exception:
            pass

    def _pin_meta(self, oid: ObjectID, size: int, spec: Optional[dict] = None) -> dict:
        """Ownership attribution shipped with a PinObject so the raylet's
        leak detector and OOM forensics can name the holder even after the
        owner's ledger entry (or the owner itself) is gone."""
        if spec is not None:
            return {
                "job_id": spec.get("job_id", b"") or b"",
                "actor_id": spec.get("actor_id") or b"",
                "task_id": spec.get("task_id", b"") or b"",
                "callsite": "task:" + spec.get("name", ""),
                "size": size,
            }
        with self.refs._lock:
            ref = self.refs._owned.get(oid)
            callsite = ref.callsite if ref else ""
            task_id = (ref.task_id if ref else None) or b""
        return {
            "job_id": self.job_id.binary(),
            "actor_id": self.actor_id or b"",
            "task_id": task_id,
            "callsite": callsite,
            "size": size,
        }

    # -- get ---------------------------------------------------------------

    def get(self, refs: List[ObjectRef], timeout: Optional[float] = None) -> List[Any]:
        if self._direct is not None and self._direct.can_serve(refs):
            # Blocking resolve in THIS thread against the direct-channel
            # staging store — zero io-loop round trips (direct_channel.py).
            # The slow-get hint is post-hoc here (no timer on the fast
            # path; two clock reads are noise next to the socket wait).
            t0 = time.time()
            out = self._direct.fast_get(refs, timeout)
            if out is not self._direct._FALLBACK:
                self._warn_slow_get(len(refs), time.time() - t0)
                return out
        deadline = None if timeout is None else time.time() + timeout
        resolutions = self._run_get_with_warning(
            self._async_resolve_many(refs, deadline), len(refs), timeout)
        out = []
        for ref, res in zip(refs, resolutions):
            value = self._materialize(ref.object_id(), res)
            if isinstance(value, ObjectLostError) and res[0] == "plasma_local":
                # Spilled between resolution and read: resolve again (the
                # raylet restores it from disk).
                res = self.io.run(self._async_resolve(ref, deadline))
                value = self._materialize(ref.object_id(), res)
            if isinstance(value, Exception):
                raise value
            out.append(value)
        return out

    @staticmethod
    def _warn_slow_get(n_refs: int, elapsed_s: float):
        """Post-hoc arm of the slow-get hint (direct-channel fast path)."""
        import sys as _sys

        warn_s = RTPU_CONFIG.get_timeout_warning_s
        if warn_s > 0 and elapsed_s >= warn_s:
            print(
                f"[ray_tpu] ray_tpu.get of {n_refs} ref(s) was blocked "
                f"for {elapsed_s:.0f}s — the producing actor call may be "
                "queued behind earlier calls or stalled (see "
                "`ray-tpu debug incidents` / `ray-tpu timeline`)",
                file=_sys.stderr, flush=True,
            )

    def _run_get_with_warning(self, coro, n_refs: int, timeout):
        """Blocking wait on the io loop with the reference's slow-get
        warning (RTPU_get_timeout_warning_s): a get blocked past the
        threshold prints ONE hint naming the count so a driver stuck on a
        never-produced ref is diagnosable before the stall watchdog fires.
        0 disables; a caller timeout shorter than the threshold wins."""
        import concurrent.futures as _cf
        import sys as _sys

        fut = self.io.post(coro)
        warn_s = RTPU_CONFIG.get_timeout_warning_s
        if warn_s <= 0 or (timeout is not None and timeout <= warn_s):
            return fut.result()
        try:
            return fut.result(warn_s)
        except _cf.TimeoutError:
            print(
                f"[ray_tpu] ray_tpu.get of {n_refs} ref(s) has been "
                f"blocked for {warn_s:.0f}s — the producing task may be "
                "queued, failed without a reply, or stalled (see "
                "`ray-tpu debug incidents` / `ray-tpu timeline`)",
                file=_sys.stderr, flush=True,
            )
            return fut.result()

    async def async_get_one(self, ref: ObjectRef):
        """IO-loop get used by the executor for dependency resolution."""
        res = await self._async_resolve(ref, None)
        loop = asyncio.get_running_loop()
        value = await loop.run_in_executor(None, self._materialize, ref.object_id(), res)
        if isinstance(value, ObjectLostError) and res[0] == "plasma_local":
            res = await self._async_resolve(ref, None)
            value = await loop.run_in_executor(
                None, self._materialize, ref.object_id(), res
            )
        if isinstance(value, Exception):
            raise value
        return value

    async def _async_resolve_many(self, refs, deadline):
        # One batch event covers every owned-pending ref (per-ref
        # gather+wait_for costs a Task + timer + Event each, ~150 µs/ref on
        # a 1000-ref get); only stragglers (borrowed, plasma, errors) take
        # the per-ref coroutine path.
        if len(refs) > 1:
            pending = [
                r.object_id() for r in refs
                if self.memory_store.is_pending(r.object_id())
            ]
            if pending:
                timeout = None if deadline is None else max(0.0, deadline - time.time())
                await self.memory_store.wait_ready_many(pending, timeout)
        results = [None] * len(refs)
        slow = []
        for i, r in enumerate(refs):
            oid = r.object_id()
            entry = self.memory_store.get_if_exists(oid)
            if entry is not None and not isinstance(entry, InPlasma):
                results[i] = (
                    entry[:2] if entry[0] in (_INLINE, _ERR) else ("value", entry)
                )
            else:
                slow.append(i)
        if slow:
            resolved = await asyncio.gather(
                *(self._async_resolve(refs[i], deadline) for i in slow)
            )
            for i, res in zip(slow, resolved):
                results[i] = res
        return results

    async def _async_resolve(self, ref: ObjectRef, deadline) -> tuple:
        """Resolve a ref to ('inline'|'err', payload) | ('plasma_local', oid) on IO loop."""
        oid = ref.object_id()
        attempt = 0
        while True:
            attempt += 1
            if self.refs.owns(oid) or self.memory_store.contains(oid) or self.memory_store.is_pending(oid):
                res = await self._resolve_owned(oid, deadline)
            else:
                res = await self._resolve_borrowed(ref, deadline)
            if res[0] != "plasma_remote_lost":
                return res
            # All copies lost: try lineage reconstruction
            # (reference: object_recovery_manager.h:41).
            if attempt > 2 or not await self._try_reconstruct(oid):
                return ("err_obj", ObjectLostError(f"object {oid.hex()} lost (all copies gone)"))

    async def _resolve_owned(self, oid: ObjectID, deadline) -> tuple:
        timeout = None if deadline is None else max(0.0, deadline - time.time())
        ready = await self.memory_store.wait_ready(oid, timeout)
        if not ready:
            return ("err_obj", GetTimeoutError(f"get() timed out on {oid.hex()}"))
        entry = self.memory_store.get_if_exists(oid)
        if entry is None:
            return ("err_obj", ObjectLostError(f"object {oid.hex()} was freed"))
        if isinstance(entry, InPlasma):
            return await self._resolve_plasma(oid, entry.locations, None, deadline)
        return entry[:2] if entry[0] in (_INLINE, _ERR) else ("value", entry)

    async def _resolve_borrowed(self, ref: ObjectRef, deadline) -> tuple:
        oid = ref.object_id()
        owner = ref.owner_address
        if owner is None:
            return ("err_obj", OwnerDiedError(f"no owner known for {oid.hex()}"))
        while True:
            timeout = 25.0
            if deadline is not None:
                timeout = min(timeout, deadline - time.time())
                if timeout <= 0:
                    return ("err_obj", GetTimeoutError(f"get() timed out on {oid.hex()}"))
            try:
                client = await self.pool.get(owner[0], owner[1])
                status = await client.call(
                    "GetObjectStatus",
                    {"object_id": oid.binary(), "wait": True, "timeout": timeout},
                    timeout=timeout + 5,
                )
            except (ConnectionLost, OSError, asyncio.TimeoutError):
                return ("err_obj", OwnerDiedError(f"owner of {oid.hex()} is unreachable"))
            st = status.get("status")
            if st == "pending":
                continue
            if st == "freed":
                return ("err_obj", ObjectLostError(f"object {oid.hex()} was freed by owner"))
            if "inline" in status:
                return (_INLINE, status["inline"])
            if "err" in status:
                return (_ERR, status["err"])
            if "plasma" in status:
                return await self._resolve_plasma(
                    oid, set(status["plasma"]["locations"]), owner, deadline
                )

    async def _resolve_plasma(self, oid: ObjectID, locations, owner, deadline) -> tuple:
        if self.plasma.contains(oid):
            return ("plasma_local", oid)
        owner_addr = list(owner) if owner else list(self.address)
        # A pull can fail transiently (restore-from-spill racing store
        # pressure, holder mid-eviction): retry before declaring the copy
        # lost — put objects have no lineage to fall back on.
        for attempt in range(3):
            try:
                timeout = None if deadline is None else max(0.1, deadline - time.time())
                reply = await self.raylet.call(
                    "PullObject",
                    {"object_id": oid.binary(), "owner_addr": owner_addr},
                    timeout=timeout,
                )
            except asyncio.TimeoutError:
                return ("err_obj", GetTimeoutError(f"get() timed out pulling {oid.hex()}"))
            if reply.get("ok") and self.plasma.contains(oid):
                return ("plasma_local", oid)
            if deadline is not None and time.time() >= deadline:
                break
            await asyncio.sleep(0.2 * (attempt + 1))
        return ("plasma_remote_lost", oid)

    def _materialize(self, oid: ObjectID, res: tuple):
        """User-thread side: turn a resolution into a Python value (may raise)."""
        kind = res[0]
        if kind == "value":
            return res[1]
        if kind == "err_obj":
            return res[1]
        if kind == _INLINE:
            value, _refs = serialization.deserialize_inline(res[1])
            return value
        if kind == _ERR:
            exc, _refs = serialization.deserialize_inline(res[1])
            if isinstance(exc, RayTpuError) and not isinstance(exc, TaskError):
                # System failures (worker crash, OOM kill, actor death...)
                # surface as their own type; only user exceptions wrap in
                # TaskError (reference: RayTaskError vs RaySystemError).
                return exc
            if isinstance(exc, Exception):
                return TaskError(exc, getattr(exc, "_rtpu_tb", str(exc)))
            return TaskError(Exception(str(exc)), str(exc))
        if kind == "plasma_local":
            return self._read_plasma_value(oid)
        raise RuntimeError(f"bad resolution {res}")

    def _read_plasma_value(self, oid: ObjectID):
        """Deserialize a sealed plasma object zero-copy. Parsing is
        serialization.read_blob — one parser, one place that knows the store
        format; the buffer_wrapper ties the plasma pin to buffer lifetime."""
        view = self.plasma.get(oid)
        if view is None:
            return ObjectLostError(f"object {oid.hex()} evicted before read")

        def release():
            try:
                view.release()
            except Exception:
                pass
            self.plasma.release(oid)

        handle = _PinHandle(release)
        try:
            value, _refs = serialization.read_blob(
                view, buffer_wrapper=lambda mv: _pinned_buffer(mv, handle)
            )
        except BaseException:
            if handle.count == 0:
                release()
            raise
        if handle.count == 0:
            # no out-of-band buffers alias the store — drop the pin now
            release()
        return value

    # ------------------------------------------------------------ wait

    def wait(self, refs, num_returns=1, timeout=None, fetch_local=True):
        deadline = None if timeout is None else time.time() + timeout
        return self.io.run(self._async_wait(refs, num_returns, deadline, fetch_local))

    async def _async_wait(self, refs, num_returns, deadline, fetch_local):
        """Event-driven wait: one waiter per pending ref. Owned refs ride the
        memory-store per-object event; borrowed refs long-poll their owner
        with wait=True (the owner's GetObjectStatus blocks server-side until
        the object resolves) — no fixed-interval polling in either path
        (reference: core_worker Wait is a callback on object availability,
        src/ray/core_worker/core_worker.cc Wait)."""
        ready: List[ObjectRef] = []
        pending: List[ObjectRef] = []
        for ref in refs:
            if await self._is_ready(ref):
                ready.append(ref)
            else:
                pending.append(ref)
        if len(ready) >= num_returns or not pending:
            # cap at num_returns (reference semantics); surplus ready refs
            # stay in pending, still in input order
            surplus = ready[num_returns:]
            ready = ready[:num_returns]
            if surplus:
                keep = set(surplus) | set(pending)
                pending = [r for r in refs if r in keep]
            return ready, pending
        waiters = {
            asyncio.ensure_future(self._wait_one(ref)): ref
            for ref in pending
        }
        try:
            while len(ready) < num_returns and waiters:
                timeout = (
                    None if deadline is None
                    else max(0.0, deadline - time.time())
                )
                done, _ = await asyncio.wait(
                    waiters.keys(), timeout=timeout,
                    return_when=asyncio.FIRST_COMPLETED,
                )
                if not done:
                    break  # deadline
                for t in done:
                    ready.append(waiters.pop(t))
        finally:
            for t in waiters:
                t.cancel()
        # Never return MORE than num_returns ready refs (reference
        # semantics: len(ready) <= num_returns) — several waiters can
        # complete in one asyncio.wait round; the surplus goes back to
        # pending so callers looping wait(num_returns=1) see every ref.
        ready_set = set(ready)
        ordered_ready = [r for r in refs if r in ready_set]
        ready = ordered_ready[:num_returns]
        ready_set = set(ready)
        pending = [r for r in refs if r not in ready_set]
        return ready, pending

    async def _wait_one(self, ref: ObjectRef) -> None:
        """Resolves when the ref is ready (value, plasma copy, or error)."""
        oid = ref.object_id()
        while True:
            if await self._is_ready(ref):
                return
            if self.memory_store.is_pending(oid):
                await self.memory_store.wait_ready(oid, None)
                continue
            if self.refs.owns(oid):
                # owned but not yet registered as pending (submit in flight)
                await asyncio.sleep(0.01)
                continue
            owner = ref.owner_address
            if owner is None:
                await asyncio.sleep(0.01)
                continue
            try:
                client = await self.pool.get(owner[0], owner[1])
                status = await client.call(
                    "GetObjectStatus",
                    {"object_id": oid.binary(), "wait": True, "timeout": 30},
                    timeout=35,
                )
                if status.get("status") != "pending":
                    return  # ready / freed / error — all count as resolved
            except Exception:
                await asyncio.sleep(0.1)

    async def _is_ready(self, ref: ObjectRef) -> bool:
        oid = ref.object_id()
        if self.memory_store.contains(oid):
            return True
        if self.memory_store.is_pending(oid):
            return False
        if self.plasma.contains(oid):
            return True
        if self.refs.owns(oid):
            return False
        owner = ref.owner_address
        if owner is None:
            return False
        try:
            client = await self.pool.get(owner[0], owner[1])
            status = await client.call(
                "GetObjectStatus", {"object_id": oid.binary(), "wait": False}, timeout=10
            )
            return status.get("status") == "ready" or "inline" in status or "plasma" in status or "err" in status
        except Exception:
            return False

    # ----------------------------------------------------- normal task submit

    def submit_task(
        self,
        fn,
        args,
        kwargs,
        *,
        name: str,
        num_returns: int = 1,
        resources: Dict[str, float],
        max_retries: int = 0,
        retry_exceptions: bool = False,
        scheduling_strategy: Optional[dict] = None,
        runtime_env: Optional[dict] = None,
    ) -> List[ObjectRef]:
        fn_key = self.functions.export(fn)
        runtime_env = self.prepare_runtime_env(runtime_env)
        wire, refs, large = ts.serialize_args(args, kwargs, self.inline_threshold)
        big_refs = self._replace_large_args(wire, large)
        refs.extend(big_refs)
        task_id = TaskID.for_task(self.job_id)
        from ray_tpu.util import tracing as _tracing

        trace_ctx = _tracing.context_for_spec()
        spec = ts.build_task_spec(
            task_id=task_id,
            job_id=self.job_id,
            name=name,
            fn_key=fn_key,
            wire_args=wire,
            num_returns=num_returns,
            resources=resources,
            owner_addr=self.address,
            owner_worker_id=self.worker_id.binary(),
            max_retries=max_retries,
            retry_exceptions=retry_exceptions,
            scheduling_strategy=scheduling_strategy,
            caller_id=self.worker_id.binary(),
            runtime_env=runtime_env,
        )
        if trace_ctx is not None:
            spec["trace_ctx"] = trace_ctx
        return_refs = self._register_pending(spec, refs)
        self._post_batched("normal", spec)
        return return_refs

    def prepare_runtime_env(self, runtime_env: Optional[dict]) -> Optional[dict]:
        """Validate and materialize a runtime_env for shipping in a spec.

        A local working_dir path is zipped and uploaded to the GCS KV once
        per content hash (reference: runtime_env/packaging.py); the spec
        carries the kv:<hash> URI so any node can extract it.
        """
        runtime_env = ts.validate_runtime_env(runtime_env)
        if not runtime_env:
            return runtime_env

        def upload_dir(path: str, arc_prefix: str = "") -> str:
            # Cache by content signature, not path: edits to the directory
            # between submits must produce a fresh upload.
            cache_key = (
                os.path.abspath(path), renv.dir_signature(path), arc_prefix
            )
            uri = self._working_dir_uris.get(cache_key)
            if uri is None:
                uri = renv.upload_working_dir(self.gcs, path, arc_prefix)
                self._working_dir_uris[cache_key] = uri
            return uri

        wd = runtime_env.get("working_dir")
        if wd and not renv.is_uploaded(wd):
            runtime_env = {**runtime_env, "working_dir": upload_dir(wd)}
        pm = runtime_env.get("py_modules")
        if pm:
            # py_modules ride the working_dir packaging machinery, nested
            # under the module dir's basename so `import <basename>` works
            # from the extracted root (reference: py_modules contract,
            # runtime_env packaging.py)
            runtime_env = {**runtime_env, "py_modules": [
                p if renv.is_uploaded(p)
                else upload_dir(p, os.path.basename(os.path.abspath(p)))
                for p in pm
            ]}
        return runtime_env

    def put_serialized(self, pickle_bytes: bytes, buffers: list) -> ObjectRef:
        """put() for an already-serialized value: the raw buffer views go
        straight into plasma with no re-pickle and no bytes() copy."""
        oid = self._next_put_id()
        self.refs.add_owned(
            oid, callsite=_mem_callsite(),
            task_id=self.current_task_id().binary())
        nbytes = self._plasma_put_payload(oid, pickle_bytes, buffers)
        self.io.run(self._register_plasma_primary(oid, nbytes))
        return ObjectRef(oid, self.address)

    def _replace_large_args(self, wire, large) -> List[ObjectRef]:
        """Oversized inline args are stored first and passed by ref
        (reference: dependency_resolver.h inlining threshold). serialize_args
        already serialized them — reuse its raw (pickle, buffers) pair."""
        big_refs = []
        if not large:
            return big_refs
        by_key = {}
        for pos_key, (p, bufs) in large:
            ref = self.put_serialized(p, bufs)
            big_refs.append(ref)
            by_key[pos_key] = ref
        for entry in wire:
            w = entry[2]
            if "big" in w:
                key = tuple(w["big"])
                ref = by_key[(key[0], key[1] if key[0] == "k" else int(key[1]))]
                entry[2] = {"ref": [ref.object_id().binary(), list(ref.owner_address)]}
        return big_refs

    def _register_pending(self, spec: dict, arg_refs: List[ObjectRef]) -> List[ObjectRef]:
        return_ids = ts.return_object_ids(spec)
        out = []
        # ledger attribution for task returns: the submitting task owns
        # them; the "callsite" is the task name (cheap — no frame walk on
        # the submit hot path).
        ret_callsite = "task:" + spec.get("name", "")
        for oid in return_ids:
            self.refs.add_owned(oid, lineage_task_id=spec["task_id"],
                                callsite=ret_callsite,
                                task_id=spec["task_id"])
        # Direct call, not io.run: a cross-thread round-trip here costs ~1 ms
        # per .remote() and caps submission at <1k tasks/s. put_pending only
        # creates dict entries + an (unbound) asyncio.Event — safe under the
        # GIL; the result cannot arrive before the spec is posted below.
        for oid in return_ids:
            self.memory_store.put_pending(oid)
        for oid in return_ids:
            out.append(ObjectRef(oid, self.address))
        for ref in arg_refs:
            if self.refs.owns(ref.object_id()):
                self.refs.add_submitted_task_ref(ref.object_id())
        self._pending_tasks[spec["task_id"]] = {
            "spec": spec,
            "retries": spec.get("max_retries", 0),
            "arg_refs": list(arg_refs),
            "return_ids": return_ids,
            # submit wall time: the watchdog's stuck-task age source
            "t_submit": time.time(),
        }
        self.task_events.record(spec, "PENDING")
        return out

    async def _submit_normal(self, spec: dict):
        if self._ring_submit(spec):
            return
        await self._submit_via_rpc(spec)

    async def _submit_via_rpc(self, spec: dict):
        """The classic lease-and-push submit path (also the explicit
        fallback for specs the submit ring bounced back)."""
        key = ts.scheduling_key(spec)
        state = self._leases.setdefault(key, _LeaseState())
        state.queue.append(spec)
        await self._pump_leases(key, state)

    # ------------------------------------------- plasma-backed submit ring

    _RING_RESOURCES = {"CPU": 1.0}

    def _ring_eligible(self, spec: dict) -> bool:
        """The ring is a fast path for the overwhelmingly common tiny-task
        shape only: default strategy, no runtime_env, exactly the default
        {CPU: 1} demand (ring leases are reused across specs, so demands
        must be homogeneous). Everything else rides the RPC path."""
        return (not spec.get("strategy")
                and not spec.get("runtime_env")
                and spec.get("resources") == self._RING_RESOURCES)

    def _ring_submit(self, spec: dict) -> bool:
        """Try the shared-memory submit path; False means the caller must
        use the RPC path (ring disabled, full, dead, or spec ineligible)."""
        if self._ring_dead or self._cfg_ring_slots <= 0 \
                or not self._ring_eligible(spec):
            return False
        if self._ring is None:
            if self._ring_attach_state == 0 and self.plasma is not None:
                self._ring_attach_state = 1
                asyncio.ensure_future(self._attach_submit_ring())
            return False
        try:
            payload = msgpack.packb(spec, use_bin_type=True)
        except Exception:
            return False  # unpackable spec (shouldn't happen): RPC path
        pushed = self._ring.try_push(payload)
        if pushed is None:
            return False  # ring full: clean fallback to RPC
        self._ring_pending[spec["task_id"]] = spec
        self._ring_submitted += 1
        self.task_events.record(spec, "SUBMITTED")
        if pushed:
            # empty→non-empty transition: the raylet's drain loop is (or is
            # about to go) asleep — the one RPC left on this path
            asyncio.ensure_future(self._ring_doorbell())
        return True

    async def _attach_submit_ring(self):
        from ray_tpu._private import submit_ring as _sr

        try:
            # exactly _OBJECT_ID_SIZE (20) bytes: the store reads a fixed
            # 20-byte key, so a short id would carry undefined tail bytes
            oid = (b"\xf1RNG" + self.worker_id.binary()).ljust(20, b"\0")[:20]
            size = _sr.ring_bytes(self._cfg_ring_slots)
            try:
                view = self.plasma.create(oid, size)
            except FileExistsError:
                self.plasma.delete(oid)
                view = self.plasma.create(oid, size)
            try:
                _sr.RingProducer(view, init=True)
            finally:
                view.release()
            # seal publishes the region (and drops the creator pin);
            # re-pin with get() for the producer's lifetime — the mapping
            # is read-write, the ring is a shared mailbox, not a value
            self.plasma.seal(oid)
            pinned = self.plasma.get(oid)
            if pinned is None:
                raise RuntimeError("ring object evicted before pin")
            producer = _sr.RingProducer(pinned)
            r = await self.raylet.call("AttachSubmitRing", {
                "object_id": oid,
                "reply_addr": list(self.address),
                "job_id": self.job_id.binary(),
            }, timeout=10)
            if not r.get("ok"):
                raise RuntimeError(r.get("error", "attach refused"))
            self._ring = producer
            self._ring_oid = oid
            self._ring_attach_t = time.time()
            asyncio.ensure_future(self._ring_liveness_loop())
        except Exception as e:
            _fr.record("rpc.error", b"", f"submit ring attach failed: {e}")
            # stay unattached; _ring_attach_state == 1 prevents retries

    async def _ring_doorbell(self):
        try:
            await self.raylet.notify(
                "SubmitRingDoorbell", {"object_id": self._ring_oid})
        except Exception:
            self._ring_mark_dead("doorbell failed (raylet connection lost)")

    async def _ring_liveness_loop(self):
        """Dead-consumer detection: the raylet heartbeats the ring header
        every drain tick; a stale beat (raylet restarted/wedged) or a lost
        raylet connection fails pending ring specs over to the RPC path."""
        while not self._ring_dead and not self.is_shutdown:
            await asyncio.sleep(1.0)
            if not self._ring_pending:
                continue
            if not self.raylet.is_connected():
                self._ring_mark_dead("raylet connection lost")
                return
            beat = self._ring.consumer_beat()
            ref = beat if beat else self._ring_attach_t
            if time.time() - ref > self._cfg_ring_dead_s:
                self._ring_mark_dead(
                    f"consumer heartbeat stale (> {self._cfg_ring_dead_s}s)")
                return

    def _ring_mark_dead(self, reason: str):
        """The drain side is gone: every not-yet-replied ring spec is
        resubmitted via RPC. The dead raylet took its undispatched backlog
        (and the local workers) with it, so this cannot double-execute an
        undispatched task; a dispatched-but-unreplied one retries under
        the same at-least-once contract as any worker crash."""
        if self._ring_dead:
            return
        self._ring_dead = True
        _fr.record("rpc.error", b"", f"submit ring dead: {reason}")
        pending, self._ring_pending = list(self._ring_pending.values()), {}
        for spec in pending:
            asyncio.ensure_future(self._submit_normal(spec))

    def _ring_close(self):
        """Clean detach at shutdown: flag the header (the raylet reclaims
        the ring object at its next tick) and drop our pin."""
        ring, self._ring = self._ring, None
        if ring is None:
            return
        try:
            ring.close()
        except Exception:
            pass
        try:
            self.plasma.release(self._ring_oid)
        except Exception:
            pass

    async def handle_SubmitRingReplies(self, req):
        """Batched task replies for ring-submitted specs, forwarded by the
        raylet (one notify per dispatched push batch)."""
        for task_id, reply in req["replies"]:
            spec = self._ring_pending.pop(task_id, None)
            if spec is None:
                record = self._pending_tasks.get(task_id)
                spec = record["spec"] if record else None
                if spec is None:
                    continue
            if reply.get("ring_bounce"):
                # local node saturated while a peer had room: re-route via
                # the RPC lease path, which knows how to spill
                await self._submit_via_rpc(spec)
            elif reply.get("worker_crashed"):
                await self._handle_worker_crash(
                    spec, RuntimeError(reply.get("error",
                                                 "ring worker died")))
            else:
                await self._process_task_reply(spec, reply)

    async def _pump_leases(self, key, state: _LeaseState):
        while state.queue and state.idle:
            lease = state.idle.popleft()
            spec = state.queue.popleft()
            asyncio.ensure_future(self._push_on_lease(key, state, lease, spec))
        # Bound in-flight lease requests: beyond a handful they only pile up
        # in the raylet's waiter queue while costing an RPC each.
        need = min(
            len(state.queue) - state.requests_in_flight,
            self._cfg_lease_inflight - state.requests_in_flight,
        )
        for _ in range(need):
            state.requests_in_flight += 1
            asyncio.ensure_future(self._request_lease(key, state))

    async def _request_lease(self, key, state: _LeaseState, raylet_client=None, hops=0):
        try:
            if not state.queue:
                return
            sample = state.queue[0]
            client = raylet_client
            if client is None and sample["strategy"].get("type") == "placement_group":
                # PG tasks lease directly from the raylet holding the bundle
                # (the local raylet has no view of remote bundle placement).
                client = await self._pg_raylet(sample["strategy"])
                if client is None:
                    err = RuntimeError(
                        "placement group not found or never became ready"
                    )
                    while state.queue:
                        self._fail_task(state.queue.popleft(), err)
                    return
            if client is None:
                client = self.raylet
            try:
                reply = await client.call(
                    "RequestWorkerLease",
                    {
                        "resources": sample["resources"],
                        "strategy": sample["strategy"],
                        "job_id": sample["job_id"],
                        "runtime_env": sample.get("runtime_env") or {},
                    },
                    timeout=RTPU_CONFIG.worker_lease_timeout_ms / 1000.0 + 10,
                )
            except (ConnectionLost, OSError, asyncio.TimeoutError):
                if raylet_client is not None:
                    # spill target died; go back to local raylet
                    state.requests_in_flight += 1
                    asyncio.ensure_future(self._request_lease(key, state))
                return
            if reply.get("granted"):
                lease = {
                    "worker_addr": tuple(reply["worker_addr"]),
                    "worker_id": reply["worker_id"],
                    "lease_id": reply["lease_id"],
                    "raylet": client,
                }
                state.all_leases.add(reply["lease_id"])
                if state.queue:
                    spec = state.queue.popleft()
                    asyncio.ensure_future(self._push_on_lease(key, state, lease, spec))
                else:
                    await self._return_lease(state, lease)
            elif reply.get("spill"):
                target = reply["spill"]
                peer = await self.pool.get(target["ip"], target["port"])
                state.requests_in_flight += 1
                if hops < 4:
                    asyncio.ensure_future(self._request_lease(key, state, peer, hops + 1))
                else:
                    asyncio.ensure_future(self._request_lease(key, state))
            elif reply.get("retry"):
                state.requests_in_flight += 1
                asyncio.ensure_future(self._request_lease(key, state))
            elif reply.get("retry_pg"):
                # Bundle not (yet) committed on the raylet we picked: drop the
                # cached placement and re-resolve from GCS — bounded, so a
                # commit that never lands fails the task instead of spinning.
                deadline = sample.setdefault(
                    "_pg_retry_deadline",
                    time.time() + RTPU_CONFIG.placement_group_ready_timeout_s,
                )
                if time.time() > deadline:
                    err = RuntimeError(
                        "placement group bundle never became available"
                    )
                    while state.queue:
                        self._fail_task(state.queue.popleft(), err)
                    return
                pg_key = (sample["strategy"]["pg_id"],
                          sample["strategy"].get("bundle_index") or 0)
                self._pg_node_cache.pop(pg_key, None)
                await asyncio.sleep(0.2)
                state.requests_in_flight += 1
                asyncio.ensure_future(self._request_lease(key, state))
            elif reply.get("error"):
                err = RuntimeError(reply["error"])
                while state.queue:
                    spec = state.queue.popleft()
                    self._fail_task(spec, err)
        finally:
            state.requests_in_flight -= 1

    async def _pg_raylet(self, strategy: dict):
        """Resolve the raylet hosting this task's PG bundle, waiting for the
        group to finish its 2PC if needed. Returns None if the PG is gone."""
        pg_key = (strategy["pg_id"], strategy.get("bundle_index") or 0)
        node_id = self._pg_node_cache.get(pg_key)
        if node_id is None:
            # Event-driven: the GCS blocks this call until the 2PC finishes
            # (WaitPlacementGroupReady arms a server-side event) — no
            # client-side polling interval. Transient RPC failures (GCS
            # restart) retry until the ready deadline; only an authoritative
            # "removed"/timeout answer fails the tasks.
            deadline = time.time() + RTPU_CONFIG.placement_group_ready_timeout_s
            while True:
                left = deadline - time.time()
                if left <= 0:
                    return None
                try:
                    reply = await self.gcs_aio.call(
                        "WaitPlacementGroupReady",
                        {"pg_id": pg_key[0], "timeout": left},
                        timeout=left + 10,
                    )
                except RemoteError:
                    return None  # GCS answered: the PG is removed
                except Exception:
                    await asyncio.sleep(0.5)  # transient; GCS may be restarting
                    continue
                if not reply.get("ready"):
                    return None
                break
            info = await self.gcs_aio.call(
                "GetPlacementGroup", {"pg_id": pg_key[0]}
            )
            if not info.get("found") or info["pg"]["state"] != "CREATED":
                return None
            node_id = info["pg"]["bundles"][pg_key[1]]["node_id"]
            self._pg_node_cache[pg_key] = node_id
        info = await self._node_info(node_id)
        if info is None:
            self._pg_node_cache.pop(pg_key, None)
            return None
        return await self.pool.get(info["ip"], info["raylet_port"])

    async def _push_on_lease(self, key, state: _LeaseState, lease, spec: dict):
        # Adaptive batching: when the queue is deep relative to the number of
        # leased workers, ship several tasks per RPC — the Python control
        # plane is message-count-bound (~0.25 ms/message), so tiny-task
        # throughput scales with batch size. A shallow queue keeps batch=1 so
        # sparse/long tasks keep per-task latency and full parallelism.
        batch = [spec]
        # Divide the queue by workers we have OR expect (outstanding lease
        # requests), so early grants don't hoard the queue and starve the
        # leases that are about to arrive.
        expected_workers = max(
            1, len(state.all_leases) + state.requests_in_flight
        )
        extra = min(
            len(state.queue) // expected_workers,
            self._cfg_push_batch - 1,
        )
        for _ in range(extra):
            if not state.queue:
                break
            batch.append(state.queue.popleft())
        try:
            client = await self.pool.get(*lease["worker_addr"])
            for s in batch:
                self._pending_tasks.get(s["task_id"], {})["lease"] = lease
                self.task_events.record(s, "SUBMITTED")
            if len(batch) == 1:
                replies = [await client.call(
                    "PushTask", {"spec": spec}, timeout=None
                )]
            else:
                r = await client.call(
                    "PushTasks", {"specs": batch}, timeout=None
                )
                replies = r["replies"]
        except (ConnectionLost, OSError) as e:
            _fr.record("rpc.error", lease["worker_id"],
                       f"PushTask: {type(e).__name__}")
            state.all_leases.discard(lease["lease_id"])
            for s in batch:
                await self._handle_worker_crash(s, e)
            await self._pump_leases(key, state)
            return
        for s, rep in zip(batch, replies):
            await self._process_task_reply(s, rep)
        # reuse the lease for queued work, else return it
        if state.queue:
            next_spec = state.queue.popleft()
            asyncio.ensure_future(self._push_on_lease(key, state, lease, next_spec))
        else:
            await self._return_lease(state, lease)

    async def _return_lease(self, state: _LeaseState, lease):
        state.all_leases.discard(lease["lease_id"])
        try:
            await lease["raylet"].notify(
                "ReturnWorker", {"worker_id": lease["worker_id"], "lease_id": lease["lease_id"]}
            )
        except Exception:
            pass

    async def _handle_worker_crash(self, spec: dict, err):
        record = self._pending_tasks.get(spec["task_id"])
        if record and record["retries"] > 0:
            record["retries"] -= 1
            self.task_events.record(spec, "RETRY")
            await self._submit_normal(spec)
        else:
            error: Exception = WorkerCrashedError(
                f"worker died executing {spec['name']}: {err}"
            )
            # If the raylet's memory monitor killed the worker, surface the
            # real cause (reference: OOM deaths raise ray.exceptions.
            # OutOfMemoryError, task_manager failure-cause plumbing).
            lease = (record or {}).get("lease")
            if lease:
                try:
                    await asyncio.sleep(0.3)  # let the death report land
                    r = await self.gcs_aio.call(
                        "GetWorkerFailures", {"limit": 200}, timeout=5
                    )
                    for f in reversed(r.get("failures", [])):
                        if f.get("worker_id") == lease["worker_id"]:
                            if "memory monitor" in f.get("reason", ""):
                                error = OutOfMemoryError(
                                    f"task {spec['name']} failed: {f['reason']}"
                                )
                            break
                except Exception:
                    pass
            self._fail_task(spec, error)

    def _fail_task(self, spec: dict, error: Exception):
        record = self._pending_tasks.pop(spec["task_id"], None)
        self.tasks_completed += 1  # failed is resolved, not stuck
        payload, _ = serialization.serialize_inline(error)
        for oid in ts.return_object_ids(spec):
            self.memory_store.put(oid, (_ERR, payload, None))
        self.task_events.record(spec, "FAILED", error=str(error)[:500])
        if record:
            self._release_task_arg_refs(record)
        if self._direct is not None:
            self._direct.notify_store()

    def _release_task_arg_refs(self, record):
        for ref in record.get("arg_refs", []):
            if self.refs.owns(ref.object_id()):
                self.refs.remove_submitted_task_ref(ref.object_id())
        record["arg_refs"] = []

    def _process_task_reply_sync(self, spec: dict, reply: dict,
                                 notify: bool = True) -> bool:
        """Synchronous fast path for the overwhelmingly common ok-inline
        reply: no awaits, no coroutine. Returns False when the reply needs
        the full async path (errors that may retry, plasma returns).
        notify=False lets batch callers coalesce the fast-get wakeup."""
        if reply.get("status") != "ok":
            return False
        results = reply["results"]
        for result in results:
            if "inline" not in result:
                return False
        record = self._pending_tasks.pop(spec["task_id"], None)
        for oid, result in zip(ts.return_object_ids(spec), results):
            # Skip oids the reference counter no longer tracks: if the
            # user-thread fast_get consumed the staged value and the ref
            # already hit zero (free ran), this deferred bookkeeping would
            # re-insert an entry for a freed object that nothing removes.
            if self.refs.owns(oid):
                self.memory_store.put(oid, (_INLINE, result["inline"], None))
        self.tasks_completed += 1
        if record:
            self._release_task_arg_refs(record)
        if notify and self._direct is not None:
            self._direct.notify_store()
        return True

    async def _process_task_reply(self, spec: dict, reply: dict):
        if self._process_task_reply_sync(spec, reply):
            return
        record = self._pending_tasks.get(spec["task_id"])
        if reply.get("status") == "error":
            if reply.get("app_error") and spec.get("retry_exceptions") and record and record["retries"] > 0:
                record["retries"] -= 1
                await self._submit_normal(spec)
                return
            if reply.get("cancelled"):
                err_payload, _ = serialization.serialize_inline(TaskCancelledError())
            elif "exception" in reply:
                err_payload = reply["exception"]
            else:
                err_payload, _ = serialization.serialize_inline(RuntimeError(reply.get("error", "task failed")))
            for oid in ts.return_object_ids(spec):
                if self.refs.owns(oid):
                    self.memory_store.put(oid, (_ERR, err_payload, None))
            self.task_events.record(spec, "FAILED", error=str(reply.get("error", ""))[:300])
        else:
            return_ids = ts.return_object_ids(spec)
            any_plasma = False
            for oid, result in zip(return_ids, reply["results"]):
                if not self.refs.owns(oid):
                    continue  # freed while in flight: don't re-insert
                if "inline" in result:
                    self.memory_store.put(oid, (_INLINE, result["inline"], None))
                elif "plasma" in result:
                    meta = result["plasma"]
                    any_plasma = True
                    self.memory_store.put(oid, InPlasma(meta["size"], {meta["node_id"]}))
                    self._object_locations.setdefault(oid.binary(), set()).add(meta["node_id"])
                    self.refs.note_size(oid, meta["size"], plasma=True)
            if any_plasma:
                self._store_lineage(spec)
        self._pending_tasks.pop(spec["task_id"], None)
        self.tasks_completed += 1
        if record:
            self._release_task_arg_refs(record)
        if self._direct is not None:
            self._direct.notify_store()

    def _store_lineage(self, spec: dict):
        """Keep specs that can recreate lost plasma returns
        (reference: task_manager.h:208 lineage, :215 max_lineage_bytes)."""
        est = 256 + sum(len(str(a)) for a in spec.get("args", []))
        if self._lineage_bytes + est > RTPU_CONFIG.max_lineage_bytes:
            return
        self._lineage[spec["task_id"]] = spec
        self._lineage_bytes += est

    async def _try_reconstruct(self, oid: ObjectID) -> bool:
        task_id = oid.task_id().binary()
        spec = self._lineage.get(task_id)
        if spec is None:
            return False
        self.memory_store.free(oid)
        for rid in ts.return_object_ids(spec):
            self.memory_store.put_pending(rid)
        self._pending_tasks[spec["task_id"]] = {
            "spec": spec, "retries": 0, "arg_refs": [], "return_ids": ts.return_object_ids(spec),
        }
        await self._submit_normal(spec)
        return True

    # ----------------------------------------------------------- actor submit

    def create_actor(
        self,
        cls,
        args,
        kwargs,
        *,
        name: str = "",
        namespace: str = "",
        num_returns: int = 0,
        resources: Dict[str, float],
        max_restarts: int = 0,
        max_concurrency: int = 1,
        lifetime: str = "",
        scheduling_strategy: Optional[dict] = None,
        runtime_env: Optional[dict] = None,
    ) -> bytes:
        actor_id = ActorID.of(self.job_id)
        fn_key = self.functions.export(cls)
        runtime_env = self.prepare_runtime_env(runtime_env)
        wire, refs, large = ts.serialize_args(args, kwargs, self.inline_threshold)
        big_refs = self._replace_large_args(wire, large)
        refs.extend(big_refs)
        task_id = TaskID.for_actor_creation(actor_id)
        spec = ts.build_task_spec(
            task_id=task_id,
            job_id=self.job_id,
            name=f"{name or getattr(cls, '__name__', 'Actor')}.__init__",
            fn_key=fn_key,
            wire_args=wire,
            num_returns=0,
            resources=resources,
            owner_addr=self.address,
            owner_worker_id=self.worker_id.binary(),
            scheduling_strategy=scheduling_strategy,
            task_type=ts.TASK_ACTOR_CREATION,
            actor_id=actor_id,
            max_concurrency=max_concurrency,
            max_restarts=max_restarts,
            caller_id=self.worker_id.binary(),
            runtime_env=runtime_env,
        )
        # Hold arg refs until creation completes (GCS drives creation).
        sub = _ActorSubmitter(actor_id.binary())
        sub.state = "PENDING_CREATION"
        self._actor_submitters[actor_id.binary()] = sub
        # keep creation arg refs alive until ALIVE (bound to submitter)
        sub.creation_refs = refs  # type: ignore[attr-defined]
        payload = {
            "actor_id": actor_id.binary(),
            "creation_spec": spec,
            "name": name,
            "namespace": namespace,
            "max_restarts": max_restarts,
            "detached": lifetime == "detached",
        }
        if name:
            # Named actors keep the synchronous round-trip: a name collision
            # must raise ValueError at .remote() time (reference:
            # actor.py _remote raising on duplicate detached names).
            try:
                self.gcs.call("RegisterActor", payload)
            except Exception as e:
                if "already taken" in str(e):
                    raise ValueError(
                        f"actor name {name!r} already taken"
                    ) from None
                raise
            self.io.post(self._watch_actor(actor_id.binary()))
            return actor_id.binary()
        # Anonymous actors register asynchronously and BATCHED: a burst of
        # .remote() calls becomes one SubscribeMany + one RegisterActors
        # round-trip instead of 3 per actor (subscribe-before-register makes
        # the state watch race-free without a refresh read).
        sub.watched = True
        self._post_batched("register_actor", (actor_id.binary(), payload))
        return actor_id.binary()

    def submit_actor_task(
        self, actor_id: bytes, method_name: str, args, kwargs, *, num_returns=1, name=""
    ) -> List[ObjectRef]:
        wire, refs, large = ts.serialize_args(args, kwargs, self.inline_threshold)
        big_refs = self._replace_large_args(wire, large)
        refs.extend(big_refs)
        task_id = TaskID.for_task(self.job_id)
        spec = ts.build_task_spec(
            task_id=task_id,
            job_id=self.job_id,
            name=name or method_name,
            fn_key=b"",
            wire_args=wire,
            num_returns=num_returns,
            resources={},
            owner_addr=self.address,
            owner_worker_id=self.worker_id.binary(),
            task_type=ts.TASK_ACTOR,
            actor_id=ActorID(actor_id),
            method_name=method_name,
            caller_id=self.worker_id.binary(),
        )
        from ray_tpu.util import tracing as _tracing

        trace_ctx = _tracing.context_for_spec()
        if trace_ctx is not None:
            spec["trace_ctx"] = trace_ctx
        return_refs = self._register_pending(spec, refs)
        if self._direct is not None:
            # Fast path: once this actor's direct channel is active, the
            # spec rides it straight from this (user) thread — the io loop
            # never sees the task (direct_channel.py).
            sub = self._actor_submitters.setdefault(
                actor_id, _ActorSubmitter(actor_id))
            if self._direct.try_submit(sub, spec):
                return return_refs
        self._post_batched("actor", (actor_id, spec))
        return return_refs

    def _route_actor_spec(self, actor_id: bytes, spec: dict):
        """Assign the per-actor sequence number and stage the spec for
        pushing. Returns the submitter iff it needs a pump kick (runs on
        the io loop, called from the batched drain)."""
        sub = self._actor_submitters.setdefault(actor_id, _ActorSubmitter(actor_id))
        if self._direct is not None and self._direct.loop_routed(sub, spec):
            return None  # forwarded onto the active direct channel
        sub.seq += 1
        spec["seq_no"] = sub.seq
        if not sub.watched:
            sub.watched = True
            asyncio.ensure_future(self._watch_actor(actor_id))
        if sub.state == "ALIVE" and sub.addr:
            sub.push_queue.append(spec)
            return sub
        if sub.state == "DEAD":
            self._fail_task(spec, ActorDiedError(actor_id, sub.death_cause or "actor is dead"))
            return None
        sub.buffer.append(spec)
        if sub.state == "UNKNOWN":
            asyncio.ensure_future(self._refresh_actor_state(sub))
        return None

    def _pump_actor(self, sub: _ActorSubmitter):
        """Push staged specs as pipelined batch RPCs (reference:
        actor_task_submitter.h pushes without waiting for prior replies;
        the receiver's seq_no reorder buffer restores order). A shallow
        queue ships single specs immediately; a burst coalesces into
        PushActorTasks batches, which is what lifts small-call throughput —
        the control plane is message-count-bound."""
        if sub.state != "ALIVE" or not sub.addr:
            return
        max_batch = self._cfg_push_batch
        while sub.push_queue and sub.pushing < self._cfg_actor_inflight:
            batch = []
            while sub.push_queue and len(batch) < max_batch:
                batch.append(sub.push_queue.popleft())
            sub.pushing += 1
            asyncio.ensure_future(self._push_actor_batch(sub, batch))

    async def _push_actor_batch(self, sub: _ActorSubmitter, batch: list):
        # A restart resets sub.pushing to 0 and bumps the epoch; any stale
        # decrement from this coroutine would drive it negative and void
        # the in-flight cap, so every decrement checks the epoch it started
        # under.
        epoch0 = sub.epoch

        def release_push_slot():
            if sub.epoch == epoch0:
                sub.pushing -= 1

        for spec in batch:
            sub.inflight[spec["task_id"]] = spec
        try:
            client = await self.pool.get(*sub.addr)
        except (ConnectionLost, OSError):
            # Connection never established: the tasks provably did not
            # execute, so it is safe to buffer them for the restarted
            # actor. Several pipelined batches can land here in any
            # order — rebuild the buffer sorted by seq so the restarted
            # executor's reorder window starts from the lowest seq.
            release_push_slot()
            for spec in batch:
                sub.inflight.pop(spec["task_id"], None)
            sub.buffer = deque(
                sorted(
                    list(batch) + list(sub.buffer),
                    key=lambda s: s.get("seq_no", 0),
                )
            )
            sub.state = "RESTARTING?"
            asyncio.ensure_future(self._refresh_actor_state(sub))
            return
        for spec in batch:
            self.task_events.record(spec, "SUBMITTED")
        if len(batch) == 1:
            # single-task fast path: reply rides the RPC response
            spec = batch[0]
            try:
                reply = await client.call(
                    "PushActorTask", {"spec": spec}, timeout=None
                )
            except (ConnectionLost, OSError):
                # Actor worker died with this task dispatched. It may have
                # already executed (e.g. it IS the task that killed the
                # actor), so replaying after restart would double-execute —
                # fail it instead, matching the reference's
                # actor_task_submitter semantics (max_task_retries
                # defaults to 0).
                release_push_slot()
                sub.inflight.pop(spec["task_id"], None)
                sub.state = "RESTARTING?"
                self._fail_task(
                    spec,
                    ActorDiedError(
                        sub.actor_id, "actor died while this task was in flight"
                    ),
                )
                asyncio.ensure_future(self._refresh_actor_state(sub))
                return
            release_push_slot()
            sub.inflight.pop(spec["task_id"], None)
            await self._process_task_reply(spec, reply)
            self._pump_actor(sub)
            if self._direct is not None and sub.direct_pending_switch:
                self._direct.maybe_activate(sub)
            return
        # Batched push: the receiver acks immediately and streams each
        # task's reply back as it resolves (handle_ActorTaskReplies), so a
        # slow task never holds a finished peer's reply. `pushing` stays
        # held until every reply in the batch lands — that is the flow
        # control bounding unreplied tasks per actor.
        batch_state = {"remaining": len(batch), "sub": sub,
                       "epoch": sub.epoch}
        for spec in batch:
            record = self._pending_tasks.get(spec["task_id"])
            if record is not None:
                record["push_batch"] = batch_state
        try:
            await client.call(
                "PushActorTasks",
                {"specs": batch, "reply_addr": list(self.address)},
                timeout=None,
            )
        except (ConnectionLost, OSError):
            sub.state = "RESTARTING?"
            release_push_slot()
            batch_state["epoch"] = -1  # stale: late replies must not double-count
            for spec in batch:
                sub.inflight.pop(spec["task_id"], None)
                record = self._pending_tasks.get(spec["task_id"])
                if record is not None:
                    record.pop("push_batch", None)
                self._fail_task(
                    spec,
                    ActorDiedError(
                        sub.actor_id, "actor died while this task was in flight"
                    ),
                )
            asyncio.ensure_future(self._refresh_actor_state(sub))

    async def _refresh_actor_state(self, sub: _ActorSubmitter):
        try:
            info = await self.gcs_aio.call("GetActorInfo", {"actor_id": sub.actor_id})
        except Exception:
            return
        if not info.get("found"):
            return
        await self._apply_actor_state(sub, info["actor"])

    async def _apply_actor_state(self, sub: _ActorSubmitter, rec: dict):
        state = rec["state"]
        _fr.record("actor.state", sub.actor_id, state)
        if state == "ALIVE" and rec.get("addr"):
            new_addr = tuple(rec["addr"])
            restarted = sub.addr is not None and new_addr != sub.addr
            sub.addr = new_addr
            sub.state = "ALIVE"
            if restarted:
                # seq keeps increasing; the fresh receiver reorders from the
                # first seq it sees. Outstanding batch accounting belongs to
                # the dead incarnation: invalidate it so late replies don't
                # double-decrement.
                sub.epoch += 1
                sub.pushing = 0
            if hasattr(sub, "creation_refs"):
                del sub.creation_refs
            if sub.buffer:
                # Rebuffered (lower-seq) specs must precede anything staged
                # while ALIVE: the fresh receiver's reorder window starts at
                # the first seq it sees, so out-of-order delivery strands
                # the lower seqs forever.
                merged = sorted(
                    list(sub.buffer) + list(sub.push_queue),
                    key=lambda s: s.get("seq_no", 0),
                )
                sub.buffer.clear()
                sub.push_queue = deque(merged)
            self._pump_actor(sub)
        elif state == "DEAD":
            sub.state = "DEAD"
            sub.death_cause = rec.get("death_cause", "")
            sub.epoch += 1
            sub.pushing = 0
            if self._direct is not None:
                self._direct.forget_actor(sub.actor_id)
            err = ActorDiedError(sub.actor_id, f"actor died: {sub.death_cause}")
            while sub.buffer:
                self._fail_task(sub.buffer.popleft(), err)
            while sub.push_queue:
                self._fail_task(sub.push_queue.popleft(), err)
            for spec in list(sub.inflight.values()):
                record = self._pending_tasks.get(spec["task_id"])
                if record is not None:
                    record.pop("push_batch", None)
                self._fail_task(spec, err)
            sub.inflight.clear()
        elif state in ("RESTARTING", "PENDING_CREATION"):
            sub.state = state
            sub.addr = None

    @staticmethod
    def _print_worker_log(msg: dict):
        """Driver-side sink of the per-node log monitors (reference:
        worker.py print_to_stdstream — '(pid=, ip=)'-prefixed relay)."""
        import sys as _sys

        stream = _sys.stderr if msg.get("is_err") else _sys.stdout
        prefix = f"(pid={msg.get('pid')}, ip={msg.get('ip')})"
        for line in msg.get("lines", []):
            print(f"{prefix} {line}", file=stream)

    def _ensure_pubsub(self):
        """Start the long-poll loop on first subscription. Workers that never
        subscribe (the common short-lived task/actor worker) keep zero
        standing GCS poll traffic — at many-worker scale the idle polls were
        a measurable share of control-plane messages."""
        if self._pubsub_task is None:
            self._pubsub_task = asyncio.ensure_future(self._pubsub_loop())

    def enable_log_to_driver(self):
        """Stream worker stdout/stderr of this job to the driver."""
        channel = f"logs:{self.job_id.binary().hex()}"
        self._subscribed_channels.add(channel)

        async def _sub():
            self._ensure_pubsub()
            await self.gcs_aio.call(
                "Subscribe",
                {"sub_id": self.worker_id.binary(), "channel": channel},
            )

        self.io.run(_sub())

    async def _watch_actor(self, actor_id: bytes):
        sub = self._actor_submitters.setdefault(actor_id, _ActorSubmitter(actor_id))
        channel = f"actor:{actor_id.hex()}"
        self._subscribed_channels.add(channel)
        self._ensure_pubsub()
        await self.gcs_aio.call(
            "Subscribe", {"sub_id": self.worker_id.binary(), "channel": channel}
        )
        await self._refresh_actor_state(sub)

    async def _resubscribe_after_gcs_restart(self) -> bool:
        """The GCS restarted (new epoch): its subscriber table is gone.

        Re-subscribe every channel we were watching and re-read actor states
        we may have missed while the GCS was down. Returns False if any
        re-subscribe failed (a flapping GCS) so the caller keeps the old
        epoch and retries on the next poll.
        """
        ok = True
        for channel in list(self._subscribed_channels):
            try:
                await self.gcs_aio.call(
                    "Subscribe",
                    {"sub_id": self.worker_id.binary(), "channel": channel},
                )
            except Exception:
                ok = False
        for sub in list(self._actor_submitters.values()):
            if sub.state != "DEAD":
                asyncio.ensure_future(self._refresh_actor_state(sub))
        return ok

    async def _pubsub_loop(self):
        """Single long-poll loop draining every GCS channel we subscribe to."""
        epoch = None
        while True:
            try:
                reply = await self.gcs_aio.call(
                    "PubsubPoll",
                    {"sub_id": self.worker_id.binary(), "timeout": 20.0},
                    timeout=40.0,
                )
            except Exception:
                await asyncio.sleep(1.0)
                continue
            new_epoch = reply.get("epoch")
            if epoch is None or new_epoch == epoch:
                epoch = new_epoch
            elif await self._resubscribe_after_gcs_restart():
                epoch = new_epoch
            for channel, msg in reply.get("batch", []):
                if channel.startswith("logs:"):
                    self._print_worker_log(msg)
                elif channel.startswith("actor:"):
                    actor_id = msg["actor_id"]
                    sub = self._actor_submitters.get(actor_id)
                    if sub is not None:
                        rec = {
                            "state": msg["state"],
                            "addr": msg.get("addr"),
                            "death_cause": msg.get("death_cause", ""),
                        }
                        await self._apply_actor_state(sub, rec)

    def kill_actor(self, actor_id: bytes, no_restart=True):
        self.gcs.call("KillActor", {"actor_id": actor_id, "no_restart": no_restart})

    def cancel_task(self, ref: ObjectRef, force=False, recursive=True):
        async def go():
            task_id = ref.object_id().task_id().binary()
            record = self._pending_tasks.get(task_id)
            if record is None:
                return
            lease = record.get("lease")
            addr = None
            if lease:
                addr = lease["worker_addr"]
            else:
                spec = record["spec"]
                if spec.get("actor_id"):
                    sub = self._actor_submitters.get(spec["actor_id"])
                    if sub and sub.addr:
                        addr = sub.addr
            if addr:
                try:
                    client = await self.pool.get(*addr)
                    await client.notify("CancelTask", {"task_id": task_id})
                except Exception:
                    pass

        self.io.run(go())

    # ----------------------------------------------------- executor services

    def _direct_upgrade(self, payload):
        """Connection-upgrade hook for the direct call channel handshake
        (runs synchronously on the io loop inside RpcServer). Only serial
        sync actors accept — everything else keeps the loop path."""
        if not self._cfg_direct:
            return {"ok": False, "reason": "direct channels disabled"}, None
        if not self._direct_server.eligible():
            return {"ok": False, "reason": "not a serial sync actor"}, None
        caller = payload.get("caller_id", b"")
        return {"ok": True}, (
            lambda sock: self._direct_server.adopt(sock, caller))

    def on_became_actor(self, actor_id: bytes, spec: dict):
        self.actor_id = actor_id
        self._actor_spec = spec

    def register_running_task(self, task_id: bytes, fut):
        self._running_async[task_id] = fut

    def unregister_running_task(self, task_id: bytes):
        self._running_async.pop(task_id, None)

    def try_cancel_running(self, task_id: bytes):
        fut = self._running_async.get(task_id)
        if fut is not None:
            fut.cancel()

    def push_task_context(self, spec: dict):
        old = getattr(self._ctx, "spec", None)
        self._ctx.spec = spec
        return old

    def pop_task_context(self, old):
        self._ctx.spec = old

    def current_task_spec(self):
        return getattr(self._ctx, "spec", None)

    async def put_return_to_plasma(self, oid: ObjectID, payload, spec) -> dict:
        """Store a large task return into local plasma; owner is the caller.
        `payload` is the executor's raw (pickle_bytes, buffers) pair — the
        buffers stream straight into shm, never materialized as bytes."""
        pickle_bytes, buffers = payload
        loop = asyncio.get_running_loop()
        size = await loop.run_in_executor(
            None, self._plasma_put_payload, oid, pickle_bytes, buffers
        )
        try:
            await self.raylet.call(
                "PinObject",
                {"object_id": oid.binary(), "owner_addr": list(spec["owner_addr"]),
                 "meta": self._pin_meta(oid, size, spec=spec)},
                timeout=30,
            )
        except Exception:
            pass
        return {"size": size, "node_id": self.node_id.binary()}

    # -------------------------------------------------------------- handlers

    async def handle_PushTask(self, req):
        return await self.executor.execute_normal(req["spec"])

    async def handle_PushTasks(self, req):
        """Batched push: one pooled thread executes the batch back-to-back,
        spilling to thread-per-task only if a task blocks (executor
        .execute_batch) — tasks that synchronize with a batch-mate still
        behave as if they'd been granted separate leases, without paying a
        threadpool round-trip per tiny task."""
        return {"replies": await self.executor.execute_batch(req["specs"])}

    async def handle_CreateActor(self, req):
        return await self.executor.create_actor(req["spec"], req["actor_id"])

    async def handle_PushActorTask(self, req):
        return await self.executor.push_actor_task(req["spec"])

    async def handle_PushActorTasks(self, req):
        """Batched actor-task push: ack immediately, stream each task's
        reply back to the owner as it resolves (batched notify frames).
        One slow task in a batch never delays a finished peer's reply
        (reference: per-call replies in core_worker.proto PushTask)."""
        specs = req["specs"]
        reply_addr = tuple(req["reply_addr"])
        futs = self.executor.enqueue_actor_tasks(specs)
        for spec, fut in zip(specs, futs):
            task_id = spec["task_id"]
            fut.add_done_callback(
                lambda f, tid=task_id: self._queue_task_reply(
                    reply_addr, tid, f
                )
            )
        return {"accepted": len(specs)}

    def _queue_task_reply(self, addr, task_id: bytes, fut):
        """Buffer a resolved task reply for its owner; one in-flight flush
        per destination burst (scheduled-drain, like _post_batched)."""
        try:
            reply = fut.result()
        except Exception as e:  # executor-level failure
            reply = {"status": "error", "error": str(e), "app_error": False}
        buf = self._reply_bufs.setdefault(addr, [])
        buf.append([task_id, reply])
        if addr not in self._reply_flush_scheduled:
            self._reply_flush_scheduled.add(addr)
            asyncio.ensure_future(self._flush_task_replies(addr))

    async def _flush_task_replies(self, addr):
        try:
            while True:
                batch = self._reply_bufs.get(addr)
                if not batch:
                    return
                self._reply_bufs[addr] = []
                # A lost reply permanently hangs the owner's get() AND
                # wedges its per-actor push window, so transient connect
                # failures must retry; only an owner unreachable for ~15 s
                # (presumed dead — nobody left to consume) drops them.
                for attempt in range(6):
                    try:
                        client = await self.pool.get(addr[0], addr[1])
                        await client.notify(
                            "ActorTaskReplies", {"replies": batch}
                        )
                        break
                    except Exception as e:
                        _fr.record("rpc.error", b"",
                                   f"ActorTaskReplies retry {attempt}: "
                                   f"{type(e).__name__}")
                        await asyncio.sleep(0.2 * (2 ** attempt))
                else:
                    _fr.record("rpc.error", b"",
                               "ActorTaskReplies dropped (owner unreachable)")
                    self._reply_bufs.pop(addr, None)
                    return
        finally:
            self._reply_flush_scheduled.discard(addr)

    async def handle_ActorTaskReplies(self, req):
        """Owner side: per-task replies streaming back from a batched
        actor-task push."""
        for task_id, reply in req["replies"]:
            record = self._pending_tasks.get(task_id)
            if record is None:
                continue
            spec = record["spec"]
            batch_state = record.pop("push_batch", None)
            await self._process_task_reply(spec, reply)
            if batch_state is not None:
                sub = batch_state["sub"]
                sub.inflight.pop(task_id, None)
                if batch_state["epoch"] == sub.epoch:
                    batch_state["remaining"] -= 1
                    if batch_state["remaining"] <= 0:
                        sub.pushing -= 1
                        self._pump_actor(sub)
                        if (self._direct is not None
                                and sub.direct_pending_switch):
                            self._direct.maybe_activate(sub)

    async def handle_GetObjectStatus(self, req):
        oid = ObjectID(req["object_id"])
        if req.get("wait"):
            timeout = min(req.get("timeout", 25.0), 25.0)
            ready = await self.memory_store.wait_ready(oid, timeout)
            if not ready:
                return {"status": "pending"}
        entry = self.memory_store.get_if_exists(oid)
        if entry is None:
            if self.memory_store.is_pending(oid):
                return {"status": "pending"}
            if self.refs.owns(oid):
                return {"status": "pending"}
            return {"status": "freed"}
        if isinstance(entry, InPlasma):
            return {
                "status": "ready",
                "plasma": {"size": entry.size, "locations": list(entry.locations)},
            }
        kind, payload = entry[0], entry[1]
        if kind == _ERR:
            return {"status": "ready", "err": payload}
        return {"status": "ready", "inline": payload}

    async def handle_AddBorrowerRef(self, req):
        self.refs.add_borrower(ObjectID(req["object_id"]), tuple(req["borrower"]))

    async def handle_RemoveBorrowerRef(self, req):
        self.refs.remove_borrower(ObjectID(req["object_id"]), tuple(req["borrower"]))

    async def handle_AddObjectLocation(self, req):
        oid = ObjectID(req["object_id"])
        self._object_locations.setdefault(oid.binary(), set()).add(req["node_id"])
        entry = self.memory_store.get_if_exists(oid)
        if isinstance(entry, InPlasma):
            entry.locations.add(req["node_id"])

    async def handle_RemoveObjectLocation(self, req):
        oid = ObjectID(req["object_id"])
        self._object_locations.get(oid.binary(), set()).discard(req["node_id"])
        entry = self.memory_store.get_if_exists(oid)
        if isinstance(entry, InPlasma):
            entry.locations.discard(req["node_id"])

    async def handle_Profile(self, req):
        """On-demand stack sampling of THIS process (reference: dashboard
        reporter profile_manager.py:78 py-spy; see _private/profiling.py)."""
        from ray_tpu._private import profiling

        loop = asyncio.get_running_loop()
        counts = await loop.run_in_executor(
            None, profiling.sample_stacks,
            req.get("duration", 2.0), req.get("hz", 100.0),
        )
        return {"folded": profiling.folded_text(counts),
                "samples": sum(counts.values()), "pid": os.getpid()}

    async def handle_StartProfile(self, req):
        """Profiling plane: kick off a timed background capture of this
        process (timestamped samples, _private/sampling_profiler.py). The
        raylet fans this out so a whole node — then the whole cluster —
        samples one synchronized window; CollectProfile fans the results
        back in."""
        from ray_tpu._private import sampling_profiler as _sp

        try:
            _sp.start_profile(
                req.get("duration", 2.0), req.get("hz", 99.0),
                role=self.mode)
        except RuntimeError as e:
            return {"error": str(e), "pid": os.getpid()}
        return {"ok": True, "pid": os.getpid()}

    async def handle_CollectProfile(self, req):
        """Blocks until the capture window started by StartProfile closes,
        then returns the sample set (off-loop: the join must not stall the
        worker's RPC loop)."""
        from ray_tpu._private import sampling_profiler as _sp

        loop = asyncio.get_running_loop()
        profile = await loop.run_in_executor(None, _sp.collect_profile)
        if profile is None:
            return {"error": "no profile capture in progress",
                    "pid": os.getpid()}
        profile["worker_id"] = self.worker_id.hex()
        return {"profile": profile, "pid": os.getpid()}

    async def handle_CancelTask(self, req):
        self.executor.cancel(req["task_id"])

    async def handle_KillActor(self, req):
        _fr.record("actor.state", self.actor_id or b"", "KILLED")
        _fr.flush_now()
        asyncio.get_running_loop().call_later(0.05, os._exit, 0)
        return {"ok": True}

    async def handle_Exit(self, req):
        _fr.record("worker.death", self.worker_id.binary(), "Exit RPC")
        _fr.flush_now()
        asyncio.get_running_loop().call_later(0.05, os._exit, 0)
        return {"ok": True}

    async def handle_DumpFlightRecorder(self, req):
        """Forensics: this process's flight-recorder ring, formatted
        (raylet fans this out for `ray-tpu debug dump`)."""
        return {
            "worker_id": self.worker_id.binary(),
            "pid": os.getpid(),
            "events": _fr.dump(req.get("limit") or 0),
        }

    async def handle_Ping(self, req):
        return {"ok": True, "worker_id": self.worker_id.binary()}

    async def handle_GetCoreWorkerStats(self, req):
        now = time.time()
        return {
            "worker_id": self.worker_id.binary(),
            "mode": self.mode,
            "actor_id": self.actor_id,
            "refs": self.refs.stats(),
            "memory_store_size": self.memory_store.size(),
            "pending_tasks": len(self._pending_tasks),
            "running_tasks": [
                {"task_id": tid, "name": name, "age": now - t0}
                for tid, (name, t0) in list(self.running_tasks.items())
            ],
        }

    async def handle_GetMemoryReport(self, req):
        """Memory observability plane: this process's object ownership
        ledger + RSS. Pull-only — the ledger snapshot is built here, on
        demand, from fields the hot paths already maintain (the raylet
        fans this out per node; util.state aggregates the cluster)."""
        from ray_tpu._private import memory_report as _mr

        limit = req.get("limit") or RTPU_CONFIG.memory_report_top_n
        return {"report": _mr.build_worker_report(self, limit=limit)}

    async def handle_CheckRefs(self, req):
        """Leak-detector probe: which of ``ids`` does this process still
        own (a live entry in its reference counter)? A pinned plasma
        primary whose owner answers False here — twice — is a leak."""
        ids = [ObjectID(b) for b in req.get("ids", [])]
        return {"owned": self.refs.owns_many(ids)}

    # ------------------------------------------------------------- shutdown

    def shutdown(self):
        if self.is_shutdown:
            return
        self.is_shutdown = True
        set_worker_hooks(None)
        # Stop the flush loop deterministically (the is_shutdown guard is
        # the backstop) — see the zombie-drain note in the loop body.
        flush_task = getattr(self, "_flush_task", None)
        if flush_task is not None:
            try:
                self.io.loop.call_soon_threadsafe(flush_task.cancel)
            except Exception:
                pass
        if self._watchdog is not None:
            self._watchdog.stop()
        _fr.flush_now()
        try:
            if self._direct is not None:
                self._direct.close_all()
            self._direct_server.close_all()
        except Exception:
            pass
        try:
            self._ring_close()
        except Exception:
            pass
        try:
            self.io.run(self.server.stop(), timeout=5)
        except Exception:
            pass
        self.executor.shutdown()
        try:
            if self.plasma:
                self.plasma.close()
        except Exception:
            pass


# ---------------------------------------------------------------- globals

global_worker: Optional[CoreWorker] = None


def get_global_worker() -> CoreWorker:
    if global_worker is None:
        raise RuntimeError("ray_tpu.init() has not been called")
    return global_worker


def set_global_worker(worker: Optional[CoreWorker]):
    global global_worker
    global_worker = worker
