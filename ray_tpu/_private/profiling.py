"""In-process stack sampling for on-demand profiling.

Reference: the dashboard's py-spy/memray integration
(dashboard/modules/reporter/profile_manager.py:78/:189). The same
capability without the binary dependency: any worker can sample its own
threads' stacks via sys._current_frames at a fixed rate and return
flamegraph-compatible folded lines ("a;b;c 42"). The dashboard asks the
raylet, the raylet asks the worker (both plain RPCs), so profiling any
process in the cluster is one HTTP call.
"""

from __future__ import annotations

import sys
import threading
import time
from collections import Counter
from typing import Dict


def _frame_label(frame) -> str:
    code = frame.f_code
    fname = code.co_filename.rsplit("/", 1)[-1]
    return f"{code.co_name} ({fname}:{frame.f_lineno})"


def sample_stacks(duration_s: float = 2.0, hz: float = 100.0,
                  include_idle: bool = False) -> Dict[str, int]:
    """Sample all threads for duration_s; returns {folded_stack: count}.

    Runs in the CALLING thread — callers dispatch it to a sampler thread
    (the worker RPC handler does) so the sampled threads keep running.
    """
    duration_s = min(float(duration_s), 60.0)
    hz = min(max(1.0, float(hz)), 500.0)
    period = 1.0 / hz
    me = threading.get_ident()
    names = {t.ident: t.name for t in threading.enumerate()}
    counts: Counter = Counter()
    end = time.monotonic() + duration_s
    while time.monotonic() < end:
        for tid, frame in sys._current_frames().items():
            if tid == me:
                continue
            name = names.get(tid) or str(tid)
            if not include_idle and (
                name.startswith("rtpu-io")
                or name.endswith("-watchdog")
            ):
                # the io loop is ~always parked in epoll; skip unless asked
                continue
            stack = []
            f = frame
            depth = 0
            while f is not None and depth < 128:
                stack.append(_frame_label(f))
                f = f.f_back
                depth += 1
            stack.reverse()
            counts[f"{name};" + ";".join(stack)] += 1
        time.sleep(period)
        names = {t.ident: t.name for t in threading.enumerate()}
    return dict(counts)


def folded_text(counts: Dict[str, int]) -> str:
    """flamegraph.pl-compatible folded output, heaviest first."""
    return "\n".join(
        f"{stack} {n}"
        for stack, n in sorted(counts.items(), key=lambda kv: -kv[1])
    )


def profile_via_raylets(nodes, *, pid=None, worker_id=None,
                        node_filter=None, duration=2.0, hz=100.0):
    """Shared fan-out used by the dashboard endpoint AND the CLI: resolve
    the target worker across alive raylets and run a ProfileWorker RPC.

    Returns (status, payload) with HTTP-shaped statuses: 200 + result,
    400 on cross-node pid ambiguity (pids are only unique per host),
    404 when no node has the worker, 502 when raylets were unreachable.
    """
    from ray_tpu._private.rpc import IoThread, RpcClient

    io = IoThread.current()
    req = {"duration": duration, "hz": hz}
    if pid is not None:
        req["pid"] = int(pid)
    if worker_id is not None:
        req["worker_id"] = worker_id
    nodes = [
        n for n in nodes
        if n.get("state", "ALIVE") == "ALIVE"
        and (not node_filter or n["node_id"].hex().startswith(node_filter))
    ]

    async def ask(n, method, payload, timeout):
        client = RpcClient(n["ip"], n["raylet_port"])
        await client.connect()
        try:
            return await client.call(method, payload, timeout=timeout)
        finally:
            await client.close()

    if pid is not None and not node_filter and len(nodes) > 1:
        holders = []
        for n in nodes:
            try:
                # short probe timeout: this runs sequentially in a sync
                # HTTP/CLI path, and an unreachable raylet must not add
                # tens of seconds before profiling starts
                info = io.run(
                    ask(n, "GetLocalWorkerInfo", {}, 4), timeout=6
                )
            except Exception:
                continue
            if any(w["pid"] == req["pid"] for w in info.get("workers", [])):
                holders.append(n)
        if len(holders) > 1:
            return 400, {
                "error": f"pid {pid} exists on {len(holders)} nodes; "
                "disambiguate with node_id",
            }
        if holders:
            nodes = holders

    transport_err = None
    worker_err = None
    for n in nodes:
        try:
            r = io.run(
                ask(n, "ProfileWorker", req, duration + 40),
                timeout=duration + 60,
            )
        except Exception as e:
            transport_err = str(e)
            continue
        if not r.get("error"):
            return 200, r
        worker_err = r["error"]
    if transport_err:
        return 502, {"error": f"some raylets unreachable: {transport_err}"}
    return 404, {"error": worker_err or "no such worker on any alive node"}
