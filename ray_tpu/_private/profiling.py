"""In-process stack sampling for on-demand profiling.

Reference: the dashboard's py-spy/memray integration
(dashboard/modules/reporter/profile_manager.py:78/:189). The same
capability without the binary dependency: any worker can sample its own
threads' stacks via sys._current_frames at a fixed rate and return
flamegraph-compatible folded lines ("a;b;c 42"). The dashboard asks the
raylet, the raylet asks the worker (both plain RPCs), so profiling any
process in the cluster is one HTTP call.
"""

from __future__ import annotations

import sys
import threading
import time
from collections import Counter
from typing import Dict


def _frame_label(frame) -> str:
    from ray_tpu._private.sampling_profiler import frame_label

    return frame_label(frame)


def sample_stacks(duration_s: float = 2.0, hz: float = 100.0,
                  include_idle: bool = False) -> Dict[str, int]:
    """Sample all threads for duration_s; returns {folded_stack: count}.

    Runs in the CALLING thread — callers dispatch it to a sampler thread
    (the worker RPC handler does) so the sampled threads keep running.
    """
    duration_s = min(float(duration_s), 60.0)
    hz = min(max(1.0, float(hz)), 500.0)
    period = 1.0 / hz
    me = threading.get_ident()
    names = {t.ident: t.name for t in threading.enumerate()}
    counts: Counter = Counter()
    end = time.monotonic() + duration_s
    while time.monotonic() < end:
        for tid, frame in sys._current_frames().items():
            if tid == me:
                continue
            name = names.get(tid) or str(tid)
            if not include_idle and (
                name.startswith("rtpu-io")
                or name.endswith("-watchdog")
            ):
                # the io loop is ~always parked in epoll; skip unless asked
                continue
            stack = []
            f = frame
            depth = 0
            while f is not None and depth < 128:
                stack.append(_frame_label(f))
                f = f.f_back
                depth += 1
            stack.reverse()
            counts[f"{name};" + ";".join(stack)] += 1
        time.sleep(period)
        names = {t.ident: t.name for t in threading.enumerate()}
    return dict(counts)


def folded_text(counts: Dict[str, int]) -> str:
    """flamegraph.pl-compatible folded output, heaviest first."""
    return "\n".join(
        f"{stack} {n}"
        for stack, n in sorted(counts.items(), key=lambda kv: -kv[1])
    )


def profile_via_raylets(nodes, *, pid=None, worker_id=None,
                        node_filter=None, duration=2.0, hz=100.0):
    """Shared fan-out used by the dashboard endpoint AND the CLI: resolve
    the target worker across alive raylets and run a ProfileWorker RPC.

    Returns (status, payload) with HTTP-shaped statuses: 200 + result,
    400 on cross-node pid ambiguity (pids are only unique per host),
    404 when no node has the worker, 502 when raylets were unreachable.
    """
    from ray_tpu._private.rpc import IoThread, RpcClient

    io = IoThread.current()
    req = {"duration": duration, "hz": hz}
    if pid is not None:
        req["pid"] = int(pid)
    if worker_id is not None:
        req["worker_id"] = worker_id
    nodes = [
        n for n in nodes
        if n.get("state", "ALIVE") == "ALIVE"
        and (not node_filter or n["node_id"].hex().startswith(node_filter))
    ]

    async def ask(n, method, payload, timeout):
        client = RpcClient(n["ip"], n["raylet_port"])
        await client.connect()
        try:
            return await client.call(method, payload, timeout=timeout)
        finally:
            await client.close()

    if pid is not None and not node_filter and len(nodes) > 1:
        holders = []
        for n in nodes:
            try:
                # short probe timeout: this runs sequentially in a sync
                # HTTP/CLI path, and an unreachable raylet must not add
                # tens of seconds before profiling starts
                info = io.run(
                    ask(n, "GetLocalWorkerInfo", {}, 4), timeout=6
                )
            except Exception:
                continue
            if any(w["pid"] == req["pid"] for w in info.get("workers", [])):
                holders.append(n)
        if len(holders) > 1:
            return 400, {
                "error": f"pid {pid} exists on {len(holders)} nodes; "
                "disambiguate with node_id",
            }
        if holders:
            nodes = holders

    transport_err = None
    worker_err = None
    for n in nodes:
        try:
            r = io.run(
                ask(n, "ProfileWorker", req, duration + 40),
                timeout=duration + 60,
            )
        except Exception as e:
            transport_err = str(e)
            continue
        if not r.get("error"):
            return 200, r
        worker_err = r["error"]
    if transport_err:
        return 502, {"error": f"some raylets unreachable: {transport_err}"}
    return 404, {"error": worker_err or "no such worker on any alive node"}


# --------------------------------------------------- cluster-wide capture
# The profiling-plane tentpole: one synchronized sampling window across
# every process in the cluster. StartProfile fans out first (raylets fan to
# their live workers), so all nodes sample the SAME wall-clock window; the
# CollectProfile pass then blocks server-side until each window closes and
# fans the per-process sample sets back in. The caller merges them with the
# task/span timeline (_private/timeline.merged_profile_trace).


def capture_cluster_profile(nodes, gcs=None, *, duration: float = 5.0,
                            hz: float = 99.0, node_filter=None,
                            include_gcs: bool = True,
                            include_drivers: bool = True) -> dict:
    """Returns a profile *bundle*:

    {"t0", "duration", "hz",
     "nodes": [{"node_id": hex, "profiles": [per-process result dicts]}],
     "drivers": [per-process result dicts],
     "gcs": per-process result dict | None,
     "errors": ["<node hex>: <why>", ...]}

    Drivers aren't in any raylet's worker pool (they register with the GCS
    through AddJob), yet the input pipeline and submission loop — prime
    slow-step suspects — run there, so running jobs' driver addresses get
    the same Start/Collect pair directly.
    """
    import asyncio
    import time

    from ray_tpu._private.rpc import IoThread, RpcClient

    duration = min(max(0.05, float(duration)), 120.0)
    hz = min(max(1.0, float(hz)), 500.0)
    nodes = [
        n for n in nodes
        if n.get("state", "ALIVE") == "ALIVE"
        and (not node_filter or n["node_id"].hex().startswith(node_filter))
    ]
    bundle = {"t0": time.time(), "duration": duration, "hz": hz,
              "nodes": [], "drivers": [], "gcs": None, "errors": []}

    driver_addrs = []
    if include_drivers and gcs is not None:
        try:
            for j in gcs.call("GetAllJobInfo", {}, timeout=10)["jobs"]:
                addr = j.get("driver_addr")
                if j.get("state") == "RUNNING" and addr and addr[1]:
                    driver_addrs.append((addr[0], int(addr[1])))
        except Exception:
            pass

    async def _capture_node(n):
        client = RpcClient(n["ip"], n["raylet_port"])
        await client.connect()
        try:
            await client.call(
                "StartProfile",
                {"duration": duration, "hz": hz, "include_workers": True},
                timeout=15,
            )
            r = await client.call(
                "CollectProfile", {}, timeout=duration + 40)
            return {"node_id": n["node_id"].hex(),
                    "profiles": r.get("profiles", [])}
        finally:
            await client.close()

    async def _capture_gcs():
        # gcs is the sync GcsClient wrapper; inside this io-thread
        # coroutine only its .aio half is usable (io.run would deadlock)
        if gcs is None or not include_gcs:
            return None
        await gcs.aio.call("StartProfile", {"duration": duration, "hz": hz},
                           timeout=15)
        r = await gcs.aio.call("CollectProfile", {}, timeout=duration + 40)
        return r.get("profile")

    async def _capture_driver(addr):
        client = RpcClient(*addr)
        await client.connect()
        try:
            await client.call(
                "StartProfile", {"duration": duration, "hz": hz}, timeout=15)
            r = await client.call("CollectProfile", {}, timeout=duration + 40)
            return r.get("profile")
        finally:
            await client.close()

    async def _all():
        tasks = [_capture_node(n) for n in nodes]
        tasks += [_capture_driver(a) for a in driver_addrs]
        tasks.append(_capture_gcs())
        return await asyncio.gather(*tasks, return_exceptions=True)

    results = IoThread.current().run(_all(), timeout=duration + 60)
    gcs_result = results[-1]
    node_results = results[:len(nodes)]
    driver_results = results[len(nodes):-1]
    for n, r in zip(nodes, node_results):
        if isinstance(r, BaseException):
            bundle["errors"].append(f"{n['node_id'].hex()[:12]}: {r}")
        else:
            bundle["nodes"].append(r)
    for a, r in zip(driver_addrs, driver_results):
        if isinstance(r, BaseException):
            bundle["errors"].append(f"driver {a[0]}:{a[1]}: {r}")
        elif r:
            bundle["drivers"].append(r)
    if isinstance(gcs_result, BaseException):
        bundle["errors"].append(f"gcs: {gcs_result}")
    else:
        bundle["gcs"] = gcs_result
    return bundle


def fold_bundle(bundle: dict) -> Dict[str, int]:
    """Aggregate a whole bundle into one folded-stack counter; lines are
    prefixed ``node:<id8>;<role>:<pid>;<thread>;frame;...`` so a cluster
    flamegraph keeps per-process attribution."""
    from ray_tpu._private.sampling_profiler import fold_samples

    out: Dict[str, int] = {}

    def _merge(profile, node_hex):
        role = profile.get("role") or "proc"
        prefix = f"node:{node_hex[:8]};{role}:{profile.get('pid', 0)};"
        for stack, c in fold_samples(profile).items():
            key = prefix + stack
            out[key] = out.get(key, 0) + c

    for node in bundle.get("nodes", []):
        for p in node.get("profiles", []):
            _merge(p, node.get("node_id", ""))
    for p in bundle.get("drivers", []):
        _merge(p, "driver")
    if bundle.get("gcs"):
        _merge(bundle["gcs"], "gcs")
    return out


# ------------------------------------------------------- capture registry
# Triggered and on-demand captures register their output path in the GCS
# KV so `ray-tpu debug dump` and the dashboard can find "the latest
# captures" without a filesystem convention shared across hosts.

_CAPTURE_NS = b"profiling"


def register_capture(gcs, path: str, *, reason: str, extra=None) -> None:
    import json
    import time

    rec = {"path": path, "reason": reason, "host": _hostname(),
           "time": time.time(), **(extra or {})}
    try:
        gcs.kv_put(_CAPTURE_NS, f"capture:{rec['time']:.6f}".encode(),
                   json.dumps(rec).encode())
    except Exception:
        pass


def register_device_trace(gcs, path: str, *, steps: int) -> None:
    import json
    import time

    rec = {"path": path, "steps": steps, "host": _hostname(),
           "time": time.time()}
    try:
        gcs.kv_put(_CAPTURE_NS, f"device_trace:{rec['time']:.6f}".encode(),
                   json.dumps(rec).encode())
    except Exception:
        pass


def list_registered(gcs, kind: str = "capture", limit: int = 20) -> list:
    """Newest-last registered records of one kind ('capture' or
    'device_trace')."""
    import json

    try:
        keys = sorted(gcs.kv_keys(_CAPTURE_NS, f"{kind}:".encode()))
    except Exception:
        return []
    out = []
    for key in keys[-limit:]:
        try:
            raw = gcs.kv_get(_CAPTURE_NS, key)
            if raw:
                out.append(json.loads(raw))
        except Exception:
            continue
    return out


def _hostname() -> str:
    import socket

    try:
        return socket.gethostname()
    except Exception:
        return ""
